"""Correlation keys — the fields that join N ranks' journals into one
mesh-wide story.

PR 3's flight recorder is strictly per-process: each rank's records
carry a run id and a per-process ``seq``, but nothing that lines up
*across* ranks — and every interesting production failure (the PR 6
drills prove it) is a cross-rank story.  Three keys fix that, stamped
by :mod:`~pencilarrays_tpu.obs.events` into **every** record:

* ``step_idx`` — a monotonic per-process step index, advanced at every
  :func:`~pencilarrays_tpu.guard.recover.guarded_step` entry (or
  explicitly via :func:`next_step` / the :func:`step` context manager).
  On a mesh every rank executes the same collective step sequence, so
  the counters align *by construction* — no communication needed: the
  hop a rank dispatched in step 7 joins its peers' step-7 hops even
  when wall clocks disagree by minutes.
* ``epoch`` — the shared recovery epoch
  (:mod:`~pencilarrays_tpu.cluster.epoch`): which incarnation of the
  timeline a record belongs to.  A step *rerun* after an agreed
  restore has the same ``step_idx`` but a later ``epoch``.
* ``plan_fp`` — a short fingerprint of the most recently
  built/dispatched plan (FFT plan schedule or reshard route), so a hop
  record names the compiled program family it belonged to.  Omitted
  until any plan exists.

``(step_idx, epoch)`` is the join key the timeline merger
(:mod:`~pencilarrays_tpu.obs.timeline`) and the straggler detector
(:mod:`~pencilarrays_tpu.obs.straggler`) group by; ``hop`` labels
disambiguate within a step.

Everything here is deliberately communication-free and cheap enough to
run with observability *disabled* (two module ints and a string): the
step counter must advance identically whether or not a given rank had
obs armed at the time, or late-armed ranks would journal misaligned
indices.
"""

from __future__ import annotations

import hashlib
import json
import threading
from contextlib import contextmanager
from typing import Optional

__all__ = [
    "current_step",
    "next_step",
    "step",
    "current_plan",
    "set_plan",
    "plan_fingerprint",
    "stamp",
]

_lock = threading.Lock()
_step = 0
_plan_fp: Optional[str] = None


def current_step() -> int:
    """The step index records are being stamped with (0 = before any
    step boundary)."""
    return _step


def next_step(label: Optional[str] = None) -> int:
    """Advance the monotonic step index (one collective step boundary)
    and return the new value.  ``guarded_step`` calls this at entry;
    application loops that do not use the guard call it per iteration."""
    global _step
    with _lock:
        _step += 1
        return _step


@contextmanager
def step(label: Optional[str] = None):
    """Scope one application step: advances the index on entry, yields
    it.  (There is nothing to restore on exit — the index is monotonic;
    the context-manager shape just marks the step's extent in code.)"""
    yield next_step(label)


def current_plan() -> Optional[str]:
    """Fingerprint of the most recently built/dispatched plan, if any."""
    return _plan_fp


def set_plan(fingerprint: Optional[str]) -> None:
    """Install the plan fingerprint subsequent records are stamped with
    (``None`` clears it).  The planners call this — ``PencilFFTPlan``
    on build/dispatch, the reshard route executor per routed chain."""
    global _plan_fp
    _plan_fp = fingerprint


def plan_fingerprint(summary) -> str:
    """Short stable fingerprint (12 hex chars of sha256) of a plan
    summary dict — the same digest family ``guard.note_plan`` uses, so
    a journal's ``plan_fp`` prefixes the crash bundle's
    ``schedule_sha256``."""
    try:
        blob = json.dumps(summary, sort_keys=True, default=str)
    except Exception:
        blob = repr(summary)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _epoch_current() -> int:
    """The recovery epoch, without importing anything heavy (the
    cluster package's __init__ pulls only stdlib + its errors)."""
    try:
        from ..cluster import epoch

        return epoch.current()
    except Exception:   # pragma: no cover - never break the recorder
        return 0


def stamp() -> dict:
    """The correlation fields :func:`~pencilarrays_tpu.obs.events.
    record_event` folds into every record."""
    out = {"step_idx": _step, "epoch": _epoch_current()}
    if _plan_fp is not None:
        out["plan_fp"] = _plan_fp
    return out


def _reset_for_tests() -> None:
    global _step, _plan_fp
    with _lock:
        _step = 0
        _plan_fp = None
