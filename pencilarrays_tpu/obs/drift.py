"""Cost-model drift tracker: predicted bytes vs measured time, per hop.

The HLO byte model (``transpose_cost`` / ``utils/hlo.py`` — the
"bytes on the wire" accounting of arXiv:1804.09536 and the
redistribution pricing of arXiv:2112.01075) is test-pinned EQUAL to the
compiled HLO, so the *bytes* are trustworthy.  What the model cannot
promise is that bytes keep translating to the same *time*: a compiler
upgrade reschedules a collective, a topology change adds a hop, a noisy
neighbor eats ICI — and the Auto planner's decisions silently go stale.
This tracker is the reconciliation loop: every hop's predicted byte
cost is paired with measured seconds, an effective bandwidth is fitted
per source class over its hops, and each hop's drift ratio

    ``drift = measured_s / (predicted_bytes / fitted_bandwidth)``

says how far that hop sits from the model (1.0 = the byte model
explains the timing; a hop drifting to 2.0 takes twice the time its
bytes predict — re-measure the Auto choice).

Sample sources, best first (the report keeps one per hop):

* ``benchtime`` — the hardened K-differenced device protocol
  (``utils/benchtime.py``), via :func:`measure_transpose` or the
  ``--obs`` bench arm;
* ``auto_measure`` — ``Auto(mode="measure")`` candidate timings (same
  protocol, timed as forward+back pairs and halved);
* ``dispatch`` — per-dispatch host wall time from instrumented
  ``transpose`` calls: free and always available, but a LOWER bound on
  wire time on real accelerators (dispatch returns at enqueue), so
  dispatch samples are fitted and reconciled strictly among themselves
  and never pollute the device-protocol fit.

Thread-safe; per-hop state is (count, total, min, last) so the report
uses BenchmarkTools-style minima, matching the bench protocol.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["DriftTracker", "drift_tracker", "record_hop_sample",
           "drift_report", "measure_transpose"]

_SOURCE_RANK = {"benchtime": 0, "auto_measure": 1, "dispatch": 2}


class DriftTracker:
    """Accumulate (hop, source) timing samples against predicted bytes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: Dict[tuple, dict] = {}
        self._version = 0

    def version(self) -> int:
        """Monotonic TRUSTED-sample-state counter (bumped by reset and
        by every non-``dispatch`` record): consumers that cache
        decisions derived from the report — the reshard route planner's
        edge weights — key on it so fresh device-protocol samples
        invalidate stale plans.  Per-dispatch samples deliberately do
        NOT bump it: the planner ignores them, and with obs armed every
        eager hop records one — bumping would churn the plan cache on
        every transpose.  0 means no trusted sample has ever landed."""
        with self._lock:
            return self._version

    def record(self, hop: str, predicted_bytes: int, measured_s: float,
               source: str = "dispatch") -> None:
        if source not in _SOURCE_RANK:
            raise ValueError(
                f"unknown drift source {source!r}; expected one of "
                f"{sorted(_SOURCE_RANK)}")
        measured_s = float(measured_s)
        key = (str(hop), source)
        with self._lock:
            if source != "dispatch":
                self._version += 1
            s = self._samples.get(key)
            if s is None:
                self._samples[key] = {
                    "hop": str(hop), "source": source,
                    "predicted_bytes": int(predicted_bytes),
                    "count": 1, "total_s": measured_s,
                    "min_s": measured_s, "last_s": measured_s,
                }
            else:
                s["predicted_bytes"] = int(predicted_bytes)
                s["count"] += 1
                s["total_s"] += measured_s
                s["min_s"] = min(s["min_s"], measured_s)
                s["last_s"] = measured_s

    def reset(self) -> None:
        with self._lock:
            self._version += 1
            self._samples.clear()

    @staticmethod
    def _fit(reps) -> Optional[float]:
        tot_bytes = sum(s["predicted_bytes"] for s in reps)
        tot_s = sum(s["min_s"] for s in reps)
        return (tot_bytes / tot_s) if tot_s > 0 and tot_bytes > 0 else None

    def report(self) -> dict:
        """Per-hop predicted-vs-measured reconciliation.

        For each hop the best-ranked source wins.  Bandwidths are fitted
        PER SOURCE CLASS (total predicted bytes / total min seconds,
        byte-weighted): ``fitted_bytes_per_s`` over the trustworthy
        device-protocol sources (benchtime/auto_measure) and
        ``dispatch_fitted_bytes_per_s`` over the dispatch proxies — the
        two must never mix, because an async dispatch time is a LOWER
        bound on wire time and one enqueue-timed hop in a shared fit
        would invert every other hop's verdict.  Each hop's ``drift`` is
        its measured min over the time its own class's fit predicts for
        its bytes.  Hops with zero predicted bytes (local permutes) are
        reported with ``drift: None`` — nothing on the wire to
        reconcile."""
        with self._lock:
            samples = [dict(s) for s in self._samples.values()]
        best: Dict[str, dict] = {}
        for s in samples:
            cur = best.get(s["hop"])
            if cur is None or (_SOURCE_RANK[s["source"]]
                               < _SOURCE_RANK[cur["source"]]):
                best[s["hop"]] = s
        wired = [s for s in best.values()
                 if s["predicted_bytes"] > 0 and s["min_s"] > 0]
        bw_trusted = self._fit([s for s in wired
                                if s["source"] != "dispatch"])
        bw_dispatch = self._fit([s for s in wired
                                 if s["source"] == "dispatch"])
        hops = {}
        for hop, s in sorted(best.items()):
            entry = {
                "source": s["source"],
                "predicted_bytes": s["predicted_bytes"],
                "measured_s": s["min_s"],
                "last_s": s["last_s"],
                # cumulative sum: lets the mesh aggregator window a
                # rate ((Δtotal)/(Δcount) between folds) so late-onset
                # degradation is visible despite the all-time min
                "total_s": s["total_s"],
                "count": s["count"],
                "bytes_per_s": (s["predicted_bytes"] / s["min_s"]
                                if s["min_s"] > 0 and s["predicted_bytes"]
                                else None),
                "drift": None,
            }
            bw = bw_dispatch if s["source"] == "dispatch" else bw_trusted
            if bw and s["predicted_bytes"] > 0 and s["min_s"] > 0:
                entry["drift"] = s["min_s"] / (s["predicted_bytes"] / bw)
            hops[hop] = entry
        return {"fitted_bytes_per_s": bw_trusted,
                "dispatch_fitted_bytes_per_s": bw_dispatch,
                "hops": hops}


drift_tracker = DriftTracker()


def record_hop_sample(hop: str, predicted_bytes: int, measured_s: float,
                      source: str = "dispatch") -> None:
    """Feed one sample into the process-wide tracker and journal it
    (non-``dispatch`` sources only — per-dispatch samples would flood
    the journal; they are visible through the metrics snapshot)."""
    drift_tracker.record(hop, predicted_bytes, measured_s, source)
    if source != "dispatch":
        from .events import record_event

        record_event("drift.sample", hop=hop,
                     predicted_bytes=int(predicted_bytes),
                     measured_s=float(measured_s), source=source)


def drift_report() -> dict:
    return drift_tracker.report()


def measure_transpose(src, dest, *, method=None, k0: int = 1, k1: int = 8,
                      repeats: int = 3) -> dict:
    """Measure one hop with the hardened benchtime protocol and feed the
    tracker (source ``benchtime``) — the explicit reconciliation entry
    point the ``--obs`` bench arm and notebooks use.

    ``src`` is a PencilArray, ``dest`` the target Pencil; the timed body
    is a forward+back pair (shape-preserving, as the K-differenced
    in-jit protocol requires), halved to per-hop seconds.
    """
    from ..parallel import transpositions as tr
    from ..utils.benchtime import device_seconds_per_iter

    pin = src.pencil
    m = tr.resolve_method(pin, dest, src.extra_dims, src.dtype,
                          method if method is not None else tr.Auto())
    R = tr.assert_compatible(pin, dest)
    from ..ops.pallas_kernels import pallas_enabled

    fwd = tr._compiled_transpose(pin, dest, R, src.ndims_extra, m, False,
                                 pallas_enabled())
    bwd = tr._compiled_transpose(dest, pin, R, src.ndims_extra, m, False,
                                 pallas_enabled())
    t_pair = device_seconds_per_iter(lambda d: bwd(fwd(d)), src.data,
                                     k0=k0, k1=k1, repeats=repeats)
    cost = tr.transpose_cost(pin, dest, src.extra_dims, src.dtype, m) \
        if R is not None else {}
    nbytes = sum(v["bytes"] for v in cost.values())
    # dtype must ride the label: the dispatch tap keys the same hop with
    # src.dtype, and source ranking only upgrades EQUAL keys
    hop = tr._hop_label(pin, dest, m, src.dtype)
    record_hop_sample(hop, nbytes, t_pair / 2.0, source="benchtime")
    return {"hop": hop, "predicted_bytes": nbytes, "seconds": t_pair / 2.0}
