"""The event flight recorder: an append-only JSONL journal.

PRs 1-2 gave the runtime rich behaviors — pipelined hops, checkpoint
commits, retries, deterministic fault injection — that were invisible at
runtime and *gone* after a crash.  The flight recorder is the durable
timeline: every record is one JSON line carrying the run id, process
index, wall + monotonic timestamps and a per-process sequence number, so
a post-mortem (e.g. after the SIGKILL-mid-write drill in
``tests/test_multiprocess.py``) can reconstruct exactly what the process
was doing when it died.

Durability discipline (shared with ``resilience/fsutil.py``):

* the journal fd is opened ``O_APPEND`` — concurrent writers (threads,
  or two processes that race before ``jax.distributed`` assigns indices)
  interleave whole lines, never tear them;
* every record is flushed to the OS immediately, so a SIGKILL cannot
  lose it (page cache survives process death);
* *critical* records (checkpoint commits, faults, retries, run
  boundaries) are additionally ``fsync``'d so even an OS crash keeps
  the commit timeline; ``PENCILARRAYS_TPU_OBS_FSYNC`` =
  ``always | critical | never`` tunes this (default ``critical``);
* the journal directory itself is fsync'd at creation
  (:func:`~pencilarrays_tpu.resilience.fsutil.fsync_dir`).

Enablement: ``PENCILARRAYS_TPU_OBS`` unset/empty/``0`` = off (the
default; :func:`record_event` is then one cached env probe).  ``1`` /
``on`` / ``true`` = on, journal under ``PENCILARRAYS_TPU_OBS_DIR``
(default ``./pa_obs``).  Any other value is itself the journal
directory.  The variable is re-read whenever it changes — a worker can
arm observability after import, exactly like the fault-injection env
(``resilience/faults.py``).

Rotation: ``PENCILARRAYS_TPU_OBS_MAX_MB`` caps the journal size — when
crossed (always at a record boundary), the active file rotates to
``journal.r<p>.<k>.jsonl`` and a fresh ``journal.r<p>.jsonl`` opens
with the same O_APPEND discipline; the per-process ``seq`` keeps
counting across segments and every reader consumes rotated segments
transparently.  Unset = never rotate (the pre-PR-7 behavior: a
long-running serving job should set the cap).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import uuid
from contextlib import contextmanager
from typing import List, Optional

from ..resilience.fsutil import fsync_dir

__all__ = [
    "ENV_VAR",
    "DIR_VAR",
    "FSYNC_VAR",
    "SCHEMA_VERSION",
    "enabled",
    "enable",
    "disable",
    "journal_dir",
    "run_id",
    "record_event",
    "read_journal",
]

ENV_VAR = "PENCILARRAYS_TPU_OBS"
DIR_VAR = "PENCILARRAYS_TPU_OBS_DIR"
FSYNC_VAR = "PENCILARRAYS_TPU_OBS_FSYNC"
MAX_MB_VAR = "PENCILARRAYS_TPU_OBS_MAX_MB"
DEFAULT_DIR = "pa_obs"
# v2 (PR 7): every record additionally carries the correlation keys
# ``step_idx`` + ``epoch`` (and ``plan_fp`` once a plan exists) — the
# fields cross-rank timeline joins group by (obs/correlate.py).  v1
# journals remain lint-clean: the requirement is versioned.
# v3 (PR 9): ``plan.build`` additionally carries the batched-throughput
# fields ``extra_dims`` (the plan's batch) and ``decomposition`` (the
# slab/pencil verdict) — see obs/schema.py V3_EVENT_FIELDS.  v1/v2
# journals again stay lint-clean.
# v5: ``serve.dispatch`` additionally carries the DAG-engine fields
# ``lane`` (the priority lane the batch was submitted on) and ``chain``
# (the dependency chain it orders within) — see obs/schema.py
# V5_EVENT_FIELDS.  Earlier journals again stay lint-clean.
# v6 (PR 18): the request-flow plane — every ``fleet.route`` /
# ``serve.request`` / ``serve.coalesce`` / ``serve.dispatch`` /
# ``serve.complete`` record additionally carries the request trace id
# ``trace`` (obs/requestflow.py; a coalesced batch's records also
# journal the B-way ``traces`` fan-in), the key ``pa-obs request``
# joins one ticket's causal timeline across router + mesh journals
# by.  v1-v5 journals again stay lint-clean.
# v7 (PR 19): the precision-downgrade rung — every ``serve.precision``
# record (a sheddable request served on a cheaper wire format instead
# of shed) must carry the full contract it was degraded under: the
# wire it moved from/to, the CALIBRATED error envelope promised
# (``serve/precision.py``, ``BENCH_WIRE.json``) and the tenant's
# declared ``max_rel_l2`` budget it fit inside — see obs/schema.py
# V7_EVENT_FIELDS.  v1-v6 journals again stay lint-clean.
# v8 (PR 20): the partition-tolerant control plane — three new
# fsync-critical event types: ``cluster.quorum`` (one record per
# quorum-gate evaluation, carrying the voter set / threshold /
# denominator arithmetic), ``cluster.fence`` (a zombie write rejected
# by the namespace fence, naming the stale token and the fence that
# beat it) and ``fleet.wal`` (a router WAL recover/replay summary:
# re-parked vs already-resolved tickets) — see obs/schema.py
# V8_EVENT_FIELDS.  v1-v7 journals again stay lint-clean.
SCHEMA_VERSION = 8

# events whose loss would blind a post-mortem: fsync'd under the default
# "critical" policy.  High-rate events (per-hop dispatch) only flush.
CRITICAL_EVENTS = frozenset({
    "run.start", "ckpt.save", "ckpt.commit", "ckpt.restore", "ckpt.verify",
    "fault", "retry", "dist.init",
    "guard.sdc", "guard.hang", "guard.recover", "guard.bundle",
    # mesh recovery coordination: each of these gates (or attributes) a
    # recovery decision, and the writer may be about to die — the
    # verdict/lease/epoch timeline is exactly what the post-mortem
    # aligns ranks by (lease events are journaled only on state
    # CHANGES — acquire/expiry — never per renewal, and routine `ok`
    # verdicts opt OUT per record via record_event's _fsync override,
    # so criticality never rides the healthy per-step path)
    "guard.epoch", "cluster.lease", "cluster.verdict",
    # elastic reformation: every stage record gates (or attributes) a
    # membership decision, and mid-reform is exactly when writers die
    "cluster.reform", "cluster.member",
    # the partition-tolerance plane (PR 20): a quorum verdict gates
    # whether a whole side of a partition lives or exits, a rejected
    # zombie write is the proof the fence worked, and a WAL replay
    # summary is the restarted router's reconciliation record — each
    # is written exactly when its writer is most likely to die next
    "cluster.quorum", "cluster.fence", "fleet.wal",
    # a flagged straggler gates a scheduling/ops decision and the
    # flagging rank may be about to act on it
    "cluster.straggler",
    # the overload-survival plane: an SLO breach, a shedding-gate
    # transition and a scale decision each gate client-visible
    # behavior (failures, capacity moves) — the record must survive
    # the crash that often follows the overload that caused it
    "serve.slo_violation", "serve.pressure", "serve.scale",
    # an error-budget burn alert gates paging/shedding policy, and it
    # fires exactly when the process is most likely to die of the
    # overload that tripped it — the record must outlive the crash
    "serve.burn_alert",
    # a precision downgrade changes the answer a client receives — the
    # record of what envelope it was served under must survive the
    # overload that caused it (same plane as shed/burn above)
    "serve.precision",
    # fleet federation: a whole-mesh failover gates every re-bound
    # ticket, and a supervisor scale action moves real capacity —
    # both must survive the crash cascade that usually surrounds
    # them.  fleet.lease expiry (not routine acquire) and fleet.scale
    # dry-run signals opt in/out per record via the _fsync override;
    # fleet.route is high-rate and only flushes.
    "fleet.failover",
})

_lock = threading.Lock()
_override: Optional[bool] = None     # programmatic enable()/disable()
_override_dir: Optional[str] = None
_run_id: Optional[str] = None
_file = None
_file_dir: Optional[str] = None
_file_proc: Optional[int] = None
_seq = 0


def enabled() -> bool:
    """THE gate every instrumented call site probes first.  One branch +
    one cached snapshot probe on the disabled path — payloads are never
    built unless this returns True.  The env value rides the engine's
    shared :class:`~pencilarrays_tpu.engine.config.RuntimeConfig`
    snapshot, which re-resolves on change (workers arm late, like
    faults)."""
    if _override is not None:
        return _override
    from ..engine import config as _rtc

    return _rtc.current().obs_on


def enable(directory: Optional[str] = None) -> None:
    """Programmatic enable (overrides the environment until
    :func:`disable`); ``directory`` overrides the journal location.
    Starts a fresh observability run: a new run id, and per-run dedup
    state (e.g. the planner's one-verdict-per-config journal filter)
    starts over."""
    global _override, _override_dir, _run_id
    with _lock:
        _close_locked()
        _override = True
        _override_dir = os.fspath(directory) if directory else None
        _run_id = None  # a fresh run id per enable (docstring contract)


def disable() -> None:
    """Programmatic disable: closes the journal and wins over the
    environment until the next :func:`enable`."""
    global _override, _override_dir
    with _lock:
        _close_locked()
        _override = False
        _override_dir = None


def _reset_for_tests() -> None:
    """Full reset: drop overrides AND the shared config snapshot (tests
    toggle the env between cases; production code never needs this)."""
    global _override, _override_dir, _run_id, _seq
    with _lock:
        _close_locked()
        _override = None
        _override_dir = None
        _run_id = None
        _seq = 0
    from ..engine import config as _rtc
    from . import correlate, requestflow

    _rtc._reset_for_tests()
    correlate._reset_for_tests()
    requestflow._reset_for_tests()


def journal_dir() -> str:
    """Resolved journal directory for the current configuration (knob
    parsing lives in ``engine/config.py``: a non-``1``/``on`` gate
    value is itself the directory)."""
    if _override_dir:
        return _override_dir
    from ..engine import config as _rtc

    cfg = _rtc.current()
    if cfg.obs_env not in ("", "0", "1", "on", "true", "off", "false"):
        return cfg.obs_env
    return cfg.obs_dir_env


def run_id() -> str:
    """Stable id of this process's observability run (new per enable)."""
    global _run_id
    if _run_id is None:
        _run_id = f"{os.getpid():x}-{uuid.uuid4().hex[:8]}"
    return _run_id


def _process_index() -> int:
    """Best-effort process index; never initializes anything.

    Deliberately does NOT call ``jax.process_index()``: that builds the
    local XLA backend as a side effect, and an event recorded before
    ``jax.distributed.initialize`` (e.g. ``dist.init connecting``) would
    then make the real initialize raise 'must be called before any JAX
    computations'.  The cluster layer's rank override wins first — a
    FileKV drill runs N mesh ranks that are all jax process 0, and
    their journals must neither collide nor mis-attribute — then the
    coordinator-assigned index from jax's distributed global state;
    absent both (single-process or pre-init) means 0, and the journal
    filename re-resolves on change."""
    try:
        from ..cluster import rank

        return rank()
    except Exception:
        return 0


def _close_locked() -> None:
    global _file, _file_dir, _file_proc
    if _file is not None:
        try:
            _file.close()
        except OSError:
            pass
    _file = None
    _file_dir = None
    _file_proc = None


def _open_locked(proc: Optional[int] = None):
    """(Re)open the journal for the resolved directory; emits the
    ``run.start`` boundary record on a fresh open.  The filename is
    re-resolved when the process index CHANGES — events recorded before
    ``jax.distributed`` connects (e.g. ``dist.init connecting``) land in
    ``journal.r0.jsonl`` on every process, but the first post-connect
    record moves each process to its own ``journal.r<p>.jsonl`` (shared
    filesystems make cross-host O_APPEND to one file unreliable)."""
    global _file, _file_dir, _file_proc
    d = journal_dir()
    if proc is None:
        proc = _process_index()
    if _file is not None and _file_dir == d and _file_proc == proc:
        return _file
    _close_locked()
    os.makedirs(d, exist_ok=True)
    fsync_dir(d)
    path = os.path.join(d, f"journal.r{proc}.jsonl")
    # O_APPEND: whole-line atomicity for concurrent small appends
    _file = open(path, "a", buffering=1)
    _file_dir = d
    _file_proc = proc
    _write_locked("run.start", {
        "pid": os.getpid(),
        "argv": list(sys.argv[:4]),
    }, proc=proc)
    return _file


def _atexit_flush() -> None:
    """Normal-exit epilogue: publish the metrics snapshot next to the
    journal (a SIGKILL skips this by design — the journal itself is the
    crash-safe artifact).  Registered at import so metrics-only runs
    (counters/gauges bumped, no journal event ever recorded) still get
    their snapshot; a no-op while observability is off."""
    try:
        if enabled():
            from .metrics import write_snapshot

            record_event("run.stop")
            write_snapshot()
    except Exception:
        pass


atexit.register(_atexit_flush)


@contextmanager
def _forced(mode: str, directory: Optional[str] = None):
    """Temporarily force the gate — ``"on"`` (journal to ``directory``)
    or ``"unset"`` (override cleared AND env var removed: the true
    shipped-default path) — restoring EVERY piece of gate state after:
    override, env var, run id, and the journal fd (closed on exit, so a
    caller deleting ``directory`` afterwards leaks nothing).  The obs
    overhead bench arm uses this; keeping the surgery here keeps it
    next to the state it touches."""
    global _override, _override_dir, _run_id
    with _lock:
        saved = (_override, _override_dir, _run_id,
                 os.environ.get(ENV_VAR))
        _close_locked()
        if mode == "on":
            _override = True
            _override_dir = os.fspath(directory) if directory else None
        elif mode == "unset":
            _override = None
            _override_dir = None
            os.environ.pop(ENV_VAR, None)
        else:
            raise ValueError(f"unknown forced mode {mode!r}")
    try:
        yield
    finally:
        with _lock:
            _close_locked()
            _override, _override_dir, _run_id = saved[0], saved[1], saved[2]
            if saved[3] is None:
                os.environ.pop(ENV_VAR, None)
            else:
                os.environ[ENV_VAR] = saved[3]


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:
        import numpy as np

        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
    except Exception:
        pass
    return str(v)


def _fsync_policy() -> str:
    from ..engine import config as _rtc

    return _rtc.current().obs_fsync      # PENCILARRAYS_TPU_OBS_FSYNC


def _max_bytes() -> Optional[int]:
    """Rotation cap from ``PENCILARRAYS_TPU_OBS_MAX_MB`` (None = never
    rotate, the pre-PR-7 behavior; parsing lives in
    ``engine/config.py``)."""
    from ..engine import config as _rtc

    return _rtc.current().obs_max_bytes


def _rotate_locked() -> None:
    """Rotate the active journal to ``journal.r<p>.<k>.jsonl`` and
    reopen a fresh ``journal.r<p>.jsonl`` — always at a record boundary
    (called after a whole line landed), preserving the O_APPEND
    discipline on the new fd.  The per-process ``seq`` keeps counting
    across segments, so readers order a rank's records without caring
    which segment they came from.  No ``run.start`` is emitted: a
    rotation is mid-run, not a new run."""
    global _file
    d, proc = _file_dir, _file_proc
    base = os.path.join(d, f"journal.r{proc}.jsonl")
    try:
        _file.close()
    except OSError:
        pass
    _file = None
    k = 1
    while os.path.exists(os.path.join(d, f"journal.r{proc}.{k}.jsonl")):
        k += 1
    try:
        os.replace(base, os.path.join(d, f"journal.r{proc}.{k}.jsonl"))
        fsync_dir(d)
    except OSError:
        pass    # a failed rename just keeps appending to the old file
    _file = open(base, "a", buffering=1)


def _write_locked(ev: str, fields: dict, proc: Optional[int] = None,
                  fsync: Optional[bool] = None) -> None:
    global _seq
    from . import correlate, requestflow

    _seq += 1
    rec = {"v": SCHEMA_VERSION, "ev": ev, "run": run_id(),
           "proc": _process_index() if proc is None else proc,
           "seq": _seq,
           "t_wall": time.time(), "t_mono": time.monotonic()}
    for k, v in fields.items():
        if k not in rec:
            rec[k] = _json_safe(v)
    # correlation keys (step_idx / epoch / plan_fp) fill in AFTER the
    # payload: every record joins the cross-rank timeline, but an
    # emitter that passes one explicitly keeps its value — a
    # cluster.verdict journals the verdict's OWN epoch, not whatever
    # the global counter reads at write time (a concurrent advance
    # between payload construction and this lock must not rewrite it)
    for k, v in correlate.stamp().items():
        rec.setdefault(k, v)
    # the ambient request trace (obs/requestflow.py) folds in by the
    # same discipline: the serve/fleet emitters pass trace= explicitly
    # (their records are written from pump/engine threads with no
    # ambient context), and that explicit value always wins
    for k, v in requestflow.stamp().items():
        rec.setdefault(k, v)
    _file.write(json.dumps(rec, separators=(",", ":")) + "\n")
    _file.flush()
    policy = _fsync_policy()
    critical = ev in CRITICAL_EVENTS if fsync is None else fsync
    if policy == "always" or (policy == "critical" and critical):
        try:
            os.fsync(_file.fileno())
        except OSError:
            pass
    cap = _max_bytes()
    if cap is not None:
        try:
            if _file.tell() >= cap:
                _rotate_locked()
        except (OSError, ValueError):
            pass


def record_event(ev: str, _fsync: Optional[bool] = None, **fields) -> bool:
    """Append one record to the journal.  Returns False (doing NOTHING,
    allocating nothing beyond the kwargs dict) when observability is
    disabled — the contract that keeps instrumented hot paths free.

    ``_fsync`` overrides the event type's CRITICAL_EVENTS membership
    for THIS record (under the default ``critical`` policy) — for event
    types whose criticality depends on the payload, e.g. a
    ``cluster.verdict`` gates recovery only when its action is not
    ``ok``, and a routine ok verdict fires once per step boundary."""
    if not enabled():
        return False
    try:
        proc = _process_index()  # once per event, outside the lock
        with _lock:
            if not enabled():
                return False  # lost a race with disable(): a stale
                # thread must not resurrect the journal while off
            _open_locked(proc)
            _write_locked(ev, fields, proc=proc, fsync=_fsync)
        return True
    except OSError:
        return False  # a full/readonly disk must never take down the job


def read_journal(directory: Optional[str] = None) -> List[dict]:
    """Parse every ``journal.r*.jsonl`` under ``directory`` (default:
    the active journal dir) into one timeline ordered by wall time then
    per-process sequence.  Rotated segments (``journal.r<p>.<k>.jsonl``,
    see ``PENCILARRAYS_TPU_OBS_MAX_MB``) match the same glob and are
    read transparently.  Unparseable lines (a torn final line from a
    crash without O_APPEND atomicity, foreign garbage) are skipped — the
    reader is a forensic tool and must not die on wreckage.  For a
    *causally* merged cross-rank view with skew correction and lint
    warnings, use :func:`~pencilarrays_tpu.obs.timeline.merge_journals`
    (or ``python -m pencilarrays_tpu.obs merge``)."""
    import glob

    d = directory or journal_dir()
    events = []
    for path in sorted(glob.glob(os.path.join(d, "journal.r*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(e, dict):
                    events.append(e)
    events.sort(key=lambda e: (e.get("t_wall", 0.0), e.get("proc", 0),
                               e.get("seq", 0)))
    return events
