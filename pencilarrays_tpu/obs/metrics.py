"""Metrics registry: counters, gauges, histograms — one process-wide sink.

Thread-safe (instrument mutations take a per-registry lock: the checksum
thread pool from the resilience subsystem and any user thread may bump
the same counter), cheap when observability is disabled (call sites guard
with ``obs.enabled()`` and never reach here), and exportable two ways:

* :func:`snapshot` — a JSON-serializable dict, atomically published via
  :func:`~pencilarrays_tpu.resilience.fsutil.atomic_write_json` (crash
  leaves the previous snapshot, never a torn file);
* :func:`to_prometheus` — the Prometheus *textfile-collector* format
  (``node_exporter --collector.textfile``), the zero-dependency way to
  ship process metrics into an existing scrape pipeline.

Metric names are dotted (``transpose.dispatch_seconds``); labels are
keyword pairs folded into the registry key, exported as Prometheus
labels.  The snapshot additionally carries the cost-model drift report
(:mod:`~pencilarrays_tpu.obs.drift`) and the most recent benchtime
spread (``utils/benchtime.py``) so every exported artifact states its
own noise floor.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "write_snapshot",
    "to_prometheus",
    "write_prometheus",
]


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic count (events, bytes, retries)."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value: Optional[float] = None
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming distribution: count/sum/min/max/last plus log2 buckets.

    Buckets are powers of two over ``[2**lo, 2**hi]`` seconds-ish scales
    (wide enough for nanosecond dispatches and minute-long saves), fixed
    so per-observation cost is one ``frexp`` + one increment — no
    allocation on the hot path.
    """

    __slots__ = ("name", "labels", "count", "total", "vmin", "vmax", "last",
                 "buckets", "_lock")

    LO, HI = -20, 12  # 2**-20 s ~ 1 us .. 2**12 s ~ 68 min

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.last: Optional[float] = None
        self.buckets = [0] * (self.HI - self.LO + 2)
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        if v > 0:
            e = math.frexp(v)[1]  # v in [2**(e-1), 2**e)
            i = min(max(e - self.LO, 0), len(self.buckets) - 1)
        else:
            i = 0
        with self._lock:
            self.count += 1
            self.total += v
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)
            self.last = v
            self.buckets[i] += 1

    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None


class MetricsRegistry:
    """Get-or-create instruments keyed on (kind, name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[tuple, object] = {}

    def _get(self, cls, name: str, labels: Dict[str, str]):
        key = (cls.__name__, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, dict(labels), self._lock)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- exporters ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serializable dump of every instrument plus the drift
        report and the latest benchtime spread (noise floor)."""
        from ..utils.benchtime import last_spread
        from .drift import drift_report
        from .events import run_id

        with self._lock:
            metrics = list(self._metrics.values())
        out = {"format": "pencilarrays-tpu-metrics", "version": 1,
               "run": run_id(), "t_wall": time.time(),
               "counters": {}, "gauges": {}, "histograms": {}}
        for m in metrics:
            key = m.name if not m.labels else (
                m.name + "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(m.labels.items())) + "}")
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = {
                    "count": m.count, "total": m.total, "mean": m.mean(),
                    "min": None if m.count == 0 else m.vmin,
                    "max": None if m.count == 0 else m.vmax,
                    "last": m.last,
                    # sparse distribution: upper bound 2**e -> count
                    "buckets_le_pow2": {
                        str(i + m.LO): c
                        for i, c in enumerate(m.buckets) if c},
                }
        out["benchtime"] = last_spread()
        out["drift"] = drift_report()
        return out

    def to_prometheus(self, prefix: str = "pa") -> str:
        """Prometheus textfile-collector exposition of the registry."""
        def pname(name: str) -> str:
            return prefix + "_" + name.replace(".", "_").replace("-", "_")

        def plabels(labels: Dict[str, str]) -> str:
            if not labels:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            return "{" + inner + "}"

        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        seen_types = set()
        for m in sorted(metrics, key=lambda m: m.name):
            n, ls = pname(m.name), plabels(m.labels)
            if isinstance(m, Counter):
                if n not in seen_types:
                    lines.append(f"# TYPE {n}_total counter")
                    seen_types.add(n)
                lines.append(f"{n}_total{ls} {m.value:g}")
            elif isinstance(m, Gauge):
                if m.value is None:
                    continue
                if n not in seen_types:
                    lines.append(f"# TYPE {n} gauge")
                    seen_types.add(n)
                lines.append(f"{n}{ls} {m.value:g}")
            else:
                if n not in seen_types:
                    lines.append(f"# TYPE {n} summary")
                    seen_types.add(n)
                lines.append(f"{n}_count{ls} {m.count}")
                lines.append(f"{n}_sum{ls} {m.total:g}")
        return "\n".join(lines) + ("\n" if lines else "")


# the process-wide registry (one sink, like the reference's shared timer)
registry = MetricsRegistry()


def counter(name: str, **labels) -> Counter:
    return registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return registry.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return registry.histogram(name, **labels)


def snapshot() -> dict:
    return registry.snapshot()


def write_snapshot(path: Optional[str] = None) -> Optional[str]:
    """Atomically publish the snapshot as JSON (default:
    ``<journal dir>/metrics.json``; no-op returning None when
    observability is disabled and no explicit path is given)."""
    import os

    from ..resilience.fsutil import atomic_write_json
    from .events import enabled, journal_dir

    if path is None:
        if not enabled():
            return None
        path = os.path.join(journal_dir(), "metrics.json")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_json(path, registry.snapshot())
    return path


def to_prometheus(prefix: str = "pa") -> str:
    return registry.to_prometheus(prefix)


def write_prometheus(path: str, prefix: str = "pa") -> str:
    """Atomically publish the textfile-collector exposition (atomic
    replace: node_exporter never scrapes a torn file)."""
    import os

    from ..resilience.fsutil import atomic_write_text

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_text(path, registry.to_prometheus(prefix))
    return path
