"""Metrics registry: counters, gauges, histograms — one process-wide sink.

Thread-safe (instrument mutations take a per-registry lock: the checksum
thread pool from the resilience subsystem and any user thread may bump
the same counter), cheap when observability is disabled (call sites guard
with ``obs.enabled()`` and never reach here), and exportable two ways:

* :func:`snapshot` — a JSON-serializable dict, atomically published via
  :func:`~pencilarrays_tpu.resilience.fsutil.atomic_write_json` (crash
  leaves the previous snapshot, never a torn file);
* :func:`to_prometheus` — the Prometheus *textfile-collector* format
  (``node_exporter --collector.textfile``), the zero-dependency way to
  ship process metrics into an existing scrape pipeline.

Metric names are dotted (``transpose.dispatch_seconds``); labels are
keyword pairs folded into the registry key, exported as Prometheus
labels.  The snapshot additionally carries the cost-model drift report
(:mod:`~pencilarrays_tpu.obs.drift`) and the most recent benchtime
spread (``utils/benchtime.py``) so every exported artifact states its
own noise floor.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "write_snapshot",
    "to_prometheus",
    "write_prometheus",
]


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# -- Prometheus exposition-format helpers -----------------------------------
# (shared by the per-process exporter and the mesh aggregator's
# rank-labeled textfile — obs/aggregate.py)


def _prom_name(name: str, prefix: str = "pa") -> str:
    """Metric/label-name sanitation: the exposition format allows only
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``; anything else becomes ``_`` so a
    dotted (or hostile) name can never break the line grammar."""
    import re

    out = re.sub(r"[^a-zA-Z0-9_]", "_", str(name))
    if prefix:
        out = prefix + "_" + out
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return out


def _prom_escape(value) -> str:
    """Label-VALUE escaping per the exposition format: backslash,
    double-quote and newline — a plan fingerprint containing ``"`` or
    ``\\n`` must not corrupt the textfile."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: Dict[str, str],
                 extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels or {})
    if extra:
        # the Prometheus honor_labels=false convention: an injected
        # label (the mesh fold's publisher `rank`) wins the name, and a
        # colliding series-own label survives as `exported_<name>` —
        # `cluster.stragglers{rank=1}` published by rank 0 must not
        # lose WHICH rank was the straggler
        for k in list(merged):
            if k in extra:
                merged[f"exported_{k}"] = merged.pop(k)
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{_prom_name(k, prefix="")}="{_prom_escape(v)}"'
        for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _drift_prometheus_lines(report: dict, prefix: str = "pa",
                            extra: Optional[Dict[str, str]] = None,
                            seen_types: Optional[set] = None) -> list:
    """The drift report as gauges: per-hop ``<prefix>_drift{hop=...}``
    plus the two per-source-class fitted bandwidths.  ``seen_types``
    dedups ``# TYPE`` headers across repeated calls (the mesh fold
    calls this once per rank — a second TYPE line for the same metric
    is an exposition-format error that fails the whole scrape)."""
    lines = []
    if seen_types is None:
        seen_types = set()

    def type_line(n: str) -> None:
        if n not in seen_types:
            seen_types.add(n)
            lines.append(f"# TYPE {n} gauge")

    hops = (report or {}).get("hops") or {}
    drifted = [(h, e) for h, e in sorted(hops.items())
               if isinstance(e.get("drift"), (int, float))]
    if drifted:
        n = _prom_name("drift", prefix)
        type_line(n)
        for hop, e in drifted:
            ls = _prom_labels({"hop": hop, "source": e.get("source", "?")},
                              extra)
            lines.append(f"{n}{ls} {e['drift']:g}")
    for key, cls in (("fitted_bytes_per_s", "device"),
                     ("dispatch_fitted_bytes_per_s", "dispatch")):
        bw = (report or {}).get(key)
        if isinstance(bw, (int, float)):
            n = _prom_name("drift_fitted_bytes_per_s", prefix)
            type_line(n)
            lines.append(
                f"{n}{_prom_labels({'class': cls}, extra)} {bw:g}")
    return lines


class Counter:
    """Monotonic count (events, bytes, retries)."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value: Optional[float] = None
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming distribution: count/sum/min/max/last plus log2 buckets.

    Buckets are powers of two over ``[2**lo, 2**hi]`` seconds-ish scales
    (wide enough for nanosecond dispatches and minute-long saves), fixed
    so per-observation cost is one ``frexp`` + one increment — no
    allocation on the hot path.
    """

    __slots__ = ("name", "labels", "count", "total", "vmin", "vmax", "last",
                 "buckets", "_lock")

    LO, HI = -20, 12  # 2**-20 s ~ 1 us .. 2**12 s ~ 68 min

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.last: Optional[float] = None
        self.buckets = [0] * (self.HI - self.LO + 2)
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        if v > 0:
            e = math.frexp(v)[1]  # v in [2**(e-1), 2**e)
            i = min(max(e - self.LO, 0), len(self.buckets) - 1)
        else:
            i = 0
        with self._lock:
            self.count += 1
            self.total += v
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)
            self.last = v
            self.buckets[i] += 1

    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None


class MetricsRegistry:
    """Get-or-create instruments keyed on (kind, name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[tuple, object] = {}

    def _get(self, cls, name: str, labels: Dict[str, str]):
        key = (cls.__name__, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, dict(labels), self._lock)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- exporters ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serializable dump of every instrument plus the drift
        report and the latest benchtime spread (noise floor).  Carries
        both the human-keyed maps (``name{k=v}`` display keys — the
        stable consumer format) and a structured ``series`` list with
        labels as dicts, which the mesh aggregator folds without
        re-parsing display keys (label VALUES may legally contain
        ``,``/``=``/``{`` — method reprs and plan fingerprints do)."""
        from ..utils.benchtime import last_spread
        from .drift import drift_report
        from .events import run_id

        with self._lock:
            metrics = list(self._metrics.values())
        out = {"format": "pencilarrays-tpu-metrics", "version": 1,
               "run": run_id(), "t_wall": time.time(),
               "counters": {}, "gauges": {}, "histograms": {},
               "series": []}
        for m in metrics:
            key = m.name if not m.labels else (
                m.name + "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(m.labels.items())) + "}")
            series = {"name": m.name,
                      "labels": {str(k): str(v)
                                 for k, v in sorted(m.labels.items())}}
            if isinstance(m, Counter):
                out["counters"][key] = m.value
                series.update(kind="counter", value=m.value)
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
                series.update(kind="gauge", value=m.value)
            else:
                h = {
                    "count": m.count, "total": m.total, "mean": m.mean(),
                    "min": None if m.count == 0 else m.vmin,
                    "max": None if m.count == 0 else m.vmax,
                    "last": m.last,
                    # sparse distribution: upper bound 2**e -> count
                    "buckets_le_pow2": {
                        str(i + m.LO): c
                        for i, c in enumerate(m.buckets) if c},
                }
                out["histograms"][key] = h
                series.update(kind="histogram", **h)
            out["series"].append(series)
        out["benchtime"] = last_spread()
        out["drift"] = drift_report()
        return out

    def to_prometheus(self, prefix: str = "pa") -> str:
        """Prometheus textfile-collector exposition of the registry,
        plus the cost-model drift report as gauges (previously
        JSON-snapshot-only, so a scrape pipeline never saw drift).
        Names and label values go through the exposition-format
        escaping below — a label value carrying ``"`` or a newline
        (plan fingerprints, free-form hop labels) must corrupt neither
        the line it is on nor the lines after it."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        seen_types = set()
        for m in sorted(metrics, key=lambda m: m.name):
            n, ls = _prom_name(m.name, prefix), _prom_labels(m.labels)
            if isinstance(m, Counter):
                if n not in seen_types:
                    lines.append(f"# TYPE {n}_total counter")
                    seen_types.add(n)
                lines.append(f"{n}_total{ls} {m.value:g}")
            elif isinstance(m, Gauge):
                if m.value is None:
                    continue
                if n not in seen_types:
                    lines.append(f"# TYPE {n} gauge")
                    seen_types.add(n)
                lines.append(f"{n}{ls} {m.value:g}")
            else:
                if n not in seen_types:
                    lines.append(f"# TYPE {n} summary")
                    seen_types.add(n)
                lines.append(f"{n}_count{ls} {m.count}")
                lines.append(f"{n}_sum{ls} {m.total:g}")
        from .drift import drift_report

        lines.extend(_drift_prometheus_lines(drift_report(), prefix))
        return "\n".join(lines) + ("\n" if lines else "")


# the process-wide registry (one sink, like the reference's shared timer)
registry = MetricsRegistry()


def counter(name: str, **labels) -> Counter:
    return registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return registry.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return registry.histogram(name, **labels)


def snapshot() -> dict:
    return registry.snapshot()


def write_snapshot(path: Optional[str] = None) -> Optional[str]:
    """Atomically publish the snapshot as JSON (default:
    ``<journal dir>/metrics.json``; no-op returning None when
    observability is disabled and no explicit path is given)."""
    import os

    from ..resilience.fsutil import atomic_write_json
    from .events import enabled, journal_dir

    if path is None:
        if not enabled():
            return None
        path = os.path.join(journal_dir(), "metrics.json")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_json(path, registry.snapshot())
    return path


def to_prometheus(prefix: str = "pa") -> str:
    return registry.to_prometheus(prefix)


def write_prometheus(path: str, prefix: str = "pa") -> str:
    """Atomically publish the textfile-collector exposition (atomic
    replace: node_exporter never scrapes a torn file)."""
    import os

    from ..resilience.fsutil import atomic_write_text

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_text(path, registry.to_prometheus(prefix))
    return path
