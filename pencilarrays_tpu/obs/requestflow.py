"""Request-scoped trace context — ONE ticket's causal story across the
fleet.

PR 7's correlation keys (``step_idx``, ``epoch``) join N ranks of ONE
mesh by construction: every rank executes the same collective step
sequence, so the counters align without communication.  The fleet
(PR 17) broke that symmetry — a request admitted at the
:class:`~pencilarrays_tpu.fleet.FleetRouter` crosses the KV wire into
whichever mesh placement chose (and, after a whole-mesh failover, a
*different* mesh), where it coalesces with strangers into a batch the
engine dispatches on some priority lane.  Three or more process
journals tell that story, and nothing joins them: per-mesh step
counters do not cross the fleet boundary.

The trace context fixes that, deliberately minimal:

* a **trace id** — 16 hex chars minted ONCE per request at an
  admission point (:func:`mint_trace`: fleet router submit, or serve
  submit when no inbound context is ambient).  The ``trace-ctx``
  lint (``analysis/lint.py``) keeps every other mint out of the tree:
  a cross-wire hop that minted fresh ids would shear the causal chain
  exactly where it matters most.
* carried in the ticket/entry/engine-task meta, across the
  ``fleet/wire.py`` request payload, and re-installed as the worker's
  thread-ambient context (:func:`installed`) while it re-submits the
  request into its local service.
* stamped into journal records two ways: the serve/fleet emitters
  pass ``trace=`` explicitly (their records are written from
  pump/engine threads where no ambient context exists), and
  :func:`stamp` folds the ambient context into everything else —
  ``fault``, ``guard.recover``, ``retry``, engine-task records — by
  the same ``setdefault`` discipline as
  :mod:`~pencilarrays_tpu.obs.correlate`, so an explicitly passed
  value always wins.

Coalescing keeps spans honest: a batch's single ``serve.coalesce`` /
``serve.dispatch`` pair journals the B-way fan-in (``traces`` — every
member's id; ``trace`` — the batch leader's), so ONE dispatch span is
shared by its member requests instead of being invisibly multiplied
B ways.

Reconstruction (:func:`reconstruct_request` and the ``pa-obs request``
/ ``pa-obs requests`` CLI) rides
:func:`~pencilarrays_tpu.obs.timeline.merge_journals`: skew-corrected
causal ordering across router + N mesh journals, and missing ranks /
torn tails / pre-v6 journals degrade to *warnings*, never exceptions —
the tool exists for post-mortems over wreckage.  The critical-path
decomposition names where the request's wall time went: wire vs
admission wait vs coalesce wait vs lane wait vs compute vs
failover/rebind.
"""

from __future__ import annotations

import threading
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "mint_trace",
    "current_trace",
    "installed",
    "stamp",
    "RequestTrace",
    "reconstruct_request",
    "list_requests",
    "render_request",
    "render_index",
]

# module-level lock: the ambient context itself is thread-local, but
# the reset hook crosses threads in tests (daemon-package discipline)
_lock = threading.Lock()
_tls = threading.local()


# ---------------------------------------------------------------------------
# the context: mint / install / stamp
# ---------------------------------------------------------------------------


def mint_trace() -> str:
    """Mint a fresh request trace id (16 hex chars).

    ONLY the two admission points call this — ``FleetRouter.submit``
    and ``PlanService.submit*`` (which first adopts any ambient
    inbound context) — enforced by the ``trace-ctx`` lint.  Everything
    downstream *propagates*; a second mint anywhere on the request
    path would break the cross-journal join."""
    return uuid.uuid4().hex[:16]


def current_trace() -> Optional[str]:
    """The thread's ambient inbound trace context (None = no request
    in flight on this thread)."""
    return getattr(_tls, "trace", None)


@contextmanager
def installed(trace: Optional[str]):
    """Install ``trace`` as this thread's ambient context for the
    duration — the cross-wire re-entry point: ``MeshWorker`` wraps
    each taken request so the local service *adopts* the router's id
    instead of minting its own, and the engine wraps task execution so
    compute-side records (``fault``, ``retry``, ``guard.recover``)
    join the request's timeline.  ``None`` installs nothing but still
    restores cleanly (an un-traced inbound request must not inherit a
    stale context from the previous one on this thread)."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield trace
    finally:
        _tls.trace = prev


def stamp() -> dict:
    """The ambient trace field :func:`~pencilarrays_tpu.obs.events.
    record_event` folds into every record (``setdefault`` — an
    explicitly passed ``trace=`` always wins).  Empty when no context
    is ambient: absence must cost one attribute probe, nothing more."""
    t = getattr(_tls, "trace", None)
    return {"trace": t} if t else {}


def _reset_for_tests() -> None:
    with _lock:
        _tls.trace = None


# ---------------------------------------------------------------------------
# per-request reconstruction (pa-obs request / requests)
# ---------------------------------------------------------------------------


def _t(e: dict) -> float:
    """Causal timestamp: the skew-corrected ``t_corr`` the timeline
    merger annotates, falling back to raw wall time for events read
    outside a merge."""
    v = e.get("t_corr", e.get("t_wall", 0.0))
    return float(v) if isinstance(v, (int, float)) else 0.0


def _matches(e: dict, trace: str) -> bool:
    if e.get("trace") == trace:
        return True
    traces = e.get("traces")
    return isinstance(traces, (list, tuple)) and trace in traces


@dataclass
class RequestTrace:
    """One request's reconstructed causal timeline.

    ``events`` is the causally ordered record list (router + every
    mesh the request touched, ``t_corr``-annotated); ``critical_path``
    decomposes the end-to-end wall time into the named phases that
    could be derived from the records present — a torn or missing
    journal shrinks the decomposition and grows ``warnings``, it never
    raises."""

    trace: str
    tenant: Optional[str] = None
    events: List[dict] = field(default_factory=list)
    ranks: List[int] = field(default_factory=list)
    outcome: Optional[str] = None
    total_s: Optional[float] = None
    fan_in: Optional[int] = None
    rebinds: int = 0
    critical_path: Dict[str, float] = field(default_factory=dict)
    warnings: List[str] = field(default_factory=list)


def _critical_path(evs: List[dict]) -> Tuple[Dict[str, float], List[str]]:
    """Decompose one request's records into the phases of its journey.
    Every phase is best-effort: a missing stage record (dead mesh,
    torn tail, pre-v6 journal) drops that phase and appends a warning."""
    warns: List[str] = []

    def first(ev: str, **match):
        for e in evs:
            if e.get("ev") == ev and all(e.get(k) == v
                                         for k, v in match.items()):
                return e
        return None

    def last(ev: str):
        for e in reversed(evs):
            if e.get("ev") == ev:
                return e
        return None

    route = first("fleet.route", reason="placed")
    req = first("serve.request")
    coal = first("serve.coalesce")
    disp = first("serve.dispatch")
    done = last("serve.complete")
    rebinds = [e for e in evs if e.get("ev") == "fleet.route"
               and e.get("reason") == "rebind"]
    failovers = [e for e in evs if e.get("ev") == "fleet.failover"]

    cp: Dict[str, float] = {}
    if route is not None and req is not None:
        # router commit -> mesh admission: KV wire + worker poll (and,
        # after a failover, the whole park-and-rebind detour)
        cp["wire_s"] = max(0.0, _t(req) - _t(route))
    elif route is not None:
        warns.append(
            f"trace {route.get('trace')}: fleet-routed but no "
            f"serve.request record — the placed mesh's journal is "
            f"missing/torn, or the mesh died before admission")
    if req is not None and disp is not None:
        cp["admission_wait_s"] = max(0.0, _t(disp) - _t(req))
    if coal is not None and isinstance(coal.get("wait_s"), (int, float)):
        cp["coalesce_wait_s"] = float(coal["wait_s"])
    if done is not None and isinstance(done.get("seconds"), (int, float)):
        cp["compute_s"] = float(done["seconds"])
        if disp is not None:
            cp["lane_wait_s"] = max(
                0.0, (_t(done) - float(done["seconds"])) - _t(disp))
    elif done is None:
        warns.append(
            "no serve.complete record — the request may still be in "
            "flight, or the resolving mesh's journal tail is torn")
    if failovers:
        cp["failover_s"] = sum(
            float(e.get("detect_s", 0.0)) for e in failovers
            if isinstance(e.get("detect_s"), (int, float)))
    return cp, warns


def reconstruct_request(directory: str, trace: str, *,
                        correct_skew: bool = True
                        ) -> Tuple[Optional[RequestTrace], List[str]]:
    """Rebuild one request's causal timeline from every journal under
    ``directory``.  Returns ``(trace_or_None, warnings)`` — ``None``
    means no record carries the id; warnings carry the merger's
    missing-rank / torn-tail / skew diagnostics plus any phases the
    decomposition could not derive.  Never raises on wreckage."""
    from .timeline import merge_journals

    mt = merge_journals(directory, correct_skew=correct_skew)
    warnings = list(mt.warnings)
    evs = sorted((e for e in mt.events if _matches(e, trace)), key=_t)
    if not evs:
        return None, warnings
    rt = RequestTrace(trace=trace, events=evs,
                      ranks=sorted({int(e.get("proc", 0)) for e in evs}))
    for e in evs:
        if rt.tenant is None and isinstance(e.get("tenant"), str):
            rt.tenant = e["tenant"]
    for e in reversed(evs):
        if e.get("ev") == "serve.complete":
            rt.outcome = e.get("outcome")
            break
    for e in evs:
        if e.get("ev") in ("serve.coalesce", "serve.dispatch") \
                and isinstance(e.get("n"), int):
            rt.fan_in = max(rt.fan_in or 0, e["n"])
    rt.rebinds = sum(1 for e in evs if e.get("ev") == "fleet.route"
                     and e.get("reason") == "rebind")
    rt.total_s = max(0.0, _t(evs[-1]) - _t(evs[0]))
    rt.critical_path, cp_warns = _critical_path(evs)
    warnings.extend(cp_warns)
    rt.warnings = warnings
    return rt, warnings


def list_requests(directory: str, *, correct_skew: bool = True
                  ) -> Tuple[List[dict], List[str]]:
    """Index every traced request under ``directory``: one summary
    dict per trace id, causally ordered by first appearance.  Shared
    fan-in records (``traces``) count toward every member.  Returns
    ``(summaries, warnings)``; wreckage degrades to warnings."""
    from .timeline import merge_journals

    mt = merge_journals(directory, correct_skew=correct_skew)
    index: Dict[str, dict] = {}
    for e in mt.events:
        ids = []
        if isinstance(e.get("trace"), str):
            ids.append(e["trace"])
        if isinstance(e.get("traces"), (list, tuple)):
            ids.extend(t for t in e["traces"] if isinstance(t, str))
        # a batch leader appears in BOTH trace and traces: one record
        # is still one event of its timeline, not two
        for tid in dict.fromkeys(ids):
            s = index.setdefault(tid, {
                "trace": tid, "tenant": None, "events": 0,
                "ranks": set(), "outcome": None, "rebinds": 0,
                "t_first": _t(e), "t_last": _t(e),
            })
            s["events"] += 1
            s["ranks"].add(int(e.get("proc", 0)))
            s["t_first"] = min(s["t_first"], _t(e))
            s["t_last"] = max(s["t_last"], _t(e))
            if s["tenant"] is None and isinstance(e.get("tenant"), str):
                s["tenant"] = e["tenant"]
            if e.get("ev") == "serve.complete" and e.get("trace") == tid:
                s["outcome"] = e.get("outcome")
            if e.get("ev") == "fleet.route" \
                    and e.get("reason") == "rebind" \
                    and e.get("trace") == tid:
                s["rebinds"] += 1
    out = []
    for s in sorted(index.values(), key=lambda s: s["t_first"]):
        s["ranks"] = sorted(s["ranks"])
        s["total_s"] = max(0.0, s["t_last"] - s["t_first"])
        out.append(s)
    return out, list(mt.warnings)


# ---------------------------------------------------------------------------
# text rendering (the pa-obs request / requests commands)
# ---------------------------------------------------------------------------

# the payload fields worth a column on a one-line event rendering
_RENDER_FIELDS = ("tenant", "mesh", "reason", "status", "key", "n",
                  "outcome", "seconds", "wait_s", "lane", "point",
                  "mode", "error", "tickets", "detect_s", "stage",
                  "burn_rate")


def _fmt_val(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_request(rt: RequestTrace) -> str:
    """One request's causal timeline + critical-path table as text."""
    lines = [
        f"trace {rt.trace}"
        + (f"  tenant={rt.tenant}" if rt.tenant else "")
        + f"  ranks={rt.ranks}"
        + (f"  fan_in={rt.fan_in}" if rt.fan_in else "")
        + (f"  rebinds={rt.rebinds}" if rt.rebinds else "")
        + (f"  outcome={rt.outcome}" if rt.outcome else ""),
    ]
    t0 = _t(rt.events[0]) if rt.events else 0.0
    for e in rt.events:
        extras = "  ".join(
            f"{k}={_fmt_val(e[k])}" for k in _RENDER_FIELDS if k in e)
        lines.append(f"  +{_t(e) - t0:9.4f}s  r{e.get('proc', 0)}  "
                     f"{e.get('ev', '?'):<18} {extras}".rstrip())
    if rt.critical_path:
        lines.append("critical path:")
        for k, v in rt.critical_path.items():
            lines.append(f"  {k:<18} {v:.4f}s")
    if rt.total_s is not None:
        lines.append(f"  {'total_s':<18} {rt.total_s:.4f}s")
    return "\n".join(lines)


def render_index(summaries: List[dict]) -> str:
    """The ``pa-obs requests`` listing as text."""
    if not summaries:
        return "no traced requests (v6 journals carry a 'trace' field)"
    lines = [f"{'trace':<18} {'tenant':<10} {'events':>6} "
             f"{'ranks':<10} {'rebinds':>7} {'total_s':>9} outcome"]
    for s in summaries:
        lines.append(
            f"{s['trace']:<18} {str(s['tenant'] or '-'):<10} "
            f"{s['events']:>6} {','.join(map(str, s['ranks'])):<10} "
            f"{s['rebinds']:>7} {s['total_s']:>9.4f} "
            f"{s['outcome'] or '-'}")
    return "\n".join(lines)
