"""Journal event schema + lint — event shapes cannot silently drift.

Every record type the flight recorder emits is registered here with its
required payload fields.  The test suite lints every journal it produces
(``tests/test_obs.py``, and the SIGKILL drill's timeline in
``tests/test_multiprocess.py``), so adding an event type without
registering it — or dropping a field a consumer relies on — fails CI
instead of quietly producing unreadable timelines.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple, Union

from .events import SCHEMA_VERSION

__all__ = ["COMMON_FIELDS", "EVENT_TYPES", "V4_EVENT_FIELDS",
           "V5_EVENT_FIELDS", "V6_EVENT_FIELDS", "V7_EVENT_FIELDS",
           "V8_EVENT_FIELDS", "lint_event", "lint_journal"]

# fields every record carries (written by events.record_event itself)
COMMON_FIELDS: Tuple[str, ...] = (
    "v", "ev", "run", "proc", "seq", "t_wall", "t_mono")

# correlation keys stamped into every record since schema v2
# (obs/correlate.py): the cross-rank join key.  ``plan_fp`` is only
# present once a plan exists, so it is not required.
V2_STAMP_FIELDS: Tuple[str, ...] = ("step_idx", "epoch")

# per-event fields required since schema v3 (the batched-throughput
# mode): a v3 ``plan.build`` record must journal the batch it prices
# its schedule at (``extra_dims``) and its slab/pencil decomposition
# verdict (``{"mode": "fixed", ...}`` for plans built on a caller-fixed
# topology).  v1/v2 journals stay lint-clean — the requirement is
# versioned, like the v2 correlation stamps.
V3_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "plan.build": ("extra_dims", "decomposition"),
}

# per-event fields required since schema v4 (memory-bounded
# redistribution synthesis): a v4 ``route.plan`` record must carry the
# footprint verdict pa-obs renders — the charged peak-HBM bytes, the
# bound the route was admitted under (``None`` = unbounded), and the
# donation assumption the pricing charged (the pinned-source
# surcharge).  Per-candidate ``chunks`` ride the candidates payload.
# v1-v3 journals stay lint-clean, as with the v2/v3 stamps.
V4_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "route.plan": ("peak_hbm_bytes", "hbm_limit", "donate"),
}

# per-event fields required since schema v5 (the DAG engine): a v5
# ``serve.dispatch`` record must carry the engine priority lane it was
# submitted on and the dependency chain it orders within (the declared
# write set, joined) — what pa-obs' per-lane timeline tracks and the
# partial-order certification render from.  v1-v4 journals stay
# lint-clean, as with the earlier versioned stamps.
V5_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "serve.dispatch": ("lane", "chain"),
}

# per-event fields required since schema v6 (the request-flow plane):
# every record on a request's path carries the trace id minted once at
# admission (obs/requestflow.py) — the key ``pa-obs request`` joins
# one ticket's causal timeline across router + N mesh journals by.  A
# coalesced batch's formation record additionally journals the B-way
# fan-in (``traces``: every member's id) so one dispatch span is
# attributable to each member request.  v1-v5 journals stay
# lint-clean, as with every earlier versioned stamp.
V6_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "fleet.route": ("trace",),
    "serve.request": ("trace",),
    "serve.coalesce": ("trace", "traces"),
    "serve.dispatch": ("trace", "traces"),
    "serve.complete": ("trace",),
}

# per-event fields required since schema v7 (the precision-downgrade
# rung, PR 19): a ``serve.precision`` record — a sheddable request
# served on a cheaper wire format instead of shed — must journal the
# full contract the degradation was admitted under: the wire precision
# it moved from and to, the calibrated worst-case relative-l2 envelope
# promised for that rung (``serve/precision.py`` / ``BENCH_WIRE.json``)
# and the tenant-declared ``max_rel_l2`` budget the envelope fit
# inside, plus the trace id so ``pa-obs request`` reconstructs WHICH
# answers were degraded.  v1-v6 journals stay lint-clean, as with
# every earlier versioned stamp.
V7_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "serve.precision": ("trace", "wire_from", "wire_to", "envelope",
                        "max_rel_l2"),
}

# per-event fields required since schema v8 (the partition-tolerant
# control plane, ISSUE 20): a ``cluster.quorum`` record must carry the
# full gate arithmetic the post-mortem re-checks — the voter set
# actually read, the strict-majority threshold and the denominator it
# was computed over (the last-agreed membership minus confirmed-gone
# ranks); a ``cluster.fence`` record names the stale token and the
# published fence that rejected it; a ``fleet.wal`` record summarizes
# a recover/replay pass (how many tickets were re-parked vs already
# resolved).  v1-v7 journals stay lint-clean, as with every earlier
# versioned stamp.
V8_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
    "cluster.quorum": ("have", "need", "of"),
    "cluster.fence": ("fence_gen", "fence_epoch"),
    "fleet.wal": ("outcome", "replayed", "resolved"),
}

# ev -> required payload fields (extra fields are allowed; missing ones
# and unknown event types are lint errors)
EVENT_TYPES: Dict[str, Tuple[str, ...]] = {
    # run boundaries
    "run.start": ("pid",),
    "run.stop": (),
    # planner / transpose engine
    "plan.build": ("shape", "transforms", "topo", "pipeline", "steps"),
    "auto.verdict": ("mode", "winner", "config"),
    "route.plan": ("src", "dest", "verdict", "candidates",
                   "predicted_bytes"),
    "hop": ("method", "r", "chunks", "predicted_bytes", "dispatch_s"),
    # I/O drivers
    "io.open": ("path", "mode"),
    "io.write": ("path", "dataset", "bytes", "seconds"),
    "io.read": ("path", "dataset", "seconds"),
    # checkpoint lifecycle
    "ckpt.save": ("step", "status"),
    "ckpt.commit": ("step",),
    "ckpt.restore": ("step", "dataset", "seconds"),
    "ckpt.verify": ("step", "ok"),
    "ckpt.gc": ("removed",),
    # resilience
    "retry": ("label", "attempt", "max_attempts", "delay_s", "error"),
    "fault": ("point", "mode", "hit"),
    "dist.init": ("status",),
    # runtime integrity guard (guard/)
    "guard.sdc": ("hop", "kind", "predicted", "observed"),
    "guard.hang": ("label", "timeout_s"),
    "guard.recover": ("label", "stage"),
    "guard.bundle": ("path", "reason"),
    "guard.epoch": ("epoch", "reason"),
    # mesh coordination layer (cluster/)
    "cluster.lease": ("rank", "status"),
    "cluster.verdict": ("label", "action", "epoch"),
    # elastic mesh reformation (cluster/elastic.py): the reformation
    # timeline (stages begin/view/membership/mesh/replan/restore/
    # complete/failed, plus join-request/join) and membership changes
    # (leave/left/drop/join)
    "cluster.reform": ("gen", "stage"),
    "cluster.member": ("rank", "change"),
    # the partition-tolerant control plane (ISSUE 20, schema v8): one
    # fsync-critical record per quorum-gate evaluation (verdict
    # pass/fail/bypass — the v8 fields carry the full arithmetic) and
    # per rejected zombie write (the stale token vs the published
    # fence)
    "cluster.quorum": ("gen", "rank", "verdict"),
    "cluster.fence": ("key", "gen", "epoch"),
    # mesh observability plane (PR 7)
    "cluster.straggler": ("rank", "hop", "excess_s", "baseline_s"),
    "clock.sync": ("ref_rank", "offset_s", "method"),
    "obs.agg": ("status",),
    # multi-tenant plan service (serve/): the request lifecycle —
    # admission (serve.request), batch formation (serve.coalesce),
    # the single coalesced dispatch (serve.dispatch) and the
    # per-request resolution (serve.complete; non-ok outcomes are
    # fsync-critical via record_event's per-record override)
    "serve.request": ("tenant", "req", "kind", "key", "nbytes"),
    "serve.coalesce": ("key", "n", "reqs", "reason", "wait_s"),
    "serve.dispatch": ("key", "n", "tenants", "score_bytes", "reason"),
    "serve.complete": ("tenant", "req", "outcome", "seconds", "key"),
    # the overload-survival plane (serve/slo.py, shed.py, autoscale.py):
    # a completion that busted its tenant's SLO deadline (the answer
    # was returned, the violation is on the record — fsync-critical),
    # a pressure-gate state transition with the projection that drove
    # it, and an autoscaler grow/shrink decision with its inputs
    "serve.slo_violation": ("tenant", "req", "deadline_s", "late_s"),
    "serve.pressure": ("state", "prev", "drain_s"),
    "serve.scale": ("direction", "reason", "projection"),
    # the SLO error-budget burn-rate monitor (serve/slo.py): a
    # tenant's budget is burning faster than the alert threshold —
    # always fsync-critical, the record must outlive the overload
    # that tripped it
    "serve.burn_alert": ("tenant", "burn_rate", "threshold",
                         "window_s"),
    # the precision-downgrade rung (serve/precision.py, schema v7):
    # one fsync-critical record per request served on a cheaper wire
    # format under pressure — v7 requires the full degradation
    # contract (V7_EVENT_FIELDS)
    "serve.precision": ("tenant", "req", "key", "gate"),
    # per-mesh task-graph executor (engine/): one record per engine
    # reformation boundary (queued dispatches dropped typed, fresh
    # RuntimeConfig snapshot, new generation)
    "engine.reform": ("gen", "stage"),
    # multi-mesh fleet federation (fleet/): a placement/rebind
    # decision with its bytes-equivalent score (fleet.route), a mesh
    # health-lease transition (fleet.lease — acquired/expired/left;
    # expiry rides record_event's per-record fsync override), a
    # whole-mesh failover sweep (fleet.failover — always
    # fsync-critical: the router may be about to re-bind onto a mesh
    # that dies too) and a supervisor scaling action (fleet.scale)
    "fleet.route": ("ticket", "tenant", "mesh", "reason",
                    "score_bytes"),
    "fleet.lease": ("mesh", "status"),
    "fleet.failover": ("mesh", "tickets", "detect_s"),
    "fleet.scale": ("action", "reason"),
    # durable router WAL (fleet/wal.py, schema v8): one fsync-critical
    # record per recover/replay pass — how the restarted router
    # reconciled its log (re-parked vs already-resolved tickets)
    "fleet.wal": ("dir",),
    # static analysis (analysis/): one record per certification —
    # ``PlanService.certify()`` registry sweeps, pa-lint SPMD runs and
    # direct ``certify_plan`` calls; non-ok outcomes are fsync-critical
    # via record_event's per-record override
    "analysis.check": ("target", "outcome", "seconds"),
    # profiling / drift
    "profile": ("dir", "status"),
    "drift.sample": ("hop", "predicted_bytes", "measured_s", "source"),
}


def lint_event(e: dict) -> List[str]:
    """Schema errors of one record ([] = clean)."""
    errors = []
    if not isinstance(e, dict):
        return [f"record is not an object: {e!r}"]
    for f in COMMON_FIELDS:
        if f not in e:
            errors.append(f"missing common field {f!r}: {e!r}")
    v = e.get("v")
    if v is not None and not isinstance(v, (int, float)):
        errors.append(f"schema version is not a number: {v!r}")
    elif v is not None and v > SCHEMA_VERSION:
        errors.append(f"schema version {v} is newer than supported "
                      f"{SCHEMA_VERSION}")
    if isinstance(v, (int, float)) and v >= 2:
        for f in V2_STAMP_FIELDS:
            if f not in e:
                errors.append(
                    f"v{v} record missing correlation key {f!r} "
                    f"(stamped by obs/correlate.py): {e!r}")
    ev = e.get("ev")
    if ev is None:
        return errors
    req = EVENT_TYPES.get(ev)
    if req is None:
        errors.append(f"unknown event type {ev!r} (register it in "
                      f"obs/schema.py EVENT_TYPES)")
        return errors
    for f in req:
        if f not in e:
            errors.append(f"event {ev!r} missing required field {f!r}: {e!r}")
    if isinstance(v, (int, float)) and v >= 3:
        for f in V3_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(batched-throughput fields, schema v3): {e!r}")
    if isinstance(v, (int, float)) and v >= 4:
        for f in V4_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(memory-bounded routing fields, schema v4): {e!r}")
    if isinstance(v, (int, float)) and v >= 5:
        for f in V5_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(DAG-engine lane fields, schema v5): {e!r}")
    if isinstance(v, (int, float)) and v >= 6:
        for f in V6_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(request-trace fields, schema v6): {e!r}")
    if isinstance(v, (int, float)) and v >= 7:
        for f in V7_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(precision-downgrade fields, schema v7): {e!r}")
    if isinstance(v, (int, float)) and v >= 8:
        for f in V8_EVENT_FIELDS.get(ev, ()):
            if f not in e:
                errors.append(
                    f"v{v} event {ev!r} missing required field {f!r} "
                    f"(partition-tolerance fields, schema v8): {e!r}")
    return errors


def lint_journal(events_or_dir: Union[str, Iterable[dict]]) -> List[str]:
    """Lint a whole journal (a directory path or an event iterable).
    Returns every error found; [] means the timeline is schema-clean."""
    if isinstance(events_or_dir, str):
        from .events import read_journal

        events = read_journal(events_or_dir)
    else:
        events = list(events_or_dir)
    errors = []
    for e in events:
        errors.extend(lint_event(e))
    return errors
