"""Straggler detection — which rank is dragging the collective.

On a pencil mesh every exchange runs at the pace of its slowest rank: a
thermally-throttled chip, a noisy ICI neighbor, or a host stuck in
page-cache writeback shows up as *every peer's* collectives slowing
down, and nothing in the per-process telemetry says **who**.  The
advanced-MPI FFT work (arXiv:1804.09536) adapts its decomposition from
measured per-stage timings, and DaggerFFT (arXiv:2601.12209) schedules
around measured worker skew — both need exactly this layer: per-hop,
per-rank duration statistics compared across the mesh.

Detection rule (:func:`detect`): for each hop label, each rank's
representative duration (the *minimum* over its dispatches — robust to
one-off compile/GC outliers) is compared against the **leave-one-out
median** of its peers.  A rank is flagged when its excess over that
baseline exceeds both

* ``min_excess_s`` — an absolute floor, so microsecond jitter on a
  2-rank drill mesh can never flag anyone, and
* ``z`` robust sigmas (``1.4826 * MAD`` of the peers), when at least
  two peers exist to estimate spread from (with a single peer the MAD
  is degenerate and the absolute floor alone governs).

Flags surface three ways: a fsync-critical ``cluster.straggler``
journal record naming the rank with its measured excess, a
``cluster.stragglers{rank=...}`` counter, and the offline path —
``pa-obs timeline`` runs :func:`detect_from_events` over a merged
journal so a post-mortem sees the same verdicts without any KV.
Deterministic drilling: the ``delay`` fault mode
(``hop.exchange:delay%rank1``, ``resilience/faults.py``) makes a chosen
rank drag every exchange by a fixed amount.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

__all__ = [
    "DEFAULT_Z",
    "DEFAULT_MIN_EXCESS_S",
    "detect",
    "hop_durations",
    "scan_snapshots",
    "detect_from_events",
]

DEFAULT_Z = 4.0
DEFAULT_MIN_EXCESS_S = 0.05


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2.0


def detect(durations_by_rank: Dict[int, Dict[str, float]], *,
           z: float = DEFAULT_Z,
           min_excess_s: float = DEFAULT_MIN_EXCESS_S) -> List[dict]:
    """Flag stragglers from per-rank per-hop representative durations.

    Returns one flag dict per (hop, rank) —
    ``{hop, rank, duration_s, baseline_s, excess_s, z, peers}`` —
    sorted by excess, worst first.  Hops present on fewer than two
    ranks are skipped (nothing to compare)."""
    flags: List[dict] = []
    hops: Dict[str, Dict[int, float]] = {}
    for rank, durs in durations_by_rank.items():
        for hop, d in (durs or {}).items():
            if isinstance(d, (int, float)) and d >= 0:
                hops.setdefault(hop, {})[int(rank)] = float(d)
    for hop, per_rank in hops.items():
        if len(per_rank) < 2:
            continue
        for rank, d in per_rank.items():
            others = [v for r, v in per_rank.items() if r != rank]
            baseline = _median(others)
            excess = d - baseline
            if excess <= min_excess_s:
                continue
            mad = _median([abs(v - baseline) for v in others])
            sigma = 1.4826 * mad
            zscore = (excess / sigma) if sigma > 0 else None
            if zscore is not None and zscore <= z:
                continue
            flags.append({
                "hop": hop, "rank": rank,
                "duration_s": d, "baseline_s": baseline,
                "excess_s": excess, "z": zscore,
                "peers": sorted(r for r in per_rank if r != rank),
            })
    flags.sort(key=lambda f: -f["excess_s"])
    return flags


def hop_durations(snapshot: dict,
                  prev: Optional[dict] = None) -> Dict[str, float]:
    """A rank's representative per-hop durations from its metrics
    snapshot.  With ``prev`` (the same rank's snapshot from the
    previous fold tick), the representative is the **windowed mean**
    ``(Δtotal_s)/(Δcount)`` of the dispatches since then — so a rank
    that degrades *after* warming up (thermal throttling mid-job) still
    drifts its representative upward; the all-time minimum would hide
    it forever.  A hop with no new dispatches in the window is omitted
    (stale — nothing to judge).  Without ``prev`` (first fold, or the
    offline path) the all-time per-hop minimum is used — robust to
    one-off compile/GC outliers on a bounded run."""
    out: Dict[str, float] = {}
    hops = ((snapshot or {}).get("drift") or {}).get("hops") or {}
    prev_hops = ((prev or {}).get("drift") or {}).get("hops") or {}
    for hop, entry in hops.items():
        p = prev_hops.get(hop)
        if (p is not None and p.get("source") == entry.get("source")
                and isinstance(entry.get("total_s"), (int, float))
                and isinstance(p.get("total_s"), (int, float))):
            dn = (entry.get("count") or 0) - (p.get("count") or 0)
            dt = entry["total_s"] - p["total_s"]
            if dn <= 0:
                continue            # no new dispatches: stale hop
            d = dt / dn
        else:
            d = entry.get("measured_s")
        if isinstance(d, (int, float)) and d >= 0:
            out[hop] = float(d)
    return out


def scan_snapshots(snaps: Dict[int, dict], *,
                   prev: Optional[Dict[int, dict]] = None,
                   z: float = DEFAULT_Z,
                   min_excess_s: float = DEFAULT_MIN_EXCESS_S,
                   emit: bool = False,
                   seen: Optional[Set[tuple]] = None) -> List[dict]:
    """Detection over KV-published per-rank snapshots (the aggregator's
    fold path).  ``prev`` — the previous fold's snapshots — windows the
    durations (see :func:`hop_durations`) so late-onset degradation is
    caught.  With ``emit``, each NEW flag — deduplicated per
    (hop, rank) via ``seen``, so a cadence loop journals one event per
    straggler, not one per tick — lands as a fsync-critical
    ``cluster.straggler`` record plus a ``cluster.stragglers{rank}``
    counter bump."""
    prev = prev or {}
    flags = detect({r: hop_durations(s, prev.get(r))
                    for r, s in snaps.items()},
                   z=z, min_excess_s=min_excess_s)
    if not emit:
        return flags
    from . import events, metrics

    for f in flags:
        key = (f["hop"], f["rank"])
        if seen is not None:
            if key in seen:
                continue
            seen.add(key)
        metrics.counter("cluster.stragglers", rank=str(f["rank"])).inc()
        events.record_event(
            "cluster.straggler", rank=f["rank"], hop=f["hop"],
            excess_s=f["excess_s"], baseline_s=f["baseline_s"],
            duration_s=f["duration_s"], z=f["z"], peers=f["peers"])
    return flags


def detect_from_events(events: Iterable[dict], *,
                       z: float = DEFAULT_Z,
                       min_excess_s: float = DEFAULT_MIN_EXCESS_S
                       ) -> List[dict]:
    """Offline detection over a merged journal: per (rank, hop) the
    representative duration is the minimum ``dispatch_s`` of that
    rank's ``hop`` records — the same statistic the live path reads
    from the drift report, so online and post-mortem verdicts agree."""
    durs: Dict[int, Dict[str, float]] = {}
    for e in events:
        if e.get("ev") != "hop":
            continue
        d = e.get("dispatch_s")
        hop = e.get("hop") or e.get("method")
        if not isinstance(d, (int, float)) or d < 0 or hop is None:
            continue
        rank = int(e.get("proc", 0))
        cur = durs.setdefault(rank, {})
        cur[hop] = min(cur.get(hop, float("inf")), float(d))
    return detect(durs, z=z, min_excess_s=min_excess_s)
