"""Cross-rank timeline reconstruction — N journals, one story.

Every rank's flight recorder is an island: ``journal.r<p>.jsonl`` (plus
rotated ``journal.r<p>.<k>.jsonl`` segments) with per-process sequence
numbers and that host's wall clock.  A post-mortem needs the *mesh*
view: which rank's hop dragged the step, which verdict the epoch
advance belongs to, what rank 1 was doing while rank 0 restored.  This
module builds it:

* :func:`merge_journals` — read every rank's segments (in rotation
  order), tolerate wreckage (torn final lines, empty files, missing
  ranks — each degrades to a *warning*, never an exception or a
  silently dropped rank), correct cross-host clock skew, and k-way
  merge into one causally-ordered event list that preserves each
  rank's append order exactly.
* skew correction — each rank's wall clock is shifted by an offset
  against a reference rank, taken from ``clock.sync`` records (the KV
  clock-offset exchange of :mod:`~pencilarrays_tpu.obs.aggregate`)
  when present, else *estimated* by aligning the fsync-critical shared
  markers both ranks journaled for the same ``(step_idx, epoch)``
  consensus round (verdicts and epoch advances happen within one KV
  poll of each other — good to ~0.1 s, which is what "skew larger
  than a hop" needs).
* :func:`to_trace` — export the merged timeline as Chrome/Perfetto
  ``trace_event`` JSON: one process ("track group") per rank, with
  hop / I/O / checkpoint / recovery / cluster tracks, and recovery
  epochs as global instant markers.  Load it at https://ui.perfetto.dev.
* :func:`render` — the ``pa-obs timeline`` text view: one line per
  ``(step_idx, epoch)`` group with each rank's activity side by side.

The joins all run on the correlation keys stamped since schema v2
(:mod:`~pencilarrays_tpu.obs.correlate`): ``(step_idx, epoch)`` is the
group key, ``hop`` labels disambiguate within a group.
"""

from __future__ import annotations

import heapq
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .straggler import _median

__all__ = [
    "MergedTimeline",
    "journal_files",
    "read_rank_journals",
    "estimate_offsets",
    "merge_journals",
    "to_trace",
    "write_trace",
    "render",
]

_JOURNAL_RE = re.compile(r"^journal\.r(\d+)(?:\.(\d+))?\.jsonl$")

# markers every rank journals for the SAME consensus round at nearly
# the same instant — the offset-estimation anchors
_MARKER_EVENTS = ("guard.epoch", "cluster.verdict")

# offsets below this are indistinguishable from KV poll jitter: applying
# them would only shuffle same-host records, so they are zeroed
_MIN_OFFSET_S = 0.5


@dataclass
class MergedTimeline:
    """The merged mesh view plus everything the merge had to tolerate."""

    directory: str
    events: List[dict] = field(default_factory=list)   # causally ordered
    ranks: List[int] = field(default_factory=list)     # journals found
    missing_ranks: List[int] = field(default_factory=list)
    offsets: Dict[int, float] = field(default_factory=dict)  # rank -> s
    offset_method: str = "none"
    warnings: List[str] = field(default_factory=list)

    def by_rank(self, rank: int) -> List[dict]:
        return [e for e in self.events if e.get("proc") == rank]

    def steps(self) -> List[Tuple[int, int]]:
        """``(step_idx, epoch)`` groups in first-appearance order."""
        seen, out = set(), []
        for e in self.events:
            key = (e.get("step_idx", 0), e.get("epoch", 0))
            if key not in seen:
                seen.add(key)
                out.append(key)
        return out


def journal_files(directory: str) -> Dict[int, List[str]]:
    """Per-rank journal segments in read order: rotated segments by
    ascending rotation index, the live (un-suffixed) file last — the
    append-order concatenation :func:`read_rank_journals` consumes."""
    by_rank: Dict[int, List[Tuple[float, str]]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return {}
    for name in names:
        m = _JOURNAL_RE.match(name)
        if not m:
            continue
        rank = int(m.group(1))
        # the live file sorts after every numbered segment
        order = int(m.group(2)) if m.group(2) else float("inf")
        by_rank.setdefault(rank, []).append(
            (order, os.path.join(directory, name)))
    return {r: [p for _, p in sorted(files)]
            for r, files in sorted(by_rank.items())}


def read_rank_journals(directory: str
                       ) -> Tuple[Dict[int, List[dict]], List[str]]:
    """Parse every rank's segments in append order.  Wreckage degrades
    to warnings: a torn/unparseable line is counted and skipped, an
    empty journal is reported but the rank stays in the result (an
    empty list — never silently dropped), an unreadable file is
    reported."""
    warnings: List[str] = []
    by_rank: Dict[int, List[dict]] = {}
    files = journal_files(directory)
    if not files:
        warnings.append(f"no journal files under {directory!r}")
        return {}, warnings
    for rank, paths in files.items():
        events: List[dict] = []
        for path in paths:
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError as e:
                warnings.append(f"rank {rank}: unreadable segment "
                                f"{os.path.basename(path)}: {e}")
                continue
            torn_mid, torn_final = 0, False
            for i, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    if i == len(lines) - 1:
                        torn_final = True
                    else:
                        torn_mid += 1
                    continue
                if isinstance(e, dict):
                    events.append(e)
            if torn_final:
                warnings.append(
                    f"rank {rank}: torn final line in "
                    f"{os.path.basename(path)} (crash mid-append — one "
                    f"record lost, the rest recovered)")
            if torn_mid:
                warnings.append(
                    f"rank {rank}: {torn_mid} unparseable mid-file "
                    f"line(s) in {os.path.basename(path)}")
        if not events:
            warnings.append(f"rank {rank}: journal is empty (rank kept "
                            f"in the timeline with no events)")
        by_rank[rank] = events
    # a hole in the rank sequence usually means a rank never got its
    # journal onto shared storage — exactly what a post-mortem must see
    present = sorted(by_rank)
    for r in range(present[-1] + 1 if present else 0):
        if r not in by_rank:
            warnings.append(f"rank {r}: no journal found (ranks present: "
                            f"{present})")
    return by_rank, warnings


def _sync_offsets(by_rank: Dict[int, List[dict]]
                  ) -> Dict[int, Tuple[float, float]]:
    """``(offset, error_bound)`` per rank from ``clock.sync`` records
    (the KV beacon exchange): each rank journaled its own measured
    offset against the reference rank, with the freshness bound of the
    sample it came from."""
    offsets: Dict[int, Tuple[float, float]] = {}
    for rank, events in by_rank.items():
        syncs = [e for e in events if e.get("ev") == "clock.sync"
                 and isinstance(e.get("offset_s"), (int, float))]
        if syncs:
            last = syncs[-1]
            bound = last.get("bound_s")
            offsets[rank] = (float(last["offset_s"]),
                             float(bound) if isinstance(
                                 bound, (int, float)) else 0.0)
    return offsets


def estimate_offsets(by_rank: Dict[int, List[dict]],
                     ref: Optional[int] = None
                     ) -> Tuple[Dict[int, float], List[str], str]:
    """Per-rank wall-clock offsets relative to ``ref`` (default: the
    lowest rank with events).  ``clock.sync`` records win; absent
    those, shared consensus markers are matched by
    ``(ev, step_idx, epoch, occurrence)`` and the median wall-time
    difference is the estimate — robust to one odd marker, and immune
    to the (corrected-away) case of skew far larger than a hop."""
    warnings: List[str] = []
    ranks_with = [r for r, evs in sorted(by_rank.items()) if evs]
    if not ranks_with:
        return {r: 0.0 for r in by_rank}, warnings, "none"
    if ref is None or ref not in ranks_with:
        ref = ranks_with[0]
    offsets = {r: 0.0 for r in by_rank}
    synced = _sync_offsets(by_rank)
    # the KV beacon's reference rank journals no clock.sync of its own:
    # the exchange is complete when every OTHER rank has one
    if len(ranks_with) > 1 and all(
            r in synced for r in ranks_with if r != ref):
        ref_off = synced.get(ref, (0.0, 0.0))[0]
        for r, (off, bound) in synced.items():
            rel = off - ref_off
            # an offset smaller than its own measurement bound (or the
            # global floor) is indistinguishable from exchange noise:
            # "correcting" an NTP-synced mesh by boot stagger would be
            # worse than leaving the clocks alone
            if abs(rel) > max(bound, _MIN_OFFSET_S):
                offsets[r] = rel
                warnings.append(
                    f"rank {r}: wall clock {rel:+.2f}s vs rank {ref} "
                    f"(KV clock exchange, bound ±{bound:.2f}s; "
                    f"corrected)")
        return offsets, warnings, "clock.sync"

    def markers(events: List[dict]) -> Dict[tuple, float]:
        seen: Dict[tuple, int] = {}
        out: Dict[tuple, float] = {}
        for e in events:
            if e.get("ev") not in _MARKER_EVENTS:
                continue
            base = (e["ev"], e.get("step_idx", 0), e.get("epoch", 0),
                    e.get("label") or e.get("reason"))
            n = seen.get(base, 0)
            seen[base] = n + 1
            out[base + (n,)] = float(e.get("t_wall", 0.0))
        return out

    ref_marks = markers(by_rank[ref])
    method = "none"
    for r in ranks_with:
        if r == ref:
            continue
        marks = markers(by_rank[r])
        diffs = [marks[k] - ref_marks[k] for k in marks if k in ref_marks]
        if not diffs:
            if len(by_rank[r]) and ref_marks:
                warnings.append(
                    f"rank {r}: no shared consensus markers with rank "
                    f"{ref} — clock skew not correctable (offset 0)")
            continue
        off = _median(diffs)
        method = "markers"
        if abs(off) >= _MIN_OFFSET_S:
            offsets[r] = off
            warnings.append(
                f"rank {r}: wall clock ~{off:+.2f}s vs rank {ref} "
                f"(estimated from {len(diffs)} shared marker(s); "
                f"corrected)")
    return offsets, warnings, method


def merge_journals(directory: str, *, correct_skew: bool = True,
                   ref: Optional[int] = None) -> MergedTimeline:
    """Build the mesh timeline for a journal directory.  Each event is
    annotated with ``t_corr`` — its skew-corrected wall time on the
    reference rank's clock — and the merge preserves every rank's own
    append order exactly (a k-way merge feeds each rank sequentially),
    so imperfect offsets can interleave ranks oddly but can never
    reorder one rank's records."""
    by_rank, warnings = read_rank_journals(directory)
    tl = MergedTimeline(directory=directory)
    tl.warnings = warnings
    tl.ranks = sorted(by_rank)
    tl.missing_ranks = sorted(
        set(range(tl.ranks[-1] + 1 if tl.ranks else 0)) - set(tl.ranks))
    if correct_skew:
        offsets, off_warnings, method = estimate_offsets(by_rank, ref)
        tl.warnings.extend(off_warnings)
    else:
        offsets, method = {r: 0.0 for r in by_rank}, "none"
    tl.offsets = offsets
    tl.offset_method = method
    streams = []
    for r, events in by_rank.items():
        off = offsets.get(r, 0.0)
        for e in events:
            e["t_corr"] = float(e.get("t_wall", 0.0)) - off
        streams.append(events)
    # k-way merge on corrected time; ties broken by (rank, position) so
    # the result is deterministic and per-rank order is preserved
    heap = []
    for si, stream in enumerate(streams):
        if stream:
            heapq.heappush(heap, (stream[0]["t_corr"], si, 0))
    merged: List[dict] = []
    while heap:
        _, si, i = heapq.heappop(heap)
        merged.append(streams[si][i])
        if i + 1 < len(streams[si]):
            heapq.heappush(heap, (streams[si][i + 1]["t_corr"], si, i + 1))
    tl.events = merged
    return tl


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ---------------------------------------------------------------------------

# per-rank tracks (Perfetto "threads"): stable ids + display order
_TRACKS = {"run": 0, "hops": 1, "io": 2, "ckpt": 3, "recovery": 4,
           "cluster": 5, "serve": 6, "fleet": 7}

# dispatches that carry a priority lane (schema v5) render on dynamic
# per-lane tracks BELOW the serve and fleet tracks, so cross-lane
# overlap — a whale batch in flight while a minnow batch issues — is
# visible as two concurrent spans instead of interleaved instants on
# one line
_LANE_TRACK_BASE = 8

_TRACK_OF = {
    "hop": "hops",
    "io.open": "io", "io.write": "io", "io.read": "io",
    "ckpt.save": "ckpt", "ckpt.commit": "ckpt", "ckpt.restore": "ckpt",
    "ckpt.verify": "ckpt", "ckpt.gc": "ckpt",
    "guard.sdc": "recovery", "guard.hang": "recovery",
    "guard.recover": "recovery", "guard.bundle": "recovery",
    "retry": "recovery", "fault": "recovery",
    "cluster.verdict": "cluster", "cluster.lease": "cluster",
    "cluster.straggler": "cluster", "clock.sync": "cluster",
    "obs.agg": "cluster",
    "cluster.reform": "cluster", "cluster.member": "cluster",
    "cluster.quorum": "cluster", "cluster.fence": "cluster",
    "serve.request": "serve", "serve.coalesce": "serve",
    "serve.dispatch": "serve", "serve.complete": "serve",
    "serve.slo_violation": "serve", "serve.pressure": "serve",
    "serve.scale": "serve",
    "fleet.route": "fleet", "fleet.lease": "fleet",
    "fleet.failover": "fleet", "fleet.scale": "fleet",
    "fleet.wal": "fleet",
}

# events exported as complete ("X") spans: payload field holding the
# duration in seconds; the journal records each at its END time
_SPAN_DURATION_FIELD = {
    "hop": "dispatch_s",
    "io.write": "seconds",
    "io.read": "seconds",
    "ckpt.restore": "seconds",
    # a serve.complete records the request's full submit->done latency
    "serve.complete": "seconds",
}


def _span_name(e: dict) -> str:
    ev = e.get("ev", "?")
    if ev == "hop":
        return f"hop {e.get('method', '?')}"
    if ev == "plan.build":
        # the batched-throughput fields (schema v3): batch + the
        # slab/pencil decomposition verdict, when the plan carries them
        name = "plan"
        extra = e.get("extra_dims") or []
        if extra:
            name += f" batch={'x'.join(str(i) for i in extra)}"
        d = e.get("decomposition")
        if isinstance(d, dict) and d.get("mode", "fixed") != "fixed":
            name += (f" decomp={d.get('mode')}:"
                     f"{d.get('family', '?')}"
                     f"{tuple(d.get('winner', ()))}")
        return name
    if ev in ("io.write", "io.read"):
        return f"{ev} {e.get('dataset', '?')}"
    if ev == "ckpt.restore":
        return f"ckpt.restore step {e.get('step', '?')}"
    if ev == "ckpt.save":
        return f"ckpt.save step {e.get('step', '?')} {e.get('status', '')}"
    if ev == "guard.recover":
        return f"recover:{e.get('stage', '?')}"
    if ev == "fault":
        return f"fault {e.get('point', '?')}:{e.get('mode', '?')}"
    if ev == "cluster.verdict":
        return f"verdict {e.get('action', '?')}"
    if ev == "guard.epoch":
        return f"epoch {e.get('epoch', '?')}"
    if ev == "cluster.straggler":
        return f"straggler r{e.get('rank', '?')}"
    if ev == "cluster.reform":
        return f"reform g{e.get('gen', '?')}:{e.get('stage', '?')}"
    if ev == "cluster.member":
        return f"member r{e.get('rank', '?')}:{e.get('change', '?')}"
    if ev == "cluster.quorum":
        # the split-brain gate's verdict: a pass is routine, a fail is
        # THE minority-side story, a bypass is an operator override —
        # all three name the arithmetic (have/need of the denominator)
        verdict = str(e.get("verdict", "?")).upper()
        have = e.get("have")
        n_have = len(have) if isinstance(have, (list, tuple)) else "?"
        of = e.get("of")
        n_of = len(of) if isinstance(of, (list, tuple)) else "?"
        return (f"QUORUM-{verdict} g{e.get('gen', '?')} "
                f"{n_have}/{e.get('need', '?')} of {n_of}")
    if ev == "cluster.fence":
        # a rejected zombie write: the fence that stopped it, vs the
        # stale token the writer carried
        return (f"FENCED g{e.get('gen', '?')}e{e.get('epoch', '?')} "
                f"(fence g{e.get('fence_gen', '?')}"
                f"e{e.get('fence_epoch', '?')}) {e.get('key', '?')}")
    if ev == "serve.request":
        return f"serve.req {e.get('tenant', '?')}#{e.get('req', '?')}"
    if ev == "serve.coalesce":
        return f"coalesce n={e.get('n', '?')} ({e.get('reason', '?')})"
    if ev == "serve.dispatch":
        name = f"serve.dispatch n={e.get('n', '?')}"
        if isinstance(e.get("lane"), int):
            name += f" lane={e['lane']}"
        chain = e.get("chain")
        if chain and chain != "*":
            name += f" [{chain}]"
        return name
    if ev == "serve.complete":
        return (f"serve {e.get('tenant', '?')}#{e.get('req', '?')}:"
                f"{e.get('outcome', '?')}")
    if ev == "serve.slo_violation":
        late = e.get("late_s")
        suffix = (f" late={late:.3f}s"
                  if isinstance(late, (int, float)) else "")
        return (f"SLO-VIOLATION {e.get('tenant', '?')}"
                f"#{e.get('req', '?')}{suffix}")
    if ev == "serve.pressure":
        d = e.get("drain_s")
        drain = f" drain={d:.3f}s" if isinstance(d, (int, float)) else ""
        return (f"pressure {e.get('prev', '?')}->"
                f"{e.get('state', '?')}{drain}")
    if ev == "serve.scale":
        # the autoscaler's verdict, with whether capacity actually
        # moved — the projection inputs ride the record's args
        acted = "" if e.get("acted") else " (signal)"
        det = e.get("detail")
        return (f"scale {e.get('direction', '?')} "
                f"[{e.get('reason', '?')}]"
                f"{f' {det}' if det else ''}{acted}")
    if ev == "fleet.route":
        sb = e.get("score_bytes")
        score = (f" {sb / 1e6:.2f}MBe"
                 if isinstance(sb, (int, float)) else "")
        return (f"route {e.get('tenant', '?')}#{e.get('ticket', '?')}"
                f"->m{e.get('mesh', '?')} [{e.get('reason', '?')}]"
                f"{score}")
    if ev == "fleet.lease":
        age = e.get("age_s")
        suffix = (f" age={age:.2f}s"
                  if isinstance(age, (int, float)) else "")
        return f"mesh-lease m{e.get('mesh', '?')}:{e.get('status', '?')}" \
               + suffix
    if ev == "fleet.failover":
        d = e.get("detect_s")
        det = f" detect={d:.2f}s" if isinstance(d, (int, float)) else ""
        return (f"FAILOVER m{e.get('mesh', '?')} "
                f"tickets={e.get('tickets', '?')}{det}")
    if ev == "fleet.scale":
        acted = "" if e.get("acted") else " (signal)"
        mesh = e.get("mesh")
        return (f"fleet-scale {e.get('action', '?')} "
                f"[{e.get('reason', '?')}]"
                f"{f' m{mesh}' if mesh is not None else ''}{acted}")
    if ev == "fleet.wal":
        return (f"WAL-REPLAY [{e.get('outcome', '?')}] "
                f"replayed={e.get('replayed', '?')} "
                f"reparked={e.get('reparked', '?')} "
                f"resolved={e.get('resolved', '?')}")
    return ev


def to_trace(tl: MergedTimeline) -> dict:
    """Convert a merged timeline into Chrome ``trace_event`` JSON
    (Perfetto-loadable).  One "process" per rank, tracks per event
    family; hops / I/O / restores are complete spans (their records
    carry durations), everything else is an instant; recovery-epoch
    advances are *global* instant markers (drawn across every track) —
    the cross-rank alignment line.  Every event's args carry the full
    journal record, correlation keys included, so the join key is one
    click away in the UI."""
    if not tl.events:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"directory": tl.directory,
                              "warnings": tl.warnings}}
    t0 = min(e["t_corr"] for e in tl.events)
    lanes = sorted({e["lane"] for e in tl.events
                    if e.get("ev") == "serve.dispatch"
                    and isinstance(e.get("lane"), int)
                    and e["lane"] >= 0})
    out: List[dict] = []
    for rank in tl.ranks:
        out.append({"ph": "M", "name": "process_name", "pid": rank,
                    "args": {"name": f"rank {rank}"}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": rank,
                    "args": {"sort_index": rank}})
        for track, tid in _TRACKS.items():
            out.append({"ph": "M", "name": "thread_name", "pid": rank,
                        "tid": tid, "args": {"name": track}})
            out.append({"ph": "M", "name": "thread_sort_index",
                        "pid": rank, "tid": tid,
                        "args": {"sort_index": tid}})
        for lane in lanes:
            tid = _LANE_TRACK_BASE + lane
            out.append({"ph": "M", "name": "thread_name", "pid": rank,
                        "tid": tid,
                        "args": {"name": f"serve.lane{lane}"}})
            out.append({"ph": "M", "name": "thread_sort_index",
                        "pid": rank, "tid": tid,
                        "args": {"sort_index": tid}})
    for e in tl.events:
        rank = int(e.get("proc", 0))
        ev = e.get("ev", "?")
        tid = _TRACKS[_TRACK_OF.get(ev, "run")]
        if (ev == "serve.dispatch" and isinstance(e.get("lane"), int)
                and e["lane"] >= 0):
            tid = _LANE_TRACK_BASE + e["lane"]
        ts_end = (e["t_corr"] - t0) * 1e6
        args = {k: v for k, v in e.items() if k != "t_corr"}
        dur_field = _SPAN_DURATION_FIELD.get(ev)
        dur_s = e.get(dur_field) if dur_field else None
        if isinstance(dur_s, (int, float)) and dur_s >= 0:
            out.append({"ph": "X", "name": _span_name(e), "pid": rank,
                        "tid": tid, "ts": ts_end - dur_s * 1e6,
                        "dur": max(dur_s * 1e6, 1.0), "args": args})
        else:
            rec = {"ph": "i", "name": _span_name(e), "pid": rank,
                   "tid": tid, "ts": ts_end, "s": "t", "args": args}
            if ev == "guard.epoch":
                rec["s"] = "g"   # the shared cross-rank marker
            elif ev == "cluster.reform" and e.get("stage") in (
                    "membership", "complete"):
                # reformation boundaries are mesh-wide alignment lines,
                # exactly like epoch advances (which they also cause)
                rec["s"] = "g"
            elif (ev == "cluster.quorum"
                  and e.get("verdict") in ("fail", "bypass")):
                # a quorum loss (or its operator override) is the
                # partition boundary itself — the mesh-wide line every
                # other rank's story hangs off
                rec["s"] = "g"
            out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {
                "directory": tl.directory,
                "ranks": tl.ranks,
                "missing_ranks": tl.missing_ranks,
                "clock_offsets_s": {str(r): o
                                    for r, o in tl.offsets.items()},
                "offset_method": tl.offset_method,
                "warnings": tl.warnings,
            }}


def write_trace(directory: str, out_path: str, **merge_kwargs) -> dict:
    """``merge_journals`` + :func:`to_trace` + atomic publish."""
    from ..resilience.fsutil import atomic_write_text

    tl = merge_journals(directory, **merge_kwargs)
    trace = to_trace(tl)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    atomic_write_text(out_path, json.dumps(trace, separators=(",", ":")))
    return trace


# ---------------------------------------------------------------------------
# text rendering (the `pa-obs timeline` view)
# ---------------------------------------------------------------------------

_QUIET_EVENTS = frozenset({"run.start", "run.stop", "drift.sample",
                           "clock.sync", "obs.agg"})


def render(tl: MergedTimeline, *, max_groups: int = 200) -> str:
    """Human-readable step timeline: one line per ``(step_idx, epoch)``
    group, each rank's activity summarized side by side, anomalies
    (faults, SDC, hangs, verdicts, stragglers) spelled out."""
    lines = [f"timeline: {tl.directory}",
             f"ranks: {tl.ranks or 'none'}"
             + (f"  MISSING: {tl.missing_ranks}" if tl.missing_ranks
                else "")]
    if any(tl.offsets.values()):
        lines.append("clock offsets vs ref (s): "
                     + ", ".join(f"r{r}={o:+.3f}"
                                 for r, o in sorted(tl.offsets.items())
                                 if o) + f"  [{tl.offset_method}]")
    for w in tl.warnings:
        lines.append(f"WARNING: {w}")
    groups = tl.steps()
    if len(groups) > max_groups:
        lines.append(f"({len(groups) - max_groups} step groups elided; "
                     f"showing the last {max_groups})")
        groups = groups[-max_groups:]
    shown = set(groups)
    by_group: Dict[tuple, Dict[int, List[dict]]] = {}
    for e in tl.events:
        key = (e.get("step_idx", 0), e.get("epoch", 0))
        if key in shown:
            by_group.setdefault(key, {}).setdefault(
                int(e.get("proc", 0)), []).append(e)
    for key in groups:
        step_idx, epoch = key
        parts = []
        for rank in sorted(by_group.get(key, {})):
            evs = by_group[key][rank]
            counts: Dict[str, int] = {}
            loud: List[str] = []
            for e in evs:
                ev = e.get("ev", "?")
                if ev in _QUIET_EVENTS:
                    continue
                if ev in ("fault", "guard.sdc", "guard.hang",
                          "guard.recover", "cluster.verdict",
                          "cluster.straggler", "guard.epoch",
                          "guard.bundle", "retry",
                          "cluster.reform", "cluster.member",
                          # the overload plane's decisions gate
                          # client-visible behavior: spell them out
                          "serve.slo_violation", "serve.pressure",
                          "serve.scale",
                          # fleet health/failover/scaling decisions
                          # gate whole meshes: always spelled out
                          # (fleet.route is high-rate and counted)
                          "fleet.lease", "fleet.failover",
                          "fleet.scale",
                          # partition-tolerance verdicts (schema v8):
                          # quorum math, rejected zombie writes and
                          # WAL replays ARE the post-mortem — loud
                          "cluster.quorum", "cluster.fence",
                          "fleet.wal"):
                    loud.append(_span_name(e))
                elif (ev == "plan.build"
                      and isinstance(e.get("decomposition"), dict)
                      and e["decomposition"].get("mode",
                                                 "fixed") != "fixed"):
                    # an auto-decomposition verdict is a planning
                    # decision worth spelling out, like a route verdict
                    loud.append(_span_name(e))
                elif (ev == "serve.complete"
                      and e.get("outcome") != "ok"):
                    # a failed request is a client-visible anomaly —
                    # name the tenant and the typed outcome
                    loud.append(_span_name(e))
                else:
                    counts[ev] = counts.get(ev, 0) + 1
            summary = " ".join(f"{ev}×{n}" if n > 1 else ev
                               for ev, n in sorted(counts.items()))
            if loud:
                summary = (summary + " " if summary else "") + \
                    " ".join(loud)
            parts.append(f"r{rank}[{summary or 'idle'}]")
        lines.append(f"step {step_idx} epoch {epoch}: " + "  ".join(parts))
    return "\n".join(lines)
