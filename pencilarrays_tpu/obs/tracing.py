"""Span/trace layer: one annotation API across host and device time.

Unifies the two channels ``utils/timers.py`` established — host-side
hierarchical :class:`~pencilarrays_tpu.utils.timers.TimerOutput` wall
time and trace-time ``jax.named_scope`` annotations (visible in XLA
device profiles) — with the metrics registry: a :func:`span` is all
three at once.  :func:`profile` adds the capture story: it wraps
``jax.profiler.trace`` and stamps plan metadata (schedule, predicted
collective costs) into the capture directory, so a trace pulled off a
pod months later still says what program it was profiling.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Optional

__all__ = ["span", "profile", "io_op"]


@contextmanager
def io_op(event: str, driver: str, path, dataset: str,
          nbytes: Optional[int] = None, **extra):
    """Time + meter + journal one driver operation (``event`` is
    ``"io.write"`` or ``"io.read"``) — the ONE instrumentation wrapper
    every I/O driver shares, so the event shape cannot drift between
    drivers.  No-op (a bare yield) when observability is disabled.

    A raising operation is journaled too — with ``ok: false`` and the
    error, and WITHOUT counting its bytes as written: the post-mortem
    timeline must show a failed write as failed.

    ``nbytes`` is the GLOBAL dataset size (what the event records — the
    post-mortem wants the dataset, not a share); the ``io.bytes_written``
    counter is incremented by this process's 1/P share of it, so
    per-process Prometheus textfiles sum to the true volume across a
    collective write instead of P times it."""
    from .events import enabled, record_event
    from .metrics import counter, histogram

    if not enabled():
        yield
        return
    t0 = time.perf_counter()
    err = None
    try:
        yield
    except BaseException as e:
        err = e
        raise
    finally:
        dt = time.perf_counter() - t0
        kind = event.rsplit(".", 1)[-1]
        if nbytes is not None and err is None:
            try:
                import jax

                share = nbytes // max(1, jax.process_count())
            except Exception:
                share = nbytes
            counter("io.bytes_written", driver=driver).inc(share)
        histogram(f"io.{kind}_seconds", driver=driver).observe(dt)
        payload = dict(path=str(path), dataset=dataset, seconds=dt,
                       driver=driver, ok=err is None, **extra)
        if err is not None:
            payload["error"] = f"{type(err).__name__}: {err}"
        if nbytes is not None:
            payload["bytes"] = nbytes
        record_event(event, **payload)


@contextmanager
def span(label: str, timer=None):
    """One section annotation, three sinks:

    * ``jax.named_scope`` — always (free: trace-time metadata only);
    * the host :class:`TimerOutput` — when debug timings are enabled
      and a timer is passed (the reference's ``@timeit_debug``);
    * an obs histogram ``span.seconds{label=...}`` — when observability
      is enabled.

    Drop-in superset of :func:`~pencilarrays_tpu.utils.timers.timeit`.
    """
    from ..utils.timers import timeit
    from .events import enabled
    from .metrics import histogram

    if not enabled():
        with timeit(timer, label):
            yield
        return
    t0 = time.perf_counter()
    try:
        with timeit(timer, label):
            yield
    finally:
        histogram("span.seconds", label=label).observe(
            time.perf_counter() - t0)


@contextmanager
def profile(logdir: str, plan=None, **metadata):
    """Capture a ``jax.profiler`` trace of the block into ``logdir`` and
    stamp run metadata into the capture directory
    (``pa_capture_metadata.json``): the obs run id, free-form
    ``metadata`` kwargs, and — when ``plan`` is a
    :class:`~pencilarrays_tpu.ops.fft.PencilFFTPlan` — the plan's
    transforms, schedule summary and predicted collective costs.  The
    capture works with observability disabled too (it is its own
    opt-in); the ``profile`` start/stop events land in the journal only
    when obs is on."""
    import os

    import jax

    from ..resilience.fsutil import atomic_write_json
    from .events import record_event, run_id

    logdir = os.fspath(logdir)
    os.makedirs(logdir, exist_ok=True)
    stamp = {"run": run_id(), "t_wall": time.time()}
    if metadata:
        stamp["metadata"] = {k: str(v) for k, v in metadata.items()}
    if plan is not None:
        stamp["plan"] = _plan_stamp(plan)
    atomic_write_json(os.path.join(logdir, "pa_capture_metadata.json"),
                      stamp)
    record_event("profile", dir=logdir, status="start",
                 plan=stamp.get("plan", {}).get("repr"))
    t0 = time.perf_counter()
    try:
        with jax.profiler.trace(logdir):
            yield logdir
    finally:
        record_event("profile", dir=logdir, status="stop",
                     seconds=time.perf_counter() - t0)


def _plan_stamp(plan) -> dict:
    """JSON summary of a PencilFFTPlan for capture stamping."""
    out = {"repr": repr(plan)}
    try:
        out["transforms"] = list(plan.transforms)
        out["shape"] = list(plan.shape_physical)
        out["topo"] = list(plan.topology.dims)
        out["pipeline_chunks"] = plan.pipeline_chunks
        out["steps"] = [s[0] for s in plan._steps]
        out["predicted_costs"] = plan.collective_costs()
    except Exception:
        pass  # stamping is best-effort; never break a capture
    return out
