from . import reductions
from . import spectral_ops
from .localgrid import LocalRectilinearGrid, localgrid
from .random import normal, uniform
from .spectral_ops import (
    curl,
    divergence,
    gradient,
    laplacian,
    solve_poisson,
)
from .reductions import (
    extrema,
    all,
    any,
    count_nonzero,
    dot,
    maximum,
    mean,
    minimum,
    norm,
    prod,
    sum,
    mapreduce,
)

__all__ = [
    "reductions",
    "spectral_ops",
    "curl",
    "divergence",
    "gradient",
    "laplacian",
    "solve_poisson",
    "extrema",
    "LocalRectilinearGrid",
    "localgrid",
    "normal",
    "uniform",
    "all",
    "any",
    "count_nonzero",
    "dot",
    "maximum",
    "mean",
    "minimum",
    "norm",
    "prod",
    "sum",
    "mapreduce",
]
