from . import reductions
from . import spectral_ops
from . import stencil
from .localgrid import LocalRectilinearGrid, localgrid
from .stencil import (
    diff,
    fd_divergence,
    fd_gradient,
    fd_laplacian,
    shift,
)
from .random import normal, uniform
from .spectral_ops import (
    curl,
    divergence,
    gradient,
    laplacian,
    solve_poisson,
)
from .reductions import (
    extrema,
    all,
    any,
    count_nonzero,
    dot,
    maximum,
    mean,
    minimum,
    norm,
    prod,
    sum,
    mapreduce,
)

__all__ = [
    "reductions",
    "spectral_ops",
    "stencil",
    "diff",
    "fd_divergence",
    "fd_gradient",
    "fd_laplacian",
    "shift",
    "curl",
    "divergence",
    "gradient",
    "laplacian",
    "solve_poisson",
    "extrema",
    "LocalRectilinearGrid",
    "localgrid",
    "normal",
    "uniform",
    "all",
    "any",
    "count_nonzero",
    "dot",
    "maximum",
    "mean",
    "minimum",
    "norm",
    "prod",
    "sum",
    "mapreduce",
]
