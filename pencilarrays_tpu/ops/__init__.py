from . import reductions
from .localgrid import LocalRectilinearGrid, localgrid
from .random import normal, uniform
from .reductions import (
    extrema,
    all,
    any,
    count_nonzero,
    dot,
    maximum,
    mean,
    minimum,
    norm,
    prod,
    sum,
    mapreduce,
)

__all__ = [
    "reductions",
    "extrema",
    "LocalRectilinearGrid",
    "localgrid",
    "normal",
    "uniform",
    "all",
    "any",
    "count_nonzero",
    "dot",
    "maximum",
    "mean",
    "minimum",
    "norm",
    "prod",
    "sum",
    "mapreduce",
]
