"""Distributed N-D FFT over pencil decompositions — the PencilFFTs proof.

The reference library exists to power PencilFFTs.jl (``README.md:29-31``):
a multidimensional FFT decomposes into per-dimension transforms, each
applied while that dimension is *local*, with global transposes in
between — the x->y->z pencil cycle (``docs/src/Transpositions.md:7-16``).
This module is that layer rebuilt TPU-first:

* **per-dimension transforms** (the PencilFFTs ``Transforms`` taxonomy:
  ``FFT``, ``RFFT``, ``R2R`` DCT/DST, ``NoTransform``): pass
  ``transforms=("rfft", "fft", "none")`` and each dim carries its own
  kind, with per-stage dtypes and global shapes derived at plan time;
* **local-dim batching**: the plan is compiled into a static *schedule*
  at construction — at every stage ALL still-pending dims that are local
  there are transformed in ONE native XLA FFT op (``jnp.fft.rfftn`` /
  ``fftn`` over several axes).  On one chip the whole 3-D r2c transform
  is a single fused XLA FFT with zero transposes — raw-``jnp.fft``
  parity by construction; on a slab (1-D) topology it is two stages
  instead of three.  The reference applies strictly one 1-D FFTW call
  per dim; batching is the TPU-first re-design (XLA's FFT kernels are
  multi-axis natively);
* between stages, the transpose engine's ``all_to_all`` exchanges ride
  ICI (``parallel/transpositions.py``); local transforms run under
  ``shard_map`` so GSPMD can never insert a hidden all-gather;
* with ``permute=True`` (default, like PencilFFTs' ``permute_dims``)
  each stage's pencil permutation places the stage's transform dim
  *last in memory*, where the FFT is contiguous;
* the whole plan is traceable: ``jit(plan.forward)`` fuses transforms,
  packing and collectives into one XLA program.

Transform dims are exact-size at their stage (a local dim is never
padded), so tail padding on *other* dims stays inert garbage, masked as
usual downstream.

Ordering constraint (PencilFFTs convention: the real transform comes
first): ``rfft``/``dct``/``dst`` act on *real* data, so on a distributed
mesh they must appear at stage indices before any ``fft`` dim has made
the data complex; violations raise at plan construction.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray
from ..parallel.pencil import LogicalOrder, MemoryOrder, Pencil
from ..parallel.topology import Topology
from ..parallel.transpositions import AllToAll, AbstractTransposeMethod, transpose
from ..utils.permutations import Permutation

__all__ = ["PencilFFTPlan"]

_KINDS = ("fft", "rfft", "dct", "dst", "none")


def _alt_signs(blk, axis):
    # (-1)^j along the transform axis, broadcast-shaped
    shape = [1] * blk.ndim
    shape[axis] = blk.shape[axis]
    j = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis)
    return jnp.where(j % 2 == 0, 1.0, -1.0).astype(blk.dtype)


def _dst(blk, axis):
    # DST-II(x) = reverse(DCT-II(x * (-1)^j))  (ortho norm; verified
    # against scipy.fft.dst) — jax.scipy has no native dst
    from jax.scipy import fft as jsfft

    return jnp.flip(
        jsfft.dct(blk * _alt_signs(blk, axis), axis=axis, norm="ortho"),
        axis=axis)


def _idst(blk, axis):
    # inverse: IDST-II(y) = (-1)^j * IDCT-II(reverse(y))
    from jax.scipy import fft as jsfft

    out = jsfft.idct(jnp.flip(blk, axis=axis), axis=axis, norm="ortho")
    return out * _alt_signs(out, axis)


@lru_cache(maxsize=512)
def _stage_fn(pen: Pencil, extra_ndims: int, ops: tuple, inverse: bool,
              pre_complex: bool, norm: str):
    """Cached batched local-transform callable for one schedule step.

    ``ops`` is a tuple of ``(kind, mem_axis, n_logical)`` — every
    transform applied at this stage, all along axes that are local
    (unsharded) in ``pen``.  Runs under ``shard_map`` so each device
    transforms its own block with zero communication: without this,
    GSPMD cannot partition the FFT op and inserts an all-gather of the
    full array per stage (observed: 6 all-gathers in a 3-D forward
    plan) — the multi-chip killer.  Caching lets eager (un-jitted)
    plans reuse function objects and hit JAX's dispatch cache.
    """
    from jax.scipy import fft as jsfft

    r2r = tuple(op for op in ops if op[0] in ("dct", "dst"))
    four = tuple(op for op in ops if op[0] in ("fft", "rfft"))
    rf = tuple(op for op in four if op[0] == "rfft")
    cax = tuple(ax for k, ax, n in four if k == "fft")
    # Fourier-dim normalization (r2r kinds are always ortho).  The
    # scaling is applied HERE with weak-typed python floats, never via
    # jnp.fft's ``norm=``: jnp's norm path materializes the factor as an
    # f64 array under jax_enable_x64, promoting c64 data to c128 — which
    # TPU does not support at all.  Bare transforms (norm=None) are
    # "backward" semantics: unscaled forward, 1/P inverse; the factors
    # below move between conventions (P = product of this stage's
    # logical Fourier extents; factors multiply across stages to the
    # full-transform convention).
    P_stage = 1.0
    for k, ax, n in four:
        P_stage *= float(n)
    fwd_scale = {"backward": 1.0, "none": 1.0, "forward": 1.0 / P_stage,
                 "ortho": P_stage ** -0.5}[norm]
    inv_scale = {"backward": 1.0, "none": P_stage, "forward": P_stage,
                 "ortho": P_stage ** 0.5}[norm]

    if not inverse:
        def op(blk):
            for k, ax, n in r2r:
                blk = (jsfft.dct(blk, axis=ax, norm="ortho") if k == "dct"
                       else _dst(blk, ax))
            if rf:
                # rfftn transforms its LAST listed axis real-to-complex
                blk = jnp.fft.rfftn(blk, axes=cax + (rf[0][1],))
            elif cax:
                blk = jnp.fft.fftn(blk, axes=cax)
            if four and fwd_scale != 1.0:
                blk = blk * fwd_scale
            return blk
    else:
        def op(blk):
            if rf:
                _, ax, n = rf[0]
                s = tuple(m for k, a, m in four if k == "fft") + (n,)
                blk = jnp.fft.irfftn(blk, s=s, axes=cax + (ax,))
            elif cax:
                blk = jnp.fft.ifftn(blk, axes=cax)
            if four and inv_scale != 1.0:
                blk = blk * inv_scale
            if not pre_complex and jnp.iscomplexobj(blk):
                # forward promoted real->complex here; the spectrum is
                # conjugate-symmetric, imag is numerically zero
                blk = blk.real
            for k, ax, n in reversed(r2r):
                blk = (jsfft.idct(blk, axis=ax, norm="ortho") if k == "dct"
                       else _idst(blk, ax))
            return blk

    if math.prod(pen.mesh.devices.shape) == 1:
        return op
    spec = pen.partition_spec(extra_ndims)
    # check_vma=False: with the static varying-mesh-axes check on, the
    # FFT primitive's TRANSPOSE rule rejects vma-tagged cotangents
    # ("cotangent type does not match function output"), breaking
    # jax.grad through any multi-chip plan.  The stage is trivially
    # per-device data-parallel (in_specs == out_specs, no collectives),
    # so the check buys nothing here; differentiability is pinned by
    # tests/test_autodiff.py.
    return jax.shard_map(op, mesh=pen.mesh, in_specs=spec, out_specs=spec,
                         check_vma=False)


def _stage_permutation(ndims: int, d: int, permute: bool):
    """Permutation placing logical dim ``d`` last in memory order."""
    if not permute:
        return None
    others = tuple(i for i in range(ndims) if i != d)
    return Permutation(others + (d,))


def _legacy_chain(N: int, M: int) -> List[Tuple[int, ...]]:
    """The classic x->y->z decomposition chain (reference
    ``docs/src/Transpositions.md:7-16``): stage 0 decomposes the last M
    dims; stage d swaps dim d+1 out for dim d."""
    out = []
    dec = list(range(N - M, N))
    for d in range(N):
        out.append(tuple(dec))
        if d + 1 < N and (d + 1) in dec:
            dec[dec.index(d + 1)] = d
    return out


def _strand_pad(n: int, P: int) -> Tuple[int, int]:
    """(empty devices, padding elements) for extent ``n`` ceil-blocked
    over ``P`` devices."""
    if P <= 1 or n == 0:
        return (0, 0)
    b = -(-n // P)
    return (P - (-(-n // b)), b * P - n)


def _build_chain(topology: Topology, global_shape: Tuple[int, ...],
                 kinds: Tuple[str, ...]) -> List[Tuple[int, ...]]:
    """Extent-aware stage chain: choose each stage's ordered decomposition
    (slot ``i`` rides mesh axis ``i``) by dim extent.

    The reference fixes the chain shape-blind (``Pencils.jl:61-63`` plus
    the x->y->z convention); here a tiny DP searches all legal chains —
    stage ``d`` keeps dim ``d`` local (unless its transform is ``none``),
    consecutive stages differ in at most ONE slot (the single-``all_to_all``
    hop contract, ``assert_compatible``) — and minimises, lexicographically,
    (number of hops, stranded devices summed over stages, padding
    elements).  Extents account for post-``rfft`` shrinkage (dim ``p`` is
    ``n//2+1`` from stage ``p+1`` on), so the spectral stages of an
    asymmetric r2c plan no longer strand devices by decomposing the
    shrunken dim over the largest mesh axis (the round-2 dryrun's own
    empty-rank warning).  Ties resolve to the legacy chain, keeping
    symmetric plans bit-stable.
    """
    from itertools import permutations as _iperms

    N = len(global_shape)
    M = topology.ndims
    dims = topology.dims
    legacy = _legacy_chain(N, M)
    spectral = tuple(n // 2 + 1 if k == "rfft" else n
                     for n, k in zip(global_shape, kinds))

    def stage_cost(dec: Tuple[int, ...], s: int) -> Tuple[int, int]:
        strands = pad = 0
        for i, p in enumerate(dec):
            n = spectral[p] if p < s else global_shape[p]
            a, b = _strand_pad(n, dims[i])
            strands += a
            pad += b
        return strands, pad

    def states(d: int) -> List[Tuple[int, ...]]:
        # dim d must be local at stage d unless it is never transformed
        pool = [p for p in range(N) if p != d or kinds[d] == "none"]
        cands = [tuple(t) for t in _iperms(pool, M)]
        cands.sort(key=lambda t: t != legacy[d])  # legacy first: tie-break
        return cands

    # DP over stages; strict < keeps the first (legacy-most) optimum.
    prev = {st: ((0,) + stage_cost(st, 0), [st]) for st in states(0)}
    for d in range(1, N):
        nxt = {}
        for st in states(d):
            sc = stage_cost(st, d)
            best = None
            for pst, (c, path) in prev.items():
                ndiff = sum(x != y for x, y in zip(pst, st))
                if ndiff > 1:
                    continue  # would not be a single-slot hop
                cand = (c[0] + (1 if ndiff else 0), c[1] + sc[0],
                        c[2] + sc[1])
                if best is None or cand < best[0]:
                    best = (cand, path + [st])
            if best is not None:
                nxt[st] = best
        prev = nxt
    return min(prev.values(), key=lambda v: v[0])[1]


class PencilFFTPlan:
    """Plan for a distributed N-D transform with per-dimension kinds.

    Mirrors PencilFFTs' ``PencilFFTPlan(dims_global, transform, proc_dims,
    comm)``: the plan owns its chain of pencil configurations; use
    :meth:`allocate_input` / :meth:`allocate_output` (or build arrays on
    :attr:`input_pencil` / :attr:`output_pencil`) and call
    :meth:`forward` / :meth:`backward`.

    ``transforms`` (or a tuple passed as ``transform``) selects one of
    ``"fft" | "rfft" | "dct" | "dst" | "none"`` per dim — the PencilFFTs
    per-dimension ``Transforms`` tuple (``RFFT x FFT x FFT``,
    ``NoTransform``, R2R mixes).  The legacy spellings remain:
    ``real=True`` = ``("rfft", "fft", ...)``; ``transform="dct"`` =
    all-DCT.

    Normalization defaults to ``jnp.fft`` semantics — unnormalized
    forward, ``1/n``-scaled inverse, ``backward(forward(u)) == u`` —
    and is selectable via ``normalization`` ("backward" | "ortho" |
    "forward" | "none"); ``"none"`` is PencilFFTs' unnormalized-BFFT
    convention with :meth:`scale_factor`.  R2R kinds are
    ortho-normalized in every mode.
    """

    def __init__(self, topology: Topology, global_shape: Sequence[int], *,
                 real: bool = False, dtype=None, permute: bool = True,
                 transform="fft", transforms: Sequence[str] = None,
                 method: AbstractTransposeMethod = AllToAll(),
                 normalization: str = "backward"):
        global_shape = tuple(int(n) for n in global_shape)
        N = len(global_shape)
        M = topology.ndims
        if M >= N:
            raise ValueError(
                f"topology ndims ({M}) must be < array ndims ({N}) so that "
                f"at least one dim is local per stage"
            )
        # -- resolve per-dim transform kinds ------------------------------
        if transforms is None and isinstance(transform, (tuple, list)):
            transforms = transform
            transform = "mixed"
        if transforms is not None:
            kinds = tuple(str(k).lower() for k in transforms)
            if len(kinds) != N:
                raise ValueError(
                    f"transforms has {len(kinds)} entries for a rank-{N} "
                    f"array")
            for k in kinds:
                if k not in _KINDS:
                    raise ValueError(
                        f"unknown transform kind {k!r}; expected one of "
                        f"{_KINDS}")
            if real:
                raise ValueError(
                    "real=True is implicit in per-dim transforms; spell the "
                    "real dim 'rfft'")
            transform = "mixed"
        else:
            if transform not in ("fft", "dct", "dst"):
                raise ValueError(f"transform must be 'fft', 'dct' or 'dst', "
                                 f"got {transform!r}")
            if transform in ("dct", "dst") and real:
                raise ValueError(
                    f"real=True is implicit for transform={transform!r}")
            if transform == "fft" and real:
                kinds = ("rfft",) + ("fft",) * (N - 1)
            else:
                kinds = (transform,) * N
        if kinds.count("rfft") > 1:
            raise ValueError("at most one dim may be 'rfft'")
        # Real-input kinds must precede any fft dim in STAGE order.  This
        # is validated upfront on the conceptual per-dim chain — not on
        # the batched schedule — so the same transforms tuple is accepted
        # or rejected identically on every topology (a slab mesh could
        # batch ("fft","rfft") into one real transform, but the plan must
        # not construct on one process grid and raise on another).
        complex_seen = False
        for d, k in enumerate(kinds):
            if k in ("rfft", "dct", "dst") and complex_seen:
                raise ValueError(
                    f"transform {k!r} on dim {d} would act on data an "
                    f"earlier 'fft' dim made complex; real-input kinds "
                    f"must come first in stage order")
            if k in ("fft", "rfft"):
                complex_seen = True
        self.transforms = kinds
        self.transform = transform  # legacy attribute
        self.real = "rfft" in kinds
        self.topology = topology
        self.shape_physical = global_shape
        self.method = method
        self.permute = permute
        # Fourier-dim normalization (PencilFFTs' fft normalization
        # taxonomy; its unnormalized-backward BFFT + scale_factor(plan)
        # convention is ``normalization="none"``): "backward" (default,
        # jnp semantics: bare forward, 1/n inverse), "ortho", "forward",
        # or "none" (bare BOTH ways; ``backward(forward(u)) ==
        # scale_factor() * u``).  R2R kinds (dct/dst) stay
        # ortho-normalized in every mode.
        if normalization not in ("backward", "ortho", "forward", "none"):
            raise ValueError(
                f"normalization must be 'backward', 'ortho', 'forward' or "
                f"'none', got {normalization!r}")
        self.normalization = normalization

        # -- dtypes -------------------------------------------------------
        needs_real = any(k in ("rfft", "dct", "dst") for k in kinds)
        if dtype is None:
            dtype = jnp.float32 if needs_real else jnp.complex64
        self.dtype_physical = jnp.dtype(dtype)
        is_cplx_in = jnp.issubdtype(self.dtype_physical, jnp.complexfloating)
        if needs_real and is_cplx_in:
            kr = next(k for k in kinds if k in ("rfft", "dct", "dst"))
            if self.real and transform != "mixed":
                raise ValueError("real=True requires a real input dtype")
            raise ValueError(f"transform {kr!r} requires a real dtype")
        if any(k in ("fft", "rfft") for k in kinds):
            self.dtype_spectral = jnp.dtype(
                jnp.result_type(self.dtype_physical, jnp.complex64))
        else:
            self.dtype_spectral = self.dtype_physical  # R2R/none: real

        self.shape_spectral = tuple(
            n // 2 + 1 if k == "rfft" else n
            for n, k in zip(global_shape, kinds))

        # -- stage configurations (decomp chain) --------------------------
        # Stage d has logical dim d local (unless kinds[d] == "none", in
        # which case the chain search may leave it decomposed to skip a
        # hop); consecutive stages differ in at most one decomposition
        # slot, so each hop is a single all_to_all.  The chain is chosen
        # extent-aware (see _build_chain).
        chain = _build_chain(topology, global_shape, kinds)
        cfgs = [(dec, _stage_permutation(N, d, permute))
                for d, dec in enumerate(chain)]

        # -- static schedule ----------------------------------------------
        # Walk the chain once at plan time; batch every pending dim that
        # is local at the current configuration.  A dim decomposed over a
        # size-1 mesh axis is local in every way that matters.
        def _is_local(pen: Pencil, p: int) -> bool:
            if p not in pen.decomposition:
                return True
            return topology.dims[pen.decomposition.index(p)] == 1

        shape = list(global_shape)
        pending = [d for d in range(N) if kinds[d] != "none"]
        is_complex = is_cplx_in
        steps: List[tuple] = []
        cur = Pencil(topology, tuple(shape), cfgs[0][0],
                     permutation=cfgs[0][1])
        self._input_pencil = cur
        for d in range(N):
            if not pending:
                break
            dec, perm = cfgs[d]
            if dec != cur.decomposition:
                tgt = Pencil(topology, tuple(shape), dec, permutation=perm)
                hop_dtype = (self.dtype_spectral if is_complex
                             else self.dtype_physical)
                steps.append(("t", cur, tgt, hop_dtype))
                cur = tgt
            if d != min(pending):
                continue  # path hop only; d's transform already applied
            batch = tuple(sorted(p for p in pending if _is_local(cur, p)))
            mem_ids = cur.permutation.apply(tuple(range(N)))
            ops = []
            for p in batch:
                k = kinds[p]
                # upfront stage-order validation guarantees real input here
                assert not (k in ("rfft", "dct", "dst") and is_complex)
                ops.append((k, mem_ids.index(p), shape[p]))
            pre = cur
            pre_complex = is_complex
            for p in batch:
                if kinds[p] == "rfft":
                    shape[p] = shape[p] // 2 + 1
            if any(kinds[p] in ("fft", "rfft") for p in batch):
                is_complex = True
            if tuple(shape) != pre.size_global():
                # A local transform never moves data: the post-stage
                # pencil must keep PRE's decomposition/permutation, not
                # this chain slot's.  (They differ when an elided hop
                # leaves the data in an earlier stage's configuration —
                # e.g. transforms=("none","rfft","fft") on a 1-D mesh,
                # where stage 1 executes in stage 0's memory order.)
                cur = Pencil(topology, tuple(shape), pre.decomposition,
                             permutation=pre.permutation)
            steps.append(("f", pre, cur, tuple(ops), pre_complex))
            pending = [p for p in pending if p not in batch]
        self._steps = tuple(steps)
        self._output_pencil = cur

        # conceptual full chain (stage d pencil at its pre-stage shape),
        # for introspection/tests; the schedule above may visit fewer.
        self._pencils: List[Pencil] = []
        sh = list(global_shape)
        for d in range(N):
            self._pencils.append(
                Pencil(topology, tuple(sh), cfgs[d][0],
                       permutation=cfgs[d][1]))
            if kinds[d] == "rfft":
                sh[d] = sh[d] // 2 + 1

    # -- pencils ----------------------------------------------------------
    @property
    def pencils(self) -> Tuple[Pencil, ...]:
        """The chain of configurations.  Stage ``d`` has logical dim ``d``
        local, except that a dim whose transform is ``"none"`` may stay
        decomposed at its own stage (the extent-aware chain search elides
        the hop; see :func:`_build_chain`)."""
        return tuple(self._pencils)

    @property
    def input_pencil(self) -> Pencil:
        return self._input_pencil

    @property
    def output_pencil(self) -> Pencil:
        """Configuration of the spectral (fully transformed) array."""
        return self._output_pencil

    def collective_costs(self, extra_dims: Tuple[int, ...] = (), *,
                         method: AbstractTransposeMethod = None) -> dict:
        """Predicted per-chip collective cost of ONE :meth:`forward`
        application (``{op: {"count", "bytes"}}``, the
        ``utils.hlo.collective_stats`` schema).  Each hop is priced by
        the analytic model (:func:`~pencilarrays_tpu.parallel.
        transpositions.transpose_cost`) at the dtype the data carries at
        that point of the schedule.  :meth:`backward` costs the same
        (the hop shapes are symmetric).  Tests and the multichip dryrun
        pin this EQUAL to the compiled HLO's measured stats — the
        validated ICI byte model."""
        from ..parallel.transpositions import transpose_cost

        method = method if method is not None else self.method
        total: dict = {}
        for step in self._steps:
            if step[0] != "t":
                continue
            _, src, dst, hop_dtype = step
            for op, c in transpose_cost(src, dst, extra_dims, hop_dtype,
                                        method).items():
                e = total.setdefault(op, {"count": 0, "bytes": 0})
                e["count"] += c["count"]
                e["bytes"] += c["bytes"]
        return total

    def allocate_input(self, extra_dims: Tuple[int, ...] = ()) -> PencilArray:
        return PencilArray.zeros(self.input_pencil, extra_dims,
                                 self.dtype_physical)

    def allocate_output(self, extra_dims: Tuple[int, ...] = ()) -> PencilArray:
        return PencilArray.zeros(self.output_pencil, extra_dims,
                                 self.dtype_spectral)

    # -- transforms -------------------------------------------------------
    @staticmethod
    def _hop_donate(x: PencilArray, owned: bool) -> bool:
        """Donate a hop's input buffer when it is an intermediate this
        plan created (``owned``) and we are NOT tracing — under an outer
        ``jit`` the whole chain is one XLA program whose buffer reuse the
        compiler already owns, and an inner-jit donation hint would only
        warn.  Eagerly, per-hop donation lets XLA alias the exchange
        in/out buffers, the analog of the reference's in-place
        ``ManyPencilArray`` transposes (``multiarrays.jl:106-130``).
        Donation is live on CPU too (verified: buffers invalidate, no
        warnings), so the virtual-mesh tests exercise this path."""
        import jax.core

        return owned and not isinstance(x.data, jax.core.Tracer)

    def forward(self, u: PencilArray, *, donate: bool = False
                ) -> PencilArray:
        """Physical -> spectral: interpret the static schedule (batched
        local transforms + single-hop transposes).  ``donate=True``
        additionally donates the INPUT array's buffer to the first hop
        (``u`` becomes invalid, like ``transpose(donate=True)``);
        intermediates are always donated when running eagerly."""
        if u.pencil != self.input_pencil:
            raise ValueError(
                f"input must live on plan.input_pencil "
                f"({self.input_pencil!r}), got {u.pencil!r}"
            )
        nd_extra = u.ndims_extra
        x = u
        owned = donate
        for step in self._steps:
            if step[0] == "t":
                x = transpose(x, step[2], method=self.method,
                              donate=self._hop_donate(x, owned))
            else:
                _, pre, post, ops, pre_complex = step
                data = _stage_fn(pre, nd_extra, ops, False, pre_complex,
                                 self.normalization)(x.data)
                x = PencilArray(post, data, x.extra_dims)
            owned = True  # every step output is plan-owned
        if x.dtype != self.dtype_spectral:
            x = PencilArray(x.pencil, x.data.astype(self.dtype_spectral),
                            x.extra_dims)
        return x

    def backward(self, uh: PencilArray, *, donate: bool = False
                 ) -> PencilArray:
        """Spectral -> physical (inverse transforms, reverse schedule).
        ``donate`` as in :meth:`forward`."""
        if uh.pencil != self.output_pencil:
            raise ValueError(
                f"input must live on plan.output_pencil "
                f"({self.output_pencil!r}), got {uh.pencil!r}"
            )
        nd_extra = uh.ndims_extra
        x = uh
        owned = donate
        for step in reversed(self._steps):
            if step[0] == "t":
                x = transpose(x, step[1], method=self.method,
                              donate=self._hop_donate(x, owned))
            else:
                _, pre, post, ops, pre_complex = step
                data = _stage_fn(post, nd_extra, ops, True, pre_complex,
                                 self.normalization)(x.data)
                x = PencilArray(pre, data, x.extra_dims)
            owned = True
        if x.dtype != self.dtype_physical:
            x = PencilArray(x.pencil, x.data.astype(self.dtype_physical),
                            x.extra_dims)
        return x

    def scale_factor(self) -> float:
        """Global normalization factor of a full round trip:
        ``backward(forward(u)) == scale_factor() * u``.  1 except for
        ``normalization="none"``, where it is the product of the
        transformed Fourier extents — the PencilFFTs ``scale_factor``
        convention for unnormalized (BFFT-style) plans."""
        if self.normalization != "none":
            return 1.0
        out = 1.0
        for n, k in zip(self.shape_physical, self.transforms):
            if k in ("fft", "rfft"):
                out *= float(n)
        return out

    # -- spectral helpers -------------------------------------------------
    @property
    def dtype_real(self):
        """Real dtype matching the plan's arithmetic (f32 for c64 etc.).
        Frequency/wavenumber components carry it so that spectral-
        coefficient products NEVER promote: under ``jax_enable_x64`` a
        default-f64 wavenumber times c64 data silently becomes c128 —
        which TPU does not support at all ("Element type C128")."""
        import numpy as np

        # host-side dtype math only: no device allocation per access
        return jnp.dtype(np.empty(0, np.dtype(self.dtype_spectral)
                                  ).real.dtype)

    def frequencies(self, d: int, *, spacing: float = 1.0):
        """Global frequency vector of logical dim ``d`` in CYCLES per
        unit for every transform kind (scale by ``2*pi`` for angular
        wavenumbers, as with ``fftfreq``): ``fftfreq``/``rfftfreq`` for
        Fourier dims; for ``'dct'`` mode ``j`` (the basis function
        ``cos(pi j (x+1/2)/n)``) has angular wavenumber
        ``pi j/(n spacing)``, i.e. ``j/(2 n spacing)`` cycles.  Returned
        in the plan's :attr:`dtype_real`."""
        n = self.shape_physical[d]
        k = self.transforms[d]
        rd = self.dtype_real
        if k == "none":
            raise ValueError(f"dim {d} has transform 'none': no frequencies")
        if k == "dct":
            return (jnp.arange(n) / (2.0 * n * spacing)).astype(rd)
        if k == "dst":
            # DST-II mode j is sin(pi (j+1) (x+1/2)/n): angular pi(j+1)/n
            return ((jnp.arange(n) + 1.0) / (2.0 * n * spacing)).astype(rd)
        if k == "rfft":
            return jnp.fft.rfftfreq(n, d=spacing).astype(rd)
        return jnp.fft.fftfreq(n, d=spacing).astype(rd)

    def wavenumbers(self, order: type = MemoryOrder):
        """Broadcast-shaped mode-number components of the OUTPUT pencil —
        one array per logical dim.  Values are ``frequencies(d) * n_d``:
        integer Fourier modes for fft/rfft dims; half-integer (j/2) /
        ((j+1)/2) mode numbers for dct/dst; zeros for 'none' dims (no
        modal meaning).  The spectral analog of localgrid components.

        ``order=MemoryOrder`` (default): non-singleton at each dim's
        memory position, padded and sharded along its mesh axis — for
        arithmetic against raw ``.data``.  ``order=LogicalOrder``:
        true-size, non-singleton at logical position ``d`` — for
        arithmetic against PencilArrays, whose broadcasting aligns raw
        operands to the logical shape (``parallel/arrays.py``)."""
        def mode_vector(d):
            # one definition serves both orders
            if self.transforms[d] == "none":
                return jnp.zeros(self.shape_spectral[d], self.dtype_real)
            return self.frequencies(d) * self.shape_physical[d]

        if order is LogicalOrder:
            ks = []
            N = len(self.shape_spectral)
            for d in range(N):
                shape = [1] * N
                shape[d] = self.shape_spectral[d]
                ks.append(mode_vector(d).reshape(shape))
            return tuple(ks)

        from jax.sharding import NamedSharding, PartitionSpec

        pen = self.output_pencil
        N = pen.ndims
        mem_ids = pen.permutation.apply(tuple(range(N)))
        ks = []
        for d in range(N):
            k = mode_vector(d)
            n_pad = pen.padded_global_shape[d]
            if n_pad != k.shape[0]:
                k = jnp.pad(k, (0, n_pad - k.shape[0]))
            pos = mem_ids.index(d)
            shape = [1] * N
            shape[pos] = n_pad
            k = k.reshape(shape)
            spec = [None] * N
            spec[pos] = pen.decomp_axis_name(d)
            k = jax.lax.with_sharding_constraint(
                k, NamedSharding(pen.mesh, PartitionSpec(*spec)))
            ks.append(k)
        return tuple(ks)

    def __repr__(self) -> str:
        return (
            f"PencilFFTPlan({'x'.join(self.transforms)}, "
            f"shape={self.shape_physical}, "
            f"topo={self.topology.dims}, permute={self.permute})"
        )
