"""Distributed N-D FFT over pencil decompositions — the PencilFFTs proof.

The reference library exists to power PencilFFTs.jl (``README.md:29-31``):
a multidimensional FFT decomposes into per-dimension 1-D transforms, each
applied while that dimension is *local*, with global transposes in
between — the x->y->z pencil cycle (``docs/src/Transpositions.md:7-16``).
This module is that layer rebuilt TPU-first:

* local transforms are XLA FFT ops (``jnp.fft``) on the sharded array,
  batched over all non-transform dims — large contiguous batches feed the
  hardware well;
* between stages, the transpose engine's ``all_to_all`` exchanges ride
  ICI (``parallel/transpositions.py``);
* with ``permute=True`` (default, like PencilFFTs' ``permute_dims``) each
  stage's pencil permutation places the transform dimension *last in
  memory*, where XLA's FFT is contiguous — the zero-cost layout trick the
  reference implements with compile-time permutations;
* the whole plan is traceable: ``jit(plan.forward)`` fuses transforms,
  packing and collectives into one XLA program.

The transform dimension is exact-size at its stage (a local dim is never
padded), so tail padding on *other* dims stays inert garbage, masked as
usual downstream.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray
from ..parallel.pencil import LogicalOrder, MemoryOrder, Pencil
from ..parallel.topology import Topology
from ..parallel.transpositions import AllToAll, AbstractTransposeMethod, transpose
from ..utils.permutations import Permutation

__all__ = ["PencilFFTPlan"]


@lru_cache(maxsize=512)
def _stage_fn(pen: Pencil, extra_ndims: int, kind: str, axis: int, n: int):
    """Cached per-stage local-transform callable (see _local_fft)."""
    from jax.scipy import fft as jsfft

    def _alt_signs(blk):
        # (-1)^j along the transform axis, broadcast-shaped
        shape = [1] * blk.ndim
        shape[axis] = blk.shape[axis]
        j = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis)
        return jnp.where(j % 2 == 0, 1.0, -1.0).astype(blk.dtype)

    def _dst(blk):
        # DST-II(x) = reverse(DCT-II(x * (-1)^j))  (ortho norm; verified
        # against scipy.fft.dst) — jax.scipy has no native dst
        return jnp.flip(
            jsfft.dct(blk * _alt_signs(blk), axis=axis, norm="ortho"),
            axis=axis)

    def _idst(blk):
        # inverse: IDST-II(y) = (-1)^j * IDCT-II(reverse(y))
        out = jsfft.idct(jnp.flip(blk, axis=axis), axis=axis, norm="ortho")
        return out * _alt_signs(out)

    ops = {
        "fft": lambda blk: jnp.fft.fft(blk, axis=axis),
        "ifft": lambda blk: jnp.fft.ifft(blk, axis=axis),
        "rfft": lambda blk: jnp.fft.rfft(blk, axis=axis),
        "irfft": lambda blk: jnp.fft.irfft(blk, n=n, axis=axis),
        # R2R transforms (PencilFFTs Transforms.R2R parity); ortho norm
        # so the inverse kinds are exact inverses
        "dct": lambda blk: jsfft.dct(blk, axis=axis, norm="ortho"),
        "idct": lambda blk: jsfft.idct(blk, axis=axis, norm="ortho"),
        "dst": _dst,
        "idst": _idst,
    }
    op = ops[kind]
    if math.prod(pen.mesh.devices.shape) == 1:
        return op
    spec = pen.partition_spec(extra_ndims)
    return jax.shard_map(op, mesh=pen.mesh, in_specs=spec, out_specs=spec)


def _stage_permutation(ndims: int, d: int, permute: bool):
    """Permutation placing logical dim ``d`` last in memory order."""
    if not permute:
        return None
    others = tuple(i for i in range(ndims) if i != d)
    return Permutation(others + (d,))


class PencilFFTPlan:
    """Plan for a distributed N-D (inverse) FFT, optionally real-to-complex
    along the first transform dimension.

    Mirrors PencilFFTs' ``PencilFFTPlan(dims_global, transform, proc_dims,
    comm)``: the plan owns its chain of pencil configurations; use
    :meth:`allocate_input` / :meth:`allocate_output` (or build arrays on
    :attr:`input_pencil` / :attr:`output_pencil`) and call
    :meth:`forward` / :meth:`backward`.

    Normalization follows ``jnp.fft`` defaults: unnormalized forward,
    ``1/n``-scaled inverse, so ``backward(forward(u)) == u``.
    """

    def __init__(self, topology: Topology, global_shape: Sequence[int], *,
                 real: bool = False, dtype=None, permute: bool = True,
                 transform: str = "fft",
                 method: AbstractTransposeMethod = AllToAll()):
        if transform not in ("fft", "dct", "dst"):
            raise ValueError(f"transform must be 'fft', 'dct' or 'dst', "
                             f"got {transform!r}")
        self.transform = transform
        if transform in ("dct", "dst") and real:
            raise ValueError(
                f"real=True is implicit for transform={transform!r}")
        global_shape = tuple(int(n) for n in global_shape)
        N = len(global_shape)
        M = topology.ndims
        if M >= N:
            raise ValueError(
                f"topology ndims ({M}) must be < array ndims ({N}) so that "
                f"at least one dim is local per stage"
            )
        self.topology = topology
        self.shape_physical = global_shape
        self.real = real
        if dtype is None:
            dtype = (jnp.float32 if (real or transform in ("dct", "dst"))
                     else jnp.complex64)
        self.dtype_physical = jnp.dtype(dtype)
        if real and jnp.issubdtype(self.dtype_physical, jnp.complexfloating):
            raise ValueError("real=True requires a real input dtype")
        if transform in ("dct", "dst"):
            if jnp.issubdtype(self.dtype_physical, jnp.complexfloating):
                raise ValueError(
                    f"transform={transform!r} requires a real dtype")
            self.dtype_spectral = self.dtype_physical  # R2R: real throughout
        else:
            self.dtype_spectral = jnp.dtype(
                jnp.result_type(self.dtype_physical, jnp.complex64))
        self.method = method
        self.permute = permute

        # spectral global shape: r2c halves dim 0 (first transform dim);
        # R2R transforms preserve every extent
        if real:
            self.shape_spectral = (global_shape[0] // 2 + 1,) + global_shape[1:]
        else:
            self.shape_spectral = global_shape

        # Stage d transforms logical dim d.  Configuration for stage d:
        # dim d local, decomposition = the M dims "after" d cyclically —
        # stage 0 is the classic x-pencil (last M dims decomposed,
        # matching Pencil's default), and consecutive stages differ in
        # exactly one decomposition slot, so each hop is a single
        # all_to_all.
        self._pencils: List[Pencil] = []
        decomp = list(range(N - M, N))  # stage 0: last M dims
        for d in range(N):
            shape = self.shape_spectral if (real and d > 0) else global_shape
            perm = _stage_permutation(N, d, permute)
            self._pencils.append(
                Pencil(topology, shape, tuple(decomp), permutation=perm))
            # next stage: dim d+1 must become local; it is decomposed in
            # exactly one slot (if any) — swap d into that slot.
            if d + 1 < N:
                nxt = d + 1
                slot = decomp.index(nxt) if nxt in decomp else None
                if slot is not None:
                    decomp[slot] = d
        # spectral-side input pencil for stage 0 of the backward pass when
        # real=True (dim 0 local but halved global size)
        if real:
            self._pencil0_spec = Pencil(
                topology, self.shape_spectral, self._pencils[0].decomposition,
                permutation=self._pencils[0].permutation)
        else:
            self._pencil0_spec = self._pencils[0]

    # -- pencils ----------------------------------------------------------
    @property
    def pencils(self) -> Tuple[Pencil, ...]:
        """The chain of configurations (stage d has logical dim d local)."""
        return tuple(self._pencils)

    @property
    def input_pencil(self) -> Pencil:
        return self._pencils[0]

    @property
    def output_pencil(self) -> Pencil:
        """Configuration of the spectral (fully transformed) array."""
        last = self._pencils[-1]
        if self.real:
            return Pencil(self.topology, self.shape_spectral,
                          last.decomposition, permutation=last.permutation)
        return last

    def allocate_input(self, extra_dims: Tuple[int, ...] = ()) -> PencilArray:
        return PencilArray.zeros(self.input_pencil, extra_dims,
                                 self.dtype_physical)

    def allocate_output(self, extra_dims: Tuple[int, ...] = ()) -> PencilArray:
        return PencilArray.zeros(self.output_pencil, extra_dims,
                                 self.dtype_spectral)

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _mem_axis(pen: Pencil, d: int) -> int:
        """Memory-order axis index of logical dim ``d``."""
        return pen.permutation.apply(tuple(range(pen.ndims))).index(d)

    @staticmethod
    def _local_fft(pen: Pencil, data, extra_ndims: int, kind: str,
                   axis: int, n: int = 0):
        """Apply a 1-D transform along a *local* (unsharded) axis under
        ``shard_map``, so each device transforms its own block with zero
        communication.  Without this, GSPMD cannot partition the FFT op
        and inserts an all-gather of the full array per stage (observed:
        6 all-gathers in a 3-D forward plan) — the multi-chip killer.
        Stage callables are cached so eager (un-jitted) plans reuse the
        same function objects and hit JAX's dispatch cache.
        """
        return _stage_fn(pen, extra_ndims, kind, axis, n)(data)

    def _spectral_pencil_for(self, pen: Pencil) -> Pencil:
        """Same configuration, spectral global shape (r2c size change)."""
        if pen.size_global() == self.shape_spectral:
            return pen
        return Pencil(self.topology, self.shape_spectral, pen.decomposition,
                      permutation=pen.permutation)

    # -- transforms -------------------------------------------------------
    def forward(self, u: PencilArray) -> PencilArray:
        """Physical -> spectral: fft along dim 0 (rfft if ``real``), then
        for each further dim: transpose so it is local, fft."""
        if u.pencil != self.input_pencil:
            raise ValueError(
                f"input must live on plan.input_pencil "
                f"({self.input_pencil!r}), got {u.pencil!r}"
            )
        N = len(self.shape_physical)
        pen = self._pencils[0]
        axis = self._mem_axis(pen, 0)
        nd_extra = u.ndims_extra
        fwd_kind = self.transform
        if self.real:
            data = self._local_fft(pen, u.data, nd_extra, "rfft", axis)
            pen = self._pencil0_spec
        else:
            data = self._local_fft(
                pen, u.data.astype(self.dtype_spectral), nd_extra, fwd_kind,
                axis)
        x = PencilArray(pen, data.astype(self.dtype_spectral), u.extra_dims)
        for d in range(1, N):
            target = self._spectral_pencil_for(self._pencils[d])
            x = transpose(x, target, method=self.method)
            axis = self._mem_axis(target, d)
            data = self._local_fft(target, x.data, nd_extra, fwd_kind, axis)
            x = PencilArray(target, data, x.extra_dims)
        return x

    def backward(self, uh: PencilArray) -> PencilArray:
        """Spectral -> physical (inverse transforms, reverse chain)."""
        if uh.pencil != self.output_pencil:
            raise ValueError(
                f"input must live on plan.output_pencil "
                f"({self.output_pencil!r}), got {uh.pencil!r}"
            )
        N = len(self.shape_physical)
        nd_extra = uh.ndims_extra
        inv_kind = "i" + self.transform
        x = uh
        for d in range(N - 1, 0, -1):
            axis = self._mem_axis(x.pencil, d)
            data = self._local_fft(x.pencil, x.data, nd_extra, inv_kind,
                                   axis)
            x = PencilArray(x.pencil, data, x.extra_dims)
            target = self._spectral_pencil_for(self._pencils[d - 1])
            x = transpose(x, target, method=self.method)
        axis = self._mem_axis(x.pencil, 0)
        if self.real:
            n0 = self.shape_physical[0]
            data = self._local_fft(self._pencil0_spec, x.data, nd_extra,
                                   "irfft", axis, n0)
            # irfft output length n0 may exceed the padded extent rule for
            # dim 0 only if dim 0 is decomposed — it is local here, so the
            # shape is exact.
            data = data.astype(self.dtype_physical)
            return PencilArray(self._pencils[0], data, x.extra_dims)
        data = self._local_fft(x.pencil, x.data, nd_extra, inv_kind, axis)
        return PencilArray(self._pencils[0], data, x.extra_dims)

    # -- spectral helpers -------------------------------------------------
    def frequencies(self, d: int, *, spacing: float = 1.0):
        """Global frequency vector of logical dim ``d`` in CYCLES per
        unit for every transform kind (scale by ``2*pi`` for angular
        wavenumbers, as with ``fftfreq``): ``fftfreq``/``rfftfreq`` for
        Fourier plans; for ``transform='dct'`` mode ``j`` (the basis
        function ``cos(pi j (x+1/2)/n)``) has angular wavenumber
        ``pi j/(n spacing)``, i.e. ``j/(2 n spacing)`` cycles."""
        n = self.shape_physical[d]
        if self.transform == "dct":
            return jnp.arange(n) / (2.0 * n * spacing)
        if self.transform == "dst":
            # DST-II mode j is sin(pi (j+1) (x+1/2)/n): angular pi(j+1)/n
            return (jnp.arange(n) + 1.0) / (2.0 * n * spacing)
        if self.real and d == 0:
            return jnp.fft.rfftfreq(n, d=spacing)
        return jnp.fft.fftfreq(n, d=spacing)

    def wavenumbers(self):
        """Broadcast-shaped, sharded mode-number components of the OUTPUT
        pencil — one array per logical dim, non-singleton only at the
        dim's memory position, sharded along its mesh axis.  Values are
        ``frequencies(d) * n_d``: integer Fourier modes for fft/rfft
        plans; half-integer (j/2) / ((j+1)/2) mode numbers for dct/dst.
        The spectral analog of localgrid components; shared by the
        spectral models."""
        from jax.sharding import NamedSharding, PartitionSpec

        pen = self.output_pencil
        N = pen.ndims
        mem_ids = pen.permutation.apply(tuple(range(N)))
        ks = []
        for d in range(N):
            k = self.frequencies(d) * self.shape_physical[d]
            n_pad = pen.padded_global_shape[d]
            if n_pad != k.shape[0]:
                k = jnp.pad(k, (0, n_pad - k.shape[0]))
            pos = mem_ids.index(d)
            shape = [1] * N
            shape[pos] = n_pad
            k = k.reshape(shape)
            spec = [None] * N
            spec[pos] = pen.decomp_axis_name(d)
            k = jax.lax.with_sharding_constraint(
                k, NamedSharding(pen.mesh, PartitionSpec(*spec)))
            ks.append(k)
        return tuple(ks)

    def __repr__(self) -> str:
        kind = self.transform if self.transform != "fft" else (
            "rfft" if self.real else "fft")
        return (
            f"PencilFFTPlan({kind}, shape={self.shape_physical}, "
            f"topo={self.topology.dims}, permute={self.permute})"
        )
