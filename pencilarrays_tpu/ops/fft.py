"""Distributed N-D FFT over pencil decompositions — the PencilFFTs proof.

The reference library exists to power PencilFFTs.jl (``README.md:29-31``):
a multidimensional FFT decomposes into per-dimension transforms, each
applied while that dimension is *local*, with global transposes in
between — the x->y->z pencil cycle (``docs/src/Transpositions.md:7-16``).
This module is that layer rebuilt TPU-first:

* **per-dimension transforms** (the PencilFFTs ``Transforms`` taxonomy:
  ``FFT``, ``RFFT``, ``R2R`` DCT/DST, ``NoTransform``): pass
  ``transforms=("rfft", "fft", "none")`` and each dim carries its own
  kind, with per-stage dtypes and global shapes derived at plan time;
* **local-dim batching**: the plan is compiled into a static *schedule*
  at construction — at every stage ALL still-pending dims that are local
  there are transformed in ONE native XLA FFT op (``jnp.fft.rfftn`` /
  ``fftn`` over several axes).  On one chip the whole 3-D r2c transform
  is a single fused XLA FFT with zero transposes — raw-``jnp.fft``
  parity by construction; on a slab (1-D) topology it is two stages
  instead of three.  The reference applies strictly one 1-D FFTW call
  per dim; batching is the TPU-first re-design (XLA's FFT kernels are
  multi-axis natively);
* between stages, the transpose engine's ``all_to_all`` exchanges ride
  ICI (``parallel/transpositions.py``); local transforms run under
  ``shard_map`` so GSPMD can never insert a hidden all-gather;
* **pipelined hops** (``pipeline=K | "auto"``): each eligible
  transpose+transform pair fuses into ONE program whose exchange is
  split into K statically-shaped chunks along a dim neither the
  exchange nor the stage's transforms touch — chunk ``k``'s collective
  has no data dependency on chunk ``k-1``'s FFT, so the latency-hiding
  scheduler overlaps wire time with compute (:func:`_fused_hop_fn`;
  the reference's ``waitall=false``/``Waitany`` pipeline and the
  overlapped redistribution of arXiv:1804.09536 / AccFFT, re-expressed
  for XLA).  K=1 is exactly the serialized schedule;
* with ``permute=True`` (default, like PencilFFTs' ``permute_dims``)
  each stage's pencil permutation places the stage's transform dim
  *last in memory*, where the FFT is contiguous;
* the whole plan is traceable: ``jit(plan.forward)`` fuses transforms,
  packing and collectives into one XLA program.

Transform dims are exact-size at their stage (a local dim is never
padded), so tail padding on *other* dims stays inert garbage, masked as
usual downstream.

Ordering constraint (PencilFFTs convention: the real transform comes
first): ``rfft``/``dct``/``dst`` act on *real* data, so on a distributed
mesh they must appear at stage indices before any ``fft`` dim has made
the data complex; violations raise at plan construction.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray, _fwd_axes, _inv_axes
from ..parallel.pencil import LogicalOrder, MemoryOrder, Pencil
from ..parallel.topology import Topology
from ..parallel.transpositions import (
    AllToAll,
    AbstractTransposeMethod,
    Auto,
    Pipelined,
    Ring,
    _chunk_bounds,
    _exchange_factory,
    _exchange_operand_extents,
    _maybe_pallas_transpose,
    _pipeline_chunk_axis,
    assert_compatible,
    resolve_method,
    transpose,
)
from ..utils.jaxcompat import shard_map
from ..utils.permutations import Permutation

__all__ = ["CompiledPlan", "PencilFFTPlan"]

_KINDS = ("fft", "rfft", "dct", "dst", "none")


def _alt_signs(blk, axis):
    # (-1)^j along the transform axis, broadcast-shaped
    shape = [1] * blk.ndim
    shape[axis] = blk.shape[axis]
    j = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis)
    return jnp.where(j % 2 == 0, 1.0, -1.0).astype(blk.dtype)


def _dst(blk, axis):
    # DST-II(x) = reverse(DCT-II(x * (-1)^j))  (ortho norm; verified
    # against scipy.fft.dst) — jax.scipy has no native dst
    from jax.scipy import fft as jsfft

    return jnp.flip(
        jsfft.dct(blk * _alt_signs(blk, axis), axis=axis, norm="ortho"),
        axis=axis)


def _idst(blk, axis):
    # inverse: IDST-II(y) = (-1)^j * IDCT-II(reverse(y))
    from jax.scipy import fft as jsfft

    out = jsfft.idct(jnp.flip(blk, axis=axis), axis=axis, norm="ortho")
    return out * _alt_signs(out, axis)


def _stage_op(ops: tuple, inverse: bool, pre_complex: bool, norm: str):
    """Pure per-block batched local-transform callable (no sharding
    machinery) — the compute body of a schedule step, shared by
    :func:`_stage_fn` (whole-block, own ``shard_map``) and
    :func:`_fused_hop_fn` (applied per chunk inside the fused hop's
    ``shard_map``, where it composes with the chunked exchange).

    ``ops`` is a tuple of ``(kind, mem_axis, n_logical)`` — every
    transform applied at this stage, all along axes that are local
    (unsharded) in the stage pencil.
    """
    from jax.scipy import fft as jsfft

    r2r = tuple(op for op in ops if op[0] in ("dct", "dst"))
    four = tuple(op for op in ops if op[0] in ("fft", "rfft"))
    rf = tuple(op for op in four if op[0] == "rfft")
    cax = tuple(ax for k, ax, n in four if k == "fft")
    # Fourier-dim normalization (r2r kinds are always ortho).  The
    # scaling is applied HERE with weak-typed python floats, never via
    # jnp.fft's ``norm=``: jnp's norm path materializes the factor as an
    # f64 array under jax_enable_x64, promoting c64 data to c128 — which
    # TPU does not support at all.  Bare transforms (norm=None) are
    # "backward" semantics: unscaled forward, 1/P inverse; the factors
    # below move between conventions (P = product of this stage's
    # logical Fourier extents; factors multiply across stages to the
    # full-transform convention).
    P_stage = 1.0
    for k, ax, n in four:
        P_stage *= float(n)
    fwd_scale = {"backward": 1.0, "none": 1.0, "forward": 1.0 / P_stage,
                 "ortho": P_stage ** -0.5}[norm]
    inv_scale = {"backward": 1.0, "none": P_stage, "forward": P_stage,
                 "ortho": P_stage ** 0.5}[norm]

    if not inverse:
        def op(blk):
            for k, ax, n in r2r:
                blk = (jsfft.dct(blk, axis=ax, norm="ortho") if k == "dct"
                       else _dst(blk, ax))
            if rf:
                # rfftn transforms its LAST listed axis real-to-complex
                blk = jnp.fft.rfftn(blk, axes=cax + (rf[0][1],))
            elif cax:
                blk = jnp.fft.fftn(blk, axes=cax)
            if four and fwd_scale != 1.0:
                blk = blk * fwd_scale
            return blk
    else:
        def op(blk):
            if rf:
                _, ax, n = rf[0]
                s = tuple(m for k, a, m in four if k == "fft") + (n,)
                blk = jnp.fft.irfftn(blk, s=s, axes=cax + (ax,))
            elif cax:
                blk = jnp.fft.ifftn(blk, axes=cax)
            if four and inv_scale != 1.0:
                blk = blk * inv_scale
            if not pre_complex and jnp.iscomplexobj(blk):
                # forward promoted real->complex here; the spectrum is
                # conjugate-symmetric, imag is numerically zero
                blk = blk.real
            for k, ax, n in reversed(r2r):
                blk = (jsfft.idct(blk, axis=ax, norm="ortho") if k == "dct"
                       else _idst(blk, ax))
            return blk

    return op


@lru_cache(maxsize=512)
def _stage_fn(pen: Pencil, extra_ndims: int, ops: tuple, inverse: bool,
              pre_complex: bool, norm: str):
    """Cached batched local-transform callable for one schedule step
    (:func:`_stage_op` body).  Runs under ``shard_map`` so each device
    transforms its own block with zero communication: without this,
    GSPMD cannot partition the FFT op and inserts an all-gather of the
    full array per stage (observed: 6 all-gathers in a 3-D forward
    plan) — the multi-chip killer.  Caching lets eager (un-jitted)
    plans reuse function objects and hit JAX's dispatch cache.
    """
    op = _stage_op(ops, inverse, pre_complex, norm)
    if math.prod(pen.mesh.devices.shape) == 1:
        return op
    spec = pen.partition_spec(extra_ndims)
    # check_vma=False: with the static varying-mesh-axes check on, the
    # FFT primitive's TRANSPOSE rule rejects vma-tagged cotangents
    # ("cotangent type does not match function output"), breaking
    # jax.grad through any multi-chip plan.  The stage is trivially
    # per-device data-parallel (in_specs == out_specs, no collectives),
    # so the check buys nothing here; differentiability is pinned by
    # tests/test_autodiff.py.
    return shard_map(op, mesh=pen.mesh, in_specs=spec, out_specs=spec,
                         check_vma=False)


def _pipeline_sweep_verdict(platform: str = None):
    """Measured verdict of the pipelined-hop sweep
    (``PIPELINE_SWEEP.json`` at the repo root, written by
    ``benchmarks/pipeline_sweep.py``; path override via
    ``PENCILARRAYS_TPU_PIPELINE_SWEEP_PATH``, mtime-invalidated) — the
    same routing discipline as the flash kernels: ``pipeline="auto"``
    follows a measured ``best_k`` when one exists.  ``None`` when no
    sweep has been captured yet, and ``None`` when the artifact was
    captured on a DIFFERENT platform than ``platform`` (the plan's OWN
    mesh platform, not the process default backend — a plan on the CPU
    virtual mesh of a TPU host must follow CPU numbers and vice versa;
    a CPU sweep measures chunking overhead, not overlap, and must not
    route TPU plans).  The sweep records ``platform`` for exactly this
    check."""
    from ..utils.artifacts import load_verdict_artifact

    doc = load_verdict_artifact("PIPELINE_SWEEP.json",
                                "PENCILARRAYS_TPU_PIPELINE_SWEEP_PATH")
    if not isinstance(doc, dict):
        return None
    captured = doc.get("platform")
    if platform is None:
        platform = jax.default_backend()
    if captured is not None and captured != platform:
        return None
    return doc.get("verdict")


# literature default for pipeline="auto" with no measured verdict: deep
# enough to hide most wire time behind per-chunk transforms, shallow
# enough that per-collective launch overhead stays amortized
# (arXiv:1804.09536 tables 2-4 land at 2-8 pipeline stages)
_PIPELINE_AUTO_DEFAULT_K = 4


@lru_cache(maxsize=512)
def _fused_hop_fn(src: Pencil, tgt: Pencil, post: Pencil,
                  extra_ndims: int, ops: tuple, inverse: bool,
                  pre_complex: bool, norm: str,
                  base: AbstractTransposeMethod,
                  chunk_dim: int, bounds: tuple, donate: bool = False,
                  _pallas: bool = False):
    """Compiled FUSED transpose+transform hop — the tentpole pipeline.

    The serialized schedule runs hop ``src -> tgt`` as one monolithic
    exchange, then the stage's batched 1-D transforms: a hard barrier
    the latency-hiding scheduler cannot break (the collective is a
    single op).  Here the hop is ONE ``shard_map`` program that chunks
    the block along logical dim ``chunk_dim`` (untouched by both the
    exchange pair and the stage's transform dims — precomputed at plan
    time with static ``bounds``) and, per chunk, runs
    exchange -> unpack -> transform.  Chunk ``k``'s exchange has NO data
    dependency on chunk ``k-1``'s transform (pinned on the jaxpr by
    ``tests/test_overlap.py``), so XLA's scheduler is free to hide each
    chunk's wire time behind the previous chunk's VPU/MXU work — the
    TPU re-expression of the reference's ``Isend``/``Waitany`` unpack
    pipeline (``Transpositions.jl:142-158``) and of the overlapped
    redistribution in arXiv:1804.09536 / AccFFT (arXiv:1506.07933).

    ``inverse=True`` is the mirrored program for :meth:`backward`:
    per chunk, inverse-transform -> pack -> reverse exchange — the
    exchange of chunk ``k`` is independent of chunk ``k+1``'s inverse
    transform, so the same overlap holds in the other direction.

    Numerics: transforms act along whole, untouched axes, so chunking
    commutes with them exactly; results match the serialized schedule
    (bit-identical data movement, identical per-element transform).
    """
    R = assert_compatible(src, tgt)
    axis = src.topology.axis_names[R]
    P = src.topology.dims[R]
    a = src.decomposition[R]  # decomposed in src, local in tgt
    b = tgt.decomposition[R]  # local in src, decomposed in tgt
    n_a = src.size_global()[a]
    n_b = src.size_global()[b]
    op = _stage_op(ops, inverse, pre_complex, norm)
    mesh = src.mesh
    # per-chunk unpack permute goes through the same opt-in Pallas tiled
    # kernel as the serialized path's unpack (_exchange_transpose);
    # _pallas rides the cache key only, so a toggled env flag cannot
    # reuse a stale executable (the _compiled_transpose convention)
    platform = mesh.devices.flat[0].platform

    if not inverse:
        b_pad = tgt.padded_global_shape[b]
        inv_in = _inv_axes(src, extra_ndims)    # src memory -> logical
        fwd_out = _fwd_axes(tgt, extra_ndims)   # logical -> tgt memory
        exchange = _exchange_factory(base, src, tgt)(axis, P, a, b)
        in_spec = src.partition_spec(extra_ndims)
        out_spec = post.partition_spec(extra_ndims)
        mem_c = fwd_out.index(chunk_dim)

        def local_fn(block):
            with jax.named_scope("pack_data"):
                x = jnp.transpose(block, inv_in)
                if b_pad != n_b:
                    pad = [(0, 0)] * x.ndim
                    pad[b] = (0, b_pad - n_b)
                    x = jnp.pad(x, pad)
            parts = []
            for s0, s1 in bounds:
                xc = jax.lax.slice_in_dim(x, s0, s1, axis=chunk_dim)
                with jax.named_scope("exchange"):
                    y = exchange(xc)
                with jax.named_scope("unpack_data"):
                    if y.shape[a] != n_a:
                        y = jax.lax.slice_in_dim(y, 0, n_a, axis=a)
                    y = _maybe_pallas_transpose(y, fwd_out, platform)
                with jax.named_scope("stage_compute"):
                    parts.append(op(y))
            return jnp.concatenate(parts, axis=mem_c)
    else:
        a_pad = src.padded_global_shape[a]
        inv_post = _inv_axes(tgt, extra_ndims)  # tgt memory -> logical
        fwd_src = _fwd_axes(src, extra_ndims)   # logical -> src memory
        # reverse hop tgt -> src: split dim a, concat dim b
        exchange = _exchange_factory(base, tgt, src)(axis, P, b, a)
        in_spec = post.partition_spec(extra_ndims)
        out_spec = src.partition_spec(extra_ndims)
        mem_c_in = _fwd_axes(post, extra_ndims).index(chunk_dim)
        mem_c_out = fwd_src.index(chunk_dim)

        def local_fn(block):
            parts = []
            for s0, s1 in bounds:
                blk = jax.lax.slice_in_dim(block, s0, s1, axis=mem_c_in)
                with jax.named_scope("stage_compute"):
                    y = op(blk)
                with jax.named_scope("pack_data"):
                    y = jnp.transpose(y, inv_post)
                    if a_pad != n_a:
                        pad = [(0, 0)] * y.ndim
                        pad[a] = (0, a_pad - n_a)
                        y = jnp.pad(y, pad)
                with jax.named_scope("exchange"):
                    y = exchange(y)
                with jax.named_scope("unpack_data"):
                    if y.shape[b] != n_b:
                        y = jax.lax.slice_in_dim(y, 0, n_b, axis=b)
                    parts.append(
                        _maybe_pallas_transpose(y, fwd_src, platform))
            return jnp.concatenate(parts, axis=mem_c_out)

    # check_vma=False for the same reason as _stage_fn: the FFT
    # primitive's transpose rule rejects vma-tagged cotangents, and the
    # fused hop must stay differentiable end to end.
    fn = shard_map(local_fn, mesh=mesh, in_specs=in_spec,
                   out_specs=out_spec, check_vma=False)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _stage_permutation(ndims: int, d: int, permute: bool):
    """Permutation placing logical dim ``d`` last in memory order."""
    if not permute:
        return None
    others = tuple(i for i in range(ndims) if i != d)
    return Permutation(others + (d,))


def _legacy_chain(N: int, M: int) -> List[Tuple[int, ...]]:
    """The classic x->y->z decomposition chain (reference
    ``docs/src/Transpositions.md:7-16``): stage 0 decomposes the last M
    dims; stage d swaps dim d+1 out for dim d."""
    out = []
    dec = list(range(N - M, N))
    for d in range(N):
        out.append(tuple(dec))
        if d + 1 < N and (d + 1) in dec:
            dec[dec.index(d + 1)] = d
    return out


def _strand_pad(n: int, P: int) -> Tuple[int, int]:
    """(empty devices, padding elements) for extent ``n`` ceil-blocked
    over ``P`` devices."""
    if P <= 1 or n == 0:
        return (0, 0)
    b = -(-n // P)
    return (P - (-(-n // b)), b * P - n)


def _build_chain(topology: Topology, global_shape: Tuple[int, ...],
                 kinds: Tuple[str, ...]) -> List[Tuple[int, ...]]:
    """Extent-aware stage chain: choose each stage's ordered decomposition
    (slot ``i`` rides mesh axis ``i``) by dim extent.

    The reference fixes the chain shape-blind (``Pencils.jl:61-63`` plus
    the x->y->z convention); here a tiny DP searches all legal chains —
    stage ``d`` keeps dim ``d`` local (unless its transform is ``none``),
    consecutive stages differ in at most ONE slot (the single-``all_to_all``
    hop contract, ``assert_compatible``) — and minimises, lexicographically,
    (number of hops, stranded devices summed over stages, padding
    elements).  Extents account for post-``rfft`` shrinkage (dim ``p`` is
    ``n//2+1`` from stage ``p+1`` on), so the spectral stages of an
    asymmetric r2c plan no longer strand devices by decomposing the
    shrunken dim over the largest mesh axis (the round-2 dryrun's own
    empty-rank warning).  Ties resolve to the legacy chain, keeping
    symmetric plans bit-stable.
    """
    from itertools import permutations as _iperms

    N = len(global_shape)
    M = topology.ndims
    dims = topology.dims
    legacy = _legacy_chain(N, M)
    spectral = tuple(n // 2 + 1 if k == "rfft" else n
                     for n, k in zip(global_shape, kinds))

    def stage_cost(dec: Tuple[int, ...], s: int) -> Tuple[int, int]:
        strands = pad = 0
        for i, p in enumerate(dec):
            n = spectral[p] if p < s else global_shape[p]
            a, b = _strand_pad(n, dims[i])
            strands += a
            pad += b
        return strands, pad

    def states(d: int) -> List[Tuple[int, ...]]:
        # dim d must be local at stage d unless it is never transformed
        pool = [p for p in range(N) if p != d or kinds[d] == "none"]
        cands = [tuple(t) for t in _iperms(pool, M)]
        cands.sort(key=lambda t: t != legacy[d])  # legacy first: tie-break
        return cands

    # DP over stages; strict < keeps the first (legacy-most) optimum.
    prev = {st: ((0,) + stage_cost(st, 0), [st]) for st in states(0)}
    for d in range(1, N):
        nxt = {}
        for st in states(d):
            sc = stage_cost(st, d)
            best = None
            for pst, (c, path) in prev.items():
                ndiff = sum(x != y for x, y in zip(pst, st))
                if ndiff > 1:
                    continue  # would not be a single-slot hop
                cand = (c[0] + (1 if ndiff else 0), c[1] + sc[0],
                        c[2] + sc[1])
                if best is None or cand < best[0]:
                    best = (cand, path + [st])
            if best is not None:
                nxt[st] = best
        prev = nxt
    return min(prev.values(), key=lambda v: v[0])[1]


def _decomposition_candidates(nprocs: int, N: int, mode: str
                              ) -> List[Tuple[int, ...]]:
    """Admissible topology shapes for ``decomposition=`` on ``nprocs``
    devices and a rank-``N`` array: the 1-D slab ``(P,)`` (needs
    ``N > 1``) and every ordered 2-D pencil factorization ``(P1, P2)``
    with both factors > 1 (needs ``N > 2``).  ``(P, 1)``-shaped grids
    are slabs in costume, so the pencil family excludes them."""
    cands: List[Tuple[int, ...]] = []
    if mode in ("auto", "slab") and N > 1:
        cands.append((nprocs,))
    if mode in ("auto", "pencil") and N > 2:
        for p1 in range(2, nprocs):
            if nprocs % p1 == 0 and nprocs // p1 >= 2:
                cands.append((p1, nprocs // p1))
    return cands


def _iter_priced_hops(steps: tuple):
    """Yield ``(src, dst, hop_dtype, base, k_mult, chunk)`` for every
    exchange step of a static schedule — the ONE definition of the
    ``t``/``ft`` step-tuple unpacking shared by :meth:`PencilFFTPlan.
    collective_costs` (the HLO-pinned pricer) and
    :func:`_schedule_score` (the decomposition scorer), so the two can
    never diverge on chunk accounting.  ``base`` is ``None`` for a
    plain ``t`` hop (price it at the plan's method — ``transpose_cost``
    itself multiplies the count for a ``Pipelined`` method); for a
    fused ``ft`` hop it is the unwrapped AllToAll/Ring base whose
    chunking the fused program owns (``k_mult`` = chunk count, and
    ``chunk = (chunk_dim, bounds)`` carries the exact slicing for
    ``transpose_cost``'s per-chunk fp8 byte accounting)."""
    for step in steps:
        if step[0] == "t":
            # a 5-element "t" step carries a per-hop method override
            # (the ``hbm_limit`` chunk synthesis): yield it as the
            # base with k_mult=1 — ``transpose_cost`` itself owns a
            # Pipelined method's count multiplication
            yield step[1], step[2], step[3], (
                step[4] if len(step) > 4 else None), 1, None
        elif step[0] == "ft":
            (_, src, dst, hop_dtype, _post, _ops, _pc, base,
             c, bounds) = step
            yield src, dst, hop_dtype, base, len(bounds), (c, bounds)


def _schedule_score(plan: "PencilFFTPlan", extra_dims: Tuple[int, ...],
                    latency_bytes: int, drift_hops: dict) -> dict:
    """Bytes-equivalent score of one full forward schedule — the
    route-planner currency (``parallel/routing.py``): each collective
    launch costs ``latency_bytes`` bytes-equivalent, wire bytes count
    at face value scaled by the hop's observed drift ratio (the PR-4
    discipline — a hop measured at 2x its modeled time gets its bytes
    doubled), and a reduced-precision hop is charged its pack/unpack
    cast traffic (``wire.cast_score_bytes``, HBM-discounted) on top of
    its halved wire bytes.  Each hop is priced at the dtype AND extents
    the data carries at that point of the schedule, so post-``rfft``
    hops are charged the Hermitian-half block, and ``extra_dims`` folds
    the batch into every hop's bytes (count unchanged)."""
    from ..parallel.routing import trusted_drift
    from ..parallel.transpositions import (_hop_label, _method_wire,
                                           transpose_cost)
    from ..parallel.wire import cast_score_bytes

    method = plan.method
    if isinstance(method, Auto) and method.mode == "measure":
        # scoring must stay cheap and deterministic (the _try_fuse_hop
        # convention): decide from the analytic model, never benchmark.
        # replace() keeps every other field — the wire_dtype in
        # particular, or a measure-mode wire plan would be scored at
        # full-precision bytes
        from dataclasses import replace

        method = replace(method, mode="estimate")
    score = hops = total_bytes = total_count = 0
    for src, dst, hop_dtype, base, k_mult, chunk in _iter_priced_hops(
            plan._steps):
        if base is None:
            # plain hop: the plan's method, resolved quietly — probe
            # candidates must not journal auto.verdict records for
            # schedules that will never be built
            m = resolve_method(src, dst, extra_dims, hop_dtype, method,
                               _quiet=True)
        else:
            m = base  # fused hop: its program owns the chunking (k_mult)
        try:
            # chunk threads the fused slicing so fp8 hops price their
            # per-chunk scale payloads; the count is already multiplied
            # then, so k_mult must not double-apply
            cost = transpose_cost(src, dst, extra_dims, hop_dtype, m,
                                  chunk=chunk)
        except (TypeError, ValueError):
            continue  # unpriceable hop: score what the model can see
        if not cost:
            continue  # local permute / trivial axis: nothing on the wire
        drift = trusted_drift(drift_hops, _hop_label(src, dst, m, hop_dtype))
        count = sum(v["count"] for v in cost.values())
        nbytes = sum(v["bytes"] for v in cost.values())
        score += int(count * latency_bytes + nbytes * drift
                     + cast_score_bytes(nbytes, hop_dtype,
                                        _method_wire(m)))
        hops += 1
        total_bytes += nbytes
        total_count += count
    return {"score_bytes": score, "hops": hops,
            "predicted_bytes": total_bytes, "collectives": total_count}


def _resolve_decomposition(topology: Topology,
                           global_shape: Tuple[int, ...], mode: str,
                           plan_kwargs: dict,
                           extra_dims: Tuple[int, ...]):
    """Pick the cheapest slab/pencil topology for ``decomposition=``
    (arXiv:1804.09536's adaptive decomposition, wired to the validated
    cost model): enumerate the admissible 1-D (slab) and 2-D (pencil)
    shapes over the SAME devices, build each candidate's full static
    schedule (a probe plan — construction only, nothing compiles),
    price it with :func:`_schedule_score` (r2c shrinkage and the batch
    included, drift-corrected like the PR-4 route planner), and return
    ``(winning topology, verdict dict)``.  Ties resolve to fewer hops,
    then to the slab (shorter dims), then to dims order — deterministic,
    and a pure function of the static configuration on pods (drift
    correction is disabled there, see ``routing.trusted_drift_hops``)."""
    import warnings

    from ..parallel.routing import trusted_drift_hops

    devices = list(topology.mesh.devices.flat)
    N = len(global_shape)
    cands = _decomposition_candidates(len(devices), N, mode)
    if not cands:
        raise ValueError(
            f"decomposition={mode!r}: no admissible topology for "
            f"{len(devices)} device(s) over a rank-{N} array")
    method = plan_kwargs.get("method")
    latency = (method.latency_bytes if isinstance(method, Auto)
               else Auto().latency_bytes)
    drift_hops = trusted_drift_hops()
    scored = []
    for dims in cands:
        # Probe errors propagate untouched: the candidate enumeration
        # already guarantees M < N, so any ValueError out of probe
        # construction is a REAL configuration error (bad transforms
        # tuple, dtype mismatch, ...) that would raise identically on a
        # fixed topology — swallowing it here would misattribute it to
        # topology admissibility.
        with warnings.catch_warnings():
            # intermediates may strand ranks; the pricer charges their
            # padding and stranded candidates score worse — the warning
            # is the SCORE's job here (router convention)
            warnings.simplefilter("ignore")
            topo_c = Topology(dims, devices=devices)
            probe = PencilFFTPlan(topo_c, global_shape, _probe=True,
                                  **plan_kwargs)
        entry = _schedule_score(probe, extra_dims, latency, drift_hops)
        entry["dims"] = dims
        entry["family"] = "slab" if len(dims) == 1 else "pencil"
        entry["topology"] = topo_c
        scored.append(entry)
    scored.sort(key=lambda c: (c["score_bytes"], c["hops"],
                               len(c["dims"]), c["dims"]))
    win = scored[0]
    verdict = {
        "mode": mode,
        "winner": list(win["dims"]),
        "family": win["family"],
        "extra_dims": list(extra_dims),
        "drift_corrected": bool(drift_hops),
        "candidates": [
            {"dims": list(c["dims"]), "family": c["family"],
             "score_bytes": c["score_bytes"], "hops": c["hops"],
             "predicted_bytes": c["predicted_bytes"],
             "collectives": c["collectives"]}
            for c in scored],
    }
    return win["topology"], verdict


class PencilFFTPlan:
    """Plan for a distributed N-D transform with per-dimension kinds.

    Mirrors PencilFFTs' ``PencilFFTPlan(dims_global, transform, proc_dims,
    comm)``: the plan owns its chain of pencil configurations; use
    :meth:`allocate_input` / :meth:`allocate_output` (or build arrays on
    :attr:`input_pencil` / :attr:`output_pencil`) and call
    :meth:`forward` / :meth:`backward`.

    ``transforms`` (or a tuple passed as ``transform``) selects one of
    ``"fft" | "rfft" | "dct" | "dst" | "none"`` per dim — the PencilFFTs
    per-dimension ``Transforms`` tuple (``RFFT x FFT x FFT``,
    ``NoTransform``, R2R mixes).  The legacy spellings remain:
    ``real=True`` = ``("rfft", "fft", ...)``; ``transform="dct"`` =
    all-DCT.

    Normalization defaults to ``jnp.fft`` semantics — unnormalized
    forward, ``1/n``-scaled inverse, ``backward(forward(u)) == u`` —
    and is selectable via ``normalization`` ("backward" | "ortho" |
    "forward" | "none"); ``"none"`` is PencilFFTs' unnormalized-BFFT
    convention with :meth:`scale_factor`.  R2R kinds are
    ortho-normalized in every mode.

    ``pipeline`` selects hop pipelining: ``None``/``1`` keeps the
    serialized hop-then-transform schedule; an int ``K > 1`` fuses each
    eligible hop with its following transform stage into one program
    interleaving a K-chunked exchange with per-chunk transforms (the
    comm/compute overlap the monolithic exchange forbids — see
    :func:`_fused_hop_fn`); ``"auto"`` follows the measured sweep
    verdict (``PIPELINE_SWEEP.json``, ``benchmarks/pipeline_sweep.py``)
    when one exists, else a literature default of
    ``_PIPELINE_AUTO_DEFAULT_K``.  The chunk dim must be static: K is
    clamped per hop by the chunkable dim's local extent, and hops with
    nothing chunkable stay serialized.  Values and gradients are
    unchanged for every K (test-pinned); only scheduling differs.

    ``batch=B`` declares a **batched throughput plan**: B independent
    transforms share this ONE exchange schedule, riding each hop's
    single collective together (bytes xB, collective count x1 — the
    per-collective latency amortization of AccFFT/arXiv:1804.09536's
    many-transform mode).  :meth:`allocate_input`,
    :meth:`allocate_output`, :meth:`compile` and
    :meth:`collective_costs` default to ``extra_dims=(B,)``;
    ``plan.compile()`` is then ONE jitted program computing all B
    transforms per dispatch, bit-identical to a per-sample loop (or
    ``vmap``) over the same plan.  Headline metric: transforms/sec at
    fixed mesh (``benchmarks/throughput.py``, ``BENCH_THROUGHPUT.json``).

    ``wire_dtype="bf16" | "f16" | "fp8_e4m3" | "fp8_e5m2"`` (default
    ``None`` = full precision, bit-identical to today) opts every
    exchange hop into the reduced-precision wire format: payloads are
    cast-packed to the wire dtype immediately before each collective
    and restored immediately after, inside the same jitted/shard_map
    program, so XLA fuses the casts into the exchange boundaries and
    the collective itself moves half the bytes on a 16-bit wire, a
    quarter plus the per-tile scale toll on fp8 (f32/c64 payloads;
    complex hops split-complex pack; fp8 block-scales per 256-element
    tile with the scales riding the same exchange — see
    ``docs/WirePrecision.md`` for the accuracy model and the guard's
    typed :class:`~pencilarrays_tpu.guard.errors.
    WirePrecisionError` tolerance contract).  Transform math stays full
    precision.  Priced end-to-end: ``collective_costs`` reports the
    halved wire bytes (still HLO-pinned), ``plan_key()`` fingerprints
    the wire dtype (mixed-precision serve traffic never coalesces
    together), and ``Auto``/``decomposition="auto"``/the reshard route
    planner select with it.

    ``hbm_limit`` bounds every exchange hop's static per-chip peak-HBM
    footprint at the plan's :attr:`batch_dims` (memory-bounded
    redistribution, arXiv:2112.01075 — the reshard route planner's
    chunked-edge synthesis applied to the plan's own schedule): a hop
    whose chunk-aware footprint busts the limit is rewritten at
    construction into a time-sliced variant — a fused ``"ft"`` hop
    re-chunks until its footprint fits, a plain ``"t"`` hop gains a
    per-hop ``Pipelined`` method override (count ×K, bytes unchanged,
    bit-identical to the unbounded schedule).  A hop that cannot fit
    (local permute over the limit, no chunkable dim, chunk extent
    exhausted, partitioner-owned collectives) raises a typed pre-flight
    :class:`~pencilarrays_tpu.analysis.errors.HbmBoundError` naming it
    — at construction, never mid-dispatch.  ``analysis.spmd.
    verify_hbm(plan, hbm_limit)`` re-certifies the same accounting
    post-hoc; ``compile()``-time ``extra_dims`` beyond ``batch_dims``
    are the caller's to re-certify.  (``decomposition="auto"`` scores
    candidates unbounded; the winner is then bounded.)

    ``decomposition="auto" | "slab" | "pencil"`` re-factorizes the
    topology's devices into the cheapest admissible process grid:
    every 1-D (slab) and 2-D (pencil) candidate's full schedule is
    priced by the validated cost model (r2c Hermitian-half extents and
    the batch included, drift-corrected like the reshard route
    planner), and the plan builds on the winner — 1804.09536's
    adaptive slab-vs-pencil selection.  The verdict (per-candidate
    scores included) is exposed as :attr:`decomposition_verdict`,
    journaled in ``plan.build`` and counted as
    ``plan.decomposition{verdict=slab|pencil}``.  ``None`` (default)
    keeps the passed topology untouched.
    """

    def __init__(self, topology: Topology, global_shape: Sequence[int], *,
                 real: bool = False, dtype=None, permute: bool = True,
                 transform="fft", transforms: Sequence[str] = None,
                 method: AbstractTransposeMethod = AllToAll(),
                 normalization: str = "backward",
                 pipeline=None, batch: Optional[int] = None,
                 decomposition: Optional[str] = None,
                 wire_dtype=None, hbm_limit: Optional[int] = None,
                 _probe: bool = False):
        global_shape = tuple(int(n) for n in global_shape)
        N = len(global_shape)
        # -- reduced-precision wire format --------------------------------
        # ``wire_dtype="bf16" | "f16" | "fp8_e4m3" | "fp8_e5m2"``
        # (default None = full precision, bit-identical) packs EVERY
        # exchange hop's payload down to the wire format immediately
        # before its collective and restores it after, inside the same
        # program (parallel/wire.py) — transform math and accumulation
        # stay full precision; only the wire narrows (bytes ÷2 on
        # 16-bit wires, ÷4 + per-tile scales on fp8, HLO-pinned).  The
        # plan's method carries it (with_wire), so pricing, execution,
        # plan_key() and the guard's tolerance model all see one truth.
        from ..parallel.transpositions import with_wire
        from ..parallel.wire import canonical_wire_dtype

        self.wire_dtype = canonical_wire_dtype(wire_dtype)
        method = with_wire(method, self.wire_dtype)
        if self.wire_dtype is None:
            from ..parallel.transpositions import _method_wire

            self.wire_dtype = _method_wire(method)
        # -- batched throughput mode --------------------------------------
        # ``batch=B`` declares B independent transforms sharing this ONE
        # exchange schedule: allocate_input/allocate_output/compile/
        # collective_costs default to extra_dims=(B,), so every hop's
        # single collective carries the whole batch (bytes xB, count x1
        # — per-collective latency amortized across the batch instead of
        # paid B times; HLO-pinned in tests/test_throughput.py).  The
        # schedule itself is batch-agnostic: forward/backward accept any
        # extra_dims, and results are bit-identical to a per-sample loop
        # (or vmap) over the same plan.
        if batch is not None and (isinstance(batch, bool)
                                  or not isinstance(batch, int)
                                  or batch < 1):
            raise ValueError(
                f"batch must be None or a positive int, got {batch!r}")
        self.batch = batch
        self.batch_dims: Tuple[int, ...] = (int(batch),) if batch else ()
        # probe plans (auto-decomposition candidates) must stay silent
        # end to end: no plan.build/guard registration (the early return
        # below) AND no auto.verdict journaling from schedule
        # construction itself (_try_fuse_hop resolves fused-hop bases)
        self._probe = bool(_probe)
        # -- slab-vs-pencil auto-decomposition ----------------------------
        # ``decomposition="auto" | "slab" | "pencil"`` re-factorizes the
        # given topology's DEVICES into the cheapest admissible 1-D/2-D
        # process grid, priced per candidate over the full schedule (r2c
        # shrinkage + batch included, drift-corrected) — see
        # :func:`_resolve_decomposition`.  ``None`` keeps the topology
        # exactly as passed.
        if decomposition is not None and decomposition not in (
                "auto", "slab", "pencil"):
            raise ValueError(
                f"decomposition must be None, 'auto', 'slab' or 'pencil', "
                f"got {decomposition!r}")
        self.decomposition = decomposition
        self.decomposition_verdict: Optional[dict] = None
        if decomposition is not None:
            topology, self.decomposition_verdict = _resolve_decomposition(
                topology, global_shape, decomposition,
                dict(real=real, dtype=dtype, permute=permute,
                     transform=transform, transforms=transforms,
                     method=method, normalization=normalization,
                     pipeline=pipeline),
                self.batch_dims)
        M = topology.ndims
        if M >= N:
            raise ValueError(
                f"topology ndims ({M}) must be < array ndims ({N}) so that "
                f"at least one dim is local per stage"
            )
        # -- resolve per-dim transform kinds ------------------------------
        if transforms is None and isinstance(transform, (tuple, list)):
            transforms = transform
            transform = "mixed"
        if transforms is not None:
            kinds = tuple(str(k).lower() for k in transforms)
            if len(kinds) != N:
                raise ValueError(
                    f"transforms has {len(kinds)} entries for a rank-{N} "
                    f"array")
            for k in kinds:
                if k not in _KINDS:
                    raise ValueError(
                        f"unknown transform kind {k!r}; expected one of "
                        f"{_KINDS}")
            if real:
                raise ValueError(
                    "real=True is implicit in per-dim transforms; spell the "
                    "real dim 'rfft'")
            transform = "mixed"
        else:
            if transform not in ("fft", "dct", "dst"):
                raise ValueError(f"transform must be 'fft', 'dct' or 'dst', "
                                 f"got {transform!r}")
            if transform in ("dct", "dst") and real:
                raise ValueError(
                    f"real=True is implicit for transform={transform!r}")
            if transform == "fft" and real:
                kinds = ("rfft",) + ("fft",) * (N - 1)
            else:
                kinds = (transform,) * N
        if kinds.count("rfft") > 1:
            raise ValueError("at most one dim may be 'rfft'")
        # Real-input kinds must precede any fft dim in STAGE order.  This
        # is validated upfront on the conceptual per-dim chain — not on
        # the batched schedule — so the same transforms tuple is accepted
        # or rejected identically on every topology (a slab mesh could
        # batch ("fft","rfft") into one real transform, but the plan must
        # not construct on one process grid and raise on another).
        complex_seen = False
        for d, k in enumerate(kinds):
            if k in ("rfft", "dct", "dst") and complex_seen:
                raise ValueError(
                    f"transform {k!r} on dim {d} would act on data an "
                    f"earlier 'fft' dim made complex; real-input kinds "
                    f"must come first in stage order")
            if k in ("fft", "rfft"):
                complex_seen = True
        self.transforms = kinds
        self.transform = transform  # legacy attribute
        self.real = "rfft" in kinds
        self.topology = topology
        self.shape_physical = global_shape
        self.method = method
        self.permute = permute
        # Fourier-dim normalization (PencilFFTs' fft normalization
        # taxonomy; its unnormalized-backward BFFT + scale_factor(plan)
        # convention is ``normalization="none"``): "backward" (default,
        # jnp semantics: bare forward, 1/n inverse), "ortho", "forward",
        # or "none" (bare BOTH ways; ``backward(forward(u)) ==
        # scale_factor() * u``).  R2R kinds (dct/dst) stay
        # ortho-normalized in every mode.
        if normalization not in ("backward", "ortho", "forward", "none"):
            raise ValueError(
                f"normalization must be 'backward', 'ortho', 'forward' or "
                f"'none', got {normalization!r}")
        self.normalization = normalization

        # -- dtypes -------------------------------------------------------
        needs_real = any(k in ("rfft", "dct", "dst") for k in kinds)
        if dtype is None:
            dtype = jnp.float32 if needs_real else jnp.complex64
        self.dtype_physical = jnp.dtype(dtype)
        is_cplx_in = jnp.issubdtype(self.dtype_physical, jnp.complexfloating)
        if needs_real and is_cplx_in:
            kr = next(k for k in kinds if k in ("rfft", "dct", "dst"))
            if self.real and transform != "mixed":
                raise ValueError("real=True requires a real input dtype")
            raise ValueError(f"transform {kr!r} requires a real dtype")
        if any(k in ("fft", "rfft") for k in kinds):
            self.dtype_spectral = jnp.dtype(
                jnp.result_type(self.dtype_physical, jnp.complex64))
        else:
            self.dtype_spectral = self.dtype_physical  # R2R/none: real

        self.shape_spectral = tuple(
            n // 2 + 1 if k == "rfft" else n
            for n, k in zip(global_shape, kinds))

        # -- stage configurations (decomp chain) --------------------------
        # Stage d has logical dim d local (unless kinds[d] == "none", in
        # which case the chain search may leave it decomposed to skip a
        # hop); consecutive stages differ in at most one decomposition
        # slot, so each hop is a single all_to_all.  The chain is chosen
        # extent-aware (see _build_chain).
        chain = _build_chain(topology, global_shape, kinds)
        cfgs = [(dec, _stage_permutation(N, d, permute))
                for d, dec in enumerate(chain)]

        # -- static schedule ----------------------------------------------
        # Walk the chain once at plan time; batch every pending dim that
        # is local at the current configuration.  A dim decomposed over a
        # size-1 mesh axis is local in every way that matters.
        def _is_local(pen: Pencil, p: int) -> bool:
            if p not in pen.decomposition:
                return True
            return topology.dims[pen.decomposition.index(p)] == 1

        shape = list(global_shape)
        pending = [d for d in range(N) if kinds[d] != "none"]
        is_complex = is_cplx_in
        steps: List[tuple] = []
        cur = Pencil(topology, tuple(shape), cfgs[0][0],
                     permutation=cfgs[0][1])
        self._input_pencil = cur
        for d in range(N):
            if not pending:
                break
            dec, perm = cfgs[d]
            if dec != cur.decomposition:
                tgt = Pencil(topology, tuple(shape), dec, permutation=perm)
                hop_dtype = (self.dtype_spectral if is_complex
                             else self.dtype_physical)
                steps.append(("t", cur, tgt, hop_dtype))
                cur = tgt
            if d != min(pending):
                continue  # path hop only; d's transform already applied
            batch = tuple(sorted(p for p in pending if _is_local(cur, p)))
            mem_ids = cur.permutation.apply(tuple(range(N)))
            ops = []
            for p in batch:
                k = kinds[p]
                # upfront stage-order validation guarantees real input here
                assert not (k in ("rfft", "dct", "dst") and is_complex)
                ops.append((k, mem_ids.index(p), shape[p]))
            pre = cur
            pre_complex = is_complex
            for p in batch:
                if kinds[p] == "rfft":
                    shape[p] = shape[p] // 2 + 1
            if any(kinds[p] in ("fft", "rfft") for p in batch):
                is_complex = True
            if tuple(shape) != pre.size_global():
                # A local transform never moves data: the post-stage
                # pencil must keep PRE's decomposition/permutation, not
                # this chain slot's.  (They differ when an elided hop
                # leaves the data in an earlier stage's configuration —
                # e.g. transforms=("none","rfft","fft") on a 1-D mesh,
                # where stage 1 executes in stage 0's memory order.)
                cur = Pencil(topology, tuple(shape), pre.decomposition,
                             permutation=pre.permutation)
            steps.append(("f", pre, cur, tuple(ops), pre_complex))
            pending = [p for p in pending if p not in batch]
        self._steps = tuple(steps)
        self._output_pencil = cur

        # -- pipelined hop fusion -----------------------------------------
        # ``pipeline=K`` rewrites every eligible ("t", ...) + ("f", ...)
        # pair into ONE fused ("ft", ...) step whose compiled program
        # interleaves a K-chunked exchange with per-chunk stage compute
        # (see _fused_hop_fn) — the overlap the serialized schedule's
        # hard hop/transform barrier forbids.  K=1 (and None) keeps the
        # serialized schedule unchanged; "auto" follows the measured
        # sweep verdict (PIPELINE_SWEEP.json) when one exists, else the
        # literature default of 4.
        if pipeline is not None and pipeline != "auto" and (
                not isinstance(pipeline, int) or pipeline < 1):
            raise ValueError(
                f"pipeline must be None, a positive int, or 'auto', got "
                f"{pipeline!r}")
        self.pipeline = pipeline
        if pipeline == "auto":
            verdict = _pipeline_sweep_verdict(
                topology.mesh.devices.flat[0].platform)
            try:
                k_req = int(verdict["best_k"]) if verdict else None
            except (TypeError, ValueError, KeyError):
                k_req = None  # malformed artifact must never break plans
            if k_req is None or k_req < 1:
                k_req = _PIPELINE_AUTO_DEFAULT_K
        else:
            k_req = int(pipeline) if pipeline is not None else 1
        self.pipeline_chunks = k_req
        if k_req > 1:
            self._steps = self._fuse_pipeline_steps(self._steps, k_req)

        # -- memory-bounded schedule synthesis ----------------------------
        # ``hbm_limit`` rewrites over-budget hops into time-sliced
        # variants (chunked fused hops / per-hop Pipelined overrides)
        # or fails typed at construction — see _bound_steps_hbm.
        self.hbm_limit = None
        if hbm_limit is not None:
            # same coercion as reshard()/plan_reshard_route: np.int64
            # from device-memory math is as good as a builtin int
            try:
                lim = (None if isinstance(hbm_limit, bool)
                       else int(hbm_limit))
            except (TypeError, ValueError):
                lim = None
            if lim is None or lim < 1:
                raise ValueError(
                    f"hbm_limit must be None or a positive int (bytes "
                    f"per chip), got {hbm_limit!r}")
            self.hbm_limit = lim
            self._steps = self._bound_steps_hbm(self._steps, lim)

        # conceptual full chain (stage d pencil at its pre-stage shape),
        # for introspection/tests; the schedule above may visit fewer.
        self._pencils: List[Pencil] = []
        sh = list(global_shape)
        for d in range(N):
            self._pencils.append(
                Pencil(topology, tuple(sh), cfgs[d][0],
                       permutation=cfgs[d][1]))
            if kinds[d] == "rfft":
                sh[d] = sh[d] // 2 + 1

        from .. import guard, obs

        self._plan_fp: Optional[str] = None
        if _probe:
            # candidate probe of the auto-decomposition search: priced
            # and discarded — it must neither journal nor register with
            # the guard's plan-fingerprint ring
            return
        if obs.enabled():
            obs.counter("fft.plans_built").inc()
            obs.counter(
                "plan.decomposition",
                verdict=(self.decomposition_verdict or {}).get(
                    "family", "fixed")).inc()
            # correlation: subsequent records (hops, faults, probes)
            # are stamped with this plan's fingerprint (obs/correlate)
            from ..obs import correlate

            correlate.set_plan(self._fingerprint())
            obs.record_event("plan.build", **self._obs_summary())
        if guard.enabled():
            # crash bundles carry the schedules of recently-built plans
            # (which compiled programs were in flight when things hung)
            guard.note_plan("fft_plan", self._obs_summary())

    def _fuse_pipeline_steps(self, steps: tuple, K: int) -> tuple:
        """Rewrite eligible hop+transform pairs into fused ``("ft", src,
        tgt, hop_dtype, post, ops, pre_complex, base, chunk_dim,
        bounds)`` steps.  A pair fuses when the hop is a real exchange
        (not a local permute), its method resolves to an explicit
        single-axis exchange (AllToAll/Ring — Gspmd hops stay
        serialized: the partitioner owns their collectives), and a
        chunkable logical dim exists that neither the exchange pair nor
        the stage's transform dims touch.  Ineligible pairs keep the
        serialized two-step schedule — ``pipeline=`` never changes what
        is computed, only how it is scheduled."""
        fused: List[tuple] = []
        i = 0
        while i < len(steps):
            s = steps[i]
            if (s[0] == "t" and i + 1 < len(steps)
                    and steps[i + 1][0] == "f"
                    and steps[i + 1][1] == s[2]):
                step = self._try_fuse_hop(s, steps[i + 1], K)
                if step is not None:
                    fused.append(step)
                    i += 2
                    continue
            fused.append(s)
            i += 1
        return tuple(fused)

    def _try_fuse_hop(self, t_step: tuple, f_step: tuple, K: int):
        _, src, tgt, hop_dtype = t_step
        _, pre, post, ops, pre_complex = f_step
        R = assert_compatible(src, tgt)
        if R is None or src.topology.dims[R] == 1:
            return None  # local permute: nothing on the wire to overlap
        method = self.method
        if isinstance(method, Auto) and method.mode == "measure":
            # plan construction must stay cheap and deterministic: the
            # fused base only needs a reasonable AllToAll/Ring pick, so
            # decide it from the analytic model rather than running
            # device benchmarks inside __init__ (measure-mode Auto
            # still times the plan's serialized "t" hops lazily, at
            # first transpose, as before).  replace() keeps the wire
            # dtype riding the downgraded resolution
            from dataclasses import replace

            method = replace(method, mode="estimate")
        # _quiet for probe plans: a discarded candidate's fused-hop
        # resolution must neither journal a phantom auto.verdict nor
        # poison the per-run dedup against the built plan's own verdict
        base = resolve_method(src, tgt, (), hop_dtype, method,
                              _quiet=self._probe)
        if isinstance(base, Pipelined):
            base = base.base  # the fused hop owns the chunking
        if not isinstance(base, (AllToAll, Ring)):
            return None  # Gspmd: collectives chosen by the partitioner
        a = src.decomposition[R]
        b = tgt.decomposition[R]
        N = src.ndims
        mem_ids = tgt.permutation.apply(tuple(range(N)))
        transform_dims = tuple(mem_ids[ax] for _, ax, _ in ops)
        # logical extents of the exchanged operand — the same shape the
        # cost model prices (shared helper, so they cannot diverge)
        ext = _exchange_operand_extents(src, tgt, R)
        c = _pipeline_chunk_axis(ext, a, b, exclude=transform_dims)
        if c is None:
            return None
        bounds = _chunk_bounds(ext[c], K)
        if len(bounds) <= 1:
            return None
        return ("ft", src, tgt, hop_dtype, post, tuple(ops), pre_complex,
                base, c, bounds)

    def _bound_steps_hbm(self, steps: tuple, limit: int) -> tuple:
        """Memory-bounded schedule synthesis (the reshard route
        planner's chunked-edge rule applied to the plan's own hops,
        arXiv:2112.01075): every exchange step whose chunk-aware
        peak-HBM footprint (``analysis.spmd.step_hop_peak`` — the ONE
        accounting shared with the router) busts ``limit`` at the
        plan's :attr:`batch_dims` is rewritten to a time-sliced
        variant, bit-identical to the original (chunking along an
        exchange-untouched dim commutes with the exchange; only the
        collective count multiplies).  A hop that cannot fit raises a
        typed pre-flight :class:`~pencilarrays_tpu.analysis.errors.
        HbmBoundError` naming it."""
        from ..analysis.errors import HbmBoundError
        from ..analysis.spmd import step_hop_peak

        extra = self.batch_dims
        out = []
        for idx, s in enumerate(steps):
            if s[0] not in ("t", "ft"):
                out.append(s)
                continue
            peak = step_hop_peak(s, extra, method=self.method,
                                 wire_dtype=self.wire_dtype)
            if peak <= limit:
                out.append(s)
                continue
            fixed = self._chunk_step_to_fit(s, extra, limit)
            if fixed is None:
                raise HbmBoundError(
                    "plan",
                    f"hop[{idx}] {s[1].decomposition}->"
                    f"{s[2].decomposition}", peak, limit)
            out.append(fixed)
        return tuple(out)

    def _chunk_step_to_fit(self, s: tuple, extra: tuple, limit: int):
        """Smallest time-slicing of ONE over-budget step that fits
        ``limit`` (K doubling from the current chunking, then the chunk
        dim's full extent), or ``None`` when nothing chunkable fits:
        fused ``"ft"`` steps re-chunk their own bounds; plain ``"t"``
        steps gain a per-hop ``Pipelined`` method override."""
        from ..analysis.spmd import step_hop_peak

        src, dst = s[1], s[2]
        R = assert_compatible(src, dst)
        if R is None or src.topology.dims[R] == 1:
            return None     # nothing on the wire to time-slice
        ext = _exchange_operand_extents(src, dst, R)

        def k_sweep(k0: int, n: int):
            k = k0
            while k < n:
                yield k
                k *= 2
            yield n          # maximal slicing: one row per chunk

        if s[0] == "ft":
            _, _, _, _, post, ops, pre_complex, base, c, bounds = s
            n = int(ext[c])
            for K in k_sweep(len(bounds) * 2, n):
                nb = _chunk_bounds(n, K)
                if len(nb) <= len(bounds):
                    continue
                cand = s[:9] + (nb,)
                if step_hop_peak(cand, extra) <= limit:
                    return cand
            return None
        # plain "t" hop: resolve the plan's method to a concrete base
        # (cheap + deterministic — the _try_fuse_hop convention) and
        # sweep Pipelined chunk factors over it
        hop_dtype = s[3]
        method = s[4] if len(s) > 4 else self.method
        if isinstance(method, Auto):
            if method.mode == "measure":
                from dataclasses import replace

                method = replace(method, mode="estimate")
            method = resolve_method(src, dst, extra, hop_dtype, method,
                                    _quiet=True)
        k0 = 2
        if isinstance(method, Pipelined):
            k0, method = method.chunks * 2, method.base
        if not isinstance(method, (AllToAll, Ring)):
            return None     # Gspmd: partitioner-owned, unboundable
        shape = tuple(ext) + tuple(extra)
        c = _pipeline_chunk_axis(shape, src.decomposition[R],
                                 dst.decomposition[R])
        if c is None:
            return None
        n = int(shape[c])
        for K in k_sweep(k0, n):
            if len(_chunk_bounds(n, K)) <= 1:
                continue
            cand = ("t", src, dst, hop_dtype,
                    Pipelined(chunks=K, base=method))
            if step_hop_peak(cand, extra) <= limit:
                return cand
        return None

    def plan_key(self) -> str:
        """Stable fingerprint of this plan's full static configuration
        — the PUBLIC registry/correlation key (12 hex chars of the
        sha256 over the canonical schedule summary, sorted-JSON
        encoded).

        Deterministic across processes and jax restarts: it hashes the
        *logical* configuration — global shape, per-dim transform
        kinds, input dtype, topology dims, method, normalization,
        pipeline chunks, batch, decomposition verdict, the hop-by-hop
        schedule with per-hop dtypes, and the predicted collective
        costs — never device ids, object identities or addresses, so
        two processes (or two tenants) that build the same plan compute
        the same key (subprocess-pinned in ``tests/test_serve.py``).

        Equal to the ``plan_fp`` stamped on journal records for this
        plan's dispatches, and a prefix of the crash bundle's
        ``schedule_sha256`` (both hash the same summary blob) — so the
        serve registry's keys, the obs timeline's correlation field and
        the guard's post-mortem fingerprints provably agree."""
        if self._plan_fp is None:
            from ..obs import correlate

            self._plan_fp = correlate.plan_fingerprint(self._obs_summary())
        return self._plan_fp

    def _fingerprint(self) -> str:
        """Correlation-stamp alias of :meth:`plan_key` (``plan_fp`` on
        journal records)."""
        return self.plan_key()

    def with_wire_dtype(self, wire_dtype) -> "PencilFFTPlan":
        """This schedule at a different wire precision — the serving
        plane's downgrade lever (``serve/service.py``): under pressure
        the gate swaps a sheddable tenant's plan for its
        bf16/fp8 variant at admission, so the coalescer key
        (:meth:`plan_key` — ``wire_dtype`` is part of the schedule
        identity), the batch pricer, the registry's compiled-variant
        cache and the dispatch log's wire-byte certification all see
        the cheaper wire automatically, with NO new code path.

        Reconstructs the plan from its own resolved attributes
        (topology, transforms, method with the old wire stripped,
        pipeline chunks, batch, hbm_limit), so the variant's schedule
        is the SAME schedule — only the exchange payloads narrow.
        ``wire_dtype=None`` variants of an unwired plan return
        ``self``; variants are cached per canonical spelling (the
        admission hot path must not rebuild a plan per request)."""
        from ..parallel.transpositions import strip_wire, with_wire
        from ..parallel.wire import canonical_wire_dtype

        wire = canonical_wire_dtype(wire_dtype)
        if wire == self.wire_dtype:
            return self
        cache = self.__dict__.setdefault("_wire_variant_cache", {})
        if wire in cache:
            return cache[wire]
        variant = PencilFFTPlan(
            self.topology, self.shape_physical,
            transforms=self.transforms, dtype=self.dtype_physical,
            permute=self.permute,
            method=with_wire(strip_wire(self.method), wire),
            normalization=self.normalization,
            pipeline=(self.pipeline_chunks
                      if self.pipeline_chunks > 1 else None),
            batch=self.batch, hbm_limit=self.hbm_limit)
        # an auto-decomposed parent resolved its topology before this
        # reconstruction; carry the verdict so the variant fingerprints
        # identically to a sibling built with the same decomposition=
        # argument (wire_dtype stays the ONLY plan_key difference)
        variant.decomposition = self.decomposition
        variant.decomposition_verdict = self.decomposition_verdict
        cache[wire] = variant
        return variant

    def _obs_summary(self) -> dict:
        """The ``plan.build`` journal payload: the static schedule and
        its predicted collective costs — what a post-mortem needs to
        know which program this run was executing."""
        from ..parallel.transpositions import _method_label

        steps = []
        for s in self._steps:
            if s[0] == "t":
                src, tgt, hop_dtype = s[1], s[2], s[3]
                entry = {"kind": "t",
                         "hop": f"{src.decomposition}->"
                                f"{tgt.decomposition}",
                         "dtype": str(jnp.dtype(hop_dtype))}
                if len(s) > 4:
                    # hbm_limit chunk override: part of the summary, so
                    # a memory-bounded plan fingerprints apart from its
                    # unbounded sibling (serve coalescing separates them)
                    entry["method"] = _method_label(s[4])
                steps.append(entry)
            elif s[0] == "ft":
                (_, src, tgt, hop_dtype, _post, ops, _pc, base, c,
                 bounds) = s
                steps.append({"kind": "ft",
                              "hop": f"{src.decomposition}->"
                                     f"{tgt.decomposition}",
                              "dtype": str(jnp.dtype(hop_dtype)),
                              "base": _method_label(base),
                              "chunk_dim": c, "chunks": len(bounds),
                              "transforms": [op[0] for op in ops]})
            else:
                _, pre, _post, ops, _pc = s
                steps.append({"kind": "f",
                              "transforms": [op[0] for op in ops]})
        try:
            costs = self.collective_costs()
        except (TypeError, ValueError):
            costs = {}  # e.g. a Gspmd plan: partitioner-owned collectives
        if self.decomposition_verdict is not None:
            decomp = {k: v for k, v in self.decomposition_verdict.items()
                      if k != "candidates"}
            decomp["n_candidates"] = len(
                self.decomposition_verdict["candidates"])
        else:
            decomp = {"mode": "fixed", "winner": list(self.topology.dims)}
        summary = {
            "shape": list(self.shape_physical),
            "transforms": list(self.transforms),
            # input dtype: single-device plans have no exchange steps
            # (whose per-hop dtypes would otherwise distinguish them),
            # and plan_key() must never collide c64 with c128 plans
            "dtype": str(jnp.dtype(self.dtype_physical)),
            "topo": list(self.topology.dims),
            "method": _method_label(self.method)
            if not isinstance(self.method, Auto)
            else f"Auto({self.method.mode})"
            + (f"[wire={self.method.wire_dtype}]"
               if self.method.wire_dtype else ""),
            "pipeline": self.pipeline_chunks,
            "normalization": self.normalization,
            # schema v3 (obs/schema.py): the batch the plan prices its
            # schedule at, and the slab/pencil decomposition verdict
            "extra_dims": list(self.batch_dims),
            "decomposition": decomp,
            "steps": steps,
            "predicted_costs": costs,
        }
        if self.wire_dtype is not None:
            # reduced-wire plans fingerprint apart from full-precision
            # siblings (serve coalescing must never mix the two); the
            # key is absent when the wire is off, so every historical
            # plan_key is byte-stable
            summary["wire_dtype"] = self.wire_dtype
        return summary

    # -- pencils ----------------------------------------------------------
    @property
    def pencils(self) -> Tuple[Pencil, ...]:
        """The chain of configurations.  Stage ``d`` has logical dim ``d``
        local, except that a dim whose transform is ``"none"`` may stay
        decomposed at its own stage (the extent-aware chain search elides
        the hop; see :func:`_build_chain`)."""
        return tuple(self._pencils)

    @property
    def input_pencil(self) -> Pencil:
        return self._input_pencil

    @property
    def output_pencil(self) -> Pencil:
        """Configuration of the spectral (fully transformed) array."""
        return self._output_pencil

    def collective_costs(self, extra_dims: Optional[Tuple[int, ...]] = None,
                         *, method: AbstractTransposeMethod = None) -> dict:
        """Predicted per-chip collective cost of ONE :meth:`forward`
        application (``{op: {"count", "bytes"}}``, the
        ``utils.hlo.collective_stats`` schema).  Each hop is priced by
        the analytic model (:func:`~pencilarrays_tpu.parallel.
        transpositions.transpose_cost`) at the dtype AND extents the
        data carries at that point of the schedule — post-``rfft`` hops
        are charged the Hermitian-half block.  ``extra_dims`` defaults
        to the plan's :attr:`batch_dims`: a batched plan prices its
        amortization honestly (bytes scale linearly in the batch, the
        collective COUNT does not — regression-pinned in
        ``tests/test_collective_costs.py``); pass ``()`` explicitly for
        the per-sample price.  :meth:`backward` costs the same (the hop
        shapes are symmetric).  Tests and the multichip dryrun pin this
        EQUAL to the compiled HLO's measured stats — the validated ICI
        byte model.  ``analysis.spmd.verify_plan`` proves the equality
        statically for any program (typed ``ScheduleMismatchError``
        naming the diverging op), and ``PlanService.certify()`` sweeps
        it over every resident executable pre-flight."""
        from ..parallel.transpositions import transpose_cost

        if extra_dims is None:
            extra_dims = self.batch_dims
        extra_dims = tuple(int(e) for e in extra_dims)
        method = method if method is not None else self.method
        total: dict = {}

        def add(src, dst, hop_dtype, m, chunk=None):
            # a fused hop's chunking rides the chunk kwarg: the count
            # multiplies by the chunk count, bytes stay whole on 16-bit
            # wires and sum per chunk on fp8 (each chunk packs its own
            # scale tensor) — same rule as the Pipelined branch of
            # transpose_cost, which owns it
            for op, c in transpose_cost(src, dst, extra_dims, hop_dtype,
                                        m, chunk=chunk).items():
                e = total.setdefault(op, {"count": 0, "bytes": 0})
                e["count"] += c["count"]
                e["bytes"] += c["bytes"]

        for src, dst, hop_dtype, base, k_mult, chunk in _iter_priced_hops(
                self._steps):
            if base is None:
                add(src, dst, hop_dtype, method)
                continue
            m = base if method is self.method else method
            if isinstance(m, Pipelined) and k_mult > 1:
                # the fused hop owns the chunking (chunk) — unwrap an
                # override so the count is not multiplied twice.  A
                # k_mult == 1 base is an hbm_limit "t"-hop Pipelined
                # override whose count transpose_cost multiplies itself
                m = m.base
            add(src, dst, hop_dtype, m,
                chunk=chunk if k_mult > 1 else None)
        return total

    def predicted_wire_bytes(self, extra_dims: Optional[Tuple[int, ...]]
                             = None) -> int:
        """Total predicted per-chip collective bytes of ONE forward (or
        backward) application — the scalar the engine dispatch log
        carries (``meta["wire_bytes"]``) and
        ``analysis.spmd.verify_dispatch_log`` re-checks against the
        plan's priced schedule, so a dispatch whose logged payload size
        disagrees with the schedule it claims to run fails typed.  With
        ``wire_dtype`` set this is the HALVED byte figure (the wire
        format is part of the price).  Cached per ``extra_dims`` on the
        plan instance: this is stamped on every async/serve dispatch,
        and the analytic pricing walk must not ride the hot dispatch
        path the executor exists to keep short."""
        if extra_dims is None:
            extra_dims = self.batch_dims
        key = tuple(int(e) for e in extra_dims)
        cache = self.__dict__.setdefault("_wire_bytes_cache", {})
        if key not in cache:
            cache[key] = sum(
                v["bytes"] for v in self.collective_costs(key).values())
        return cache[key]

    def allocate_input(self, extra_dims: Optional[Tuple[int, ...]] = None
                       ) -> PencilArray:
        """Zero physical-space input; ``extra_dims`` defaults to the
        plan's :attr:`batch_dims` (``(B,)`` for a ``batch=B`` plan)."""
        if extra_dims is None:
            extra_dims = self.batch_dims
        return PencilArray.zeros(self.input_pencil, extra_dims,
                                 self.dtype_physical)

    def allocate_output(self, extra_dims: Optional[Tuple[int, ...]] = None
                        ) -> PencilArray:
        """Zero spectral-space output; ``extra_dims`` defaults to the
        plan's :attr:`batch_dims`."""
        if extra_dims is None:
            extra_dims = self.batch_dims
        return PencilArray.zeros(self.output_pencil, extra_dims,
                                 self.dtype_spectral)

    def compile(self, extra_dims: Optional[Tuple[int, ...]] = None, *,
                donate: bool = False, _counters: bool = True
                ) -> "CompiledPlan":
        """Whole-plan fusion: ONE jitted program each for the full
        forward and the mirrored backward chain (:class:`CompiledPlan`).

        The eager :meth:`forward` interprets the static schedule from
        Python — one executable dispatch per hop/stage (~hundreds of µs
        each on a driver round trip).  The compiled plan traces the
        whole chain into a single XLA program, so per-hop Python
        dispatch disappears and the latency-hiding scheduler sees every
        exchange and every transform at once (the whole-program
        scheduling win of arXiv:1804.09536's fused transpose chains).
        Intermediates become compiler-owned buffers; ``donate=True``
        additionally donates the INPUT buffer to the program (the
        argument array becomes invalid after each call).

        Results are bit-identical to the eager schedule (same traced
        ops; test-pinned).  ``extra_dims`` defaults to the plan's
        :attr:`batch_dims`, so ``PencilFFTPlan(batch=B).compile()`` IS
        the batched executable: one program, one collective per hop,
        all B transforms riding it.  Compiled plans are cached per
        ``(extra_dims, donate)`` on the plan instance."""
        if extra_dims is None:
            extra_dims = self.batch_dims
        key = (tuple(int(e) for e in extra_dims), bool(donate))
        cache = self.__dict__.setdefault("_compiled_plans", {})
        hit = key in cache
        if not hit:
            cache[key] = CompiledPlan(self, key[0], donate=key[1])
        from .. import obs

        # _counters=False: a caller that does its OWN cache accounting
        # (the serve registry labels the same resolve cache="serve"
        # with a per-tenant dimension) suppresses the plan-level count
        # — one resolve must be one counted cache event, never two
        if _counters and obs.enabled():
            obs.counter(f"compile.cache_{'hits' if hit else 'misses'}",
                        cache="plan").inc()
        return cache[key]

    # -- transforms -------------------------------------------------------
    @staticmethod
    def _dispatch_fused(fn, x: PencilArray, hop_src: Pencil,
                        hop_tgt: Pencil, hop_dtype, base, bounds):
        """Dispatch one fused pipelined hop, journaling it when
        observability is on (same tap as standalone ``transpose`` —
        ``hop_src -> hop_tgt`` is the direction the wire actually moves
        data, so forward and backward price identically; eager
        dispatches only, like the transpose tap — under an outer jit
        this runs at trace time)."""
        import jax.core

        from .. import obs

        if not obs.enabled() or isinstance(x.data, jax.core.Tracer):
            return fn(x.data)
        import time as _time

        from ..parallel.transpositions import _obs_record_hop

        t0 = _time.perf_counter()
        data = fn(x.data)
        _obs_record_hop(hop_src, hop_tgt, assert_compatible(hop_src,
                                                            hop_tgt),
                        base, x.extra_dims, hop_dtype,
                        _time.perf_counter() - t0, fused_k=len(bounds))
        return data

    @staticmethod
    def _hop_donate(x: PencilArray, owned: bool) -> bool:
        """Donate a hop's input buffer when it is an intermediate this
        plan created (``owned``) and we are NOT tracing — under an outer
        ``jit`` the whole chain is one XLA program whose buffer reuse the
        compiler already owns, and an inner-jit donation hint would only
        warn.  Eagerly, per-hop donation lets XLA alias the exchange
        in/out buffers, the analog of the reference's in-place
        ``ManyPencilArray`` transposes (``multiarrays.jl:106-130``).
        Donation is live on CPU too (verified: buffers invalidate, no
        warnings), so the virtual-mesh tests exercise this path."""
        import jax.core

        return owned and not isinstance(x.data, jax.core.Tracer)

    def forward(self, u: PencilArray, *, donate: bool = False
                ) -> PencilArray:
        """Physical -> spectral: interpret the static schedule (batched
        local transforms + single-hop transposes).  ``donate=True``
        additionally donates the INPUT array's buffer to the first hop
        (``u`` becomes invalid, like ``transpose(donate=True)``);
        intermediates are always donated when running eagerly."""
        if u.pencil != self.input_pencil:
            raise ValueError(
                f"input must live on plan.input_pencil "
                f"({self.input_pencil!r}), got {u.pencil!r}"
            )
        from .. import obs

        if obs.enabled():
            # correlation: this dispatch's hop records carry the plan
            from ..obs import correlate

            correlate.set_plan(self._fingerprint())
        tap = self._guard_tap_pre(u)
        nd_extra = u.ndims_extra
        x = u
        owned = donate
        for step in self._steps:
            if step[0] == "t":
                x = transpose(x, step[2],
                              method=(step[4] if len(step) > 4
                                      else self.method),
                              donate=self._hop_donate(x, owned))
            elif step[0] == "ft":
                # fused pipelined hop: chunked exchange interleaved with
                # per-chunk stage compute in ONE program (_fused_hop_fn)
                (_, src, tgt, hop_dtype, post, ops, pre_complex, base,
                 chunk_dim, bounds) = step
                from .pallas_kernels import pallas_enabled

                fn = _fused_hop_fn(src, tgt, post, nd_extra, ops,
                                   False, pre_complex,
                                   self.normalization, base,
                                   chunk_dim, bounds,
                                   self._hop_donate(x, owned),
                                   pallas_enabled())
                data = self._dispatch_fused(fn, x, src, tgt, hop_dtype,
                                            base, bounds)
                x = PencilArray(post, data, x.extra_dims)
            else:
                _, pre, post, ops, pre_complex = step
                data = _stage_fn(pre, nd_extra, ops, False, pre_complex,
                                 self.normalization)(x.data)
                x = PencilArray(post, data, x.extra_dims)
            owned = True  # every step output is plan-owned
        if x.dtype != self.dtype_spectral:
            x = PencilArray(x.pencil, x.data.astype(self.dtype_spectral),
                            x.extra_dims)
        self._guard_tap_post(tap, "fft.forward", x)
        return x

    @staticmethod
    def _guard_tap_pre(u: PencilArray) -> bool:
        """Sampled finiteness boundary tap, input side (the "NaN born
        mid-FFT" detector): returns True when this eager call was
        sampled AND the input is wholly finite — the precondition the
        output check needs.  The input count is taken BEFORE the chain
        because ``donate=True`` invalidates the input buffer.  One
        cached env probe when the guard is off."""
        import jax.core

        from .. import guard

        if not guard.enabled() or isinstance(u.data, jax.core.Tracer) \
                or not guard.finite_tick():
            return False
        from ..guard import integrity as gi

        return gi.nonfinite_count(u.data) == 0

    @staticmethod
    def _guard_tap_post(tap: bool, label: str, x: PencilArray) -> None:
        """Output side of the sampled tap: a nonfinite value born across
        the transform chain raises a typed ``IntegrityError`` (journal
        ``guard.sdc``, crash bundle) instead of flowing downstream."""
        if not tap:
            return
        from ..guard import integrity as gi

        gi.report_nonfinite_birth(label, gi.nonfinite_count(x.data),
                                  ctx={"shape": list(x.pencil.size_global())})

    def backward(self, uh: PencilArray, *, donate: bool = False
                 ) -> PencilArray:
        """Spectral -> physical (inverse transforms, reverse schedule).
        ``donate`` as in :meth:`forward`."""
        if uh.pencil != self.output_pencil:
            raise ValueError(
                f"input must live on plan.output_pencil "
                f"({self.output_pencil!r}), got {uh.pencil!r}"
            )
        from .. import obs

        if obs.enabled():
            from ..obs import correlate

            correlate.set_plan(self._fingerprint())
        tap = self._guard_tap_pre(uh)
        nd_extra = uh.ndims_extra
        x = uh
        owned = donate
        for step in reversed(self._steps):
            if step[0] == "t":
                x = transpose(x, step[1],
                              method=(step[4] if len(step) > 4
                                      else self.method),
                              donate=self._hop_donate(x, owned))
            elif step[0] == "ft":
                # mirrored fused hop: per-chunk inverse transform, then
                # the reverse exchange — same overlap, other direction
                (_, src, tgt, hop_dtype, post, ops, pre_complex, base,
                 chunk_dim, bounds) = step
                from .pallas_kernels import pallas_enabled

                fn = _fused_hop_fn(src, tgt, post, nd_extra, ops,
                                   True, pre_complex,
                                   self.normalization, base,
                                   chunk_dim, bounds,
                                   self._hop_donate(x, owned),
                                   pallas_enabled())
                data = self._dispatch_fused(fn, x, tgt, src, hop_dtype,
                                            base, bounds)
                x = PencilArray(src, data, x.extra_dims)
            else:
                _, pre, post, ops, pre_complex = step
                data = _stage_fn(post, nd_extra, ops, True, pre_complex,
                                 self.normalization)(x.data)
                x = PencilArray(pre, data, x.extra_dims)
            owned = True
        if x.dtype != self.dtype_physical:
            x = PencilArray(x.pencil, x.data.astype(self.dtype_physical),
                            x.extra_dims)
        self._guard_tap_post(tap, "fft.backward", x)
        return x

    def forward_async(self, u: Optional[PencilArray] = None, *,
                      pack=None, engine=None, donate: bool = False):
        """Submit one forward transform as an ordered engine dispatch;
        returns its :class:`~pencilarrays_tpu.engine.StepFuture` — the
        step-as-future form (DaggerFFT's task-graph shape) an
        application loop pipelines with.

        Exactly one of ``u``/``pack``: ``u`` is a ready
        :class:`PencilArray` (dispatch only), ``pack`` is a zero-arg
        callable run on the engine's HOST pool returning the sample in
        the plan's global logical shape — built while the previous
        step's device program runs (double-buffered step pipelines:
        submit step *k+1*'s ``pack`` while *k* computes).  The consumer
        thread scatters it (``from_global``) and issues the transform
        chain, so device work never leaves the ordered queue.  Engine
        defaults to the process's shared one."""
        return self._submit_async("forward", u, pack, engine, donate)

    def backward_async(self, uh: Optional[PencilArray] = None, *,
                       pack=None, engine=None, donate: bool = False):
        """The mirrored :meth:`forward_async` (spectral -> physical;
        a ``pack`` callable returns the spectral-shape host sample)."""
        return self._submit_async("backward", uh, pack, engine, donate)

    def _submit_async(self, direction: str, u, pack, engine,
                      donate: bool):
        import numpy as np

        from ..engine import get_engine

        eng = engine if engine is not None else get_engine()
        if (u is None) == (pack is None):
            raise ValueError(
                f"{direction}_async needs exactly one of u= (a ready "
                f"PencilArray) or pack= (a host-pool operand builder)")
        fwd = direction == "forward"
        run_plan = self.forward if fwd else self.backward
        label = f"fft.{direction}:{self.plan_key()[:8]}"
        if pack is None:
            return eng.submit(lambda: run_plan(u, donate=donate),
                              label=label,
                              meta={"plan": self, "direction": direction,
                                    "extra_dims": u.extra_dims,
                                    "wire_dtype": self.wire_dtype,
                                    "wire_bytes": self.predicted_wire_bytes(
                                        u.extra_dims)})
        pen = self.input_pencil if fwd else self.output_pencil
        dt = self.dtype_physical if fwd else self.dtype_spectral
        base_ndim = len(self.shape_physical)
        # the pack form's batch is unknown until pack runs: the
        # dispatch's certification metadata is completed INSIDE run
        # (the engine's DispatchRecord holds this same dict and only
        # snapshots it into the log after run returns), so
        # verify_dispatch_log re-traces the program that actually
        # dispatched — never a false unbatched certification
        meta = {"plan": self, "direction": direction,
                "wire_dtype": self.wire_dtype}

        def run(host):
            host = np.asarray(host, dtype=dt)
            meta["extra_dims"] = tuple(host.shape[base_ndim:])
            meta["wire_bytes"] = self.predicted_wire_bytes(
                meta["extra_dims"])
            arr = PencilArray.from_global(
                pen, host, extra_ndims=host.ndim - base_ndim)
            # the scatter's buffer is plan-owned: donate it to the
            # first hop regardless of the caller's flag (there is no
            # caller-visible input array to invalidate)
            return run_plan(arr, donate=True)

        return eng.submit(run, pack=pack, label=label, meta=meta)

    def scale_factor(self) -> float:
        """Global normalization factor of a full round trip:
        ``backward(forward(u)) == scale_factor() * u``.  1 except for
        ``normalization="none"``, where it is the product of the
        transformed Fourier extents — the PencilFFTs ``scale_factor``
        convention for unnormalized (BFFT-style) plans."""
        if self.normalization != "none":
            return 1.0
        out = 1.0
        for n, k in zip(self.shape_physical, self.transforms):
            if k in ("fft", "rfft"):
                out *= float(n)
        return out

    # -- spectral helpers -------------------------------------------------
    @property
    def dtype_real(self):
        """Real dtype matching the plan's arithmetic (f32 for c64 etc.).
        Frequency/wavenumber components carry it so that spectral-
        coefficient products NEVER promote: under ``jax_enable_x64`` a
        default-f64 wavenumber times c64 data silently becomes c128 —
        which TPU does not support at all ("Element type C128")."""
        import numpy as np

        # host-side dtype math only: no device allocation per access
        return jnp.dtype(np.empty(0, np.dtype(self.dtype_spectral)
                                  ).real.dtype)

    def frequencies(self, d: int, *, spacing: float = 1.0):
        """Global frequency vector of logical dim ``d`` in CYCLES per
        unit for every transform kind (scale by ``2*pi`` for angular
        wavenumbers, as with ``fftfreq``): ``fftfreq``/``rfftfreq`` for
        Fourier dims; for ``'dct'`` mode ``j`` (the basis function
        ``cos(pi j (x+1/2)/n)``) has angular wavenumber
        ``pi j/(n spacing)``, i.e. ``j/(2 n spacing)`` cycles.  Returned
        in the plan's :attr:`dtype_real`."""
        n = self.shape_physical[d]
        k = self.transforms[d]
        rd = self.dtype_real
        if k == "none":
            raise ValueError(f"dim {d} has transform 'none': no frequencies")
        if k == "dct":
            return (jnp.arange(n) / (2.0 * n * spacing)).astype(rd)
        if k == "dst":
            # DST-II mode j is sin(pi (j+1) (x+1/2)/n): angular pi(j+1)/n
            return ((jnp.arange(n) + 1.0) / (2.0 * n * spacing)).astype(rd)
        if k == "rfft":
            return jnp.fft.rfftfreq(n, d=spacing).astype(rd)
        return jnp.fft.fftfreq(n, d=spacing).astype(rd)

    def wavenumbers(self, order: type = MemoryOrder):
        """Broadcast-shaped mode-number components of the OUTPUT pencil —
        one array per logical dim.  Values are ``frequencies(d) * n_d``:
        integer Fourier modes for fft/rfft dims; half-integer (j/2) /
        ((j+1)/2) mode numbers for dct/dst; zeros for 'none' dims (no
        modal meaning).  The spectral analog of localgrid components.

        ``order=MemoryOrder`` (default): non-singleton at each dim's
        memory position, padded and sharded along its mesh axis — for
        arithmetic against raw ``.data``.  ``order=LogicalOrder``:
        true-size, non-singleton at logical position ``d`` — for
        arithmetic against PencilArrays, whose broadcasting aligns raw
        operands to the logical shape (``parallel/arrays.py``)."""
        def mode_vector(d):
            # one definition serves both orders
            if self.transforms[d] == "none":
                return jnp.zeros(self.shape_spectral[d], self.dtype_real)
            return self.frequencies(d) * self.shape_physical[d]

        if order is LogicalOrder:
            ks = []
            N = len(self.shape_spectral)
            for d in range(N):
                shape = [1] * N
                shape[d] = self.shape_spectral[d]
                ks.append(mode_vector(d).reshape(shape))
            return tuple(ks)

        from jax.sharding import NamedSharding, PartitionSpec

        pen = self.output_pencil
        N = pen.ndims
        mem_ids = pen.permutation.apply(tuple(range(N)))
        ks = []
        for d in range(N):
            k = mode_vector(d)
            n_pad = pen.padded_global_shape[d]
            if n_pad != k.shape[0]:
                k = jnp.pad(k, (0, n_pad - k.shape[0]))
            pos = mem_ids.index(d)
            shape = [1] * N
            shape[pos] = n_pad
            k = k.reshape(shape)
            spec = [None] * N
            spec[pos] = pen.decomp_axis_name(d)
            k = jax.lax.with_sharding_constraint(
                k, NamedSharding(pen.mesh, PartitionSpec(*spec)))
            ks.append(k)
        return tuple(ks)

    def __repr__(self) -> str:
        return (
            f"PencilFFTPlan({'x'.join(self.transforms)}, "
            f"shape={self.shape_physical}, "
            f"topo={self.topology.dims}, permute={self.permute})"
        )


class CompiledPlan:
    """One-dispatch executables for a plan's full transform chains
    (built by :meth:`PencilFFTPlan.compile`).

    :meth:`forward` / :meth:`backward` each call ONE jitted program
    tracing the plan's whole schedule — hops, fused pipelined hops and
    batched local transforms included — so XLA owns every intermediate
    buffer and schedules the entire chain at once; Python dispatch is a
    single executable launch.  The first call of each direction traces
    and compiles (measure-mode ``Auto`` hops resolve then, as under any
    outer jit); subsequent calls hit the C++ dispatch cache.

    With ``donate=True`` the input array's buffer is donated to the
    program: the argument becomes invalid after each call (the
    ``transpose(donate=True)`` contract, program-wide).
    """

    def __init__(self, plan: PencilFFTPlan, extra_dims: Tuple[int, ...],
                 *, donate: bool = False):
        self.plan = plan
        self.extra_dims = tuple(extra_dims)
        self.donate = bool(donate)
        dn = (0,) if donate else ()
        # plan.forward/backward resolve via attribute lookup at trace
        # time (not captured), so instance-level instrumentation in
        # tests observes exactly one trace per direction
        self._fwd = jax.jit(
            lambda d: plan.forward(
                PencilArray(plan.input_pencil, d, self.extra_dims)).data,
            donate_argnums=dn)
        self._bwd = jax.jit(
            lambda d: plan.backward(
                PencilArray(plan.output_pencil, d, self.extra_dims)).data,
            donate_argnums=dn)

    def _check(self, u: PencilArray, pen, what: str) -> None:
        if u.pencil != pen:
            raise ValueError(
                f"input must live on plan.{what} ({pen!r}), got {u.pencil!r}")
        if u.extra_dims != self.extra_dims:
            raise ValueError(
                f"compiled for extra_dims={self.extra_dims}, got "
                f"{u.extra_dims} (compile() again for this batch shape)")

    def forward(self, u: PencilArray) -> PencilArray:
        """Physical -> spectral, one program dispatch."""
        self._check(u, self.plan.input_pencil, "input_pencil")
        return PencilArray(self.plan.output_pencil, self._fwd(u.data),
                           self.extra_dims)

    def backward(self, uh: PencilArray) -> PencilArray:
        """Spectral -> physical, one program dispatch."""
        self._check(uh, self.plan.output_pencil, "output_pencil")
        return PencilArray(self.plan.input_pencil, self._bwd(uh.data),
                           self.extra_dims)

    def __repr__(self) -> str:
        return (f"CompiledPlan({self.plan!r}, extra_dims={self.extra_dims}, "
                f"donate={self.donate})")
