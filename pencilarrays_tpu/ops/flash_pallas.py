"""Pallas TPU flash-attention kernel — the hot local op of both
sequence-parallel schemes, hand-tiled.

The XLA path (``models.attention.flash_attention``) streams k/v chunks
with a ``lax.scan``; each chunk's score block and exp round-trip through
HBM between scan steps.  This kernel keeps the whole inner loop —
``q @ k^T``, the running-max softmax statistics, and ``p @ v`` — in VMEM
across the key-block grid dimension, so the only HBM traffic is the
q/k/v/out blocks themselves (the FlashAttention tiling argument, mapped
onto the Mosaic pipeline: scores hit the MXU at (block_q x block_k),
statistics live in VMEM scratch carried across the innermost grid dim).

Where the permute kernel experiment concluded XLA owns *data movement*
(``pallas_kernels.py``), attention is the opposite regime — a
compute-dense fusion XLA will not synthesize from a scan — which is why
this kernel is worth having while the permute kernel is a demonstrator.

Layout contract: raw arrays shaped ``(S, H, *batch, D)`` (the attention
module's public layout); the wrapper folds to ``(H*B, S, D)`` for the
kernel grid ``(H*B, Sq-blocks, Skv-blocks)``.  Sequence lengths need NOT
divide the block sizes: both are padded and the kernel masks the key
tail by global position (same mask path as causal).  Causal masking is
start-aligned global-position, matching ``dense_attention``; the
offsets ride in SMEM, so they may be **traced** values — that is what
lets ring attention feed each round's rotating block position straight
into the kernel.

Two output modes:

* default — the normalized attention output (``acc / l``);
* ``partials=True`` — the raw flash statistics ``(m, l, acc)`` in the
  accumulator-carry convention (``m``/``l``: ``(H, B, Sq)``, ``acc``:
  ``(Sq, H, B, D)``, all f32; input must be the folded 4-D layout).
  Partial results from disjoint key sets merge exactly (the standard
  flash/“flash-decoding” combine), which is how the ring schedule
  accumulates one kernel call per round.

Differentiation: both modes have matching hand-tiled backwards.
:func:`pallas_flash_attention_bwd` rebuilds each score block from the
saved logsumexp (``return_stats=True`` residuals) and produces dq/dk/dv
in two passes (standard flash practice: the backward is itself a
streaming recompute, so only per-row statistics are stored);
``models.attention`` wires it as the ``custom_vjp`` of the public
``flash_attention`` routing.  :func:`pallas_flash_attention_bwd_partials`
runs the same two kernels against a GLOBAL logsumexp for one visited
key block — the per-round building block of the ring/zigzag schedules'
hand-tiled backward (``models.attention._ring_flash_pallas`` /
``_zigzag_flash_pallas``), where k/v rotate around the ring again and a
rotating dk/dv accumulator carries each block's gradient home.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["pallas_flash_attention", "pallas_flash_attention_bwd",
           "pallas_flash_attention_bwd_partials", "supported"]

_DEF_BLOCK_Q = 256
_DEF_BLOCK_K = 256
_NEG = float(jnp.finfo(jnp.float32).min) / 2  # matches attention._neg_value


def supported(sq: int, skv: int, d: int, dtype, *, q_offset=0, kv_offset=0,
              platform: Optional[str] = None) -> bool:
    """Whether the Pallas kernel handles this case.

    Requirements: f32/bf16 element type, a head dim that tiles the lane
    axis without pathological padding, and — on real accelerators —
    enough rows for the tiling to pay for itself (tiny shapes go through
    the XLA scan path, which XLA fuses fine).  Offsets may be traced
    (they live in SMEM); they are accepted here unconditionally and only
    the *public* ``flash_attention`` routing restricts them to static
    ints (its ``custom_vjp`` hashes them as nondiff arguments).
    """
    del q_offset, kv_offset
    dt = jnp.dtype(dtype)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    if d % 8 != 0 or d > 1024:
        return False
    if platform is None:
        platform = jax.default_backend()
    if platform not in ("tpu", "cpu"):
        return False  # native Mosaic is TPU-only; cpu runs interpret mode
    if platform != "cpu" and (sq < 128 or skv < 128):
        return False
    return True


def _flash_kernel(offs_ref, q_ref, k_ref, v_ref, *refs,
                  scale: float, causal: bool, skv: int, bq: int, bk: int,
                  nk: int, out_dtype, partials: bool,
                  return_stats: bool = False):
    if partials:
        acc_o, m_o, l_o, m_ref, l_ref, acc_ref = refs
    elif return_stats:
        o_ref, m_o, l_o, m_ref, l_ref, acc_ref = refs
    else:
        (o_ref, m_ref, l_ref, acc_ref) = refs
    q_off = offs_ref[0]
    kv_off = offs_ref[1]
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0]                                    # (bq, D)
        k = k_ref[0]                                    # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)

        tail_pad = skv % bk != 0
        if causal or tail_pad:
            cols = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)                 # local key index
            valid = cols < skv
            if causal:
                rows = q_off + i * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)             # global q position
                valid = jnp.logical_and(valid, rows >= kv_off + cols)
            s = jnp.where(valid, s, _NEG)

        m_prev = m_ref[:, :1]                           # (bq, 1)
        blk_m = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_m)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)                  # (bq, 1)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, D)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip blocks with no visible keys — the wedge above the
        # diagonal.  (Predication skips the FLOPs; the block fetch is
        # pipelined regardless.  Padded key tails are handled by the
        # ``cols < skv`` mask, not skipped: the last key block always
        # contains at least one real key.  The predicate may be traced —
        # offsets live in SMEM.)
        pl.when(q_off + (i + 1) * bq - 1 >= kv_off + j * bk)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        if partials:
            acc_o[0] = acc_ref[:]
            m_o[0] = m_ref[:, 0]
            l_o[0] = l_ref[:, 0]
        else:
            l = l_ref[:, :1]
            # a q row whose visible-key set is empty has l == 0; the
            # dense reference returns an unspecified finite value there —
            # keep it finite rather than 0/0
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_ref[:] / l).astype(out_dtype)
            if return_stats:
                m_o[0] = m_ref[:, 0]
                l_o[0] = l_ref[:, 0]


# imported lazily so module import never requires a Pallas-capable jax
pl = None


def _ensure_pallas():
    global pl
    if pl is None:
        from jax.experimental import pallas as _pl
        pl = _pl
    return pl


def _compiler_params(**kw):
    """Mosaic compiler params across jax versions (TPUCompilerParams was
    renamed CompilerParams)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _pad_to(x: jax.Array, axis: int, target: int) -> jax.Array:
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def pallas_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           causal: bool = False, q_offset=0, kv_offset=0,
                           block_q: int = _DEF_BLOCK_Q,
                           block_k: int = _DEF_BLOCK_K,
                           interpret: Optional[bool] = None,
                           partials: bool = False,
                           return_stats: bool = False):
    """Flash attention on ``(S, H, *batch, D)`` arrays as one Pallas
    kernel per (head x batch) slice.  See the module docstring for the
    VJP wiring and the ``partials`` output mode (which requires the
    folded 4-D ``(S, H, B, D)`` layout).  Offsets may be traced
    scalars.  Callers should gate on :func:`supported`.
    ``interpret=None`` auto-selects interpreter mode on CPU (the
    virtual-mesh test backend) and native Mosaic elsewhere.

    ``return_stats=True`` additionally returns the flash softmax
    statistics ``(m, l)`` in FOLDED row layout ``(H*B, Sq)`` (f32, q
    padding sliced off) — the residuals :func:`pallas_flash_attention_bwd`
    consumes; the return value becomes ``(out, (m, l))``.
    """
    _ensure_pallas()
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if partials and q.ndim != 4:
        raise ValueError("partials mode expects the folded (S, H, B, D) "
                         "layout")
    if partials and return_stats:
        raise ValueError("partials already returns the statistics")

    out_shape, out_dtype = q.shape, q.dtype
    sq, h = q.shape[:2]
    d = q.shape[-1]
    skv = k.shape[0]
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])

    def fold(x):  # (S, H, *batch, D) -> (H*B, S, D)
        s = x.shape[0]
        x = x.reshape(s, h, -1, d)
        return jnp.moveaxis(x, 0, 2).reshape(-1, s, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    hb = qf.shape[0]

    bq = min(block_q, -(-sq // 8) * 8)
    bk = min(block_k, -(-skv // 128) * 128)
    nq = -(-sq // bq)
    nk = -(-skv // bk)
    qf = _pad_to(qf, 1, nq * bq)
    kf = _pad_to(kf, 1, nk * bk)
    vf = _pad_to(vf, 1, nk * bk)

    kernel = functools.partial(
        _flash_kernel, scale=1.0 / math.sqrt(d), causal=causal,
        skv=skv, bq=bq, bk=bk, nk=nk, out_dtype=out_dtype,
        partials=partials, return_stats=return_stats)

    spec_q = pl.BlockSpec((1, bq, d), lambda hbi, i, j: (hbi, i, 0))
    spec_kv = pl.BlockSpec((1, bk, d), lambda hbi, i, j: (hbi, j, 0))
    spec_row = pl.BlockSpec((1, bq), lambda hbi, i, j: (hbi, i))
    if partials:
        out_shapes = [
            jax.ShapeDtypeStruct((hb, nq * bq, d), jnp.float32),  # acc
            jax.ShapeDtypeStruct((hb, nq * bq), jnp.float32),     # m
            jax.ShapeDtypeStruct((hb, nq * bq), jnp.float32),     # l
        ]
        out_specs = [spec_q, spec_row, spec_row]
    elif return_stats:
        out_shapes = [
            jax.ShapeDtypeStruct((hb, nq * bq, d), out_dtype),
            jax.ShapeDtypeStruct((hb, nq * bq), jnp.float32),     # m
            jax.ShapeDtypeStruct((hb, nq * bq), jnp.float32),     # l
        ]
        out_specs = [spec_q, spec_row, spec_row]
    else:
        out_shapes = jax.ShapeDtypeStruct((hb, nq * bq, d), out_dtype)
        out_specs = spec_q

    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(hb, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # offsets
            spec_q, spec_kv, spec_kv,
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running denominator l
            pltpu.VMEM((bq, d), jnp.float32),     # numerator accumulator
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, qf, kf, vf)

    if partials:
        acc, m, l = res
        b = q.shape[2]
        acc = acc[:, :sq].reshape(h, b, sq, d)
        acc = jnp.moveaxis(acc, 2, 0)                   # (Sq, H, B, D)
        m = m[:, :sq].reshape(h, b, sq)                 # (H, B, Sq)
        l = l[:, :sq].reshape(h, b, sq)
        return m, l, acc

    if return_stats:
        res, m, l = res
        m, l = m[:, :sq], l[:, :sq]                     # (H*B, Sq)
    out = res[:, :sq]                                   # drop q padding
    out = out.reshape(h, -1, sq, d)
    out = jnp.moveaxis(out, 2, 0).reshape(out_shape)
    return (out, (m, l)) if return_stats else out


# ---------------------------------------------------------------------------
# Backward: hand-tiled dq / dk / dv kernels (the flash backward recompute).
#
# Standard two-pass structure (same tiling argument as the forward — the
# (bq x bk) score block is rebuilt in VMEM from q/k and the saved
# logsumexp, never materialized in HBM):
#
#   P_ij = exp(s_ij - L_i)              s = scale * q k^T, L = m + log l
#   dV_j = sum_i P_ij^T dO_i
#   dP_ij = dO_i . v_j
#   dS_ij = P_ij (dP_ij - D_i)          D_i = rowsum(dO_i * O_i)
#   dQ_i = scale * sum_j dS_ij k_j      (pass 1: grid j inner)
#   dK_j = scale * sum_i dS_ij^T q_i    (pass 2: grid i inner)
#
# L rides per-row as (1, bq, 1) blocks; padded q rows carry L = +inf so
# P == 0 there (their dO is zero-padded too), padded keys are masked by
# global position — so no pad value ever contaminates a real gradient.
# Capability bar: the in-tree JAX kernel's dq/dkv split
# (jax/experimental/pallas/ops/tpu/flash_attention.py); this
# implementation keeps this module's layout contract and traced-offset
# SMEM convention instead of its (B, H, S, D) layout.
# ---------------------------------------------------------------------------


def _bwd_common(q, k, v, do, L_ref, D_ref, *, scale, causal, skv,
                bq, bk, i, j, q_off, kv_off):
    """Rebuild P and dS for one (bq x bk) block (f32).

    Scores are masked BEFORE exponentiation (mirroring the forward):
    a masked raw score is not bounded by L, so ``exp(s - L)`` on it
    could overflow to inf for garbage-L rows (fully-masked rows whose
    forward left ``l > 0``) and the correctness would then hang on a
    where() re-applying exactly the forward's mask.  Masking first
    means no intermediate inf ever exists.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # (bq, bk)
    tail_pad = skv % bk != 0
    valid = None
    if causal or tail_pad:
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = cols < skv
        if causal:
            rows = q_off + i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            valid = jnp.logical_and(valid, rows >= kv_off + cols)
        s = jnp.where(valid, s, _NEG)
    L = L_ref[0]                                          # (bq, 1)
    p = jnp.exp(s - L)
    if valid is not None:
        # exp(_NEG - L) is exactly 0 for any finite L >= the row's real
        # max; this where() additionally zeroes masked entries of
        # garbage-L rows (L << 0), keeping the old contract bit-for-bit
        p = jnp.where(valid, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)               # (bq, bk)
    ds = p * (dp - D_ref[0])                              # (bq, bk)
    return p, ds


def _flash_bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, L_ref,
                         D_ref, dq_o, dq_acc, *, scale, causal, skv,
                         bq, bk, nk, out_dtype):
    i = pl.program_id(1)
    j = pl.program_id(2)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        _, ds = _bwd_common(q, k, v, do, L_ref, D_ref, scale=scale,
                            causal=causal, skv=skv, bq=bq, bk=bk,
                            i=i, j=j, q_off=q_off, kv_off=kv_off)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(q_off + (i + 1) * bq - 1 >= kv_off + j * bk)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        dq_o[0] = dq_acc[:].astype(out_dtype)


def _flash_bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, L_ref,
                          D_ref, dk_o, dv_o, dk_acc, dv_acc, *, scale,
                          causal, skv, bq, bk, nq, out_dtype):
    j = pl.program_id(1)   # key block: outer
    i = pl.program_id(2)   # q block: inner (accumulated)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p, ds = _bwd_common(q, k, v, do, L_ref, D_ref, scale=scale,
                            causal=causal, skv=skv, bq=bq, bk=bk,
                            i=i, j=j, q_off=q_off, kv_off=kv_off)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, D)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bk, D)

    if causal:
        pl.when(q_off + (i + 1) * bq - 1 >= kv_off + j * bk)(_compute)
    else:
        _compute()

    @pl.when(i == nq - 1)
    def _finish():
        dk_o[0] = dk_acc[:].astype(out_dtype)
        dv_o[0] = dv_acc[:].astype(out_dtype)


def pallas_flash_attention_bwd(q, k, v, out, do, m, l, *,
                               causal: bool = False, q_offset=0,
                               kv_offset=0, block_q: int = _DEF_BLOCK_Q,
                               block_k: int = _DEF_BLOCK_K,
                               interpret: Optional[bool] = None):
    """Flash-attention backward as two Pallas kernels: ``(dq, dk, dv)``
    from the forward residuals (``out`` plus the folded ``(m, l)``
    statistics from ``return_stats=True``).  Layouts/dtypes mirror the
    forward's ``(S, H, *batch, D)`` contract; gradients come back in
    the inputs' dtypes with f32 accumulation inside the kernels.
    """
    sq, h = q.shape[:2]
    d = q.shape[-1]
    skv = k.shape[0]

    def fold(x):  # (S, H, *batch, D) -> (H*B, S, D)
        s = x.shape[0]
        x = x.reshape(s, h, -1, d)
        return jnp.moveaxis(x, 0, 2).reshape(-1, s, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    outf, dof = fold(out), fold(do)

    # per-row residuals: logsumexp L (+inf where no key is visible, so
    # the rebuilt P is exactly 0 there) and D = rowsum(dO * O) — cheap
    # elementwise work left to XLA
    Lrow = jnp.where(l > 0.0, m + jnp.log(l), jnp.inf)    # (H*B, Sq)
    Drow = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                   axis=-1)                               # (H*B, Sq)

    dqf, dkf, dvf = _bwd_folded(
        qf, kf, vf, dof, Lrow, Drow, q_offset, kv_offset,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, dq_dtype=q.dtype, dk_dtype=k.dtype,
        dv_dtype=v.dtype)

    def unfold(x, s, like):
        x = x.reshape(h, -1, s, d)
        return jnp.moveaxis(x, 2, 0).reshape(like.shape)

    return (unfold(dqf, sq, q), unfold(dkf, skv, k), unfold(dvf, skv, v))


def pallas_flash_attention_bwd_partials(q, k, v, do, L, D, *,
                                        causal: bool = False, q_offset=0,
                                        kv_offset=0,
                                        block_q: int = _DEF_BLOCK_Q,
                                        block_k: int = _DEF_BLOCK_K,
                                        interpret: Optional[bool] = None):
    """Backward for ONE key block of a partials-mode accumulation.

    The ring/zigzag schedules merge per-round partials into a single
    global softmax; their backward is the standard flash recompute per
    visited block with the GLOBAL logsumexp.  This entry point runs the
    same two hand-tiled kernels as :func:`pallas_flash_attention_bwd`
    but takes the partials-layout residuals directly:

    * ``q/k/v/do``: folded 4-D ``(S, H, B, D)`` (the partials-mode
      layout contract);
    * ``L``: ``(H, B, Sq)`` f32 — the global logsumexp rows
      (``m + log l`` after ALL rounds merged; +inf where ``l == 0``);
    * ``D``: ``(H, B, Sq)`` f32 — ``rowsum(dO * O)`` with ``O`` the
      final normalized output.

    Offsets may be traced (SMEM), which is what lets each ring round
    feed its rotating block position in.  Returns ``(dq, dk, dv)`` in
    f32 (the caller accumulates across rounds before casting).
    """
    sq, h = q.shape[:2]
    d = q.shape[-1]
    skv = k.shape[0]

    def fold(x):  # (S, H, B, D) -> (H*B, S, D)
        s = x.shape[0]
        return jnp.moveaxis(x, 0, 2).reshape(-1, s, d)

    qf, kf, vf, dof = fold(q), fold(k), fold(v), fold(do)
    Lrow = L.reshape(-1, sq)                              # (H*B, Sq)
    Drow = D.reshape(-1, sq)
    dqf, dkf, dvf = _bwd_folded(
        qf, kf, vf, dof, Lrow, Drow, q_offset, kv_offset,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, dq_dtype=jnp.float32,
        dk_dtype=jnp.float32, dv_dtype=jnp.float32)

    def unfold(x, s):
        return jnp.moveaxis(x.reshape(h, -1, s, d), 2, 0)

    return unfold(dqf, sq), unfold(dkf, skv), unfold(dvf, skv)


def _bwd_folded(qf, kf, vf, dof, Lrow, Drow, q_offset, kv_offset, *,
                causal, block_q, block_k, interpret, dq_dtype, dk_dtype,
                dv_dtype):
    """Shared backward core on folded ``(H*B, S, D)`` operands with
    per-row residuals ``Lrow``/``Drow`` ``(H*B, Sq)``.  Returns folded
    ``(dq, dk, dv)`` sliced back to the real sequence lengths."""
    _ensure_pallas()
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    hb, sq, d = qf.shape
    skv = kf.shape[1]
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])

    bq = min(block_q, -(-sq // 8) * 8)
    bk = min(block_k, -(-skv // 128) * 128)
    nq = -(-sq // bq)
    nk = -(-skv // bk)
    qf = _pad_to(qf, 1, nq * bq)
    dof = _pad_to(dof, 1, nq * bq)
    kf = _pad_to(kf, 1, nk * bk)
    vf = _pad_to(vf, 1, nk * bk)
    pad_rows = nq * bq - sq
    if pad_rows:
        Lrow = jnp.pad(Lrow, ((0, 0), (0, pad_rows)),
                       constant_values=jnp.inf)
        Drow = jnp.pad(Drow, ((0, 0), (0, pad_rows)))
    Lcol = Lrow[..., None]                                # (H*B, Sqp, 1)
    Dcol = Drow[..., None]

    scale = 1.0 / math.sqrt(d)
    spec_q = pl.BlockSpec((1, bq, d), lambda hbi, i, j: (hbi, i, 0))
    spec_row = pl.BlockSpec((1, bq, 1), lambda hbi, i, j: (hbi, i, 0))
    spec_kv = pl.BlockSpec((1, bk, d), lambda hbi, i, j: (hbi, j, 0))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)

    dqf = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, skv=skv, bq=bq, bk=bk, nk=nk,
                          out_dtype=dq_dtype),
        out_shape=jax.ShapeDtypeStruct((hb, nq * bq, d), dq_dtype),
        grid=(hb, nq, nk),
        in_specs=[smem, spec_q, spec_kv, spec_kv, spec_q, spec_row,
                  spec_row],
        out_specs=spec_q,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, qf, kf, vf, dof, Lcol, Dcol)

    # dkv pass: key blocks outer, q blocks inner (accumulated), so the
    # q/do/L/D specs index by the INNER grid dim
    spec_q_i = pl.BlockSpec((1, bq, d), lambda hbi, j, i: (hbi, i, 0))
    spec_row_i = pl.BlockSpec((1, bq, 1), lambda hbi, j, i: (hbi, i, 0))
    spec_kv_j = pl.BlockSpec((1, bk, d), lambda hbi, j, i: (hbi, j, 0))
    dkf, dvf = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, skv=skv, bq=bq, bk=bk, nq=nq,
                          out_dtype=dk_dtype),
        out_shape=[jax.ShapeDtypeStruct((hb, nk * bk, d), dk_dtype),
                   jax.ShapeDtypeStruct((hb, nk * bk, d), dv_dtype)],
        grid=(hb, nk, nq),
        in_specs=[smem, spec_q_i, spec_kv_j, spec_kv_j, spec_q_i,
                  spec_row_i, spec_row_i],
        out_specs=[spec_kv_j, spec_kv_j],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, qf, kf, vf, dof, Lcol, Dcol)

    return dqf[:, :sq], dkf[:, :skv], dvf[:, :skv]
