"""Rectilinear grids aligned with a pencil decomposition.

Reference ``src/LocalGrids/`` + the ``localgrid`` hook
(``Pencils.jl:600-605``): per-rank views of global coordinate vectors,
whose components broadcast against PencilArrays by reshaping to a permuted
singleton shape (``rectilinear.jl:132-139``), so that
``@. u = f(grid.x, grid.y, grid.z)`` fuses with zero allocation.

TPU re-design: a component for logical dim ``d`` is the global coordinate
vector padded to the pencil's padded extent, reshaped so its only
non-singleton axis sits at dim ``d``'s *memory* position, and sharded along
that dim's mesh axis.  Broadcasting such components against ``x.data``
(memory-order padded storage) is then elementwise-aligned shard-by-shard —
XLA fuses the whole expression into one kernel with no data movement,
the analog of the reference's zero-allocation fused broadcast
(``benchmarks/grids.jl`` is the perf baseline for exactly this).
"""

from __future__ import annotations

import math
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.arrays import PencilArray
from ..parallel.pencil import LogicalOrder, MemoryOrder, Pencil

__all__ = ["LocalRectilinearGrid", "localgrid"]

_COMPONENT_NAMES = "xyzw"


class LocalRectilinearGrid:
    """Grid of per-dimension coordinate vectors over a pencil
    (reference ``LocalRectilinearGrid``, ``rectilinear.jl:8-15``).

    Components are accessed as ``g[0]``/``g[1]``/... or ``g.x``/``g.y``/
    ``g.z``/``g.w`` (``rectilinear.jl:159-169``) and come back as
    broadcast-ready sharded arrays aligned with ``PencilArray.data``.

    Protocol note (mirrors the reference): ``g[i]`` indexes COMPONENTS
    (per-dimension coordinate arrays, like Julia ``g[Val(i)]``), while
    iteration and ``len`` range over GRID POINTS (like Julia's grid
    iteration).  ``__reversed__`` is provided so the mixed protocol does
    not confuse Python's sequence fallback.
    """

    def __init__(self, pencil: Pencil, coords_global: Sequence):
        if len(coords_global) != pencil.ndims:
            raise ValueError(
                f"need {pencil.ndims} coordinate vectors, got "
                f"{len(coords_global)}"
            )
        self._pencil = pencil
        self._coords = []
        for d, c in enumerate(coords_global):
            c = jnp.asarray(c)
            if c.ndim != 1 or c.shape[0] != pencil.size_global()[d]:
                raise ValueError(
                    f"coordinate vector {d} must be 1-D of length "
                    f"{pencil.size_global()[d]}, got shape {c.shape}"
                )
            self._coords.append(c)

    @property
    def pencil(self) -> Pencil:
        return self._pencil

    @property
    def ndims(self) -> int:
        return self._pencil.ndims

    def coordinate(self, d: int):
        """The raw (global, true-length) coordinate vector of dim ``d``."""
        return self._coords[d]

    def __getitem__(self, d: int):
        """Broadcastable component for logical dim ``d``: padded, reshaped
        into memory order, sharded along the dim's mesh axis (the analog of
        ``rectilinear.jl:132-139``)."""
        pen = self._pencil
        N = pen.ndims
        if not (0 <= d < N):
            raise IndexError(f"component {d} out of range for {N} dims")
        c = self._coords[d]
        n_pad = pen.padded_global_shape[d]
        if n_pad != c.shape[0]:
            c = jnp.pad(c, (0, n_pad - c.shape[0]))
        # memory position of logical dim d
        mem_ids = pen.permutation.apply(tuple(range(N)))
        pos = mem_ids.index(d)
        shape = [1] * N
        shape[pos] = n_pad
        c = c.reshape(shape)
        # shard along this dim's mesh axis (replicated over the others)
        from jax.sharding import NamedSharding, PartitionSpec

        spec = [None] * N
        spec[pos] = pen.decomp_axis_name(d)
        c = jax.lax.with_sharding_constraint(
            c, NamedSharding(pen.mesh, PartitionSpec(*spec))
        )
        return c

    def __getattr__(self, name: str):
        if len(name) == 1 and name in _COMPONENT_NAMES:
            d = _COMPONENT_NAMES.index(name)
            if d < self.ndims:
                return self[d]
        raise AttributeError(name)

    def components(self) -> Tuple:
        """All broadcastable components (reference ``components(g)``)."""
        return tuple(self[d] for d in range(self.ndims))

    def _wrap(self, val, extra_dims: Tuple[int, ...]) -> PencilArray:
        """Broadcast a memory-order value to the padded target, apply the
        pencil sharding, wrap — shared result-materialization tail of
        :meth:`evaluate` and :meth:`zip_with`."""
        pen = self._pencil
        target = pen.padded_size_global(MemoryOrder) + tuple(extra_dims)
        val = jnp.broadcast_to(val, target)
        val = jax.lax.with_sharding_constraint(
            val, pen.sharding(len(extra_dims)))
        return PencilArray(pen, val, tuple(extra_dims))

    def evaluate(self, f: Callable, extra_dims: Tuple[int, ...] = ()) -> PencilArray:
        """``u = f(x, y, z, ...)`` broadcast over the grid, returned as a
        PencilArray — the fused grid-broadcast pattern of
        ``README.md:101`` / ``benchmarks/grids.jl``."""
        val = f(*self.components())
        if extra_dims:
            # keep spatial dims left-aligned: extras are trailing singletons
            val = val.reshape(val.shape + (1,) * len(extra_dims))
        return self._wrap(val, extra_dims)

    def zip_with(self, f: Callable, *arrays: PencilArray) -> PencilArray:
        """``v = f(u1, ..., x, y, z)`` fused elementwise over array
        values and grid coordinates — the ``zip(eachindex(u), grid)``
        iteration style of ``benchmarks/grids.jl:117`` as ONE XLA kernel
        (values and coordinates stream together in memory order, no
        index arithmetic).  Arrays must live on this grid's pencil and
        share extra dims; grid components broadcast over extra dims."""
        pen = self._pencil
        for a in arrays:
            if a.pencil != pen:
                raise ValueError(
                    "zip_with: array pencil differs from grid pencil")
        extra = arrays[0].extra_dims if arrays else ()
        for a in arrays[1:]:
            if a.extra_dims != extra:
                raise ValueError("zip_with: extra_dims mismatch")
        comps = self.components()
        if extra:
            comps = tuple(c.reshape(c.shape + (1,) * len(extra))
                          for c in comps)
        val = f(*(a.data for a in arrays), *comps)
        return self._wrap(val, extra)

    def __len__(self) -> int:
        return math.prod(self._pencil.size_global())

    def __iter__(self):
        """Host-side iteration over global grid points in MEMORY order,
        yielding logical-order coordinate tuples — the reference's grid
        iteration invariant (``rectilinear.jl:110-130``).  For compute,
        use :meth:`evaluate`/:meth:`components`; this is for tests and
        debug walks."""
        from ..utils.permuted_indices import PermutedCartesianIndices

        coords = [np.asarray(c) for c in self._coords]
        for idx in PermutedCartesianIndices(self._pencil.size_global(),
                                            self._pencil.permutation):
            yield tuple(coords[d][i] for d, i in enumerate(idx))

    def __reversed__(self):
        return reversed(list(self))

    def meshgrid(self):
        """Dense sharded coordinate arrays (one full-size array per dim,
        broadcast from the components) — ``jnp.meshgrid`` parity for code
        that wants explicit coordinate fields."""
        target = self._pencil.padded_size_global(MemoryOrder)
        return tuple(
            jax.lax.with_sharding_constraint(
                jnp.broadcast_to(self[d], target), self._pencil.sharding())
            for d in range(self.ndims)
        )

    def __repr__(self) -> str:
        return (
            f"LocalRectilinearGrid(ndims={self.ndims}, "
            f"pencil={self._pencil!r})"
        )


def localgrid(pencil: Pencil, coords_global: Sequence) -> LocalRectilinearGrid:
    """Build a grid over a pencil from global coordinate vectors
    (reference ``localgrid``, ``Pencils.jl:600-605``)."""
    return LocalRectilinearGrid(pencil, coords_global)
