"""Pallas TPU kernels — hand-tiled local data movement.

The reference leans on Strided.jl for cache-friendly strided
``permutedims!`` in the transpose unpack (``Transpositions.jl:13,
636-648``): the one place where a naive loop order wrecks memory
bandwidth.  The XLA analog is usually automatic, but the local permute
(memory-order change without communication, ``Transpositions.jl:214-271``)
is exactly the kind of bandwidth-bound op where a VMEM-tiled Pallas
kernel can control tiling explicitly.

:func:`pallas_permute` implements N-D ``jnp.transpose`` as a Pallas grid
over VMEM tiles, choosing tile extents so that BOTH the input's and the
output's minor (lane) dimension run at 128 elements — the in-VMEM
transpose then happens at register granularity instead of strided HBM
access.

**Measured verdict (v5e, benchmarks/PALLAS_SWEEP.json)**: XLA's own
transpose runs at/near the HBM roofline in every shape class; this
kernel never beats it (best 0.96x on the 256^3 f32 (2,0,1) class, worst
0.02x on 4-D batched permutes; bf16 loses ~2x to XLA's packed-sublane
handling).  A bandwidth-bound permute leaves no headroom for hand
kernels on this hardware — the TPU-first conclusion is to let XLA own
local data movement, exactly as the framework lets it own collective
scheduling.  The kernel is therefore retained as an opt-in
*integration demonstrator* of the Pallas path (grid/BlockSpec tiling
under ``shard_map``, interpret-mode CPU tests), gated to the one
near-parity class; ``supported()`` rejects every measured-regression
class so the opt-in can never be a trap.  Enable with
``PENCILARRAYS_TPU_PALLAS=1``; anything unsupported falls back to
``jnp.transpose`` transparently.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["pallas_permute", "pallas_enabled", "supported"]

_LANE = 128
_SUBLANE = 8


def pallas_enabled() -> bool:
    return os.environ.get("PENCILARRAYS_TPU_PALLAS", "0") == "1"


def _tile_shape(shape_out: Tuple[int, ...], axes: Tuple[int, ...]):
    """Choose an output tile: 128 along the output minor dim AND along the
    output dim that is the *input's* minor dim; 8 elsewhere (sublane
    granularity).  Returns None if the shape doesn't tile evenly."""
    nd = len(shape_out)
    # output dim k reads input dim axes[k]; input minor dim = nd-1
    k_in_minor = axes.index(nd - 1)
    tile = []
    for k in range(nd):
        want = _LANE if (k == nd - 1 or k == k_in_minor) else _SUBLANE
        want = min(want, shape_out[k])
        if shape_out[k] % want != 0:
            return None
        tile.append(want)
    return tuple(tile)


def supported(shape: Sequence[int], axes: Sequence[int], dtype,
              platform: str = "tpu") -> bool:
    """Whether :func:`pallas_permute` handles this case at near-XLA
    performance.  Gated to the measured near-parity class
    (benchmarks/PALLAS_SWEEP.json): 3-D f32/i32 permutes whose OUTPUT
    leading dim is the input's minor dim (the (2,0,1) family, 0.92-0.96x
    XLA), at HBM-bound sizes.  bf16 (packed-sublane losses), 2-D,
    4-D/batched and the (1,2,0) family are rejected — all measured at
    0.02-0.6x XLA.  The size cut is waived ONLY for the interpret-mode
    CPU path the virtual-mesh tests drive; any accelerator platform
    (tpu, gpu, ...) keeps it, since interpret-mode emulation of a small
    permute would be far slower than the native fallback."""
    shape, axes = tuple(shape), tuple(axes)
    if len(shape) != 3 or axes != (2, 0, 1):
        return False  # only the measured both-minors-tiled rotation
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.int32)):
        return False
    if platform != "cpu" and shape[0] * shape[1] * shape[2] < 8 * 1024 * 1024:
        return False  # cache-resident sizes: 128^3 measured 0.61x; the
        # near-parity class is HBM-bound (>= 32 MB f32)
    shape_out = tuple(shape[a] for a in axes)
    return _tile_shape(shape_out, axes) is not None


def _permute_kernel(axes, in_ref, out_ref):
    out_ref[:] = jnp.transpose(in_ref[:], axes)


def pallas_permute(x: jax.Array, axes: Sequence[int], *,
                   interpret: bool = False) -> jax.Array:
    """``jnp.transpose(x, axes)`` as a tiled Pallas kernel.

    Requires :func:`supported`; callers fall back to ``jnp.transpose``
    otherwise.
    """
    from jax.experimental import pallas as pl

    axes = tuple(int(a) for a in axes)
    nd = x.ndim
    shape_out = tuple(x.shape[a] for a in axes)
    tile_out = _tile_shape(shape_out, axes)
    if tile_out is None:
        raise ValueError(f"unsupported permute {x.shape} axes={axes}")
    # input tile: B_in[axes[k]] = B_out[k]
    tile_in = [0] * nd
    for k in range(nd):
        tile_in[axes[k]] = tile_out[k]
    tile_in = tuple(tile_in)
    grid = tuple(s // t for s, t in zip(shape_out, tile_out))

    def in_index(*bidx):
        # out block (b_0..b_{n-1}) reads in block J with J[axes[k]] = b_k
        J = [0] * nd
        for k in range(nd):
            J[axes[k]] = bidx[k]
        return tuple(J)

    def out_index(*bidx):
        return tuple(bidx)

    return pl.pallas_call(
        partial(_permute_kernel, axes),
        out_shape=jax.ShapeDtypeStruct(shape_out, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec(tile_in, in_index)],
        out_specs=pl.BlockSpec(tile_out, out_index),
        interpret=interpret,
    )(x)
