"""Pallas TPU kernels — hand-tiled local data movement.

The reference leans on Strided.jl for cache-friendly strided
``permutedims!`` in the transpose unpack (``Transpositions.jl:13,
636-648``): the one place where a naive loop order wrecks memory
bandwidth.  The XLA analog is usually automatic, but the local permute
(memory-order change without communication, ``Transpositions.jl:214-271``)
is exactly the kind of bandwidth-bound op where a VMEM-tiled Pallas
kernel can control tiling explicitly.

:func:`pallas_permute` implements N-D ``jnp.transpose`` as a Pallas grid
over VMEM tiles, choosing tile extents so that BOTH the input's and the
output's minor (lane) dimension run at 128 elements — the in-VMEM
transpose then happens at register granularity instead of strided HBM
access.  Used as an opt-in fast path by the transpose engine (set the
``PENCILARRAYS_TPU_PALLAS=1`` environment variable); anything the kernel
does not support falls back to ``jnp.transpose`` transparently.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["pallas_permute", "pallas_enabled", "supported"]

_LANE = 128
_SUBLANE = 8


def pallas_enabled() -> bool:
    return os.environ.get("PENCILARRAYS_TPU_PALLAS", "0") == "1"


def _tile_shape(shape_out: Tuple[int, ...], axes: Tuple[int, ...]):
    """Choose an output tile: 128 along the output minor dim AND along the
    output dim that is the *input's* minor dim; 8 elsewhere (sublane
    granularity).  Returns None if the shape doesn't tile evenly."""
    nd = len(shape_out)
    # output dim k reads input dim axes[k]; input minor dim = nd-1
    k_in_minor = axes.index(nd - 1)
    tile = []
    for k in range(nd):
        want = _LANE if (k == nd - 1 or k == k_in_minor) else _SUBLANE
        want = min(want, shape_out[k])
        if shape_out[k] % want != 0:
            return None
        tile.append(want)
    return tuple(tile)


def supported(shape: Sequence[int], axes: Sequence[int], dtype) -> bool:
    """Whether :func:`pallas_permute` handles this case natively."""
    shape, axes = tuple(shape), tuple(axes)
    if len(shape) < 2 or len(shape) > 4:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.int32)):
        return False
    shape_out = tuple(shape[a] for a in axes)
    return _tile_shape(shape_out, axes) is not None


def _permute_kernel(axes, in_ref, out_ref):
    out_ref[:] = jnp.transpose(in_ref[:], axes)


def pallas_permute(x: jax.Array, axes: Sequence[int], *,
                   interpret: bool = False) -> jax.Array:
    """``jnp.transpose(x, axes)`` as a tiled Pallas kernel.

    Requires :func:`supported`; callers fall back to ``jnp.transpose``
    otherwise.
    """
    from jax.experimental import pallas as pl

    axes = tuple(int(a) for a in axes)
    nd = x.ndim
    shape_out = tuple(x.shape[a] for a in axes)
    tile_out = _tile_shape(shape_out, axes)
    if tile_out is None:
        raise ValueError(f"unsupported permute {x.shape} axes={axes}")
    # input tile: B_in[axes[k]] = B_out[k]
    tile_in = [0] * nd
    for k in range(nd):
        tile_in[axes[k]] = tile_out[k]
    tile_in = tuple(tile_in)
    grid = tuple(s // t for s, t in zip(shape_out, tile_out))

    def in_index(*bidx):
        # out block (b_0..b_{n-1}) reads in block J with J[axes[k]] = b_k
        J = [0] * nd
        for k in range(nd):
            J[axes[k]] = bidx[k]
        return tuple(J)

    def out_index(*bidx):
        return tuple(bidx)

    return pl.pallas_call(
        partial(_permute_kernel, axes),
        out_shape=jax.ShapeDtypeStruct(shape_out, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec(tile_in, in_index)],
        out_specs=pl.BlockSpec(tile_out, out_index),
        interpret=interpret,
    )(x)
