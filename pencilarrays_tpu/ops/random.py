"""Random fills for PencilArrays.

Reference ``src/random.jl``: ``rand!``/``randn!`` forward to the parent
array so GPU backends fill without scalar indexing (``random.jl:3-16``).
Here the analog generates directly into the sharded padded parent with
``jax.random`` (counter-based, so sharded generation is deterministic
given the key, independent of device count).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray
from ..parallel.pencil import MemoryOrder, Pencil

__all__ = ["uniform", "normal"]


def _filled(pencil: Pencil, key, extra_dims: Tuple[int, ...], dtype, sampler):
    shape = pencil.padded_size_global(MemoryOrder) + tuple(extra_dims)
    # Generate directly into the sharded layout (counter-based PRNG makes
    # this deterministic per global position): never a full single-device
    # replica, so fills scale to arrays that only fit distributed.
    fill = jax.jit(lambda k: sampler(k, shape, dtype),
                   out_shardings=pencil.sharding(len(extra_dims)))
    return PencilArray(pencil, fill(key), tuple(extra_dims))


def uniform(pencil: Pencil, key, extra_dims: Tuple[int, ...] = (),
            dtype=jnp.float32) -> PencilArray:
    """U[0,1) fill (reference ``rand!``)."""
    return _filled(pencil, key, extra_dims, dtype,
                   lambda k, s, d: jax.random.uniform(k, s, dtype=d))


def normal(pencil: Pencil, key, extra_dims: Tuple[int, ...] = (),
           dtype=jnp.float32) -> PencilArray:
    """Standard-normal fill (reference ``randn!``).  Complex dtypes are
    supported natively by ``jax.random.normal`` with the standard complex
    normal's variance 1 (0.5 per component), matching Julia ``randn``."""
    return _filled(pencil, key, extra_dims, dtype,
                   lambda k, s, d: jax.random.normal(k, s, dtype=d))
