"""Distributed reductions over PencilArrays.

Reference ``src/reductions.jl``: local reduce followed by ``MPI.Allreduce``
with a custom operator (``reductions.jl:9-28``), giving ``sum``/``minimum``/
``maximum``/``any``/``all`` and friends globally-consistent values on every
rank — the property that makes distributed ODE time-stepping agree across
ranks (``ext/PencilArraysDiffEqExt.jl``).

Under single-controller JAX a reduction over the sharded global array *is*
the Allreduce: ``jnp.sum`` on a sharded operand compiles to local reduce +
``psum`` over the mesh, scheduled by XLA onto ICI.  What this module adds
is **padding masking**: the backing array carries tail padding on
decomposed dims (see ``parallel/arrays.py``), which must not contaminate
reductions.  Masking (rather than slicing to the true shape) keeps shards
even, so no resharding is triggered — the mask is an iota comparison XLA
fuses into the reduction kernel.

All functions reduce in *memory order* over the parent array, like the
reference's parent-level reductions — valid because the reductions exposed
here are order-insensitive (the reference makes the same argument for its
Allreduce ops, ``reductions.jl:17``).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray
from ..parallel.pencil import MemoryOrder, Pencil

__all__ = [
    "mapreduce",
    "sum",
    "mean",
    "prod",
    "minimum",
    "maximum",
    "any",
    "all",
    "norm",
    "dot",
    "count_nonzero",
]

def _order_identity(dtype, kind: str):
    """Neutral element for min/max over ``dtype`` (written into padding)."""
    if jnp.issubdtype(dtype, jnp.complexfloating):
        raise TypeError(f"no ordering for complex dtype {dtype}")
    if dtype == jnp.bool_:
        return kind == "min"  # True for min, False for max
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if kind == "min" else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if kind == "min" else info.min


def _valid_mask(pencil: Pencil, extra_ndims: int):
    """Boolean mask over the padded memory-order array: True on true data,
    False on tail padding.  Cheap: per-dim iota comparisons, broadcast."""
    padded = pencil.padded_size_global(MemoryOrder)
    true = pencil.size_global(MemoryOrder)
    mask = None
    for d, (np_, nt) in enumerate(zip(padded, true)):
        if np_ == nt:
            continue
        shape = [1] * (len(padded) + extra_ndims)
        shape[d] = np_
        m = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), d) < nt
        mask = m if mask is None else mask & m
    return mask  # None when nothing is padded


def mapreduce(f: Callable, op: Callable, *arrays: PencilArray,
              identity) -> jax.Array:
    """``op``-reduce of ``f`` applied elementwise over one or more aligned
    PencilArrays (reference zipped mapreduce, ``reductions.jl:21-27``).

    ``op`` must be an associative jnp reduction like ``jnp.sum`` taking the
    array; ``identity`` is its neutral element, written into padding.
    """
    x0 = arrays[0]
    for a in arrays[1:]:
        if a.pencil != x0.pencil or a.extra_dims != x0.extra_dims:
            raise ValueError("mapreduce operands must share pencil/extra dims")
    val = f(*(a.data for a in arrays))
    mask = _valid_mask(x0.pencil, x0.ndims_extra)
    if mask is not None:
        val = jnp.where(mask, val, identity)
    return op(val)


def sum(x: PencilArray, *, dtype=None) -> jax.Array:
    return mapreduce(lambda d: d if dtype is None else d.astype(dtype),
                     jnp.sum, x, identity=0)


def prod(x: PencilArray) -> jax.Array:
    return mapreduce(lambda d: d, jnp.prod, x, identity=1)


def mean(x: PencilArray) -> jax.Array:
    return sum(x) / x.length_global()


def minimum(x: PencilArray) -> jax.Array:
    return mapreduce(lambda d: d, jnp.min, x,
                     identity=_order_identity(x.dtype, "min"))


def maximum(x: PencilArray) -> jax.Array:
    return mapreduce(lambda d: d, jnp.max, x,
                     identity=_order_identity(x.dtype, "max"))


def any(x: PencilArray, pred: Optional[Callable] = None) -> jax.Array:
    """Global ``any`` (reference ``reductions.jl:30-38``: Allreduce with
    ``|``).  With ``pred``, tests ``pred(x)`` elementwise first."""
    f = (lambda d: pred(d).astype(bool)) if pred else (lambda d: d.astype(bool))
    return mapreduce(f, jnp.any, x, identity=False)


def all(x: PencilArray, pred: Optional[Callable] = None) -> jax.Array:
    f = (lambda d: pred(d).astype(bool)) if pred else (lambda d: d.astype(bool))
    return mapreduce(f, jnp.all, x, identity=True)


def count_nonzero(x: PencilArray) -> jax.Array:
    return mapreduce(lambda d: (d != 0).astype(jnp.int32), jnp.sum, x,
                     identity=0)


def extrema(x: PencilArray):
    """Global ``(min, max)`` pair (Julia ``extrema`` parity)."""
    return minimum(x), maximum(x)


def norm(x: PencilArray, ord: int = 2) -> jax.Array:
    """Global p-norm (what DiffEq-style error control needs to be
    decomposition-independent, cf. ``ext/PencilArraysDiffEqExt.jl:5-9``)."""
    if ord == 2:
        return jnp.sqrt(mapreduce(lambda d: jnp.abs(d) ** 2, jnp.sum, x,
                                  identity=0))
    if ord == 1:
        return mapreduce(lambda d: jnp.abs(d), jnp.sum, x, identity=0)
    if ord == jnp.inf or ord == math.inf:
        return mapreduce(lambda d: jnp.abs(d), jnp.max, x, identity=0)
    return mapreduce(lambda d: jnp.abs(d) ** ord, jnp.sum, x,
                     identity=0) ** (1.0 / ord)


def dot(x: PencilArray, y: PencilArray) -> jax.Array:
    """Global inner product ``<x, y>`` (conjugating the first argument for
    complex dtypes)."""
    return mapreduce(lambda a, b: jnp.conj(a) * b, jnp.sum, x, y, identity=0)
