"""Spectral differential operators on pencil-decomposed fields.

The standard pseudo-spectral toolbox (PencilFFTs' examples build these by
hand from ``wavenumbers``; the Navier-Stokes model in
``models/spectral.py`` inlines them): gradient, divergence, curl,
Laplacian and a Poisson solve, each acting on SPECTRAL PencilArrays that
live on a plan's ``output_pencil``.

All operators are pure elementwise multiplies by broadcast-shaped
wavenumber components (``PencilFFTPlan.wavenumbers(LogicalOrder)``
aligned by the NumPy-protocol broadcasting of ``parallel/arrays.py``) —
zero collectives, fully traced, differentiable, and XLA fuses them into
neighbouring stages.

Conventions: periodic box of length ``lengths[d]`` (default ``2*pi``, so
angular wavenumbers equal integer mode numbers); vector fields carry
their components in ONE trailing extra dim of size N (the
``extra_dims`` idiom, reference ``arrays.jl:34-47``).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp

from ..parallel.arrays import PencilArray
from ..parallel.pencil import LogicalOrder

__all__ = ["gradient", "divergence", "curl", "laplacian", "solve_poisson"]


def _angular_ks(plan, lengths):
    """Broadcast-shaped angular wavenumber components (logical order)."""
    N = len(plan.shape_physical)
    if lengths is None:
        lengths = (2.0 * math.pi,) * N
    if len(lengths) != N:
        raise ValueError(f"lengths has {len(lengths)} entries for a "
                         f"rank-{N} transform")
    ks = plan.wavenumbers(LogicalOrder)
    return tuple(k * (2.0 * math.pi / float(L))
                 for k, L in zip(ks, lengths))


def _check_spectral(plan, uh: PencilArray, ncomp: int = 0):
    if uh.pencil != plan.output_pencil:
        raise ValueError("operand must live on plan.output_pencil")
    if ncomp and uh.extra_dims != (ncomp,):
        raise ValueError(
            f"expected a vector field with extra_dims=({ncomp},), got "
            f"extra_dims={uh.extra_dims}")


def _aligned(k, fh: PencilArray):
    """A wavenumber component broadcastable against ``fh`` including its
    extra dims (raw operands align from the TAIL of logical shape +
    extra_dims, so component/batch axes need explicit singletons)."""
    return k[(...,) + (None,) * fh.ndims_extra]


def gradient(plan, fh: PencilArray, *,
             lengths: Sequence[float] = None) -> PencilArray:
    """Spectral gradient: ``(i k_d f^)_d`` stacked into a NEW trailing
    component dim of size N (existing extra dims are treated as batch
    dims and broadcast)."""
    _check_spectral(plan, fh)
    ks = _angular_ks(plan, lengths)
    comps = [fh * (1j * _aligned(k, fh)) for k in ks]
    return PencilArray.stack(comps)


def divergence(plan, uh: PencilArray, *,
               lengths: Sequence[float] = None) -> PencilArray:
    """Spectral divergence of a vector field (trailing component dim of
    size N): ``sum_d i k_d u_d^``."""
    N = len(plan.shape_physical)
    _check_spectral(plan, uh, N)
    ks = _angular_ks(plan, lengths)
    out = None
    for d, k in enumerate(ks):
        term = uh.component(d) * (1j * k)
        out = term if out is None else out + term
    return out


def curl(plan, uh: PencilArray, *,
         lengths: Sequence[float] = None) -> PencilArray:
    """Spectral curl of a 3-D vector field: ``i k x u^``."""
    if len(plan.shape_physical) != 3:
        raise ValueError("curl is defined for 3-D transforms")
    _check_spectral(plan, uh, 3)
    kx, ky, kz = _angular_ks(plan, lengths)
    ux, uy, uz = (uh.component(d) for d in range(3))
    return PencilArray.stack([
        uy * (-1j * kz) + uz * (1j * ky),
        uz * (-1j * kx) + ux * (1j * kz),
        ux * (-1j * ky) + uy * (1j * kx),
    ])


def _k2_for(plan, fh: PencilArray, lengths):
    """|k|^2 broadcast-aligned to ``fh`` including its extra dims (the
    ``mask[..., None]`` pattern of ``models/spectral.py``)."""
    ks = _angular_ks(plan, lengths)
    k2 = None
    for k in ks:
        k2 = k * k if k2 is None else k2 + k * k
    return _aligned(k2, fh)


def laplacian(plan, fh: PencilArray, *,
              lengths: Sequence[float] = None) -> PencilArray:
    """Spectral Laplacian: ``-|k|^2 f^`` (componentwise on vector
    fields — any extra dims broadcast)."""
    _check_spectral(plan, fh)
    return fh * (-_k2_for(plan, fh, lengths))


def solve_poisson(plan, fh: PencilArray, *,
                  lengths: Sequence[float] = None) -> PencilArray:
    """Solve ``lap(phi) = f`` spectrally: ``phi^ = -f^/|k|^2`` with the
    zero mode (the undetermined mean) set to 0 (componentwise on vector
    fields)."""
    _check_spectral(plan, fh)
    k2 = _k2_for(plan, fh, lengths)
    inv = jnp.where(k2 == 0, 0.0, -1.0 / jnp.where(k2 == 0, 1.0, k2))
    return fh * inv
