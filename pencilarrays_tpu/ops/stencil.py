"""Finite-difference stencils on pencils — halo exchange the TPU way.

MPI stencil codes pack ghost layers and post neighbor sends by hand.
The TPU-first design does neither: a shifted view of a sharded global
array (``jnp.roll`` / slice + concat under ``jit``) makes GSPMD insert
the minimal boundary ``collective-permute`` between ring neighbors on
the decomposed mesh axis — the halo exchange *is* the compiler's
partitioning of the shift (guarded by ``tests/test_stencil.py``'s HLO
budget: no all-gathers, neighbor permutes only).  On top of
:func:`shift` this module provides the standard second-order centered
difference operators, boundary-aware and differentiable, completing the
grid toolbox next to the spectral operators (``ops/spectral_ops.py``).

Layout subtlety: PencilArray data is stored in memory order with
ceil-rule tail padding on decomposed dims (``parallel/arrays.py``
storage contract).  A shift along a *padded* dim must not let values
cross the pad gap, so the wrap is stitched from two rolls selected at
the seam (keeping the constructors' zero-fill contract) — everything
stays shape-preserving because GSPMD segfaults/all-gathers on unevenly
-resharded slices.  Roll shifts are congruent mod the padded extent, so
the seam roll's effective depth is ``|k| + pad`` (the roll amounts
``n - r`` and ``-(r + pad)`` lower identically): the sharded-axis
exchange is a thin boundary layer — ``|k|`` rows for the bulk plus
``|k| + pad`` for the seam — never a full shard, a bound pinned by
``tests/test_stencil.py::test_padded_dim_halo_bytes``.  Unpadded dims
shift as one roll.  Either way the result keeps the input's pencil and
sharding.

The reference has no stencil layer (its grid utilities stop at
coordinate broadcasts, ``src/LocalGrids``); this module is the analog of
what its users hand-write with ``range_local`` + ghost cells, expressed
as whole-array ops (cf. ``docs/src/PencilArrays.md`` usage notes).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..parallel.arrays import PencilArray

__all__ = ["shift", "diff", "fd_gradient", "fd_divergence", "fd_laplacian"]

_BOUNDARIES = ("periodic", "zero")


def _mem_axis(pencil, axis: int) -> int:
    perm = pencil.permutation
    if perm.is_identity():
        return axis
    return perm.axes().index(axis)


def _axis_index(shape, axis: int) -> jax.Array:
    """Index-along-axis vector shaped for broadcasting against ``shape``
    (the whole shift stays shape-preserving: rolls + masked selects,
    never an unevenly-resharded slice, which GSPMD handles poorly)."""
    s = [1] * len(shape)
    s[axis] = shape[axis]
    return jnp.arange(shape[axis], dtype=jnp.int32).reshape(s)


def shift(u: PencilArray, axis: int, offset: int, *,
          boundary: str = "periodic") -> PencilArray:
    """``shift(u, axis, k)[..., i, ...] == u[..., i+k, ...]`` along a
    logical spatial ``axis`` — data moves *toward lower indices* for
    positive ``k`` (the upwind neighbor view).

    ``boundary``: ``"periodic"`` wraps indices mod the true extent;
    ``"zero"`` reads out-of-range positions as 0.  Works along any dim —
    local, decomposed, padded, permuted; on a decomposed dim the
    compiled program exchanges only boundary layers with ring neighbors
    (GSPMD collective-permute): ``|k|`` deep on evenly-divided dims,
    at most ``2|k| + pad`` deep on ceil-padded dims (the seam needs a
    second small roll past the pad gap).
    """
    if boundary not in _BOUNDARIES:
        raise ValueError(f"boundary must be one of {_BOUNDARIES}")
    pen = u.pencil
    if not 0 <= axis < pen.ndims:
        raise ValueError(f"axis {axis} out of range for {pen.ndims}-dim pencil")
    k = int(offset)
    n = pen.size_global()[axis]
    npad = pen.padded_global_shape[axis]
    ax = _mem_axis(pen, axis)
    data = u.data
    zero = jnp.zeros((), data.dtype)
    if boundary == "periodic":
        if npad == n:
            out = jnp.roll(data, -k, axis=ax)
        else:
            # result[i] = data[(i+k) mod n] inside the true extent n of
            # the padded dim.  Below the seam at n-r that is data[i+r]
            # (roll by -r); the seam rows i in [n-r, n) need the FIRST r
            # global rows, which sit r+p positions ahead once the p pad
            # rows are skipped (p = npad - n).  The -(r+p) form makes the
            # bounded depth visible; it lowers identically to the
            # congruent n-r roll (shifts are mod npad), so the exchange
            # is a (2r+p)-deep boundary layer either way.  Tail padding
            # re-zeroed; no pad row is ever read into the true extent
            # (lo reads i+r < n, hi reads (i+r+p) mod npad in [0, r)).
            r = k % n
            if r == 0:
                out = data
                idx = _axis_index(data.shape, ax)
            else:
                p = npad - n
                idx = _axis_index(data.shape, ax)
                lo = jnp.roll(data, -r, axis=ax)
                hi = jnp.roll(data, -(r + p), axis=ax)
                out = jnp.where(idx < n - r, lo, hi)
            out = jnp.where(idx < n, out, zero)
    else:
        # result[i] = data[i+k] where 0 <= i+k < n, else 0; the rolled
        # array equals data[i+k] on exactly that index window
        rolled = jnp.roll(data, -k, axis=ax)
        idx = _axis_index(data.shape, ax)
        lo_i, hi_i = max(0, -k), min(n, n - k)
        out = jnp.where((idx >= lo_i) & (idx < hi_i), rolled, zero)
    out = jax.lax.with_sharding_constraint(out, pen.sharding(u.ndims_extra))
    return PencilArray(pen, out, u.extra_dims)


def diff(u: PencilArray, axis: int, *, order: int = 1,
         spacing: float = 1.0, boundary: str = "periodic") -> PencilArray:
    """Second-order centered finite difference along a logical axis.

    ``order=1``: ``(u[i+1] - u[i-1]) / (2 h)``;
    ``order=2``: ``(u[i+1] - 2 u[i] + u[i-1]) / h^2``.
    """
    up = shift(u, axis, +1, boundary=boundary)
    dn = shift(u, axis, -1, boundary=boundary)
    if order == 1:
        return (up - dn) * (0.5 / spacing)
    if order == 2:
        return (up - u * 2.0 + dn) * (1.0 / spacing ** 2)
    raise ValueError("order must be 1 or 2 (centered stencils)")


def _spacings(pen, spacing) -> Tuple[float, ...]:
    if isinstance(spacing, (int, float)):
        return (float(spacing),) * pen.ndims
    out = tuple(float(s) for s in spacing)
    if len(out) != pen.ndims:
        raise ValueError("need one spacing per spatial dim")
    return out


def fd_gradient(u: PencilArray, *, spacing=1.0,
                boundary: str = "periodic") -> Tuple[PencilArray, ...]:
    """Centered-difference gradient: one PencilArray per spatial dim
    (the FD analog of ``ops.spectral_ops.gradient``)."""
    hs = _spacings(u.pencil, spacing)
    return tuple(diff(u, d, order=1, spacing=hs[d], boundary=boundary)
                 for d in range(u.pencil.ndims))


def fd_divergence(fields: Sequence[PencilArray], *, spacing=1.0,
                  boundary: str = "periodic") -> PencilArray:
    """Divergence of a vector field given as one PencilArray per dim."""
    fields = tuple(fields)
    pen = fields[0].pencil
    if len(fields) != pen.ndims:
        raise ValueError("need one field component per spatial dim")
    hs = _spacings(pen, spacing)
    out = diff(fields[0], 0, order=1, spacing=hs[0], boundary=boundary)
    for d in range(1, pen.ndims):
        out = out + diff(fields[d], d, order=1, spacing=hs[d],
                         boundary=boundary)
    return out


def fd_laplacian(u: PencilArray, *, spacing=1.0,
                 boundary: str = "periodic") -> PencilArray:
    """Centered-difference Laplacian (sum of second differences)."""
    hs = _spacings(u.pencil, spacing)
    out = diff(u, 0, order=2, spacing=hs[0], boundary=boundary)
    for d in range(1, u.pencil.ndims):
        out = out + diff(u, d, order=2, spacing=hs[d], boundary=boundary)
    return out
