from .topology import Topology, default_axis_names, dims_create
from .pencil import (
    IndexOrder,
    LogicalOrder,
    MemoryOrder,
    Pencil,
    complete_dims,
    local_data_range,
    make_pencil,
)

__all__ = [
    "Topology",
    "default_axis_names",
    "dims_create",
    "IndexOrder",
    "LogicalOrder",
    "MemoryOrder",
    "Pencil",
    "complete_dims",
    "local_data_range",
    "make_pencil",
]
