from .topology import Topology, default_axis_names, dims_create
from .pencil import (
    IndexOrder,
    LogicalOrder,
    MemoryOrder,
    Pencil,
    complete_dims,
    local_data_range,
    make_pencil,
)
from .arrays import PencilArray, global_view
from .transpositions import (
    AllToAll,
    Alltoallv,
    Auto,
    Pipelined,
    PointToPoint,
    Ring,
    Gspmd,
    Transposition,
    assert_compatible,
    gspmd_reshard_cost,
    reshard,
    resolve_method,
    transpose,
    transpose_cost,
)
from .routing import (
    ReshardRoute,
    RouteHop,
    execute_route,
    plan_reshard_route,
)
from .gather import gather
from .multiarrays import ManyPencilArray
from . import distributed

__all__ = [
    "ManyPencilArray",
    "Alltoallv",
    "Auto",
    "Pipelined",
    "PointToPoint",
    "resolve_method",
    "Ring",
    "distributed",
    "PencilArray",
    "global_view",
    "AllToAll",
    "Gspmd",
    "Transposition",
    "ReshardRoute",
    "RouteHop",
    "assert_compatible",
    "execute_route",
    "gspmd_reshard_cost",
    "plan_reshard_route",
    "reshard",
    "transpose",
    "transpose_cost",
    "gather",
    "Topology",
    "default_axis_names",
    "dims_create",
    "IndexOrder",
    "LogicalOrder",
    "MemoryOrder",
    "Pencil",
    "complete_dims",
    "local_data_range",
    "make_pencil",
]
