"""PencilArray — a distributed array wrapping a sharded global ``jax.Array``.

TPU-native re-design of ``src/arrays.jl`` (struct at ``arrays.jl:81-122``).
The reference wraps each rank's *local* block, stored in memory order and
indexed in logical order; the global object only exists implicitly.  Under
JAX's single-controller SPMD model the natural primary object is the
**global** array: a :class:`PencilArray` holds one ``jax.Array`` whose
``NamedSharding`` is derived from its :class:`Pencil`, letting GSPMD own the
local-block bookkeeping the reference does by hand.

Storage contract (checked at construction, cf. ``arrays.jl:108-114``):

``data.shape == pencil.padded_size_global(MemoryOrder) + extra_dims``

i.e. the backing array is stored in *memory order* (the pencil's
permutation applied), with each decomposed dim padded to a multiple of its
device count (JAX requires evenly divisible shards), plus trailing
*extra dims* — non-spatial component axes that are never permuted nor
decomposed (``arrays.jl:34-47``).  Padding lives at the tail of each
decomposed dim and is kept zero-filled by constructors; reductions mask it
(see ``ops/reductions.py``), transposes slice it off before re-padding.

Indexing divergence: reference ``getindex`` takes *local* logical indices
(``arrays.jl:327-337``); here ``__getitem__`` takes **global** logical
indices, because the wrapper is the global array.  The reference's
``GlobalPencilArray``/``global_view`` (``global_view.jl:20-26``) therefore
collapses to the identity here, and local blocks are available via
:meth:`local_block`.

PencilArray is a registered pytree (data leaf; pencil/extra static), so it
flows through ``jax.jit``/``grad``/``vmap`` unchanged.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.permutations import NO_PERMUTATION
from .pencil import IndexOrder, LogicalOrder, MemoryOrder, Pencil

__all__ = ["PencilArray", "global_view"]


# -- jnp.* unwrap policy ----------------------------------------------------
# ``jnp.cos(u)`` has no dispatch protocol and unwraps the PencilArray to a
# plain logical-order jax.Array.  Policy via PENCILARRAYS_TPU_UNWRAP:
#   "warn" (default) — allow, but warn ONCE per process with guidance;
#   "allow"          — silent (pre-round-3 behavior);
#   "error"          — raise TypeError at the unwrap site.
# The wrapped alternatives never unwrap: ``np.cos(u)``, ``u.map(jnp.cos)``,
# or the ``pencilarrays_tpu.numpy`` namespace.
# Caveat: jnp functions jit-cache per input signature, and PencilArray is
# a pytree — after the first call the unwrap is baked into the compiled
# artifact and this hook is bypassed, so the policy binds at TRACE time
# (set the env var before first use, the normal way env policies work).
_unwrap_warned = False


def _on_jax_unwrap():
    import os
    import warnings

    policy = os.environ.get("PENCILARRAYS_TPU_UNWRAP", "warn").lower()
    if policy == "allow":
        return
    msg = (
        "jnp.* function applied to a PencilArray: the result is a plain "
        "logical-order jax.Array (the pencil is dropped and the permute "
        "materializes). Use np.cos(u)-style NumPy ufuncs, u.map(jnp.cos), "
        "or pencilarrays_tpu.numpy to stay wrapped; set "
        "PENCILARRAYS_TPU_UNWRAP=allow to silence, =error to forbid."
    )
    if policy == "error":
        raise TypeError(msg)
    global _unwrap_warned
    if not _unwrap_warned:
        _unwrap_warned = True
        warnings.warn(msg, stacklevel=3)


def _fwd_axes(pencil: Pencil, extra_ndims: int) -> Tuple[int, ...]:
    """Axes tuple for ``jnp.transpose`` converting logical -> memory order:
    ``transpose(u, perm.axes())`` has shape ``perm.apply(u.shape)`` and
    satisfies ``mem[perm.apply(I)] == u[I]`` (extra dims ride along)."""
    perm = pencil.permutation
    if perm is NO_PERMUTATION or perm.is_identity():
        return tuple(range(pencil.ndims + extra_ndims))
    return perm.append(extra_ndims).axes()


def _inv_axes(pencil: Pencil, extra_ndims: int) -> Tuple[int, ...]:
    """Axes tuple converting memory order -> logical order (extra dims kept)."""
    perm = pencil.permutation
    if perm is NO_PERMUTATION or perm.is_identity():
        return tuple(range(pencil.ndims + extra_ndims))
    return perm.inverse().append(extra_ndims).axes()


class PencilArray:
    """Distributed N-dim array over a :class:`Pencil` decomposition."""

    __slots__ = ("_pencil", "_data", "_extra_dims")

    def __init__(self, pencil: Pencil, data, extra_dims: Optional[Tuple[int, ...]] = None):
        expected_space = pencil.padded_size_global(MemoryOrder)
        if extra_dims is None:
            # Infer trailing extra dims (cf. reference ``arrays.jl:97-121``
            # where extra dims are the axes beyond the pencil's N).
            nspace = len(expected_space)
            extra_dims = tuple(int(d) for d in data.shape[nspace:])
        extra_dims = tuple(int(d) for d in extra_dims)
        expected = expected_space + extra_dims
        if tuple(data.shape) != expected:
            raise ValueError(
                f"data shape {tuple(data.shape)} does not match pencil's padded "
                f"memory-order shape {expected_space} + extra dims {extra_dims} "
                f"(= {expected})"
            )
        self._pencil = pencil
        self._data = data
        self._extra_dims = extra_dims

    # -- constructors -----------------------------------------------------
    @classmethod
    def zeros(cls, pencil: Pencil, extra_dims: Tuple[int, ...] = (),
              dtype=jnp.float32) -> "PencilArray":
        shape = pencil.padded_size_global(MemoryOrder) + tuple(extra_dims)
        data = jnp.zeros(shape, dtype=dtype, device=pencil.sharding(len(extra_dims)))
        return cls(pencil, data, tuple(extra_dims))

    @classmethod
    def full(cls, pencil: Pencil, fill_value, extra_dims: Tuple[int, ...] = (),
             dtype=None) -> "PencilArray":
        # Note: padding is also filled; reductions mask it, but keep this in
        # mind when reading raw .data.
        shape = pencil.padded_size_global(MemoryOrder) + tuple(extra_dims)
        data = jnp.full(shape, fill_value, dtype=dtype,
                        device=pencil.sharding(len(extra_dims)))
        return cls(pencil, data, tuple(extra_dims))

    @classmethod
    def from_global(cls, pencil: Pencil, array,
                    extra_ndims: Optional[int] = None) -> "PencilArray":
        """Build from a true-shape, *logical-order* global array (NumPy or
        JAX), padding/permuting/sharding as the pencil dictates.

        Note: under JAX's default ``jax_enable_x64=False``, 64-bit NumPy
        input is downcast to 32 bits; a warning is emitted because the
        reference (Julia) world preserves Float64 silently and the
        precision loss has bitten real users.
        """
        import warnings

        arr = jnp.asarray(array)
        if hasattr(array, "dtype") and arr.dtype != array.dtype:
            warnings.warn(
                f"from_global: input dtype {array.dtype} stored as "
                f"{arr.dtype} (enable jax_enable_x64 for 64-bit arrays)",
                stacklevel=2,
            )
        N = pencil.ndims
        if extra_ndims is None:
            extra_ndims = arr.ndim - N
        if extra_ndims != arr.ndim - N:
            raise ValueError(
                f"extra_ndims={extra_ndims} inconsistent with array rank "
                f"{arr.ndim} and pencil rank {N}"
            )
        if extra_ndims < 0:
            raise ValueError(
                f"array rank {arr.ndim} below pencil rank {N}")
        space_shape = tuple(arr.shape[:N])
        extra_dims = tuple(arr.shape[N:])
        if space_shape != pencil.size_global(LogicalOrder):
            raise ValueError(
                f"array spatial shape {space_shape} != pencil global shape "
                f"{pencil.size_global(LogicalOrder)}"
            )
        padded = pencil.padded_global_shape
        pad = [(0, p - n) for n, p in zip(space_shape, padded)]
        pad += [(0, 0)] * extra_ndims
        arr = jnp.pad(arr, pad)
        arr = jnp.transpose(arr, _fwd_axes(pencil, extra_ndims))
        arr = jax.device_put(arr, pencil.sharding(extra_ndims))
        return cls(pencil, arr, extra_dims)

    def similar(self, pencil: Optional[Pencil] = None, dtype=None,
                extra_dims: Optional[Tuple[int, ...]] = None) -> "PencilArray":
        """Uninitialized (zero) array, possibly over another pencil/type —
        the cross-pencil ``similar`` of ``arrays.jl:287-303``."""
        pen = self._pencil if pencil is None else pencil
        dt = self._data.dtype if dtype is None else dtype
        ed = self._extra_dims if extra_dims is None else tuple(extra_dims)
        return PencilArray.zeros(pen, ed, dt)

    # -- pytree -----------------------------------------------------------
    def tree_flatten(self):
        return (self._data,), (self._pencil, self._extra_dims)

    @classmethod
    def tree_unflatten(cls, aux, children):
        pencil, extra_dims = aux
        (data,) = children
        obj = cls.__new__(cls)
        obj._pencil = pencil
        obj._data = data
        obj._extra_dims = extra_dims
        return obj

    # -- accessors --------------------------------------------------------
    @property
    def pencil(self) -> Pencil:
        return self._pencil

    @property
    def data(self):
        """Backing memory-order padded ``jax.Array`` (reference ``parent``)."""
        return self._data

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def extra_dims(self) -> Tuple[int, ...]:
        return self._extra_dims

    @property
    def ndims_extra(self) -> int:
        """Reference ``ndims_extra`` (``arrays.jl:217-224``)."""
        return len(self._extra_dims)

    @property
    def ndims_space(self) -> int:
        """Reference ``ndims_space``."""
        return self._pencil.ndims

    @property
    def ndim(self) -> int:
        return self._pencil.ndims + len(self._extra_dims)

    @property
    def shape(self) -> Tuple[int, ...]:
        """True global logical shape + extra dims.

        Divergence from the reference, where ``size(x)`` is the *local*
        shape (``size.jl:22-23``): under single-controller JAX the wrapper
        is the global array, so the global shape is the primary one.  Use
        :meth:`size_local` for the per-block shape.
        """
        return self.size_global()

    def size_global(self, order: IndexOrder = LogicalOrder) -> Tuple[int, ...]:
        return self._pencil.size_global(order) + self._extra_dims

    def size_local(self, coords=None, order: IndexOrder = LogicalOrder):
        return self._pencil.size_local(coords, order) + self._extra_dims

    def range_local(self, coords=None, order: IndexOrder = LogicalOrder):
        if coords is None:
            coords = (0,) * self._pencil.topology.ndims
        return self._pencil.range_local(coords, order) + tuple(
            range(0, d) for d in self._extra_dims
        )

    def length_global(self) -> int:
        return math.prod(self.size_global())

    def __len__(self) -> int:
        return self.shape[0] if self.shape else 0

    @property
    def sharding(self):
        return self._data.sharding

    def sizeof_global(self) -> int:
        """Total global size in bytes (reference ``sizeof_global``,
        ``arrays.jl:428``); excludes padding."""
        return self.length_global() * self._data.dtype.itemsize

    # -- views ------------------------------------------------------------
    def logical(self):
        """The true-shape global array in logical order (a traced value —
        lazy under ``jit``, materializes when consumed eagerly)."""
        nd = len(self._extra_dims)
        arr = jnp.transpose(self._data, _inv_axes(self._pencil, nd))
        slices = tuple(slice(0, n) for n in self._pencil.size_global(LogicalOrder))
        return arr[slices]

    def local_block(self, coords=None, order: IndexOrder = LogicalOrder):
        """The true-size block owned by topology ``coords`` as a jnp value
        (reference: the wrapped local array itself)."""
        if coords is None:
            coords = (0,) * self._pencil.topology.ndims
        pen = self._pencil
        block = pen.padded_size_local(LogicalOrder)
        # Logical-order slices into the padded array: decomposed dim d at
        # topology position i starts at coords[i] * padded_block_extent.
        idx_logical = []
        for d in range(pen.ndims):
            try:
                i = pen.decomposition.index(d)
            except ValueError:
                start = 0
            else:
                start = coords[i] * block[d]
            extent = len(pen.range_local(tuple(coords), LogicalOrder)[d])
            idx_logical.append(slice(start, start + extent))
        idx = list(pen.permutation.apply(tuple(idx_logical)))
        idx += [slice(None)] * len(self._extra_dims)
        block = self._data[tuple(idx)]
        if order is LogicalOrder:
            block = jnp.transpose(block, _inv_axes(self._pencil, len(self._extra_dims)))
        return block

    # -- indexing ---------------------------------------------------------
    def _normalize_index(self, key):
        N = self._pencil.ndims
        nd = self.ndim
        if not isinstance(key, tuple):
            key = (key,)
        if any(k is Ellipsis for k in key):
            n_missing = nd - (len(key) - 1)
            out = []
            for k in key:
                if k is Ellipsis:
                    out.extend([slice(None)] * n_missing)
                else:
                    out.append(k)
            key = tuple(out)
        if len(key) < nd:
            key = key + (slice(None),) * (nd - len(key))
        if len(key) != nd:
            raise IndexError(f"too many indices ({len(key)}) for rank {nd}")
        # Resolve against true sizes (negative wrap, slice clamping) so that
        # padding is never addressed.
        true = self.size_global()
        resolved = []
        for k, n in zip(key, true):
            if isinstance(k, slice):
                start, stop, step = k.indices(n)
                # A reversed slice reaching index 0 normalizes to stop=-1,
                # which must NOT be re-fed literally (it would wrap to the
                # padded tail); use None ("past the beginning") instead.
                resolved.append(slice(start, None if stop < 0 else stop, step))
            elif isinstance(k, (int, np.integer)):
                kk = int(k)
                if kk < -n or kk >= n:
                    raise IndexError(f"index {kk} out of bounds for size {n}")
                resolved.append(kk % n if kk < 0 else kk)
            else:
                raise NotImplementedError(
                    "PencilArray indexing supports int/slice/Ellipsis only; "
                    "for fancy indexing use .logical()"
                )
        return tuple(resolved)

    def __getitem__(self, key):
        """Global *logical* basic indexing (see module docstring for the
        divergence from reference local indexing).  The permutation is
        applied to the index tuple at trace time — the analog of the
        reference's ``parent[perm * I]`` (``arrays.jl:327-337``)."""
        key = self._normalize_index(key)
        N = self._pencil.ndims
        space, extra = key[:N], key[N:]
        mem_key = self._pencil.permutation.apply(space) + extra
        out = self._data[mem_key]
        # Result axes arrive in memory order of the kept (sliced) spatial
        # dims; reorder them back to logical order.
        mem_logical_ids = self._pencil.permutation.apply(tuple(range(N)))
        kept = [d for d, k in zip(mem_logical_ids, mem_key[:N])
                if isinstance(k, slice)]
        ax = tuple(int(i) for i in np.argsort(kept, kind="stable"))
        if ax != tuple(range(len(ax))):
            n_extra_kept = sum(isinstance(k, slice) for k in extra)
            out = jnp.transpose(
                out, ax + tuple(range(len(ax), len(ax) + n_extra_kept))
            )
        return out

    # -- conversion -------------------------------------------------------
    def __array__(self, dtype=None):
        arr = np.asarray(jax.device_get(self.logical()))
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        # ``jnp.cos(u)`` lands here (jnp.* has no third-party dispatch
        # protocol) and would silently drop the pencil; the round-2
        # verdict called the silent unwrap a trap, so it is loud now.
        _on_jax_unwrap()
        return self.logical()

    # -- broadcasting interop (reference broadcast.jl:15-89) --------------
    # The reference gives PencilArray full participation in Julia's
    # broadcast machinery: mixed PencilArray/scalar/array operands, style
    # resolution where PencilArrayStyle beats plain array styles, all
    # running on the *parents* in memory order with zero layout churn
    # (``broadcast.jl:31-57``).  The Python analog is the NumPy
    # ``__array_ufunc__`` protocol: ``np.cos(u)``, ``np.add(u, v)`` and
    # ``u * raw_array`` all dispatch here, run on the memory-order padded
    # parent, and return PencilArrays.  Raw operands are interpreted
    # against the LOGICAL global shape under standard (right-aligned)
    # NumPy broadcasting rules, then permuted/padded to the parent
    # layout — a few 1-D-ish ops XLA fuses away, never a collective.
    #
    # Divergence: ``jnp.*`` functions have no third-party dispatch
    # protocol; ``jnp.cos(u)`` works via ``__jax_array__`` but returns a
    # plain logical-order jax.Array (and costs the logical() permute).
    # Keep PencilArray on the left of mixed infix expressions, or use the
    # ``np.*`` ufunc spellings / ``u.map(jnp.cos)``.

    def _align_to_parent(self, arr):
        """Broadcast a raw array against the logical global shape, then
        permute/pad it into the parent's memory-order padded layout.
        Tail padding is zero-filled (inert: reductions mask it,
        transposes slice it)."""
        arr = jnp.asarray(arr)
        nd_extra = len(self._extra_dims)
        logical = self._pencil.size_global(LogicalOrder) + self._extra_dims
        if arr.ndim > len(logical):
            raise ValueError(
                f"operand rank {arr.ndim} exceeds array rank {len(logical)}")
        shape = (1,) * (len(logical) - arr.ndim) + tuple(arr.shape)
        for s, n in zip(shape, logical):
            if s not in (1, n):
                raise ValueError(
                    f"operand shape {tuple(arr.shape)} not broadcastable "
                    f"to logical shape {logical}")
        arr = arr.reshape(shape)
        arr = jnp.transpose(arr, _fwd_axes(self._pencil, nd_extra))
        padded = self._pencil.padded_size_global(MemoryOrder) + self._extra_dims
        pad = [(0, p - s) if s != 1 else (0, 0)
               for s, p in zip(arr.shape, padded)]
        if any(p != (0, 0) for p in pad):
            arr = jnp.pad(arr, pad)
        return arr

    @staticmethod
    def _is_scalar(x) -> bool:
        return isinstance(x, (int, float, complex, bool, np.generic)) or (
            hasattr(x, "shape") and getattr(x, "shape", None) == ()
        )

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__":
            return NotImplemented
        if kwargs.pop("out", None) is not None or kwargs:
            return NotImplemented  # out=/where=/casting= unsupported
        if getattr(ufunc, "signature", None) is not None or ufunc.nout != 1:
            # only elementwise single-output ufuncs act on the memory-order
            # parent: a gufunc (np.matmul) would contract over a MEMORY
            # axis (wrong logical axis, padding included), and nout>1
            # (np.modf) has no single wrapped result
            return NotImplemented
        f = getattr(jnp, ufunc.__name__, None)
        if f is None:
            return NotImplemented
        args = []
        for x in inputs:
            if isinstance(x, PencilArray):
                if (x._pencil != self._pencil
                        or x._extra_dims != self._extra_dims):
                    raise ValueError(
                        "operands live on different pencils; transpose "
                        "first (cf. reference broadcast.jl which requires "
                        "matching pencil configurations)"
                    )
                args.append(x._data)
            elif self._is_scalar(x):
                args.append(x)
            elif isinstance(x, (np.ndarray, jax.Array, list, tuple)):
                args.append(self._align_to_parent(x))
            else:
                return NotImplemented
        return PencilArray(self._pencil, f(*args), self._extra_dims)

    def __array_function__(self, func, types, args, kwargs):
        """Whitelisted NumPy free functions (``np.sum(u)`` etc.) forward
        to the padding-masked distributed reductions."""
        from ..ops import reductions

        table = {
            np.sum: reductions.sum,
            np.prod: reductions.prod,
            np.mean: reductions.mean,
            np.min: reductions.minimum,
            np.max: reductions.maximum,
            np.all: reductions.all,
            np.any: reductions.any,
            np.count_nonzero: reductions.count_nonzero,
        }
        if func is np.result_type:
            # dtype-only query — older jax's dtypes.dtype() probes it
            # before the __jax_array__ unwrap; answer from the dtypes
            # without materializing anything
            return np.result_type(*(a.dtype if isinstance(a, PencilArray)
                                    else a for a in args))
        f = table.get(func)
        if (f is None or kwargs or len(args) != 1
                or not isinstance(args[0], PencilArray)):
            return NotImplemented
        return f(args[0])

    # -- extra-dims components -------------------------------------------
    def component(self, *idx: int) -> "PencilArray":
        """The spatial field at extra-dims index ``idx`` (one index per
        extra dim) as a PencilArray with ``extra_dims=()`` — zero-copy at
        trace time (a trailing-axis slice of the parent)."""
        if len(idx) != len(self._extra_dims):
            raise ValueError(
                f"component expects {len(self._extra_dims)} indices, "
                f"got {len(idx)}")
        data = self._data[(Ellipsis,) + tuple(int(i) for i in idx)]
        return PencilArray(self._pencil, data, ())

    @classmethod
    def stack(cls, components: Sequence["PencilArray"]) -> "PencilArray":
        """Stack same-pencil arrays along a NEW trailing extra dim (the
        inverse of :meth:`component`)."""
        first = components[0]
        for c in components[1:]:
            if c._pencil != first._pencil or c._extra_dims != first._extra_dims:
                raise ValueError("stack: pencil/extra_dims mismatch")
        data = jnp.stack([c._data for c in components], axis=-1)
        return cls(first._pencil, data, first._extra_dims + (len(components),))

    def unstack(self) -> Tuple["PencilArray", ...]:
        """Split the trailing extra dim into a tuple of components — the
        inverse of :meth:`stack` (and the read-side of collection-level
        I/O, reference ``PencilArrayCollection`` datasets,
        ``ext/PencilArraysHDF5Ext.jl:222-229``)."""
        if not self._extra_dims:
            raise ValueError("unstack: array has no extra dims")
        n = self._extra_dims[-1]
        return tuple(
            PencilArray(self._pencil, self._data[..., i],
                        self._extra_dims[:-1])
            for i in range(n))

    # -- arithmetic (memory-order, parent-level: broadcast.jl parity) -----
    def _binop(self, other, op):
        if isinstance(other, PencilArray):
            if other._pencil != self._pencil:
                raise ValueError(
                    "operands live on different pencils; transpose first "
                    "(cf. reference broadcast.jl which requires matching "
                    "pencil configurations)"
                )
            if other._extra_dims != self._extra_dims:
                raise ValueError(
                    f"extra_dims mismatch: {self._extra_dims} vs "
                    f"{other._extra_dims}"
                )
            return PencilArray(self._pencil, op(self._data, other._data),
                               self._extra_dims)
        if self._is_scalar(other):
            return PencilArray(self._pencil, op(self._data, other),
                               self._extra_dims)
        if isinstance(other, (np.ndarray, jax.Array, list, tuple)):
            # raw array broadcastable against the logical shape: align to
            # the parent layout (zero collectives, see broadcasting note)
            return PencilArray(self._pencil,
                               op(self._data, self._align_to_parent(other)),
                               self._extra_dims)
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a)

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: b / a)

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a**b)

    def __neg__(self):
        return PencilArray(self._pencil, -self._data, self._extra_dims)

    def __abs__(self):
        return PencilArray(self._pencil, jnp.abs(self._data), self._extra_dims)

    def map(self, f, *others: "PencilArray") -> "PencilArray":
        """Elementwise map in memory order over parents — the analog of the
        reference's broadcasting, which unwraps every PencilArray and runs
        on parents so no scalar indexing / no layout churn happens
        (``broadcast.jl:31-57``)."""
        for o in others:
            if o._pencil != self._pencil:
                raise ValueError("pencil mismatch in map")
        out = f(self._data, *(o._data for o in others))
        return PencilArray(self._pencil, out, self._extra_dims)

    def astype(self, dtype) -> "PencilArray":
        """Backend/dtype adaptation — the role of ``Adapt.adapt_structure``
        (``arrays.jl:142-146``) for element types."""
        return PencilArray(self._pencil, self._data.astype(dtype),
                           self._extra_dims)

    @property
    def real(self) -> "PencilArray":
        return PencilArray(self._pencil, self._data.real, self._extra_dims)

    @property
    def imag(self) -> "PencilArray":
        return PencilArray(self._pencil, self._data.imag, self._extra_dims)

    def conj(self) -> "PencilArray":
        return PencilArray(self._pencil, jnp.conj(self._data),
                           self._extra_dims)

    def copy(self) -> "PencilArray":
        return PencilArray(self._pencil, jnp.copy(self._data),
                           self._extra_dims)

    def fill(self, value) -> "PencilArray":
        """Return a filled copy (reference ``fill!``, ``arrays.jl:494-526``)."""
        return PencilArray(
            self._pencil, jnp.full_like(self._data, value), self._extra_dims
        )

    # -- comparison -------------------------------------------------------
    def equals(self, other: "PencilArray"):
        """Elementwise-equality reduction as a traced scalar ``jax.Array``
        — the jit-safe form of ``==``.  Compares logical (true-shape)
        views: tail padding is storage detail and may legitimately differ
        (e.g. after scalar arithmetic which also touches padding)."""
        if not isinstance(other, PencilArray):
            raise TypeError(f"equals() expects a PencilArray, got "
                            f"{type(other).__name__}")
        if self._pencil != other._pencil or self._extra_dims != other._extra_dims:
            return jnp.asarray(False)
        return (self.logical() == other.logical()).all()

    def __eq__(self, other):
        # Eager-only (returns a Python bool): inside jit, use equals().
        if isinstance(other, PencilArray):
            eq = self.equals(other)
            try:
                return bool(eq)
            except jax.errors.TracerBoolConversionError:
                raise TypeError(
                    "PencilArray == PencilArray returns a Python bool and "
                    "is eager-only; inside jit-traced code use "
                    "u.equals(v), which returns a traced scalar"
                ) from None
        return NotImplemented

    __hash__ = None

    def allclose(self, other: "PencilArray", **kw) -> bool:
        if self._pencil != other._pencil:
            raise ValueError("pencil mismatch")
        return bool(jnp.allclose(self.logical(), other.logical(), **kw))

    def __repr__(self) -> str:
        return (
            f"PencilArray(shape={self.shape}, dtype={self.dtype}, "
            f"pencil={self._pencil!r}, extra_dims={self._extra_dims})"
        )


jax.tree_util.register_pytree_node(
    PencilArray,
    lambda x: x.tree_flatten(),
    PencilArray.tree_unflatten,
)


def global_view(x: PencilArray) -> PencilArray:
    """Reference ``global_view`` (``global_view.jl``): returns an object
    indexed by global indices.  Here the PencilArray already *is* globally
    indexed, so this is the identity (kept for API parity)."""
    return x
