"""Multi-host (multi-process) support — the ``mpiexec`` analog.

The reference runs SPMD under ``mpiexec`` with MPI as the wire
(``test/runtests.jl:48-53``); scaling past one host is free because every
rank is its own process.  JAX is single-controller *per process* but
multi-process capable: each host runs the same program, connected through
:func:`jax.distributed.initialize`, and ``jax.devices()`` then spans all
hosts, so a :class:`~pencilarrays_tpu.parallel.topology.Topology` built
from it covers the full pod slice and XLA lays collectives across
ICI *and* DCN automatically.

This module wraps the bootstrap and the few host-aware queries the rest
of the framework needs.  Single-process use (including the CPU test mesh)
needs none of this — every function degrades to the trivial answer.
"""

from __future__ import annotations

from typing import Optional

import jax

__all__ = [
    "initialize",
    "is_initialized",
    "process_index",
    "process_count",
    "is_multiprocess",
    "local_devices",
    "sync_global_devices",
]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, **kw) -> None:
    """Connect this process to the multi-host job
    (``jax.distributed.initialize``; on Cloud TPU all arguments are
    auto-detected from the metadata server).  Call before any jax API,
    exactly once per process — the moral equivalent of ``MPI.Init``."""
    global _initialized
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kw)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    """This host's index (the reference's ``MPI.Comm_rank`` over hosts)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def local_devices():
    return jax.local_devices()


def sync_global_devices(name: str = "pa_barrier") -> None:
    """Cross-host barrier (``MPI.Barrier`` analog)."""
    if is_multiprocess():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
