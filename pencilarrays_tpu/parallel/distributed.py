"""Multi-host (multi-process) support — the ``mpiexec`` analog.

The reference runs SPMD under ``mpiexec`` with MPI as the wire
(``test/runtests.jl:48-53``); scaling past one host is free because every
rank is its own process.  JAX is single-controller *per process* but
multi-process capable: each host runs the same program, connected through
:func:`jax.distributed.initialize`, and ``jax.devices()`` then spans all
hosts, so a :class:`~pencilarrays_tpu.parallel.topology.Topology` built
from it covers the full pod slice and XLA lays collectives across
ICI *and* DCN automatically.

This module wraps the bootstrap and the few host-aware queries the rest
of the framework needs.  Single-process use (including the CPU test mesh)
needs none of this — every function degrades to the trivial answer.

Resilience (see ``docs/Resilience.md``): the coordinator connection is
the first cross-process rendezvous of a job and the coordinator may
simply not be up yet when a restarted worker arrives — so
:func:`initialize` retries under a
:class:`~pencilarrays_tpu.resilience.RetryPolicy` (bounded exponential
backoff, not a hang and not a crash), guards against double
initialization with a clear error instead of an opaque jax failure, and
both it and :func:`sync_global_devices` consult the ``dist.initialize``
/ ``barrier`` fault-injection points.
"""

from __future__ import annotations

import re
from typing import Optional

import jax

from .. import guard
from ..guard.errors import HangTimeoutError
from ..resilience import faults
from ..resilience.retry import RetryPolicy

__all__ = [
    "initialize",
    "ensure_initialized",
    "is_initialized",
    "kv_client",
    "process_index",
    "process_count",
    "is_multiprocess",
    "local_devices",
    "sync_global_devices",
]

_initialized = False


def _jax_already_initialized() -> bool:
    """Probe jax's own distributed state (version-tolerant): True when a
    coordinator client exists even if it was created outside this
    module."""
    state = getattr(jax.distributed, "global_state", None)
    return getattr(state, "client", None) is not None


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, *,
               retry: Optional[RetryPolicy] = None, **kw) -> None:
    """Connect this process to the multi-host job
    (``jax.distributed.initialize``; on Cloud TPU all arguments are
    auto-detected from the metadata server).  Call before any jax API,
    exactly once per process — the moral equivalent of ``MPI.Init``.

    Calling it twice raises a clear ``RuntimeError`` up front (instead
    of an opaque failure from inside jax); use :func:`ensure_initialized`
    for idempotent bootstrap paths like restart workers.  The
    coordinator connection is retried on transient failures under
    ``retry`` (default: env-tuned
    :meth:`~pencilarrays_tpu.resilience.RetryPolicy.from_env`) — a
    coordinator that is not up *yet* is backed off against, bounded by
    the policy deadline.  ``_initialized`` flips only after the
    connection succeeds."""
    global _initialized
    if _initialized or _jax_already_initialized():
        raise RuntimeError(
            "distributed.initialize() called twice: jax.distributed is "
            "already connected in this process.  Use ensure_initialized() "
            "if the call site cannot know whether bootstrap already "
            "happened (e.g. a restart worker).")
    policy = retry or RetryPolicy.from_env()
    # align jax's own connect timeout with the policy deadline (its
    # default is 300 s, which would make a single attempt outlive the
    # whole retry budget — the deadline is only checked between attempts)
    kw.setdefault("initialization_timeout", max(1, int(policy.deadline)))

    def _connect():
        faults.fire("dist.initialize", coordinator=coordinator_address,
                    process_id=process_id)
        try:
            # each connect attempt runs under the guard's hang watchdog
            # (no-op when PENCILARRAYS_TPU_GUARD is off): a wedged
            # coordinator produces a crash bundle + typed
            # HangTimeoutError instead of relying solely on jax's
            # clamped internal timeout — and because HangTimeoutError
            # is a TimeoutError, the retry policy backs off against it
            # like any other transient rendezvous failure
            with guard.watchdog("dist.initialize", kind="dist",
                                coordinator=coordinator_address,
                                process_id=process_id):
                jax.distributed.initialize(coordinator_address,
                                           num_processes, process_id, **kw)
        except HangTimeoutError:
            _reset_jax_partial_state()
            raise
        except RuntimeError as e:
            # A failed connect leaves jax's global_state partially set
            # (client/service created before connect()), which would make
            # every retry die on jax's 'should only be called once' guard
            # AND make is_initialized() lie — reset it first.
            _reset_jax_partial_state()
            # jax wraps coordinator-unreachable in RuntimeError; surface
            # the TRANSIENT-looking ones as ConnectionError so the policy
            # retries them, while config errors (bad address, mismatched
            # process counts, already-initialized) still fail fast
            if re.search(r"unavailable|refused|unreachable|reset|"
                         r"connect|timed.?out|deadline",
                         str(e), re.IGNORECASE):
                raise ConnectionError(str(e)) from e
            raise
        except Exception:
            _reset_jax_partial_state()
            raise

    from ..obs import enabled as _obs_enabled, record_event as _record_event

    if _obs_enabled():
        _record_event("dist.init", status="connecting",
                      coordinator=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    try:
        policy.call(_connect, label="dist.initialize")
    except BaseException as e:
        if _obs_enabled():
            _record_event("dist.init", status="failed",
                          error=f"{type(e).__name__}: {e}")
        raise
    _initialized = True
    if _obs_enabled():
        # read the coordinator-assigned identity from jax.distributed's
        # own state — jax.process_index() here would eagerly build the
        # XLA backend as a side effect, which is not this function's job
        state = getattr(jax.distributed, "global_state", None)
        _record_event(
            "dist.init", status="connected",
            process_id=getattr(state, "process_id", process_id),
            num_processes=getattr(state, "num_processes", num_processes))


def _reset_jax_partial_state() -> None:
    """Best-effort rollback of a half-initialized ``jax.distributed``
    ``global_state`` (client/service objects created before a failed
    ``connect()``), releasing the coordinator port so a retry can bind
    again.  Version-tolerant: unknown shapes are left untouched."""
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        return
    for attr in ("client", "service", "preemption_sync_manager"):
        obj = getattr(state, attr, None)
        if obj is None:
            continue
        try:
            obj.shutdown()
        except Exception:
            pass
        try:
            setattr(state, attr, None)
        except Exception:
            pass
    if getattr(state, "coordinator_address", None) is not None:
        try:
            state.coordinator_address = None
        except Exception:
            pass


def _multihost_env() -> bool:
    """Does the environment itself declare a multi-host job (Cloud TPU
    pod metadata), so an argument-less bootstrap should auto-detect?"""
    import os

    return any(k in os.environ for k in (
        "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
        "MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))


def ensure_initialized(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None, **kw) -> bool:
    """Idempotent :func:`initialize`: connect if (and only if) this
    process is not yet part of the job.  Returns True when it actually
    initialized.  No-op cases — so restart workers can call this
    untouched whatever configuration they relaunch under:

    * already connected (by us or by a direct jax call);
    * an explicitly single-process configuration (``num_processes`` <= 1
      with no coordinator address);
    * no arguments at all *and* no pod-environment markers — a plain
      local run.  On a Cloud TPU pod slice the metadata environment
      (``TPU_WORKER_ID`` etc.) is detected and the argument-less
      auto-bootstrap still happens, matching ``initialize()``'s
      auto-detection contract."""
    if is_initialized():
        return False
    if coordinator_address is None:
        if num_processes is not None and num_processes <= 1:
            return False  # explicitly single-process
        if num_processes is None and process_id is None and not kw \
                and not _multihost_env():
            return False  # plain local run, nothing to auto-detect
    initialize(coordinator_address, num_processes, process_id, **kw)
    return True


def is_initialized() -> bool:
    return _initialized or _jax_already_initialized()


def kv_client():
    """The job's distributed key-value store client (the coordinator
    service every ``jax.distributed`` job runs) — ``None`` before
    :func:`initialize` or in single-process runs.  The cluster
    coordination layer (``pencilarrays_tpu.cluster``) builds its
    consensus/lease wire on this; reading it never initializes
    anything (the obs ``_process_index`` convention)."""
    state = getattr(jax.distributed, "global_state", None)
    return getattr(state, "client", None)


def process_index() -> int:
    """This host's index (the reference's ``MPI.Comm_rank`` over hosts)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def local_devices():
    return jax.local_devices()


def sync_global_devices(name: str = "pa_barrier") -> None:
    """Cross-host barrier (``MPI.Barrier`` analog).  Consults the
    ``barrier`` fault point (before the single-process early-out, so
    chaos tests can drill barrier failures on one process too).  With
    the integrity guard armed, the wait runs under the hang watchdog —
    a peer that never arrives produces a crash bundle and a typed
    ``HangTimeoutError`` instead of an unexplained stall."""
    faults.fire("barrier", name=name)
    if is_multiprocess():
        from jax.experimental import multihost_utils

        with guard.watchdog(f"barrier:{name}", kind="barrier"):
            multihost_utils.sync_global_devices(name)
