"""Gather a distributed array to the host — verification/debug path.

Reference ``src/gather.jl``: every rank un-permutes its block, converts to
a CPU array, and ``Isend``s it to the root, which assembles the global
array (``gather.jl:17-100``).  Root-only return made sense per-rank; under
single-controller JAX the analog is simply fetching the logical view to
host memory (``jax.device_get`` of the unpermuted, unpadded global value)
— one collective-free device->host copy per shard, assembled by the
runtime.

Like the reference (``docs/src/Transpositions.md:18-24``), this is meant
for tests and debugging, not the hot path.
"""

from __future__ import annotations

import numpy as np

from .arrays import PencilArray

__all__ = ["gather"]


def gather(x: PencilArray, root: int = 0) -> np.ndarray:
    """Return the full global array (logical order, true shape) as NumPy.

    The ``root`` argument exists for signature parity with the reference
    (``gather(x, root=0)``); in a single-controller program every caller
    is "root", so the array is always returned.
    """
    del root
    import jax

    from ..utils.timers import timeit

    with timeit(x.pencil.timer, "gather"):
        if jax.process_count() > 1:
            # multi-host: the logical view is not fully addressable here;
            # all-gather it across hosts (the Isend-to-root of gather.jl,
            # except every host receives — single-controller semantics)
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(x.logical(), tiled=True))
        return np.asarray(x)
