"""ManyPencilArray — one storage budget shared across pencil configurations.

Reference ``src/multiarrays.jl``: M ``PencilArray`` views over **one** flat
buffer sized for the largest configuration (``multiarrays.jl:106-130``),
built with ``unsafe_wrap`` pointer aliasing, enabling in-place transposes
(``transpose!(A[i+1], A[i])`` writes into the same memory).

Pointer aliasing cannot (and should not) be replicated under XLA, where
buffer reuse is the compiler's job.  The contract is therefore
**re-specified**: a :class:`ManyPencilArray` owns the *chain* of pencil
configurations and exactly **one live array at a time** — the "current"
configuration.  :meth:`transpose_to` moves the data to another
configuration with **buffer donation**, so XLA may write the exchange
output into the donated source allocation: the reference's in-place
semantics, expressed as a donation rather than an alias.  Accessing a
non-current configuration's view raises, which makes the aliasing hazard
(reading a stale view) a structural impossibility instead of a runtime
race (cf. the reference's ``Base.mightalias`` machinery,
``Transpositions.jl:250-264``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .arrays import PencilArray
from .pencil import Pencil
from .transpositions import AbstractTransposeMethod, AllToAll, transpose

__all__ = ["ManyPencilArray"]


class ManyPencilArray:
    """A chain of pencil configurations sharing one storage budget."""

    def __init__(self, *pencils: Pencil, dtype=jnp.float32,
                 extra_dims: Tuple[int, ...] = (),
                 first: Optional[PencilArray] = None):
        if not pencils:
            raise ValueError("need at least one pencil")
        topo = pencils[0].topology
        shape = pencils[0].size_global()
        for p in pencils[1:]:
            if p.topology != topo:
                raise ValueError("all pencils must share a topology")
            if p.size_global() != shape:
                raise ValueError("all pencils must share the global shape")
        self._pencils = tuple(pencils)
        self._index = 0
        if first is not None:
            if first.pencil != pencils[0]:
                raise ValueError("`first` must live on the first pencil")
            self._array = first
        else:
            self._array = PencilArray.zeros(pencils[0], tuple(extra_dims),
                                            dtype)

    # -- queries ---------------------------------------------------------
    @property
    def pencils(self) -> Tuple[Pencil, ...]:
        return self._pencils

    def __len__(self) -> int:
        return len(self._pencils)

    @property
    def index(self) -> int:
        """Index of the live configuration."""
        return self._index

    @property
    def current(self) -> PencilArray:
        return self._array

    @property
    def first(self) -> PencilArray:
        """Reference ``first(A)`` (``multiarrays.jl:40-47``) — valid only
        while configuration 0 is live."""
        return self[0]

    @property
    def last(self) -> PencilArray:
        return self[len(self._pencils) - 1]

    def __getitem__(self, i: int) -> PencilArray:
        """Reference ``A[i]`` (``multiarrays.jl:70-79``), restricted to the
        live configuration (stale views are unrepresentable)."""
        if i != self._index:
            raise RuntimeError(
                f"configuration {i} is not live (current: {self._index}); "
                f"call transpose_to({i}) first — stale views are invalid "
                f"by construction in the XLA re-specification"
            )
        return self._array

    # -- mutation --------------------------------------------------------
    def set(self, arr: PencilArray) -> None:
        """Install data for whichever configuration ``arr`` lives on."""
        try:
            i = self._pencils.index(arr.pencil)
        except ValueError:
            raise ValueError("array's pencil is not part of this chain")
        self._index = i
        self._array = arr

    def transpose_to(self, i: int, *,
                     method: AbstractTransposeMethod = AllToAll(),
                     donate: bool = True) -> PencilArray:
        """Move the live data to configuration ``i`` (donating the source
        buffer by default) — the in-place ``transpose!(A[i], A[j])`` of the
        reference.  Non-adjacent configurations are reached by hopping
        through the intermediate ones, exactly like the reference's
        chained x->y->z transposes (single-axis change per hop,
        ``Transpositions.jl:182-199``)."""
        if not (0 <= i < len(self._pencils)):
            raise IndexError(f"configuration {i} out of range")
        step = 1 if i > self._index else -1
        while self._index != i:
            nxt = self._index + step
            self._array = transpose(self._array, self._pencils[nxt],
                                    method=method, donate=donate)
            self._index = nxt
        return self._array

    def reshard_to(self, i: int, *, donate: bool = True,
                   method: Optional[AbstractTransposeMethod] = None
                   ) -> PencilArray:
        """Jump the live data straight to configuration ``i`` as ONE
        routed reshard: the route planner (``parallel/routing.py``)
        searches the pencil graph and the winner executes as a single
        fused program — unlike :meth:`transpose_to`, which Python-loops
        through this chain's intermediate configurations one dispatch
        per hop.  Equivalent data movement, fewer dispatches; the
        planner may even find a cheaper chain than the stored one."""
        from .transpositions import Auto, reshard

        if not (0 <= i < len(self._pencils)):
            raise IndexError(f"configuration {i} out of range")
        if i == self._index:
            return self._array
        self._array = reshard(self._array, self._pencils[i],
                              method=method if method is not None else Auto(),
                              donate=donate)
        self._index = i
        return self._array

    def cycle(self, *, method: AbstractTransposeMethod = AllToAll()):
        """Generator over the full chain 0 -> 1 -> ... -> M-1, yielding
        each configuration's array (the x->y->z sweep of a PencilFFT)."""
        if self._index != 0:
            self.transpose_to(0, method=method)
        yield self._array
        for i in range(1, len(self._pencils)):
            yield self.transpose_to(i, method=method)

    def __repr__(self) -> str:
        return (
            f"ManyPencilArray(n={len(self._pencils)}, live={self._index}, "
            f"shape={self._array.shape}, dtype={self._array.dtype})"
        )
