"""Pencil (block) decomposition descriptor.

TPU-native re-design of the decomposition core of the reference:
``src/Pencils/Pencils.jl`` (struct at ``Pencils.jl:151-192``),
``src/Pencils/data_ranges.jl`` and ``src/Pencils/index_orders.jl``.

A :class:`Pencil` describes how an N-dimensional global array is decomposed
over an M-dimensional :class:`~pencilarrays_tpu.parallel.topology.Topology`
along ``M <= N`` chosen *logical* dimensions, with an optional compile-time
:class:`~pencilarrays_tpu.utils.permutations.Permutation` selecting the
*memory* (storage) order of the local/global data.

Design deltas vs the reference, driven by the TPU execution model:

* **Block distribution rule.** The reference assigns rank ``p`` of ``P``
  the rows ``(n*(p-1))÷P+1 : (n*p)÷P`` (``data_ranges.jl:4-9``) — balanced
  with the remainder spread across ranks.  XLA's GSPMD partitioner instead
  requires equal shard extents, so we use the *ceil-block* rule: with
  ``b = ceil(n / P)``, rank ``p`` owns ``[p*b, min((p+1)*b, n))`` and the
  global dim is padded to ``P*b`` in device memory.  Both rules are
  contiguous and near-even; ours additionally matches the device layout
  XLA produces, so shard math and compiler bookkeeping agree.  Padding
  always sits at the *tail* of the padded dim, which keeps the all-to-all
  transpose exchange a pure pad → exchange → slice pipeline.
* **Shared send/recv buffers** (``Pencils.jl:151-192``) do not exist:
  buffer reuse and aliasing are XLA's job (donation at the jit boundary).
* ``MemoryOrder``/``LogicalOrder`` singleton tags (``index_orders.jl``)
  become the :class:`IndexOrder` enum with the same default (logical).

A Pencil is frozen and hashable, so it can be a static argument under
``jax.jit`` — all its math happens at trace time.
"""

from __future__ import annotations

import enum
import math
import warnings
from functools import cached_property
from typing import Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec

from ..utils.permutations import (
    AbstractPermutation,
    NO_PERMUTATION,
    PermutationLike,
    as_permutation,
)
from .topology import Topology

__all__ = [
    "IndexOrder",
    "MemoryOrder",
    "LogicalOrder",
    "Pencil",
    "local_data_range",
    "complete_dims",
]


class IndexOrder(enum.Enum):
    """Which of the two index views an accessor returns
    (reference ``index_orders.jl:9-27``; default is logical)."""

    LOGICAL = "logical"
    MEMORY = "memory"


LogicalOrder = IndexOrder.LOGICAL
MemoryOrder = IndexOrder.MEMORY


def local_data_range(p: int, P: int, n: int) -> range:
    """Range of global indices owned by block ``p`` (0-based) of ``P`` along a
    dim of true size ``n`` — ceil-block rule (see module docstring for the
    deliberate divergence from reference ``data_ranges.jl:4-9``).

    May be empty for tail blocks when ``P`` approaches/exceeds ``n``.
    """
    b = -(-n // P)  # ceil
    lo = min(p * b, n)
    hi = min((p + 1) * b, n)
    return range(lo, hi)


def complete_dims(ndims: int, decomp_dims: Sequence[int], vals: Sequence[int],
                  fill: int = 1) -> Tuple[int, ...]:
    """Scatter per-decomposed-dim values into a full ``ndims`` tuple, padding
    undecomposed dims with ``fill`` (reference ``data_ranges.jl:15-26``)."""
    out = [fill] * ndims
    for d, v in zip(decomp_dims, vals):
        out[d] = v
    return tuple(out)


class Pencil:
    """Decomposition descriptor (reference ``Pencil{N,M,P}``,
    ``Pencils.jl:151-192``).

    Parameters
    ----------
    topology:
        M-dimensional device topology.  Decomposed dim ``decomp_dims[i]`` is
        sharded over topology axis ``i`` (mesh axis name
        ``topology.axis_names[i]``).
    global_shape:
        True global *logical* shape (N dims, unpadded).
    decomp_dims:
        The ``M`` logical dims to decompose (0-based).  Defaults to the
        *last* ``M`` dims — matching the reference's
        ``default_decomposition`` which picks ``(2, 3, ..., M+1)`` i.e.
        skips the leading dim (``Pencils.jl:387-390``).
    permutation:
        Logical→memory index permutation (``None`` = no permutation).
    """

    def __init__(
        self,
        topology: Topology,
        global_shape: Sequence[int],
        decomp_dims: Optional[Sequence[int]] = None,
        *,
        permutation: PermutationLike = None,
        timer=None,
    ):
        global_shape = tuple(int(n) for n in global_shape)
        if any(n < 0 for n in global_shape):
            raise ValueError(f"invalid global shape {global_shape}")
        N = len(global_shape)
        M = topology.ndims
        if decomp_dims is None:
            # Reference default: decompose the *last* M dims so that the
            # leading (fastest / FFT) dim stays local (cf.
            # ``Pencils.jl:387-390`` default_decomposition -> (2, 3)).
            decomp_dims = tuple(range(N - M, N))
        decomp_dims = tuple(int(d) for d in decomp_dims)
        self._check_selected_dimensions(N, M, decomp_dims)
        self._topology = topology
        self._global_shape = global_shape
        self._decomp_dims = decomp_dims
        self._perm = as_permutation(permutation, N)
        self.timer = timer  # shared, excluded from eq/hash (Pencils.jl:191)
        self._warn_empty_ranks()

    # -- validation -------------------------------------------------------
    @staticmethod
    def _check_selected_dimensions(N: int, M: int, decomp: Tuple[int, ...]):
        # Mirrors ``Pencils.jl:393-406``.
        if len(decomp) != M:
            raise ValueError(
                f"number of decomposed dims ({len(decomp)}) must match "
                f"topology ndims ({M})"
            )
        if len(set(decomp)) != len(decomp):
            raise ValueError(f"decomposed dims must be unique: {decomp}")
        for d in decomp:
            if not (0 <= d < N):
                raise ValueError(f"decomposed dim {d} out of range 0..{N-1}")

    def _warn_empty_ranks(self):
        # Reference warns when P_i > N_i leaves ranks without data
        # (``Pencils.jl:193-218``).
        for d, P in zip(self._decomp_dims, self._topology.dims):
            n = self._global_shape[d]
            b = -(-n // P) if P else 0
            if P > 1 and (n == 0 or (P - 1) * b >= n):
                warnings.warn(
                    f"Pencil: decomposed dim {d} (size {n}) over {P} devices "
                    f"leaves some devices with no data; performance will "
                    f"suffer (cf. reference Pencils.jl:193-218)",
                    stacklevel=3,
                )

    # -- basic accessors --------------------------------------------------
    @property
    def topology(self) -> Topology:
        return self._topology

    @property
    def ndims(self) -> int:
        return len(self._global_shape)

    @property
    def decomposition(self) -> Tuple[int, ...]:
        """Decomposed logical dims (reference ``decomposition(p)``)."""
        return self._decomp_dims

    @property
    def permutation(self) -> AbstractPermutation:
        return self._perm

    @property
    def mesh(self):
        return self._topology.mesh

    def decomp_axis_name(self, dim: int) -> Optional[str]:
        """Mesh axis name sharding logical dim ``dim`` (or None if local)."""
        try:
            i = self._decomp_dims.index(dim)
        except ValueError:
            return None
        return self._topology.axis_names[i]

    def proc_count(self, dim: int) -> int:
        """Number of blocks along logical dim ``dim`` (1 if not decomposed)."""
        try:
            i = self._decomp_dims.index(dim)
        except ValueError:
            return 1
        return self._topology.dims[i]

    # -- shapes -----------------------------------------------------------
    def size_global(self, order: IndexOrder = LogicalOrder) -> Tuple[int, ...]:
        """True global shape (reference ``size_global``, ``Pencils.jl:555-559``)."""
        if order is MemoryOrder:
            return self._perm.apply(self._global_shape)
        return self._global_shape

    @cached_property
    def padded_global_shape(self) -> Tuple[int, ...]:
        """Global logical shape with each decomposed dim rounded up to a
        multiple of its device count — the shape of the backing
        ``jax.Array`` (in memory order) before un-padding."""
        out = list(self._global_shape)
        for d, P in zip(self._decomp_dims, self._topology.dims):
            out[d] = P * (-(-out[d] // P)) if out[d] else 0
        return tuple(out)

    def padded_size_global(self, order: IndexOrder = LogicalOrder):
        if order is MemoryOrder:
            return self._perm.apply(self.padded_global_shape)
        return self.padded_global_shape

    def range_local(self, coords: Sequence[int],
                    order: IndexOrder = LogicalOrder) -> Tuple[range, ...]:
        """Global index ranges owned by the block at topology ``coords``
        (reference ``range_local``, ``Pencils.jl:512-514``)."""
        ranges = []
        for d, n in enumerate(self._global_shape):
            try:
                i = self._decomp_dims.index(d)
            except ValueError:
                ranges.append(range(0, n))
            else:
                ranges.append(local_data_range(coords[i], self._topology.dims[i], n))
        t = tuple(ranges)
        return self._perm.apply(t) if order is MemoryOrder else t

    def range_remote(self, rank_or_coords,
                     order: IndexOrder = LogicalOrder) -> Tuple[range, ...]:
        """Ranges owned by an arbitrary rank (reference ``range_remote``,
        ``Pencils.jl:529-536``)."""
        if isinstance(rank_or_coords, int):
            coords = self._topology.coords(rank_or_coords)
        else:
            coords = tuple(rank_or_coords)
        return self.range_local(coords, order)

    @cached_property
    def axes_all(self):
        """Owner table: an object-array over topology dims whose entry at
        ``coords`` is the logical-order range tuple owned by that block
        (reference ``generate_axes_matrix``, ``data_ranges.jl:30-45``)."""
        import numpy as np

        out = np.empty(self._topology.dims, dtype=object)
        for rank in range(len(self._topology)):
            coords = self._topology.coords(rank)
            out[coords] = self.range_local(coords, LogicalOrder)
        return out

    def size_local(self, coords: Sequence[int] = None,
                   order: IndexOrder = LogicalOrder) -> Tuple[int, ...]:
        """Local block shape at ``coords`` (defaults to coords (0,..,0));
        reference ``size_local`` (``Pencils.jl:546-551``)."""
        if coords is None:
            coords = (0,) * self._topology.ndims
        return tuple(len(r) for r in self.range_local(coords, order))

    def padded_size_local(self, order: IndexOrder = LogicalOrder):
        """Equal per-device block shape of the padded backing array."""
        out = []
        for d, n in enumerate(self.padded_global_shape):
            out.append(n // self.proc_count(d))
        t = tuple(out)
        return self._perm.apply(t) if order is MemoryOrder else t

    def length_global(self) -> int:
        return math.prod(self._global_shape)

    def length_local(self, coords=None) -> int:
        return math.prod(self.size_local(coords))

    def bytes_per_device(self, extra_dims: Sequence[int] = (),
                         dtype=None, *, isize: Optional[int] = None) -> int:
        """Per-chip bytes of the padded backing block (+ replicated
        extra dims) — the HBM accounting unit the reshard route
        planner's peak bound uses (``parallel/routing.py``).  ``isize``
        overrides the dtype's itemsize when the caller already has it."""
        import numpy as np

        if isize is None:
            isize = np.dtype(dtype if dtype is not None
                             else np.float32).itemsize
        n = math.prod(self.padded_size_local(LogicalOrder))
        for e in extra_dims:
            n *= int(e)
        return n * int(isize)

    def to_local(self, global_inds: Sequence[int], coords: Sequence[int] = None,
                 order: IndexOrder = LogicalOrder) -> Tuple[int, ...]:
        """Convert global indices to indices local to the block at ``coords``
        (reference ``to_local``, ``Pencils.jl:579-587``)."""
        if coords is None:
            coords = (0,) * self._topology.ndims
        ranges = self.range_local(coords, order)
        return tuple(int(i) - r.start for i, r in zip(global_inds, ranges))

    # -- sharding ---------------------------------------------------------
    def partition_spec(self, extra_ndims: int = 0) -> PartitionSpec:
        """PartitionSpec of the *memory-order* backing array (+ trailing
        replicated extra dims, cf. ``arrays.jl:34-47``)."""
        mem_dims = self._perm.apply(tuple(range(self.ndims)))
        entries = [self.decomp_axis_name(d) for d in mem_dims]
        entries += [None] * extra_ndims
        return PartitionSpec(*entries)

    def sharding(self, extra_ndims: int = 0) -> NamedSharding:
        return NamedSharding(self.mesh, self.partition_spec(extra_ndims))

    # -- derivation -------------------------------------------------------
    def replace(self, *, decomp_dims=None, permutation="keep",
                global_shape=None, timer="keep") -> "Pencil":
        """Derive a new pencil sharing this topology — the analog of the
        reference's derived constructor ``Pencil(p; decomp_dims, permute)``
        (``Pencils.jl:257-271``; buffer sharing is moot under XLA)."""
        return Pencil(
            self._topology,
            self._global_shape if global_shape is None else global_shape,
            self._decomp_dims if decomp_dims is None else decomp_dims,
            permutation=self._perm if permutation == "keep" else permutation,
            timer=self.timer if timer == "keep" else timer,
        )

    def similar(self, global_shape=None) -> "Pencil":
        """Same decomposition over a (possibly) new global shape
        (reference ``similar(p, dims)``, ``Pencils.jl:315-361``)."""
        return self.replace(global_shape=global_shape)

    # -- comparison / hashing --------------------------------------------
    def _key(self):
        return (
            self._topology,
            self._global_shape,
            self._decomp_dims,
            self._perm,
        )

    def __eq__(self, other) -> bool:
        if not isinstance(other, Pencil):
            return NotImplemented
        return self._key() == other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    def __repr__(self) -> str:
        return (
            f"Pencil(shape={self._global_shape}, decomp={self._decomp_dims}, "
            f"topo={self._topology.dims}, perm={self._perm})"
        )


def make_pencil(
    global_shape: Sequence[int],
    ndims_decomp: Optional[int] = None,
    *,
    devices=None,
    permutation: PermutationLike = None,
    timer=None,
) -> Pencil:
    """Convenience constructor from a device list — the analog of
    ``Pencil(dims_global, comm)`` (``Pencils.jl:274-280``): builds a balanced
    topology over all devices decomposing the last ``ndims_decomp`` dims
    (default ``N - 1``)."""
    N = len(global_shape)
    if ndims_decomp is None:
        ndims_decomp = max(N - 1, 1)
    topo = Topology.auto(ndims_decomp, devices=devices)
    return Pencil(topo, global_shape, permutation=permutation, timer=timer)
