"""Cost-driven reshard route planner — searched single-axis hop chains.

The unrestricted :func:`~pencilarrays_tpu.parallel.transpositions.reshard`
historically punted every multi-slot redistribution to one opaque
GSPMD-partitioned exchange.  "Memory-efficient array redistribution
through portable collective communication" (arXiv:2112.01075) shows the
alternative: decompose the redistribution into a *searched sequence* of
cheap single-axis collectives, each of which the framework can price,
schedule and verify.  This module is that planner for pencil
configurations:

* **nodes** — every valid decomposition assignment on the topology:
  ordered tuples ``(d_0, ..., d_{M-1})`` of distinct logical dims,
  slot ``i`` riding mesh axis ``i`` (the state space the reference's
  x->y->z chains walk by hand);
* **edges** — single-slot exchanges (exactly what
  :func:`~pencilarrays_tpu.parallel.transpositions.transpose` executes),
  priced by the validated analytic byte model
  (:func:`~pencilarrays_tpu.parallel.transpositions.transpose_cost`) in
  the same bytes-equivalent score :class:`Auto` uses
  (``count * latency_bytes + bytes``), and **corrected by the PR-3
  drift tracker** when trusted timing samples exist for an edge (a hop
  drifting to 2x its modeled time gets its bytes doubled in the search);
* **search** — Dijkstra from ``src.decomposition`` to
  ``dest.decomposition`` with a per-hop peak-HBM bound (the exchange
  operand + result must fit); an edge that busts the bound is not
  simply pruned: the planner first tries to **synthesize** a feasible
  variant by time-slicing the exchange into K smaller collectives
  (``Pipelined(chunks=K)`` along an exchange-untouched dim — the
  reference's memory-bounded redistribution move, arXiv:2112.01075
  §4), priced at its true time-sliced footprint (live input slice +
  one in-flight wire chunk + accumulated output) and its true cost
  (count ×K, bytes unchanged).  Donation is part of edge pricing: a
  non-donated source block stays resident under the whole fused chain
  and is charged on every edge, while ``donate=True`` retires it into
  the first hop chunk-by-chunk — so donating admits routes that
  non-donating pricing still prunes;
* **baseline** — the GSPMD reshard, priced from its own partitioned HLO
  (:func:`~pencilarrays_tpu.parallel.transpositions.gspmd_reshard_cost`),
  so the verdict is a like-for-like byte comparison.  The planner never
  selects a route the model prices worse than GSPMD; when the search
  finds no admissible route at all (e.g. a fully-decomposed topology,
  where no single-slot move exists) it falls back to GSPMD.

The winning route executes as **one fused jitted chain**
(:func:`execute_route`): every hop's pack -> exchange -> unpack is traced
into a single XLA program, so intermediates are compiler-owned buffers
(donated by construction) and per-hop Python dispatch disappears —
the whole-redistribution analog of the FFT plan's fused pipelined hops.

Every planning decision is journaled as a ``route.plan`` event
(candidates, predicted bytes, verdict) when observability is armed.

Determinism on pods: drift correction uses *process-local* samples, so
with ``jax.process_count() > 1`` it is disabled and the plan is a pure
function of the (identical) static configuration — every process builds
the same collective program, the same discipline as measure-mode Auto's
broadcast winner.
"""

from __future__ import annotations

import heapq
import warnings
from dataclasses import dataclass
from functools import lru_cache
from itertools import permutations as _iperms
from typing import Dict, Optional, Tuple

import jax

from .. import guard, obs
from ..obs.drift import drift_tracker
from ..resilience import faults
from .arrays import PencilArray
from .pencil import Pencil
from .transpositions import (
    AbstractTransposeMethod,
    AllToAll,
    Auto,
    Gspmd,
    Pipelined,
    Ring,
    _chunk_bounds,
    _exchange_factory,
    _exchange_operand_extents,
    _exchange_transpose,
    _hop_label,
    _method_label,
    _method_wire,
    _metered_cached,
    _pipeline_chunk_axis,
    _transpose_local,
    assert_compatible,
    gspmd_reshard_cost,
    resolve_method,
    transpose_cost,
)
from .wire import cast_score_bytes, wire_bytes

__all__ = [
    "ReshardRoute",
    "RouteHop",
    "plan_reshard_route",
    "execute_route",
    "reshard_key",
    "trusted_drift_hops",
    "trusted_drift",
]


def reshard_key(pin: Pencil, dest: Pencil, dtype=None, method=None,
                extra_dims: Tuple[int, ...] = ()) -> str:
    """Stable fingerprint of one reshard *configuration* — the serve
    registry/coalescing key for routed-reshard traffic, the sibling of
    :meth:`~pencilarrays_tpu.ops.fft.PencilFFTPlan.plan_key`.

    Hashes the logical configuration only (global shape, topology dims,
    src/dest decomposition + memory-order permutations, dtype, method
    label, extra dims) with the same digest family the obs correlation
    layer uses — deterministic across processes and jax restarts; never
    device ids or object identities."""
    import numpy as np

    from ..obs.correlate import plan_fingerprint

    dt = np.dtype(dtype if dtype is not None else np.float32)
    summary = {
        "kind": "reshard",
        "shape": list(pin.size_global()),
        "topo": list(pin.topology.dims),
        "src": [list(pin.decomposition),
                list(pin.permutation.apply(tuple(range(pin.ndims))))],
        "dest": [list(dest.decomposition),
                 list(dest.permutation.apply(tuple(range(dest.ndims))))],
        "dtype": dt.name,
        "method": _method_label(method) if method is not None else "Auto",
        "extra_dims": list(extra_dims),
    }
    return plan_fingerprint(summary)


def trusted_drift_hops() -> Dict[str, dict]:
    """The drift tracker's per-hop report, for cost-model correction —
    or ``{}`` when no samples exist yet, or when running
    multi-controller (``process_count() > 1``): drift samples are
    process-local, and every process must plan the same collective
    program from the same (static) inputs.  Shared by the route
    planner's edge pricing and the FFT planner's slab/pencil
    auto-decomposition scoring (``ops/fft.py``), so the two pricers can
    never disagree about which measurements steer plans."""
    if jax.process_count() > 1 or not drift_tracker.version():
        return {}
    return drift_tracker.report()["hops"]


def trusted_drift(drift_hops: Dict[str, dict], label: str) -> float:
    """Observed drift ratio of one hop (1.0 when unmeasured).  Trusted
    (device-protocol) samples only: dispatch wall times are lower
    bounds on wire time (``obs/drift.py``) and host jitter must not
    flip planning decisions."""
    e = drift_hops.get(label)
    if e and e.get("drift") and e.get("source") != "dispatch":
        return float(e["drift"])
    return 1.0


@dataclass(frozen=True)
class RouteHop:
    """One edge of a planned route: a single-slot exchange ``src ->
    dest`` via ``method``, with its priced collective cost, the
    bytes-equivalent score the search charged it, and the per-chip HBM
    high-water mark its exchange needs (operand + result)."""

    src: Pencil
    dest: Pencil
    method: AbstractTransposeMethod
    cost: dict
    score_bytes: int
    peak_hbm_bytes: int


@dataclass(frozen=True)
class ReshardRoute:
    """A planning verdict: the best single-axis hop chain found (may be
    empty when no admissible route exists), the GSPMD baseline price,
    and whether :func:`~pencilarrays_tpu.parallel.transpositions.reshard`
    should execute the route (``use_route``) or fall back.

    ``verdict`` is one of ``"routed"`` (route wins the Auto price
    comparison), ``"routed:forced"`` (an explicit non-Auto method asked
    for explicit exchanges — no GSPMD substitution, no baseline
    pricing), ``"routed:hbm"`` (an ``hbm_limit`` was given and an
    admissible — possibly chunk-synthesized — route exists: a bounded
    plan never falls back to the partitioner, whose peak is
    unknowable), ``"gspmd"`` (route found but not cheaper),
    ``"gspmd:no-route"`` (search exhausted — e.g. fully-decomposed
    topologies have no single-slot moves, or no chunking fits the
    ``hbm_limit``) or ``"gspmd:unpriced"`` (route found, GSPMD
    baseline could not be priced — the priced route wins by default).

    ``donate`` and ``hbm_limit`` record the pricing assumptions the
    per-hop ``peak_hbm_bytes`` were charged under, so the static
    verifier (``analysis.spmd.predicted_peak_hbm``) reproduces the
    exact same accounting."""

    src: Pencil
    dest: Pencil
    hops: Tuple[RouteHop, ...]
    score_bytes: Optional[int]
    peak_hbm_bytes: Optional[int]
    gspmd_cost: Optional[dict]
    gspmd_score_bytes: Optional[int]
    use_route: bool
    verdict: str
    searched_nodes: int
    donate: bool = False
    hbm_limit: Optional[int] = None

    @property
    def pencils(self) -> Tuple[Pencil, ...]:
        """The full configuration chain, ``src`` first, ``dest`` last."""
        return (self.src,) + tuple(h.dest for h in self.hops)


def _score(cost: dict, latency_bytes: int, drift: float = 1.0,
           dtype=None, wire_dtype: Optional[str] = None) -> int:
    """Bytes-equivalent score of one priced hop — the Auto(estimate)
    currency: each collective launch costs ``latency_bytes``
    bytes-equivalent, wire bytes count at face value scaled by the
    hop's observed drift ratio (1.0 when unmeasured), and a
    reduced-precision edge is additionally charged its pack/unpack
    cast traffic (``wire.cast_score_bytes`` — HBM-discounted, so the
    wire's halved ICI bytes win unless the hop was tiny)."""
    count = sum(v["count"] for v in cost.values())
    nbytes = sum(v["bytes"] for v in cost.values())
    return int(count * latency_bytes + nbytes * drift
               + cast_score_bytes(nbytes, dtype, wire_dtype))


def _hop_peak_bytes(pin: Pencil, pout: Pencil, R: Optional[int],
                    extra_dims: Tuple[int, ...], dtype,
                    method: Optional[AbstractTransposeMethod] = None, *,
                    chunk_dim: Optional[int] = None,
                    bounds: Optional[Tuple[Tuple[int, int], ...]] = None
                    ) -> int:
    """Per-chip HBM high-water mark of one hop — the ONE footprint
    accounting shared by the route planner's ``hbm_limit`` admission
    and the static verifier (``analysis/spmd.py``), its only other
    sanctioned caller (enforced by ``pa-lint hop-peak``).

    Exchange hops charge ``elems * itemsize + chunk_elems * wire``:

    * ``elems * itemsize`` — the restored full-precision result plus
      the retiring input: at time-slice ``k`` of a chunked exchange the
      not-yet-packed input slices and the already-accumulated output
      chunks together never exceed one full operand (the input retires
      chunk-by-chunk as it packs; the planner adds a pinned-source
      surcharge when the caller does NOT donate — see
      :func:`plan_reshard_route`);
    * ``chunk_elems * wire`` — the one in-flight wire-packed chunk.
      Unchunked (``chunk_elems == elems``) this reproduces the
      historical operand+result bound ``elems * (wire + itemsize)``
      exactly, and a reduced-wire hop's in-flight share is the PACKED
      bytes — which is how wire edges fit under an ``hbm_limit`` that
      pruned their full-precision siblings (PR 13).

    ``method`` supplies both the wire dtype and the chunking (a
    :class:`~pencilarrays_tpu.parallel.transpositions.Pipelined`
    method's K slices along the same exchange-untouched dim the
    runtime factory chunks); ``chunk_dim``/``bounds`` override the
    method-derived choice for fused plan hops whose program owns its
    own chunk dim (``ops/fft.py`` ``"ft"`` steps).  Local permutes
    charge in+out blocks, as before."""
    import numpy as np

    isize = np.dtype(dtype if dtype is not None else np.float32).itemsize
    if R is None:  # local permute: in + out blocks (nothing packs)
        return (pin.bytes_per_device(extra_dims, isize=isize)
                + pout.bytes_per_device(extra_dims, isize=isize))
    a, b = pin.decomposition[R], pout.decomposition[R]
    ext = _exchange_operand_extents(pin, pout, R)
    shape = tuple(ext) + tuple(extra_dims)
    elems = int(np.prod(shape, dtype=np.int64))
    if bounds is None and isinstance(method, Pipelined):
        chunk_dim = _pipeline_chunk_axis(shape, a, b)
        if chunk_dim is not None:
            bounds = _chunk_bounds(shape[chunk_dim], method.chunks)
    chunk_shape = shape
    if chunk_dim is not None and bounds is not None and len(bounds) > 1:
        widest = max(s1 - s0 for s0, s1 in bounds)
        chunk_shape = (shape[:chunk_dim] + (widest,)
                       + shape[chunk_dim + 1:])
    # the in-flight packed chunk at the shared wire_bytes accounting —
    # on an fp8 wire this includes the chunk's own scale side payload
    packed = wire_bytes(dtype, _method_wire(method), chunk_shape,
                        axes=(a, b))
    return elems * isize + packed


def _synthesize_chunked(psrc: Pencil, pdst: Pencil, R: int,
                        extra_dims: Tuple[int, ...], dtype,
                        m: AbstractTransposeMethod, budget: int):
    """Memory-bounded edge synthesis (arXiv:2112.01075): time-slice one
    over-budget exchange into the SMALLEST ``Pipelined(chunks=K)``
    variant (K doubling, then the chunk dim's full extent) whose
    time-sliced footprint fits ``budget``.  Returns ``(method, peak)``
    or ``(None, 0)`` when nothing chunkable fits — data movement of
    every candidate is bit-identical to ``m`` (chunking along an
    exchange-untouched dim commutes with the exchange); only the
    collective count (×K) and the footprint change."""
    base = m.base if isinstance(m, Pipelined) else m
    shape = (tuple(_exchange_operand_extents(psrc, pdst, R))
             + tuple(extra_dims))
    c = _pipeline_chunk_axis(shape, psrc.decomposition[R],
                             pdst.decomposition[R])
    if c is None or budget <= 0:
        return None, 0
    n = int(shape[c])
    ks = []
    k = (m.chunks if isinstance(m, Pipelined) else 1) * 2
    while k < n:
        ks.append(k)
        k *= 2
    ks.append(n)  # maximal slicing: one chunk per row
    for k in ks:
        if len(_chunk_bounds(n, k)) <= 1:
            continue
        cand = Pipelined(chunks=k, base=base)
        peak = _hop_peak_bytes(psrc, pdst, R, extra_dims, dtype, cand)
        if peak <= budget:
            return cand, peak
    return None, 0


def _node_pencil(node: Tuple[int, ...], pin: Pencil, dest: Pencil) -> Pencil:
    """Materialize a graph node: the endpoints keep their exact pencils
    (permutation included — the final hop must land ON ``dest``);
    intermediates take the default memory order.  Empty-rank warnings
    are suppressed for intermediates: the planner prices their padding,
    and stranded candidates simply score (and bound) worse."""
    if node == dest.decomposition:
        return dest
    if node == pin.decomposition:
        return pin
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return Pencil(pin.topology, pin.size_global(), node)


@lru_cache(maxsize=512)
def _plan_cached(pin: Pencil, dest: Pencil, extra_dims: Tuple[int, ...],
                 dtype_str: str, method: AbstractTransposeMethod,
                 latency_bytes: int, hbm_limit: Optional[int],
                 donate: bool, _drift_v: int) -> ReshardRoute:
    """The search proper, cached per static configuration.  ``_drift_v``
    is the drift tracker's version counter: new timing samples invalidate
    cached plans (the compiled route executors have their own cache, so
    replanning never recompiles an unchanged winner)."""
    import numpy as np

    dtype = np.dtype(dtype_str)
    N = pin.ndims
    M = pin.topology.ndims
    drift_hops: Dict[str, dict] = {}
    if _drift_v:
        drift_hops = drift_tracker.report()["hops"]
    # donation accounting: a non-donated source block stays resident
    # under the ENTIRE fused chain (the caller still owns it), so every
    # edge is charged it on top of its own working set; donate=True
    # retires it into the first hop (chunk-by-chunk when chunked) and
    # the surcharge disappears — which is exactly how reshard(
    # donate=True) admits routes non-donating pricing still prunes
    pinned = 0 if donate else pin.bytes_per_device(
        extra_dims, isize=dtype.itemsize)

    def edge(psrc: Pencil, pdst: Pencil, first: bool = False):
        m = resolve_method(psrc, pdst, extra_dims, dtype, method)
        R = assert_compatible(psrc, pdst)
        # a first-hop local permute's input IS the source block: the
        # in+out charge already counts it, so no surcharge there
        surcharge = 0 if (first and R is None) else pinned
        peak = _hop_peak_bytes(psrc, pdst, R, extra_dims, dtype, m) \
            + surcharge
        if (hbm_limit is not None and peak > hbm_limit and R is not None
                and psrc.topology.dims[R] > 1
                and isinstance(m, (AllToAll, Ring, Pipelined))):
            # memory-bounded synthesis: time-slice the over-budget
            # exchange instead of pruning it outright
            m2, p2 = _synthesize_chunked(psrc, pdst, R, extra_dims,
                                         dtype, m, hbm_limit - surcharge)
            if m2 is not None:
                m, peak = m2, p2 + surcharge
        cost = transpose_cost(psrc, pdst, extra_dims, dtype, m)
        drift = trusted_drift(drift_hops, _hop_label(psrc, pdst, m, dtype))
        wire = _method_wire(m)
        return RouteHop(psrc, pdst, m, cost,
                        _score(cost, latency_bytes, drift, dtype, wire),
                        peak)

    hops: Tuple[RouteHop, ...] = ()
    searched = 0
    if pin.decomposition == dest.decomposition:
        # permutation-only change: a single local-permute "hop"
        hops = (edge(pin, dest, first=True),)
        searched = 1
    else:
        # Dijkstra over ordered decomposition tuples (slot i <-> mesh
        # axis i); neighbors differ in exactly one slot.  The state
        # space is N!/(N-M)! nodes — single digits for real pencils.
        nodes = set(_iperms(range(N), M))
        start, goal = pin.decomposition, dest.decomposition
        best_score: Dict[tuple, int] = {start: 0}
        prev: Dict[tuple, Tuple[tuple, RouteHop]] = {}
        heap = [(0, start)]
        done = set()
        while heap:
            d, u = heapq.heappop(heap)
            if u in done:
                continue
            done.add(u)
            searched += 1
            if u == goal:
                break
            pu = _node_pencil(u, pin, dest)
            for slot in range(M):
                for nd in range(N):
                    v = u[:slot] + (nd,) + u[slot + 1:]
                    if nd == u[slot] or v not in nodes or v in done:
                        continue
                    h = edge(pu, _node_pencil(v, pin, dest),
                             first=u == start)
                    if hbm_limit is not None and h.peak_hbm_bytes > hbm_limit:
                        continue  # no chunking fits either: prune
                    nd_score = d + h.score_bytes
                    if nd_score < best_score.get(v, 2 ** 62):
                        best_score[v] = nd_score
                        prev[v] = (u, h)
                        heapq.heappush(heap, (nd_score, v))
        if goal in best_score:
            chain = []
            u = goal
            while u != start:
                u, h = prev[u]
                chain.append(h)
            hops = tuple(reversed(chain))

    if not hops or (hbm_limit is not None
                    and max(h.peak_hbm_bytes for h in hops) > hbm_limit):
        # search exhausted — or the only "route" is a local permute
        # whose in+out blocks bust the bound (nothing to time-slice)
        return ReshardRoute(pin, dest, (), None, None, None, None, False,
                            "gspmd:no-route", searched, donate, hbm_limit)

    score = sum(h.score_bytes for h in hops)
    peak = max(h.peak_hbm_bytes for h in hops)
    if hbm_limit is not None:
        # a bounded plan never falls back to the partitioner: GSPMD's
        # peak allocation is partitioner-owned and unboundable, so an
        # admissible (possibly chunk-synthesized) route IS the verdict
        # (explicit methods are honored per edge — the chunk synthesis
        # only ever WRAPS them in Pipelined, bit-identical — so the
        # bound verdict subsumes "routed:forced")
        return ReshardRoute(pin, dest, hops, score, peak, None, None, True,
                            "routed:hbm", searched, donate, hbm_limit)
    if not isinstance(method, Auto):
        # an EXPLICIT method is a user decision (pin collectives, dodge
        # a partitioner bug): never silently substitute the GSPMD
        # exchange for it — the baseline comparison is Auto's job
        return ReshardRoute(pin, dest, hops, score, peak, None, None, True,
                            "routed:forced", searched, donate, hbm_limit)
    try:
        gcost = gspmd_reshard_cost(pin, dest, extra_dims, dtype)
    except Exception:  # pricing is best-effort: a lowering quirk must
        gcost = None   # never make reshard() itself fail
    if gcost is None:
        return ReshardRoute(pin, dest, hops, score, peak, None, None, True,
                            "gspmd:unpriced", searched, donate, hbm_limit)
    gscore = _score(gcost, latency_bytes)
    use = score < gscore
    return ReshardRoute(pin, dest, hops, score, peak, gcost, gscore, use,
                        "routed" if use else "gspmd", searched, donate,
                        hbm_limit)


def plan_reshard_route(pin: Pencil, dest: Pencil,
                       extra_dims: Tuple[int, ...] = (), dtype=None, *,
                       method: AbstractTransposeMethod = Auto(),
                       hbm_limit: Optional[int] = None,
                       donate: bool = False) -> ReshardRoute:
    """Plan the redistribution ``pin -> dest``: search the pencil graph
    for the cheapest admissible single-axis hop chain and compare it
    against the priced GSPMD baseline.  See the module docstring for
    the graph, scoring and fallback rules.

    ``method`` resolves each edge (:class:`Auto` per hop; measure-mode
    Auto plans with the estimate rule — planning must stay cheap and
    deterministic).

    ``hbm_limit`` bounds each hop's charged per-chip footprint
    (``_hop_peak_bytes``'s time-sliced working set, plus the resident
    source block on every edge when ``donate=False``).  An over-budget
    edge is not pruned outright: the planner first synthesizes a
    ``Pipelined(chunks=K)`` time-sliced variant (smallest fitting K —
    doubling, then maximal) whose footprint fits, priced at count ×K /
    bytes unchanged and bit-identical to the unchunked exchange.  With
    a limit set the planner never falls back to GSPMD (whose peak is
    partitioner-owned and unboundable): an admissible route carries
    verdict ``"routed:hbm"``, an exhausted search ``"gspmd:no-route"``.

    ``donate`` declares that the source buffer will be donated to the
    executed chain (``reshard(donate=True)`` plans with it): the
    pinned-source surcharge disappears, so donating callers are
    admitted under limits that prune non-donating ones.  Plan and
    execution must agree — ``execute_route(donate=)`` should match the
    planned ``route.donate`` when the route was hbm-bounded.

    ``analysis.spmd.verify_route`` statically proves a planned route's
    fused executable compiles to EXACTLY the per-hop priced
    collectives, and ``analysis.spmd.verify_hbm``/``verify_donation``
    check the same (chunk- and donation-aware) peak-HBM accounting and
    the donation elision the pricing assumes — the pre-flight sibling
    of this planner.
    """
    import numpy as np

    if pin.topology != dest.topology:
        raise ValueError("plan_reshard_route: pencil topologies differ")
    if pin.size_global() != dest.size_global():
        raise ValueError("plan_reshard_route: global shapes differ")
    if isinstance(method, Gspmd):
        raise ValueError("plan_reshard_route prices Gspmd as the baseline; "
                         "pass an explicit exchange method or Auto()")
    if isinstance(method, Auto) and method.mode == "measure":
        # planning stays deterministic & benchmark-free (the fused-hop
        # planner's convention, ops/fft.py:_try_fuse_hop); replace()
        # keeps the wire_dtype riding the downgraded resolution
        from dataclasses import replace

        method = replace(method, mode="estimate")
    latency = method.latency_bytes if isinstance(method, Auto) \
        else Auto().latency_bytes
    dt = np.dtype(dtype if dtype is not None else np.float32)
    # drift samples are process-local: multi-controller planning must be
    # a pure function of the static config (see module docstring)
    v = drift_tracker.version() if jax.process_count() == 1 else 0
    return _plan_cached(pin, dest, tuple(int(e) for e in extra_dims),
                        dt.str, method, int(latency),
                        int(hbm_limit) if hbm_limit is not None else None,
                        bool(donate), v)


# ---------------------------------------------------------------------------
# fused route execution
# ---------------------------------------------------------------------------


def _apply_hop(data, pin: Pencil, pout: Pencil, R: Optional[int],
               method: AbstractTransposeMethod, extra_ndims: int):
    if R is None:
        return _transpose_local(data, pin, pout, extra_ndims)
    if isinstance(method, (AllToAll, Ring, Pipelined)):
        # the factory owns the method's chunking and wire pack/unpack —
        # the same one-path rule as transpositions._hop_body, so a
        # routed edge's wire_dtype packs exactly like a standalone hop
        return _exchange_transpose(data, pin, pout, R, extra_ndims,
                                   _exchange_factory(method, pin, pout))
    raise TypeError(f"no explicit hop executor for method {method!r}")


@lru_cache(maxsize=256)
def _compiled_route(pencils: Tuple[Pencil, ...],
                    methods: Tuple[AbstractTransposeMethod, ...],
                    extra_ndims: int, donate: bool = False,
                    _pallas: bool = False):
    """ONE jitted program for the whole hop chain: every hop's
    pack -> exchange -> unpack traces into a single executable, so the
    intermediates are compiler-owned (and reusable) buffers and the
    latency-hiding scheduler sees the full chain at once — per-hop
    Python dispatch happens exactly once per configuration, at trace
    time.  ``_pallas`` rides the key only (the _compiled_transpose
    convention: a toggled env flag must not reuse a stale executable)."""
    hops = tuple((a, b, assert_compatible(a, b), m)
                 for a, b, m in zip(pencils, pencils[1:], methods))

    def chain(data):
        for pin, pout, R, m in hops:
            data = _apply_hop(data, pin, pout, R, m, extra_ndims)
        return data

    return jax.jit(chain, donate_argnums=(0,) if donate else ())


@lru_cache(maxsize=256)
def _compiled_guarded_route(pencils: Tuple[Pencil, ...],
                            methods: Tuple[AbstractTransposeMethod, ...],
                            extra_ndims: int, donate: bool = False,
                            _pallas: bool = False, finite: bool = False,
                            corrupt: bool = False):
    """Guard-instrumented sibling of :func:`_compiled_route`: the SAME
    fused chain with one invariant probe before the first hop and one
    after EVERY hop, all inside the single jitted program — every hop
    is pure data movement, so each post-probe must match the source
    probe and the first mismatching index names the corrupted hop.
    ``corrupt=True`` compiles the SDC drill variant (poke after the
    first hop, counter-addressed traced index)."""
    from ..guard import integrity as gi

    hops = tuple((a, b, assert_compatible(a, b), m)
                 for a, b, m in zip(pencils, pencils[1:], methods))

    if corrupt:
        def chain(data, poke_idx):
            probes = [gi.probe_stats(data, finite)]
            for k, (pin, pout, R, m) in enumerate(hops):
                data = _apply_hop(data, pin, pout, R, m, extra_ndims)
                if k == 0:
                    data = gi.corrupt_block(data, poke_idx)
                probes.append(gi.probe_stats(data, finite))
            return data, probes
    else:
        def chain(data):
            probes = [gi.probe_stats(data, finite)]
            for pin, pout, R, m in hops:
                data = _apply_hop(data, pin, pout, R, m, extra_ndims)
                probes.append(gi.probe_stats(data, finite))
            return data, probes

    return jax.jit(chain, donate_argnums=(0,) if donate else ())


def _execute_route_guarded(src: PencilArray, route: ReshardRoute,
                           donate: bool, corrupt: bool) -> PencilArray:
    """Guarded eager route dispatch: per-hop probes in the fused chain,
    hang watchdog over the dispatch + probe fetch, typed
    :class:`~pencilarrays_tpu.guard.IntegrityError` naming the first
    corrupted hop."""
    from ..guard import integrity as gi
    from ..ops.pallas_kernels import pallas_enabled

    finite = guard.finite_tick()
    fn = _metered_cached(
        _compiled_guarded_route, "route", route.pencils,
        tuple(h.method for h in route.hops), src.ndims_extra, donate,
        pallas_enabled(), finite, corrupt)
    with guard.watchdog("route", kind="route", hops=len(route.hops)):
        if corrupt:
            out, probes = fn(
                src.data, max(0, faults.hit_count("hop.exchange") - 1))
        else:
            out, probes = fn(src.data)
        count = int(src.data.size)
        wired, wire_hops = None, 0
        for k, h in enumerate(route.hops):
            # each post-probe is compared against the SOURCE probe, so
            # a wire hop anywhere upstream makes the compare
            # tolerance-bound by the wire model from that hop on —
            # scaled by how many packed exchanges the data has crossed
            hop_wire = _method_wire(h.method)
            if hop_wire is not None:
                # mixed-wire chains bound by the coarsest format seen
                wired = ("bf16" if "bf16" in (wired, hop_wire)
                         else hop_wire)
                wire_hops += 1
            gi.check_hop_probes(
                f"route[{k}] {_hop_label(h.src, h.dest, h.method, src.dtype)}",
                probes[0], probes[k + 1], count, src.dtype, finite=finite,
                wire_dtype=wired, wire_hops=wire_hops,
                ctx={"hop_index": k, "hops": len(route.hops)})
    return PencilArray(route.dest, out, src.extra_dims)


def execute_route(src: PencilArray, route: ReshardRoute, *,
                  donate: bool = False) -> PencilArray:
    """Execute a planned route as its fused chain (one dispatch).
    ``donate=True`` donates the SOURCE buffer to the chain (``src``
    becomes invalid); intermediates are compiler-owned either way.
    With the integrity guard armed (``PENCILARRAYS_TPU_GUARD``), eager
    dispatches run the probe-instrumented chain instead — same data
    movement, per-hop invariant checks, hang watchdog."""
    import jax.core

    from ..ops.pallas_kernels import pallas_enabled

    if src.pencil != route.src:
        raise ValueError(
            f"array lives on {src.pencil!r}, route starts at {route.src!r}")
    if not route.hops:
        raise ValueError("route has no hops (planner fell back to Gspmd)")
    eager = not isinstance(src.data, jax.core.Tracer)
    donate = donate and eager
    # the SDC drill point fires for every eager routed dispatch, guard
    # on or off — the hit counter must address the same dispatches
    # either way ("the same spec replays the same failure")
    act = None
    if eager and faults.armed("hop.exchange"):
        act = faults.fire("hop.exchange", kind="route",
                          hops=len(route.hops))
        if act == "torn":   # this site cannot tear: treat as kill
            faults.kill_now()
    if eager and (obs.enabled() or guard.enabled()):
        # ONE summary feeds both digests: the journal's plan_fp must be
        # a prefix of the crash bundle's schedule_sha256 (both hash the
        # same sorted-JSON blob), or a post-mortem cannot match a hop
        # record to the route that was in flight
        summary = {
            "route": [list(h.dest.decomposition) for h in route.hops],
            "methods": [_method_label(h.method) for h in route.hops],
            "verdict": route.verdict,
            "shape": list(route.src.size_global()),
            "topo": list(route.src.topology.dims)}
        if obs.enabled():
            from ..obs import correlate

            correlate.set_plan(correlate.plan_fingerprint(summary))
        if guard.enabled():
            guard.note_plan("reshard_route", summary)
        return _execute_route_guarded(src, route, donate,
                                      corrupt=act == "corrupt")
    fn = _metered_cached(
        _compiled_route, "route", route.pencils,
        tuple(h.method for h in route.hops), src.ndims_extra, donate,
        pallas_enabled())
    out = fn(src.data)
    if act == "corrupt":
        # guard off: the poke flows through undetected (the silent
        # garbage the guard exists to catch — pinned by tests)
        from ..guard import integrity as gi

        out = gi.corrupt_eager(out, faults.hit_count("hop.exchange") - 1)
    return PencilArray(route.dest, out, src.extra_dims)


# ---------------------------------------------------------------------------
# observability tap
# ---------------------------------------------------------------------------


_ROUTE_LOGGED: set = set()


def _obs_record_route_plan(route: ReshardRoute, extra_dims: tuple,
                           dtype) -> None:
    """Journal one planning verdict per (obs run, configuration) — the
    ``route.plan`` event: every candidate with its predicted bytes and
    score, and which one reshard() will execute."""
    import numpy as np

    dt = np.dtype(dtype if dtype is not None else np.float32)
    config = (f"{route.src.size_global()}@{route.src.topology.dims} "
              f"{route.src.decomposition}->{route.dest.decomposition} "
              f"{dt.name} extra={tuple(extra_dims)}"
              + (f" hbm={route.hbm_limit} donate={route.donate}"
                 if route.hbm_limit is not None else ""))
    key = (obs.run_id(), config)
    if key in _ROUTE_LOGGED:
        return
    _ROUTE_LOGGED.add(key)
    candidates = []
    if route.hops:
        candidates.append({
            "kind": "routed",
            "route": [list(h.dest.decomposition) for h in route.hops],
            "methods": [_method_label(h.method) for h in route.hops],
            # per-hop chunk factors + charged footprints: what a
            # post-mortem (pa-obs) needs to see WHY a whale request was
            # admitted — the synthesized time-slicing and the bound it
            # was priced against
            "chunks": [h.method.chunks
                       if isinstance(h.method, Pipelined) else 1
                       for h in route.hops],
            "hop_peak_hbm_bytes": [h.peak_hbm_bytes for h in route.hops],
            "predicted_bytes": sum(
                v["bytes"] for h in route.hops for v in h.cost.values()),
            "score_bytes": route.score_bytes,
            "peak_hbm_bytes": route.peak_hbm_bytes,
        })
    if route.gspmd_cost is not None:
        candidates.append({
            "kind": "gspmd",
            "predicted_bytes": sum(
                v["bytes"] for v in route.gspmd_cost.values()),
            "score_bytes": route.gspmd_score_bytes,
            "cost": route.gspmd_cost,
        })
    winner = candidates[0] if route.use_route else (
        candidates[-1] if candidates else None)
    obs.record_event(
        "route.plan", src=str(route.src.decomposition),
        dest=str(route.dest.decomposition),
        shape=list(route.src.size_global()),
        topo=list(route.src.topology.dims), dtype=dt.name,
        verdict=route.verdict, candidates=candidates,
        predicted_bytes=(winner or {}).get("predicted_bytes", 0),
        peak_hbm_bytes=route.peak_hbm_bytes,
        hbm_limit=route.hbm_limit, donate=route.donate,
        searched_nodes=route.searched_nodes)
    obs.counter("route.plans", verdict=route.verdict).inc()
