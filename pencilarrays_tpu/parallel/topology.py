"""Cartesian process/device topology over a TPU mesh.

TPU-native re-design of ``src/Pencils/MPITopologies.jl`` (reference
``MPITopologies.jl:72-136``).  The reference builds an M-dimensional
Cartesian MPI communicator (``MPI.Cart_create``), one 1-D sub-communicator
per decomposed axis (``MPI.Cart_sub``, ``MPITopologies.jl:244-251``) and
rank lookup tables (``MPITopologies.jl:208-242``).

On TPU the entire stack collapses onto :class:`jax.sharding.Mesh`:

* the Cartesian communicator is the mesh itself — XLA partitions programs
  over it and lays collectives onto the ICI torus;
* each 1-D sub-communicator becomes a *named mesh axis*: a collective
  issued with ``axis_name='p1'`` is exactly an exchange confined to that
  axis's process columns (cf. ``Transpositions.jl:294-298`` where the
  transpose picks ``topology.subcomms[R]``);
* rank tables become the mesh's ``devices`` ndarray.

``dims_create`` mirrors ``MPI.Dims_create`` (``MPITopologies.jl:138-144``):
a balanced factorization of the device count over the topology dims.
"""

from __future__ import annotations

import math
from functools import cached_property
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.jaxcompat import AxisType

__all__ = ["Topology", "dims_create", "default_axis_names"]


def default_axis_names(ndims: int) -> Tuple[str, ...]:
    """Axis names ``('p1', ..., 'pN')`` — the sub-communicator handles."""
    return tuple(f"p{i + 1}" for i in range(ndims))


def dims_create(nprocs: int, ndims: int) -> Tuple[int, ...]:
    """Balanced factorization of ``nprocs`` into ``ndims`` factors,
    mimicking ``MPI_Dims_create`` (reference ``MPITopologies.jl:138-144``).

    Returns dims sorted in non-increasing order, as MPI does.
    """
    if nprocs <= 0:
        raise ValueError(f"nprocs must be positive, got {nprocs}")
    if ndims <= 0:
        raise ValueError(f"ndims must be positive, got {ndims}")
    dims = [1] * ndims
    # Greedy: repeatedly divide nprocs by its smallest prime factor and
    # multiply it into the currently-smallest dim.
    n = nprocs
    factors = []
    f = 2
    while f * f <= n:
        while n % f == 0:
            factors.append(f)
            n //= f
        f += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        i = int(np.argmin(dims))
        dims[i] *= f
    return tuple(sorted(dims, reverse=True))


class Topology:
    """An M-dimensional Cartesian topology of TPU devices.

    Parity with reference ``MPITopology{N}`` (``MPITopologies.jl:72-92``):

    ========================  ==========================================
    reference                 here
    ========================  ==========================================
    ``get_comm(t)``           :attr:`mesh`
    ``t.subcomms[i]``         :attr:`axis_names` ``[i]``
    ``t.dims``                :attr:`dims`
    ``t.coords_local``        :meth:`coords` (of any device)
    ``t.ranks``               :attr:`ranks`
    ``length(t)``             :meth:`__len__`
    ``ndims(t)``              :attr:`ndims`
    ========================  ==========================================
    """

    def __init__(
        self,
        dims: Sequence[int],
        *,
        devices: Optional[Sequence] = None,
        axis_names: Optional[Sequence[str]] = None,
    ):
        dims = tuple(int(d) for d in dims)
        if any(d <= 0 for d in dims):
            raise ValueError(f"topology dims must be positive: {dims}")
        if devices is None:
            devices = jax.devices()
        n = math.prod(dims)
        if n != len(devices):
            # Reference errors on a comm/topology size mismatch
            # (``MPITopologies.jl:152-156``); silently using a subset would
            # leave devices idle. Pass an explicit ``devices=`` subset to
            # build a topology over fewer devices.
            raise ValueError(
                f"topology {dims} needs exactly {n} devices, got {len(devices)}"
            )
        devices = list(devices)
        if axis_names is None:
            axis_names = default_axis_names(len(dims))
        axis_names = tuple(axis_names)
        if len(axis_names) != len(dims):
            raise ValueError("axis_names length must match dims length")
        if len(set(axis_names)) != len(axis_names):
            raise ValueError(f"duplicate axis names: {axis_names}")
        dev_array = np.array(devices, dtype=object).reshape(dims)
        # Auto axis types: classic GSPMD partitioning — sharding decisions
        # may be refined by the compiler outside shard_map regions.  On
        # pre-AxisType jax every mesh axis already behaves as Auto.
        if AxisType is None:
            self._mesh = Mesh(dev_array, axis_names)
        else:
            self._mesh = Mesh(
                dev_array, axis_names,
                axis_types=(AxisType.Auto,) * len(dims)
            )
        self._dims = dims
        self._axis_names = axis_names

    # -- constructors -----------------------------------------------------
    @classmethod
    def auto(cls, ndims: int, *, devices=None, axis_names=None) -> "Topology":
        """Balanced topology over all (or the given) devices — the analog of
        ``MPITopology(comm, Val(M))`` (``MPITopologies.jl:133-136``)."""
        if devices is None:
            devices = jax.devices()
        dims = dims_create(len(devices), ndims)
        return cls(dims, devices=devices, axis_names=axis_names)

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "Topology":
        """Adopt an existing ``jax.sharding.Mesh`` as a topology.

        Validates what the constructor validates: positive dims, unique
        axis names — plus ``Auto`` axis types, since ``Explicit``/
        ``Manual`` meshes reject the ``shard_map`` collectives the
        transpose engine issues (the failure would otherwise surface
        later as an opaque shard_map error)."""
        bad = ([str(t) for t in getattr(mesh, "axis_types", ())
                if t != AxisType.Auto] if AxisType is not None else [])
        if bad:
            raise ValueError(
                f"from_mesh requires Auto axis types, got {bad}; build the "
                f"mesh with axis_types=(AxisType.Auto, ...) or use the "
                f"Topology constructor")
        axis_names = tuple(mesh.axis_names)
        if len(set(axis_names)) != len(axis_names):
            raise ValueError(f"duplicate axis names: {axis_names}")
        dims = tuple(int(d) for d in mesh.devices.shape)
        if any(d <= 0 for d in dims):
            raise ValueError(f"topology dims must be positive: {dims}")
        t = cls.__new__(cls)
        t._mesh = mesh
        t._dims = dims
        t._axis_names = axis_names
        return t

    # -- accessors --------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def dims(self) -> Tuple[int, ...]:
        return self._dims

    @property
    def ndims(self) -> int:
        return len(self._dims)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self._axis_names

    def __len__(self) -> int:
        return math.prod(self._dims)

    @cached_property
    def ranks(self) -> np.ndarray:
        """Linear rank of each coordinate (reference ``t.ranks``,
        ``MPITopologies.jl:208-226``).  Ranks are row-major positions in the
        device grid."""
        return np.arange(len(self)).reshape(self._dims)

    def coords(self, rank: int) -> Tuple[int, ...]:
        """Cartesian coordinates of a linear rank."""
        return tuple(int(c) for c in np.unravel_index(rank, self._dims))

    def rank(self, coords: Sequence[int]) -> int:
        """Linear rank of Cartesian coordinates (``MPI.Cart_rank``)."""
        return int(np.ravel_multi_index(tuple(coords), self._dims))

    def subcomm(self, i: int) -> str:
        """The named mesh axis playing the role of ``subcomms[i]``."""
        return self._axis_names[i]

    def device(self, coords: Sequence[int]):
        return self._mesh.devices[tuple(coords)]

    @cached_property
    def _device_coords(self):
        return {
            dev.id: tuple(int(c) for c in coords)
            for coords, dev in np.ndenumerate(self._mesh.devices)
        }

    def coords_of_device(self, device) -> Tuple[int, ...]:
        """Cartesian coordinates of a device in this topology."""
        return self._device_coords[device.id]

    # -- comparison -------------------------------------------------------
    def __eq__(self, other) -> bool:
        # Reference compares communicators with MPI.Comm_compare ∈
        # {IDENT, CONGRUENT} (``MPITopologies.jl:121-123``): same process
        # set and same Cartesian arrangement.
        if not isinstance(other, Topology):
            return NotImplemented
        return (
            self._dims == other._dims
            and self._axis_names == other._axis_names
            and self._mesh.devices.tolist() == other._mesh.devices.tolist()
        )

    def __hash__(self) -> int:
        return hash((self._dims, self._axis_names,
                     tuple(d.id for d in self._mesh.devices.flat)))

    def __repr__(self) -> str:
        return (
            f"Topology(dims={self._dims}, axes={self._axis_names}, "
            f"devices={len(self)})"
        )
