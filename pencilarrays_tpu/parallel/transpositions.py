"""Global transpose (redistribution) engine — THE hot path.

TPU-native re-design of ``src/Transpositions/Transpositions.jl``.  The
reference implements decomposition-to-decomposition redistribution by hand:
per-peer intersection ranges (``Transpositions.jl:383-388``), pack into
shared byte buffers (``copy_range!``, ``:555-586``), a nonblocking
``Isend/Irecv``/``Waitany`` pipeline or a single ``MPI.Alltoallv!``
(``:61-68``), and a permuting unpack (``copy_permuted!``, ``:636-667``).

On TPU none of that is hand-scheduled.  The whole exchange is expressed as
a traced function XLA compiles onto the ICI fabric:

* the per-peer send/recv sets collapse to one ``jax.lax.all_to_all`` on
  the *single differing mesh axis* — exactly the reference's exchange
  confined to ``topology.subcomms[R]`` (``Transpositions.jl:294-298``);
* pack/unpack become ``jnp.transpose`` / pad / slice that XLA fuses with
  neighbouring ops (the reference's Strided.jl lazy permuted copies,
  ``:636-648``, are what the fusion replaces);
* ragged (non-divisible) blocks are handled by the pencil's tail padding:
  pad the to-be-split dim, exchange equal tiles, slice the now-local dim
  back to its true size — padding is contiguous at the global tail because
  of the ceil-block distribution, so a single slice removes it;
* overlap (``waitall=false`` + ``MPI.Waitany`` unpack loop,
  ``:142-158, 510-516``) is XLA's latency-hiding scheduler's job: the
  collective is async at dispatch and the compiler interleaves it with
  independent compute — by design there is no user-visible wait handle.

Three methods (reference ``Transpositions.jl:17-24``):

* :class:`AllToAll` (default) — explicit ``shard_map`` + ``lax.all_to_all``
  on the differing axis.  Deterministic collective choice; the analog of
  ``Alltoallv()``.  Restricted, like the reference, to configurations
  whose decompositions differ in at most one slot (``:182-199``).
* :class:`Ring` (alias ``PointToPoint``) — P-1 staged ``ppermute``
  rounds, one peer tile each: the reference's nonblocking per-peer
  pipeline, re-expressed for the compiler's scheduler.
* :class:`Gspmd` — express only the *layout change* and let the GSPMD
  partitioner insert collectives (``with_sharding_constraint``); also
  powers the unrestricted :func:`reshard`, which can change any number
  of decomposed dims at once (beyond reference capability).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import guard, obs
from ..resilience import faults
from ..utils.jaxcompat import shard_map
from ..utils.timers import timeit
from .arrays import PencilArray, _fwd_axes, _inv_axes
from .pencil import LogicalOrder, MemoryOrder, Pencil


def _maybe_pallas_transpose(a, axes, platform: str):
    """Local permute: VMEM-tiled Pallas kernel when enabled & supported
    (near-XLA-parity class only, 0.92-0.96x measured on v5e — the
    Strided.jl role, ``Transpositions.jl:636-648``; see
    ``ops/pallas_kernels.py`` for the measured verdict), else
    ``jnp.transpose``.  On CPU the kernel runs in interpret mode so the
    virtual-mesh tests exercise the same code path."""
    axes = tuple(axes)
    if axes == tuple(range(a.ndim)):
        return a
    from ..ops import pallas_kernels as pk

    if pk.pallas_enabled() and pk.supported(a.shape, axes, a.dtype,
                                            platform):
        return pk.pallas_permute(a, axes, interpret=(platform != "tpu"))
    return jnp.transpose(a, axes)

__all__ = [
    "AllToAll",
    "Alltoallv",
    "Auto",
    "Gspmd",
    "Pipelined",
    "PointToPoint",
    "Ring",
    "Transposition",
    "transpose",
    "transpose_cost",
    "with_wire",
    "gspmd_reshard_cost",
    "resolve_method",
    "reshard",
    "assert_compatible",
    "last_measure_reports",
]


class AbstractTransposeMethod:
    pass


def _canon_wire_field(method) -> None:
    """Normalize a frozen method's ``wire_dtype`` field at construction
    (``"bfloat16"`` and jnp dtypes collapse to the canonical ``"bf16"``/
    ``"f16"`` spelling, so method equality/hashing — the executable
    cache key — never splits on spelling)."""
    from .wire import canonical_wire_dtype

    object.__setattr__(method, "wire_dtype",
                       canonical_wire_dtype(method.wire_dtype))


def _method_wire(method: "AbstractTransposeMethod") -> Optional[str]:
    """The wire dtype one concrete method puts on the fabric (``None``
    = full precision).  Pipelined hops inherit their base's wire; Gspmd
    has no explicit exchange to pack."""
    if isinstance(method, (AllToAll, Ring, Auto)):
        return method.wire_dtype
    if isinstance(method, Pipelined):
        return _method_wire(method.base)
    return None


def with_wire(method: "AbstractTransposeMethod",
              wire_dtype) -> "AbstractTransposeMethod":
    """Return ``method`` carrying ``wire_dtype`` on its exchange(s) —
    the plan-level spelling (``PencilFFTPlan(wire_dtype=...)`` wraps
    its method through here).  ``None`` passes the method through
    unchanged; a method that already carries a DIFFERENT wire dtype is
    a conflict, not a silent override."""
    from dataclasses import replace

    from .wire import canonical_wire_dtype

    wire = canonical_wire_dtype(wire_dtype)
    if wire is None:
        return method
    cur = _method_wire(method)
    if cur is not None and cur != wire:
        raise ValueError(
            f"method {method!r} already carries wire_dtype={cur!r}; "
            f"conflicting wire_dtype={wire!r} requested")
    if isinstance(method, (AllToAll, Ring, Auto)):
        return replace(method, wire_dtype=wire)
    if isinstance(method, Pipelined):
        return replace(method, base=with_wire(method.base, wire))
    raise ValueError(
        f"wire_dtype is only supported on explicit exchange methods "
        f"(AllToAll/Ring/Pipelined) and Auto; got {method!r} (Gspmd "
        f"exchanges are partitioner-owned and cannot be packed)")


def strip_wire(method: "AbstractTransposeMethod"
               ) -> "AbstractTransposeMethod":
    """Return ``method`` with its ``wire_dtype`` removed throughout —
    the inverse of :func:`with_wire`, used by
    ``PencilFFTPlan.with_wire_dtype`` to re-derive precision variants
    of one schedule (the serving plane's downgrade ladder) from a plan
    whose method already carries a wire."""
    from dataclasses import replace

    if isinstance(method, (AllToAll, Ring, Auto)):
        return (replace(method, wire_dtype=None)
                if method.wire_dtype is not None else method)
    if isinstance(method, Pipelined):
        return replace(method, base=strip_wire(method.base))
    return method


@dataclass(frozen=True)
class AllToAll(AbstractTransposeMethod):
    """Explicit single-axis ``lax.all_to_all`` under ``shard_map``.

    ``wire_dtype="bf16" | "f16" | "fp8_e4m3" | "fp8_e5m2"`` (default
    ``None`` = full precision, bit-identical to the historical
    behavior) packs the exchanged payload down to the reduced wire
    format immediately before the collective and restores it
    immediately after, inside the same traced program
    (``parallel/wire.py``): a 16-bit wire moves half the bytes
    (f32/c64; a quarter for f64/c128), an fp8 wire a quarter plus
    4 bytes of max-abs scale per 256-element tile riding the same
    exchange, while all surrounding math stays full precision.
    Complex payloads split-complex pack."""

    wire_dtype: Optional[str] = None

    def __post_init__(self):
        _canon_wire_field(self)


@dataclass(frozen=True)
class Gspmd(AbstractTransposeMethod):
    """Compiler-scheduled resharding via ``with_sharding_constraint``."""


@dataclass(frozen=True)
class Ring(AbstractTransposeMethod):
    """Staged peer-to-peer exchange: shifted ``lax.ppermute`` rounds,
    each moving one peer's tile — the reference's ``PointToPoint()``
    flavor (nonblocking per-peer sends with unpack-as-they-arrive,
    ``Transpositions.jl:61-65, 510-516``), re-expressed so XLA's
    latency-hiding scheduler can overlap rounds with the unpack placement.
    RAGGED-AWARE: runs G-1 rounds among the G nonempty ceil-rule
    participants instead of P-1 (see :func:`_ring_factory`).
    Data movement is bit-identical to :class:`AllToAll`; which is faster
    is a hardware/topology question (shifted ppermute rounds the fabric
    routes over up to r hops each, vs one fused collective).
    ``wire_dtype`` as on :class:`AllToAll`: every ppermute round's tile
    rides the fabric in the reduced wire format."""

    wire_dtype: Optional[str] = None

    def __post_init__(self):
        _canon_wire_field(self)


# reference method-name aliases (Transpositions.jl:17-24)
PointToPoint = Ring
Alltoallv = AllToAll


@dataclass(frozen=True)
class Pipelined(AbstractTransposeMethod):
    """Chunked exchange: split the hop into ``chunks`` statically-shaped
    pieces along a dimension the exchange never touches (any dim other
    than the split/concat pair — including dims decomposed in BOTH
    pencils, whose local tile rides along unchanged — or the extra
    dims), and run one ``base``-method exchange per chunk.

    This is the TPU re-expression of the reference's ``waitall=false`` +
    ``Waitany`` unpack pipeline (``Transpositions.jl:142-158, 510-516``)
    at the *data* level: a monolithic collective is an atomic unit the
    latency-hiding scheduler can only overlap with OTHER work, but a
    chunked exchange gives the scheduler K independent collective/compute
    pairs — chunk ``k``'s wire time hides behind chunk ``k-1``'s compute
    whenever a consumer (e.g. the next FFT stage,
    ``PencilFFTPlan(pipeline=K)``) is fused per-chunk into the same
    program (arXiv:1804.09536 §4; AccFFT's overlapped redistribution).

    Standalone (no fused consumer) the chunks serialize on the one mesh
    axis and ``Pipelined(K)`` simply costs K collective launches for the
    same bytes — the win exists only inside a fused hop.  Data movement
    is BIT-IDENTICAL to ``base`` for every K (chunking along an
    untouched dim commutes with the exchange); ``chunks=1`` IS ``base``.

    Static-shape constraint: chunk boundaries are fixed at trace time
    (ceil-sized chunks, a short tail chunk when the extent does not
    divide), and the chunk dim's local extent bounds the usable K.  When
    no chunkable dim exists (e.g. a 2-D array whose both dims are the
    exchange pair, with no extra dims) the method degenerates to
    ``base`` unchunked.
    """

    chunks: int = 4
    base: AbstractTransposeMethod = AllToAll()

    def __post_init__(self):
        if not isinstance(self.chunks, int) or self.chunks < 1:
            raise ValueError(
                f"Pipelined chunks must be a positive int, got "
                f"{self.chunks!r}")
        if not isinstance(self.base, (AllToAll, Ring)):
            raise ValueError(
                f"Pipelined base must be AllToAll() or Ring() (explicit "
                f"single-axis exchanges), got {self.base!r}")


def _chunk_bounds(n: int, K: int) -> Tuple[Tuple[int, int], ...]:
    """Static chunk boundaries for extent ``n`` in <= K ceil-sized
    pieces: ``((0, s), (s, 2s), ..., (., n))`` with ``s = ceil(n/K)``.
    Every piece has a shape known at trace time (SPMD requirement)."""
    K = max(1, min(int(K), int(n)))
    step = -(-n // K)
    return tuple((s0, min(s0 + step, n)) for s0 in range(0, n, step))


def _pipeline_chunk_axis(shape: Tuple[int, ...], a: int, b: int,
                         exclude: Tuple[int, ...] = ()) -> Optional[int]:
    """Choose the chunk axis of a logical-order local block: the
    largest-extent axis that is neither the split dim ``b`` nor the
    concat dim ``a`` (nor excluded — fused hops also exclude the stage's
    transform dims, which must stay whole for their FFT).  Deterministic
    (ties resolve to the lowest axis index); ``None`` when nothing is
    chunkable."""
    best = None
    for c, n in enumerate(shape):
        if c == a or c == b or c in exclude or n < 2:
            continue
        if best is None or n > shape[best]:
            best = c
    return best


@dataclass(frozen=True)
class Auto(AbstractTransposeMethod):
    """Pick the exchange method per (pin, pout) configuration — the
    planner role FFTW's ``ESTIMATE``/``MEASURE`` flags play for the
    reference's FFT consumer (PencilFFTs lets callers sweep methods by
    hand; here the framework chooses).

    ``mode="estimate"`` (default): decide from the *validated* analytic
    byte model (:func:`transpose_cost` — prediction is test-pinned equal
    to compiled-HLO measurement).  :class:`Ring` is chosen exactly when
    its ragged-aware round elision moves fewer modeled wire bytes than
    one fused ``all_to_all``, charging each serialized ppermute round a
    latency toll of ``latency_bytes`` bytes-equivalent:

    ``(G-1) * (latency_bytes + tile)  <  latency_bytes + (P-1) * tile``

    With divisible extents ``G == P`` and AllToAll always wins (one
    fused collective, same bytes); strong raggedness (``G << P``) tips
    to Ring once tiles outweigh per-round latency.

    ``mode="measure"``: FFTW_MEASURE-style — compile every candidate for
    the actual configuration (:class:`AllToAll`, :class:`Ring`, and on
    chunkable configurations the :class:`Pipelined` sweep over
    ``K in {2, 4, 8}``) and time a forward+back pair on device
    (hardened K-differenced protocol, ``utils/benchtime.py``), caching
    the winner per configuration for the life of the process.

    Either way the data movement is bit-identical across candidates
    (test-pinned), so Auto never changes results — only scheduling.

    ``wire_dtype`` rides the resolution: every candidate (and the
    winner) carries it, so an ``Auto(wire_dtype="bf16")`` hop prices
    AND executes the halved-byte exchange whichever method wins (the
    method choice itself is wire-invariant in estimate mode — both
    scores scale by the same per-element wire bytes — but measure mode
    times the packed candidates for real).
    """

    mode: str = "estimate"
    latency_bytes: int = 128 * 1024
    wire_dtype: Optional[str] = None

    def __post_init__(self):
        if self.mode not in ("estimate", "measure"):
            raise ValueError(
                f"Auto mode must be 'estimate' or 'measure', got "
                f"{self.mode!r}")
        _canon_wire_field(self)


def assert_compatible(pin: Pencil, pout: Pencil) -> Optional[int]:
    """Check transposability and return the differing decomposition slot
    ``R`` (or ``None`` if decompositions are identical).

    Mirrors ``assert_compatible`` (``Transpositions.jl:182-199``): same
    topology, same global size, decompositions differing in at most one
    slot.
    """
    if pin.topology != pout.topology:
        raise ValueError("transpose: pencil topologies differ")
    if pin.size_global() != pout.size_global():
        raise ValueError(
            f"transpose: global shapes differ "
            f"({pin.size_global()} vs {pout.size_global()})"
        )
    diff = [
        i for i, (a, b) in enumerate(zip(pin.decomposition, pout.decomposition))
        if a != b
    ]
    if len(diff) > 1:
        raise ValueError(
            f"transpose: decompositions {pin.decomposition} -> "
            f"{pout.decomposition} differ in more than one slot; chain "
            f"transposes (x->y->z) or use reshard()"
        )
    return diff[0] if diff else None


# ---------------------------------------------------------------------------
# explicit all-to-all path
# ---------------------------------------------------------------------------


def _exchange_transpose(data, pin: Pencil, pout: Pencil, R: int,
                        extra_ndims: int, exchange_factory):
    """Shared pack -> exchange -> unpack structure for the explicit
    single-axis methods.  ``exchange_factory(axis, P, a, b)`` returns the
    function applied to the packed logical-order padded block."""
    mesh = pin.mesh
    axis = pin.topology.axis_names[R]
    P = pin.topology.dims[R]
    a = pin.decomposition[R]  # decomposed in input, local in output
    b = pout.decomposition[R]  # local in input, decomposed in output
    n_a = pin.size_global()[a]
    n_b = pin.size_global()[b]
    b_pad = pout.padded_global_shape[b]  # post-exchange padded extent of dim b

    in_spec = pin.partition_spec(extra_ndims)
    out_spec = pout.partition_spec(extra_ndims)
    inv_in = _inv_axes(pin, extra_ndims)     # memory -> logical
    fwd_out = _fwd_axes(pout, extra_ndims)   # logical -> memory
    platform = mesh.devices.flat[0].platform
    exchange = exchange_factory(axis, P, a, b)

    def local_fn(block):
        # Phase labels mirror the reference's timer sections
        # (``Transpositions.jl:173-177``) and show up in device profiles.
        with jax.named_scope("pack_data"):
            # block: local memory-order tile; go logical for the exchange.
            x = jnp.transpose(block, inv_in)
            # Pad dim b (fully local here) to its post-exchange padded extent.
            if b_pad != n_b:
                pad = [(0, 0)] * x.ndim
                pad[b] = (0, b_pad - n_b)
                x = jnp.pad(x, pad)
        with jax.named_scope("exchange"):
            x = exchange(x)
        with jax.named_scope("unpack_data"):
            # Dim a is now fully local with padded extent; drop tail padding.
            if x.shape[a] != n_a:
                x = jax.lax.slice_in_dim(x, 0, n_a, axis=a)
            # Store in the output pencil's memory order.
            return _maybe_pallas_transpose(x, fwd_out, platform)

    # check_vma=False only when the Pallas unpack kernel can actually run
    # for this block shape/dtype (pallas_call outputs carry no
    # varying-mesh-axes metadata, which the static check rejects); when
    # the plain jnp.transpose path runs the check stays on.
    from ..ops import pallas_kernels as pk

    out_block = tuple(pout.padded_size_local(LogicalOrder)) + tuple(
        data.shape[pin.ndims:])
    pallas_may_run = (
        fwd_out != tuple(range(len(fwd_out)))
        and pk.pallas_enabled()
        and pk.supported(out_block, fwd_out, data.dtype, platform))
    fn = shard_map(local_fn, mesh=mesh, in_specs=in_spec,
                       out_specs=out_spec,
                       check_vma=not pallas_may_run)
    return fn(data)


def _a2a_factory(pin: Pencil, pout: Pencil):
    """Exchange factory: one ``lax.all_to_all`` — the reference's entire
    pack -> Alltoallv -> unpack pipeline in one op (split dim b into P
    tiles, concat received tiles along dim a)."""
    def factory(axis, P, a, b):
        return lambda x: jax.lax.all_to_all(
            x, axis, split_axis=b, concat_axis=a, tiled=True)

    return factory


def _transpose_local(data, pin: Pencil, pout: Pencil, extra_ndims: int):
    """Same decomposition — only the permutation (storage order) changes;
    a pure local permute (reference ``transpose_impl!`` local path,
    ``Transpositions.jl:214-271``)."""
    rel = pout.permutation / pin.permutation
    if rel.is_identity():
        return data
    # memory(in) -> logical -> memory(out), as one transpose.
    axes_logical_to_out = _fwd_axes(pout, extra_ndims)
    axes_in_to_logical = _inv_axes(pin, extra_ndims)
    axes = tuple(axes_in_to_logical[i] for i in axes_logical_to_out)
    mesh = pin.mesh
    platform = mesh.devices.flat[0].platform
    from ..ops import pallas_kernels as pk

    local_shape = pin.padded_size_local(MemoryOrder) + data.shape[
        pin.ndims:]
    if pk.pallas_enabled() and pk.supported(local_shape, axes, data.dtype,
                                            platform):
        # per-block tiled permute under shard_map (block layouts are
        # identical across devices, so one kernel serves all); gating and
        # interpret policy live in _maybe_pallas_transpose
        fn = shard_map(
            lambda blk: _maybe_pallas_transpose(blk, axes, platform),
            mesh=mesh, in_specs=pin.partition_spec(extra_ndims),
            out_specs=pout.partition_spec(extra_ndims), check_vma=False)
        return fn(data)
    out = jnp.transpose(data, axes)
    return jax.lax.with_sharding_constraint(out, pout.sharding(extra_ndims))


def _ring_factory(pin: Pencil, pout: Pencil):
    """Exchange factory for :class:`Ring`: staged shifted ``ppermute``
    rounds of single tiles — RAGGED-AWARE.  The returned exchange
    closure is shape-polymorphic along every dim other than (a, b): it
    serves the whole block and any :class:`Pipelined` chunk of it
    equally.

    Bytes-on-the-wire model (vs reference ``Transpositions.jl:383-389``,
    which sends exact per-peer intersection ranges): under XLA SPMD every
    round's tile must have ONE static shape across devices, while the
    true intersection extents vary per (source, dest) pair — so exact
    intersection-size transfers are unrepresentable, and for dense
    configurations padded-uniform tiles are already optimal.  What IS
    statically known is which ceil-rule blocks are *entirely empty*:
    with ``n`` true elements in ``P`` blocks of ``ceil(n/P)``, only the
    first ``S = ceil(n / ceil(n/P))`` devices own data.  The ring
    therefore runs ``G-1`` rounds among the first ``G = max(S_a, S_b)``
    participants instead of ``P-1``: for the pathological raggedness the
    padded scheme is worst at (``n`` barely above ``P``), this removes
    most of the pure-padding traffic — e.g. ``n_a = n_b = 9, P = 8``
    runs 4 rounds instead of 7.  Structurally-empty destination blocks
    are zero-filled, keeping the padding-is-zeros invariant and
    bit-identity with :class:`AllToAll`."""
    def factory(axis, P, a, b):
        n_a = pin.size_global()[a]
        n_b = pin.size_global()[b]
        a_blk = pin.padded_global_shape[a] // P
        b_blk = pout.padded_global_shape[b] // P
        S_a = -(-n_a // a_blk)  # nonempty source blocks (ceil division)
        S_b = -(-n_b // b_blk)  # nonempty destination blocks
        G = max(S_a, S_b)       # ring participants

        def exchange(x):
            tiles = jnp.stack(
                [jax.lax.slice_in_dim(x, j * b_blk, (j + 1) * b_blk, axis=b)
                 for j in range(G)], axis=0)
            me = jnp.asarray(jax.lax.axis_index(axis), jnp.int32)
            # received[s] must hold sender s's tile for me; my own tile
            # seeds the buffer, round r delivers sender (me - r)'s.
            # (Devices >= G hold only padding; their clamped seeds and
            # received zeros are overwritten by the final mask.)
            received = jnp.zeros_like(tiles)
            own = jax.lax.dynamic_index_in_dim(tiles, me, axis=0)
            received = jax.lax.dynamic_update_index_in_dim(
                received, own, me, axis=0)
            # one round per shift r (unrolled: each round's ppermute has a
            # distinct static permutation; G-1 rounds total, only the
            # nonempty participants exchange)
            for r in range(1, G):
                # participant i sends tile[(i + r) % G] to peer (i + r) % G
                send = jax.lax.dynamic_index_in_dim(
                    tiles, jax.lax.rem(me + jnp.int32(r), jnp.int32(G)),
                    axis=0)
                moved = jax.lax.ppermute(
                    send, axis, [(i, (i + r) % G) for i in range(G)])
                # moved holds sender (me - r)'s tile for me
                src = jax.lax.rem(me - jnp.int32(r) + jnp.int32(G),
                                  jnp.int32(G))
                received = jax.lax.dynamic_update_index_in_dim(
                    received, moved, src, axis=0)
            # merge the sender axis into dim a (sender order = global
            # padded order, as with tiled all_to_all); senders >= G hold
            # no true rows (G >= S_a), appended as zeros
            out = jnp.moveaxis(received, 0, a)
            shape = list(out.shape)
            shape[a:a + 2] = [shape[a] * shape[a + 1]]
            out = out.reshape(shape)
            # dim a now has G*a_blk >= n_a rows; the unpack slices to n_a.
            if G < P:
                # destinations >= S_b own only padding columns, and
                # devices >= G saw clamped seeds: zero-fill their blocks
                # (padding-is-zeros invariant, bit-identity with AllToAll)
                out = jnp.where(me < jnp.int32(S_b), out,
                                jnp.zeros_like(out))
            return out

        return exchange

    return factory


def _wire_wrapped_factory(inner_factory, wire_dtype: str):
    """Bracket an exchange factory's closures with the sanctioned wire
    pack/unpack (``parallel/wire.py``): cast down immediately before
    the collective, restore immediately after — INSIDE the exchange
    closure, so a :class:`Pipelined` chunk packs per chunk (the chunked
    program stays chunk-local; no full-array cast materializes to kill
    the overlap win) and Ring rounds move packed tiles.  The exchange
    axes ``(a, b)`` and the pre-pack shape thread through to
    pack/unpack — the fp8 formats lay their per-tile scale windows
    along an axis the exchange leaves untouched and re-derive the tile
    geometry on arrival (:func:`~pencilarrays_tpu.parallel.wire
    .fp8_tile_axis`)."""
    from . import wire as _wire

    def factory(axis, P, a, b):
        inner = inner_factory(axis, P, a, b)

        def exchange(x):
            with jax.named_scope("wire_pack"):
                packed = _wire.pack(x, wire_dtype, axes=(a, b))
            moved = inner(packed)
            with jax.named_scope("wire_unpack"):
                return _wire.unpack(moved, x.dtype, wire_dtype,
                                    axes=(a, b), orig_shape=x.shape)

        return exchange

    return factory


def _exchange_factory(method: AbstractTransposeMethod, pin: Pencil,
                      pout: Pencil):
    """Dispatch the explicit single-axis exchange factory for a concrete
    method; :class:`Pipelined` wraps its base factory per-chunk and a
    ``wire_dtype`` brackets the innermost exchange with the reduced-
    precision pack/unpack.  Shared with the FFT planner's fused
    pipelined hops (``ops/fft.py``)."""
    if isinstance(method, AllToAll):
        f = _a2a_factory(pin, pout)
        return (_wire_wrapped_factory(f, method.wire_dtype)
                if method.wire_dtype else f)
    if isinstance(method, Ring):
        f = _ring_factory(pin, pout)
        return (_wire_wrapped_factory(f, method.wire_dtype)
                if method.wire_dtype else f)
    if isinstance(method, Pipelined):
        inner_f = _exchange_factory(method.base, pin, pout)

        def factory(axis, P, a, b):
            inner = inner_f(axis, P, a, b)

            def exchange(x):
                c = _pipeline_chunk_axis(x.shape, a, b)
                if c is None:
                    return inner(x)
                bounds = _chunk_bounds(x.shape[c], method.chunks)
                if len(bounds) == 1:
                    return inner(x)
                parts = [inner(jax.lax.slice_in_dim(x, s0, s1, axis=c))
                         for s0, s1 in bounds]
                return jnp.concatenate(parts, axis=c)

            return exchange

        return factory
    raise TypeError(f"no explicit exchange factory for method {method!r}")


# ---------------------------------------------------------------------------
# analytic cost model
# ---------------------------------------------------------------------------


def _exchange_operand_extents(pin: Pencil, pout: Pencil, R: int
                              ) -> Tuple[int, ...]:
    """Logical extents of the exchanged operand: the local block with
    the to-be-split dim ``b`` padded to its post-exchange padded extent
    — ``padded_global[i] / P_i`` for every dim decomposed in the input,
    ``pout.padded_global[b]`` for ``b``, true extent for other local
    dims.  The ONE definition shared by :func:`transpose_cost` (pricing)
    and the FFT planner's fused-hop chunk-axis choice (``ops/fft.py``),
    so the priced shape and the chunked shape can never diverge."""
    b = pout.decomposition[R]
    ext = []
    for i in range(pin.ndims):
        if i == b:
            ext.append(pout.padded_global_shape[b])
        elif i in pin.decomposition:
            j = pin.decomposition.index(i)
            ext.append(pin.padded_global_shape[i] // pin.topology.dims[j])
        else:
            ext.append(pin.size_global()[i])
    return tuple(ext)


def transpose_cost(pin: Pencil, pout: Pencil, extra_dims: Tuple[int, ...] = (),
                   dtype=None, method: AbstractTransposeMethod = AllToAll(),
                   *, chunk=None) -> dict:
    """Predicted per-chip collective cost of one transpose hop, in the
    same ``{op: {"count", "bytes"}}`` schema ``utils.hlo.collective_stats``
    measures from compiled HLO — so prediction and measurement are
    directly comparable (and the tests pin them EQUAL, which is what
    makes the byte model trustworthy).

    The analytic shape: the exchanged operand is the logical-order local
    block with the to-be-split dim ``b`` padded to its post-exchange
    padded extent — extent ``padded_global[i] / P_i`` for every dim
    decomposed in the input, ``pout.padded_global[b]`` for ``b``, true
    extent for other local dims.  AllToAll prices one application at the
    full block (the wire moves ``(P-1)/P`` of it; the self-share stays);
    Ring prices ``G - 1`` single-tile ``ppermute`` rounds among the
    ``G = max(S_a, S_b)`` nonempty ceil-rule participants.  This is the
    TPU analog of the reference's per-peer send-size accounting
    (``Transpositions.jl:383-389``).

    Batched scaling law: ``extra_dims`` ride the exchanged block, so a
    batch of B independent transforms multiplies every method's BYTES
    by B while the collective COUNT stays fixed — the amortization a
    ``PencilFFTPlan(batch=B)`` buys, regression-pinned against compiled
    batched HLO in ``tests/test_collective_costs.py``.  (Pipelined's
    chunk axis is chosen over the shape INCLUDING the extra dims, the
    same rule the runtime exchange uses, so prediction cannot diverge
    from execution on batched hops.)

    Precision dimension: a method carrying ``wire_dtype`` is priced at
    the wire format's per-element bytes (``parallel/wire.py``'s
    :func:`~pencilarrays_tpu.parallel.wire.wire_bytes` — 2 bytes per
    real component on bf16/f16, 1 on fp8 plus the exactly-priced
    per-tile scale side payload) — and the compiled HLO's collective
    shapes genuinely ARE the wire dtype, so the prediction stays
    pinned EQUAL to measurement with the wire on.

    fp8 exception to the Pipelined rule: pack runs per chunk, so each
    chunk ships its OWN scale tensor — when the chunk axis is also the
    tile axis, chunking multiplies the number of scale windows, and
    total bytes genuinely grow with the chunk count.  The fp8 branch
    therefore prices per-chunk operands and SUMS them (honest
    accounting, still HLO-pinned) instead of assuming byte invariance.

    ``chunk=(chunk_dim, bounds)`` prices an explicit AllToAll/Ring
    exchange whose caller owns the chunking (the FFT planner's fused
    ``ft`` hops — their program slices the operand itself): the
    collective count multiplies by ``len(bounds)``, bytes stay whole
    on 16-bit wires and sum per chunk on fp8 — the SAME rule the
    :class:`Pipelined` branch applies to its own chunk choice.
    """
    from .wire import FP8_WIRE_DTYPES, wire_bytes

    R = assert_compatible(pin, pout)
    if isinstance(method, Auto):
        method = resolve_method(pin, pout, extra_dims, dtype, method)
    if R is None:
        return {}
    P = pin.topology.dims[R]
    if P == 1:
        return {}
    if isinstance(method, Gspmd):
        # no analytic model exists (the partitioner owns the collective
        # choice), but the hop IS priceable: measure its own partitioned
        # HLO once and cache it — so Auto/the route planner can compare
        # Gspmd against explicit alternatives instead of skipping it
        return gspmd_reshard_cost(pin, pout, extra_dims, dtype)
    a = pin.decomposition[R]
    b = pout.decomposition[R]
    ext = _exchange_operand_extents(pin, pout, R)
    shape = tuple(ext) + tuple(extra_dims)
    wire = _method_wire(method)

    def _operand_bytes(s):
        # wire_bytes is the ONE per-operand byte definition shared with
        # collective_costs (via this function) and routing.py; the
        # exchange axes make the fp8 scale overhead exactly priceable
        return wire_bytes(dtype, wire, s, axes=(a, b))

    def _base_cost(m, s):
        """Cost of one explicit base exchange of operand shape ``s``."""
        if isinstance(m, AllToAll):
            return {"all-to-all": {"count": 1, "bytes": _operand_bytes(s)}}
        if isinstance(m, Ring):
            n_a = pin.size_global()[a]
            n_b = pin.size_global()[b]
            a_blk = pin.padded_global_shape[a] // P
            b_blk = pout.padded_global_shape[b] // P
            G = max(-(-n_a // a_blk), -(-n_b // b_blk))
            if G <= 1:
                return {}
            # each round moves one b-block tile of the packed operand
            ts = s[:b] + (b_blk,) + s[b + 1:]
            return {"collective-permute":
                    {"count": G - 1, "bytes": (G - 1) * _operand_bytes(ts)}}
        raise ValueError(f"no analytic cost model for method {m!r}")

    def _chunked_cost(m, c, bounds):
        k_eff = len(bounds)
        if wire not in FP8_WIRE_DTYPES or k_eff == 1:
            # chunking multiplies the collective COUNT and leaves total
            # wire bytes unchanged (ceil chunks partition the block
            # exactly) — the schema prediction stays equal to
            # compiled-HLO measurement
            base = transpose_cost(pin, pout, extra_dims, dtype, m)
            return {op: {"count": v["count"] * k_eff, "bytes": v["bytes"]}
                    for op, v in base.items()}
        # fp8: pack runs per chunk — sum each chunk's exact packed bytes
        out: dict = {}
        for s0, s1 in bounds:
            cs = shape[:c] + (s1 - s0,) + shape[c + 1:]
            for op, v in _base_cost(m, cs).items():
                e = out.setdefault(op, {"count": 0, "bytes": 0})
                e["count"] += v["count"]
                e["bytes"] += v["bytes"]
        return out

    if isinstance(method, Pipelined):
        c = _pipeline_chunk_axis(shape, a, b)
        if c is None:
            return transpose_cost(pin, pout, extra_dims, dtype,
                                  method.base)
        return _chunked_cost(method.base,
                             c, _chunk_bounds(shape[c], method.chunks))
    if chunk is not None and len(chunk[1]) > 1:
        return _chunked_cost(method, chunk[0], tuple(chunk[1]))
    return _base_cost(method, shape)


# ---------------------------------------------------------------------------
# automatic method selection
# ---------------------------------------------------------------------------


_MEASURE_REPORTS: dict = {}
_MEASURE_TIMINGS: dict = {}


def _obs_record_measure_verdict(pin: Pencil, pout: Pencil, R: int,
                                extra_dims: tuple, dtype,
                                wire: Optional[str] = None) -> None:
    """Journal a measure-mode Auto verdict + its candidate timings as
    drift samples, once per (obs run, config).  Reads the cached
    measurement, so late-armed observability still journals configs
    measured earlier in the process."""
    import numpy as np

    key = (pin, pout, R, extra_dims, np.dtype(dtype).str, wire)
    report = _MEASURE_REPORTS.get(key)
    if report is None:
        return
    dedup = (obs.run_id(), "measure", report["config"])
    if dedup in _ESTIMATE_LOGGED:
        return
    _ESTIMATE_LOGGED.add(dedup)
    obs.record_event("auto.verdict", mode="measure", **report)
    for cand, t in _MEASURE_TIMINGS.get(key, ()):
        # candidate timings are fwd+back pairs of the SAME hop shape:
        # halve to per-hop seconds and feed the drift tracker (true
        # device timings — they outrank dispatch samples)
        cost = transpose_cost(pin, pout, extra_dims, dtype, cand)
        obs.record_hop_sample(
            _hop_label(pin, pout, cand, dtype),
            sum(v["bytes"] for v in cost.values()), t / 2.0,
            source="auto_measure")


def _method_label(m: AbstractTransposeMethod) -> str:
    """Stable human-readable audit label for a candidate method.  The
    wire dtype is part of the label (``AllToAll[wire=bf16]``) so drift
    keys, journal records, ``plan_key()`` fingerprints and the serve
    coalescing keys all separate reduced- from full-precision traffic;
    full-precision labels are byte-identical to the historical ones."""
    if isinstance(m, Pipelined):
        return f"Pipelined(chunks={m.chunks}, base={_method_label(m.base)})"
    wire = _method_wire(m) if isinstance(m, (AllToAll, Ring, Auto)) else None
    if wire is not None:
        return f"{type(m).__name__}[wire={wire}]"
    return type(m).__name__


# ---------------------------------------------------------------------------
# observability taps (active only when obs.enabled(); see obs/ package)
# ---------------------------------------------------------------------------


def _hop_label(pin: Pencil, pout: Pencil, method: AbstractTransposeMethod,
               dtype=None) -> str:
    """Stable per-configuration key for metrics/drift: global shape,
    mesh, decomposition change, method, dtype — everything the byte
    model prices."""
    import numpy as np

    dt = np.dtype(dtype if dtype is not None else np.float32).name
    return (f"{pin.size_global()}@{pin.topology.dims} "
            f"{pin.decomposition}->{pout.decomposition} "
            f"{_method_label(method)} {dt}")


@lru_cache(maxsize=512)
def _cached_hop_cost(pin: Pencil, pout: Pencil, extra_dims: tuple,
                     dtype_str: str, method: AbstractTransposeMethod) -> dict:
    """transpose_cost cached per static configuration, so per-dispatch
    instrumentation never re-prices a hop it has already priced."""
    import numpy as np

    return transpose_cost(pin, pout, extra_dims, np.dtype(dtype_str), method)


def _obs_record_hop(pin: Pencil, pout: Pencil, R: Optional[int],
                    method: AbstractTransposeMethod, extra_dims: tuple,
                    dtype, dispatch_s: float, fused_k: int = 0) -> None:
    """Journal + meter one dispatched hop (obs-enabled paths only).
    ``fused_k > 0`` marks a pipelined hop fused with its transform stage
    (``ops/fft.py``), whose chunk count is owned by the fused program."""
    import numpy as np

    label = _method_label(method)
    chunks = fused_k or (method.chunks if isinstance(method, Pipelined) else 1)
    dtype_str = np.dtype(dtype).str
    try:
        cost = (_cached_hop_cost(pin, pout, tuple(extra_dims), dtype_str,
                                 method) if R is not None else {})
    except (TypeError, ValueError):
        cost = {}  # e.g. Gspmd: the partitioner owns the collectives
    nbytes = sum(v["bytes"] for v in cost.values())
    hop = _hop_label(pin, pout, method, dtype)
    if fused_k:
        # a fused hop's dispatch time includes its transform stage — it
        # must not share a drift key with the bare exchange's samples
        hop += f" fused(K={fused_k})"
    obs.counter("transpose.dispatches", method=label).inc()
    obs.counter("transpose.predicted_bytes").inc(nbytes)
    obs.histogram("transpose.dispatch_seconds", method=label).observe(
        dispatch_s)
    # per-dispatch host wall time: the free drift proxy (benchtime /
    # auto-measure samples outrank it in the report).  Zero-byte hops
    # (local permutes) are recorded too: their drift stays None (nothing
    # on the wire to reconcile) but their measured duration is what the
    # mesh straggler detector compares across ranks (obs/straggler.py)
    obs.record_hop_sample(hop, nbytes, dispatch_s, source="dispatch")
    obs.record_event(
        "hop", method=label, hop=hop, r=R, chunks=chunks,
        fused=bool(fused_k), predicted_bytes=nbytes, predicted=cost,
        dispatch_s=dispatch_s,
        shape=list(pin.size_global()), topo=list(pin.topology.dims))


def last_measure_reports() -> list:
    """Variance-aware audit trail of every ``Auto(mode='measure')``
    decision taken in this process: per-candidate seconds, the k1-arm
    worst/best spread of each measurement, and whether the winner's
    margin clears the observed noise floor.  A decision whose
    ``margin_over_noise`` is < 1 is a coin flip on a noisy tunnel and
    should be re-measured before being trusted (VERDICT r3 weak #7)."""
    return list(_MEASURE_REPORTS.values())


@lru_cache(maxsize=512)
def _measured_choice(pin: Pencil, pout: Pencil, R: int, extra_dims: tuple,
                     dtype_str: str, wire: Optional[str] = None
                     ) -> AbstractTransposeMethod:
    """Time every explicit candidate on the actual configuration and
    cache the winner (FFTW_MEASURE analog): AllToAll, Ring, and — when
    the configuration has a chunkable dim — the Pipelined K in {2,4,8}
    sweep.  ``wire`` rides every candidate (the packed exchange is what
    gets timed AND what the cached winner executes — a reduced-wire
    config never shares a verdict with its full-precision sibling).
    The timed body is a forward+back pair — shape-preserving, so the
    hardened in-jit K-differenced protocol (``utils/benchtime.py``)
    applies directly.  Each decision is recorded with its noise floor in
    :func:`last_measure_reports`."""
    import numpy as np

    from ..utils.benchtime import device_seconds_per_iter, last_spread

    from ..ops.pallas_kernels import pallas_enabled

    dtype = np.dtype(dtype_str)
    x0 = PencilArray.zeros(pin, extra_dims, dtype).data
    extra_ndims = len(extra_dims)
    # Chunked candidates sweep K in {2, 4, 8} (K=1 IS AllToAll) when the
    # configuration has a chunkable dim — the pipelined-hop sweep the
    # FFT planner's ``pipeline="auto"`` consumes; standalone hops rarely
    # reward chunking (K serialized launches, same bytes), and an honest
    # measurement says so.
    a = pin.decomposition[R]
    b = pout.decomposition[R]
    blk = tuple(pin.padded_size_local(LogicalOrder)) + tuple(extra_dims)
    c = _pipeline_chunk_axis(blk, a, b)
    candidates = [AllToAll(wire_dtype=wire), Ring(wire_dtype=wire)]
    if c is not None:
        candidates += [
            Pipelined(chunks=k, base=AllToAll(wire_dtype=wire))
            for k in (2, 4, 8) if len(_chunk_bounds(blk[c], k)) > 1]
    candidates = tuple(candidates)
    best, best_t = 0, float("inf")
    times, spreads = [], []
    for i, cand in enumerate(candidates):
        # positional args only: lru_cache keys kwargs differently, and
        # transpose() looks this executable up positionally — the winner
        # must be a cache HIT there, not a recompile
        fwd = _compiled_transpose(pin, pout, R, extra_ndims, cand, False,
                                  pallas_enabled())
        bwd = _compiled_transpose(pout, pin, R, extra_ndims, cand, False,
                                  pallas_enabled())
        t = device_seconds_per_iter(lambda d: bwd(fwd(d)), x0,
                                    k0=1, k1=8, repeats=5)
        times.append(t)
        spreads.append(last_spread()["k1_worst_over_best"])
        if t < best_t:
            best, best_t = i, t
    # confidence = winner vs the RUNNER-UP (with >2 candidates the
    # slowest loser would overstate the margin of a narrow win)
    loser_t = min(t for i, t in enumerate(times) if i != best) \
        if len(times) > 1 else best_t
    noise = max(s for s in spreads if s is not None) if any(
        s is not None for s in spreads) else None
    report = {
        "config": f"{pin.size_global()}@{pin.topology.dims} R={R} "
                  f"{dtype_str}" + (f" wire={wire}" if wire else ""),
        "candidates": [_method_label(c) for c in candidates],
        "seconds": times,
        "k1_spreads": spreads,
        "winner": _method_label(candidates[best]),
        # ratio of the loser/winner time gap to the measurement noise:
        # > 1 means the decision clears the observed jitter
        "margin_over_noise": (round((loser_t / best_t) / noise, 3)
                              if noise and best_t > 0 else None),
    }
    _MEASURE_REPORTS[(pin, pout, R, extra_dims, dtype_str, wire)] = report
    # timings are kept (method objects + seconds) for the obs tap in
    # resolve_method — journaling must NOT live inside this lru_cache,
    # or a config resolved before obs was armed would never appear in a
    # later run's journal (the late-arming contract)
    _MEASURE_TIMINGS[(pin, pout, R, extra_dims, dtype_str, wire)] = tuple(
        zip(candidates, times))
    if jax.process_count() > 1:
        # Multi-controller: every process MUST run the same collective
        # program — local timing noise could split the vote, issuing
        # ppermute rounds on one host and all_to_all on another (pod
        # deadlock).  Process 0's winner is authoritative.
        from jax.experimental import multihost_utils

        best = int(multihost_utils.broadcast_one_to_all(
            jnp.int32(best)))
    return candidates[best]


def resolve_method(pin: Pencil, pout: Pencil,
                   extra_dims: Tuple[int, ...] = (), dtype=None,
                   method: AbstractTransposeMethod = Auto(), *,
                   _quiet: bool = False) -> AbstractTransposeMethod:
    """Resolve :class:`Auto` to a concrete method for one hop (concrete
    methods pass through unchanged).  See :class:`Auto` for the decision
    rule; different hops of one FFT plan may resolve differently.

    ``_quiet=True`` suppresses the ``auto.verdict`` journal tap (and
    its per-run dedup): the slab/pencil decomposition scorer
    (``ops/fft.py``) resolves hops of candidate schedules that are
    priced and DISCARDED — journaling them would put phantom hop
    configurations in the timeline, and marking them deduped would
    silence the real verdict when the built plan's hop later resolves."""
    if not isinstance(method, Auto):
        return method
    R = assert_compatible(pin, pout)
    wire = method.wire_dtype
    if R is None or pin.topology.dims[R] == 1:
        # local permute / trivial axis: method is moot (wire rides along
        # for label/key fidelity; nothing packs on a zero-wire hop)
        return AllToAll(wire_dtype=wire)
    if method.mode == "measure":
        import numpy as np

        dt = np.dtype(dtype if dtype is not None else np.float32)
        choice = _measured_choice(pin, pout, R, tuple(extra_dims), dt.str,
                                  wire)
        if obs.enabled() and not _quiet:
            _obs_record_measure_verdict(pin, pout, R, tuple(extra_dims),
                                        dt, wire)
        return choice
    P = pin.topology.dims[R]
    ring = transpose_cost(pin, pout, tuple(extra_dims), dtype,
                          Ring(wire_dtype=wire))
    if not ring:
        return AllToAll(wire_dtype=wire)  # G <= 1: nothing on the wire
    rc = ring["collective-permute"]
    tile = rc["bytes"] // rc["count"]
    rounds = rc["count"]  # G - 1
    L = method.latency_bytes
    score_ring = rounds * (L + tile)
    score_a2a = L + (P - 1) * tile
    winner = (Ring(wire_dtype=wire) if score_ring < score_a2a
              else AllToAll(wire_dtype=wire))
    if obs.enabled() and not _quiet:
        config = _hop_label(pin, pout, method, dtype)
        # one journaled verdict per config PER OBS RUN (run ids are
        # fresh per obs.enable(), so a later run's journal is complete)
        key = (obs.run_id(), config)
        if key not in _ESTIMATE_LOGGED:
            _ESTIMATE_LOGGED.add(key)
            obs.record_event(
                "auto.verdict", mode="estimate", config=config,
                winner=_method_label(winner),
                score_ring_bytes=int(score_ring),
                score_a2a_bytes=int(score_a2a),
                latency_bytes=int(L))
    return winner


_ESTIMATE_LOGGED: set = set()


# ---------------------------------------------------------------------------
# GSPMD path
# ---------------------------------------------------------------------------

def _reshard_gspmd(data, pin: Pencil, pout: Pencil, extra_ndims: int):
    """Express the layout change; let the partitioner insert collectives.

    Handles arbitrary decomposition changes (not just single-slot)."""
    # memory(in), padded(in) -> logical true shape
    x = jnp.transpose(data, _inv_axes(pin, extra_ndims))
    true = pin.size_global()
    if x.shape[: pin.ndims] != true:
        x = x[tuple(slice(0, n) for n in true) + (slice(None),) * extra_ndims]
    # logical true -> padded(out)
    padded = pout.padded_global_shape
    if padded != true:
        pad = [(0, p - n) for n, p in zip(true, padded)]
        pad += [(0, 0)] * extra_ndims
        x = jnp.pad(x, pad)
    x = jnp.transpose(x, _fwd_axes(pout, extra_ndims))
    return jax.lax.with_sharding_constraint(x, pout.sharding(extra_ndims))


@lru_cache(maxsize=256)
def _gspmd_collective_cost(pin: Pencil, pout: Pencil,
                           extra_dims: Tuple[int, ...],
                           dtype_str: str) -> dict:
    import numpy as np

    from ..utils.hlo import collective_stats

    extra_ndims = len(extra_dims)
    shape = tuple(pin.padded_size_global(MemoryOrder)) + tuple(extra_dims)
    aval = jax.ShapeDtypeStruct(shape, np.dtype(dtype_str),
                                sharding=pin.sharding(extra_ndims))
    hlo = (jax.jit(lambda d: _reshard_gspmd(d, pin, pout, extra_ndims))
           .lower(aval).compile().as_text())
    return collective_stats(hlo)


def gspmd_reshard_cost(pin: Pencil, pout: Pencil,
                       extra_dims: Tuple[int, ...] = (),
                       dtype=None) -> dict:
    """Measured per-chip collective cost of the GSPMD redistribution
    ``pin -> pout`` (any number of differing slots), in the
    ``transpose_cost`` / ``utils.hlo.collective_stats`` schema.

    GSPMD hops have no analytic model — the partitioner owns the
    collective choice — so the price IS the measurement: the layout
    change is lowered, SPMD-partitioned and compiled once per static
    configuration (cached), and the compiled HLO's collective
    applications are counted and byte-priced.  This is what lets
    ``Auto`` and the route planner (``parallel/routing.py``) compare
    Gspmd against routed alternatives instead of skipping it."""
    import numpy as np

    if pin.topology != pout.topology:
        raise ValueError("gspmd_reshard_cost: pencil topologies differ")
    if pin.size_global() != pout.size_global():
        raise ValueError("gspmd_reshard_cost: global shapes differ")
    dt = np.dtype(dtype if dtype is not None else np.float32)
    return _gspmd_collective_cost(pin, pout,
                                  tuple(int(e) for e in extra_dims), dt.str)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _metered_cached(cache_fn, kind: str, *args):
    """Call an ``lru_cache``'d executable factory, metering hit/miss on
    the obs registry (``compile.cache_hits|misses{cache=<kind>}``) —
    the per-cache counters the persistent-compilation-cache knob's
    effectiveness is judged by.  Disabled-path cost: one ``enabled()``
    probe (the metering itself only runs when obs is armed)."""
    if not obs.enabled():
        return cache_fn(*args)
    before = cache_fn.cache_info().misses
    out = cache_fn(*args)
    label = ("misses" if cache_fn.cache_info().misses > before else "hits")
    obs.counter(f"compile.cache_{label}", cache=kind).inc()
    return out

def _hop_body(pin: Pencil, pout: Pencil, R: Optional[int],
              extra_ndims: int, method: AbstractTransposeMethod):
    """The traced data->data body of one hop — the ONE definition both
    the plain and the guard-instrumented executables wrap, so enabling
    the guard can never change the data movement itself."""
    if R is None:
        return lambda data: _transpose_local(data, pin, pout, extra_ndims)
    if isinstance(method, (AllToAll, Ring, Pipelined)):
        # one path for every explicit exchange: the factory owns the
        # method's chunking AND its wire pack/unpack, so a Pipelined
        # base's wire_dtype packs per chunk by construction
        return lambda data: _exchange_transpose(
            data, pin, pout, R, extra_ndims,
            _exchange_factory(method, pin, pout))
    if isinstance(method, Gspmd):
        return lambda data: _reshard_gspmd(data, pin, pout, extra_ndims)
    raise TypeError(f"unknown transpose method {method!r}")


@lru_cache(maxsize=512)
def _compiled_transpose(pin: Pencil, pout: Pencil, R: Optional[int],
                        extra_ndims: int,
                        method: AbstractTransposeMethod,
                        donate: bool = False,
                        _pallas: bool = False):
    # _pallas participates only as a cache key: the kernels read the env
    # flag themselves, and keying on it prevents a stale cached executable
    # after the flag is toggled mid-process.
    """Compiled data->data transpose, cached on the static configuration.

    Pencils are frozen/hashable, so (pin, pout, method) is a complete key.
    Without this cache, eager callers would re-trace (and re-compile) the
    shard_map closure on every call — the analog of the reference reusing
    its preallocated send/recv buffers across transposes
    (``Pencils.jl:151-192``), but for compiled executables.
    """
    fn = _hop_body(pin, pout, R, extra_ndims, method)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


@lru_cache(maxsize=512)
def _compiled_guarded_transpose(pin: Pencil, pout: Pencil, R: Optional[int],
                                extra_ndims: int,
                                method: AbstractTransposeMethod,
                                donate: bool = False, _pallas: bool = False,
                                finite: bool = False, corrupt: bool = False):
    """Probe-instrumented sibling of :func:`_compiled_transpose`: the
    SAME hop body bracketed by the guard's invariant probes
    (``guard/integrity.py``) **inside one jitted program** — no extra
    dispatch, no host copy; the probes are two small reductions XLA
    schedules around the exchange.  ``corrupt=True`` compiles the SDC
    drill variant, which pokes the hop output (counter-addressed, the
    index is a traced arg) between the exchange and the post probe —
    exactly where a flipped wire bit would land."""
    from ..guard import integrity as gi

    core = _hop_body(pin, pout, R, extra_ndims, method)

    if corrupt:
        def fn(data, poke_idx):
            pre = gi.probe_stats(data, finite)
            out = gi.corrupt_block(core(data), poke_idx)
            return out, pre, gi.probe_stats(out, finite)
    else:
        def fn(data):
            pre = gi.probe_stats(data, finite)
            out = core(data)
            return out, pre, gi.probe_stats(out, finite)

    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _dispatch_guarded_hop(pin: Pencil, pout: Pencil, R: Optional[int],
                          extra_ndims: int,
                          method: AbstractTransposeMethod, data,
                          donate: bool, dtype,
                          corrupt_hit: Optional[int] = None,
                          label: Optional[str] = None):
    """Dispatch one eager hop through the guard: probe-instrumented
    executable, hang watchdog around the dispatch + probe fetch, and
    the host-side invariant check (raising
    :class:`~pencilarrays_tpu.guard.IntegrityError` on mismatch —
    typed error, never garbage)."""
    from ..guard import integrity as gi
    from ..ops.pallas_kernels import pallas_enabled

    finite = guard.finite_tick()
    fn = _metered_cached(_compiled_guarded_transpose, "hop", pin, pout, R,
                         extra_ndims, method, donate, pallas_enabled(),
                         finite, corrupt_hit is not None)
    hop = label or _hop_label(pin, pout, method, dtype)
    count = int(data.size)
    with guard.watchdog(f"hop:{_method_label(method)}", kind="hop",
                        hop=hop):
        if corrupt_hit is not None:
            out, pre, post = fn(data, max(0, corrupt_hit - 1))
        else:
            out, pre, post = fn(data)
        # the probe fetch inside check_hop_probes blocks until the
        # device program completes — a hung collective parks THERE,
        # under the armed deadline
        gi.check_hop_probes(hop, pre, post, count, dtype, finite=finite,
                            wire_dtype=_method_wire(method),
                            ctx={"r": R, "method": _method_label(method)})
    return out


@lru_cache(maxsize=512)
def _compiled_reshard(pin: Pencil, pout: Pencil, extra_ndims: int,
                      donate: bool = False):
    """Compiled GSPMD reshard, cached on the static configuration (the
    ``_compiled_transpose`` discipline: without it every eager call
    would jit a fresh lambda and recompile)."""
    return jax.jit(lambda data: _reshard_gspmd(data, pin, pout, extra_ndims),
                   donate_argnums=(0,) if donate else ())


def transpose(src: PencilArray, dest: Pencil, *,
              method: AbstractTransposeMethod = AllToAll(),
              donate: bool = False) -> PencilArray:
    """Redistribute ``src`` into the ``dest`` pencil configuration
    (reference ``transpose!``, ``Transpositions.jl:161-180``).

    Traceable: call it inside ``jax.jit`` and the exchange fuses into the
    surrounding program.  Pure (returns a new PencilArray); with
    ``donate=True`` the source buffer is donated to XLA for reuse — the
    re-specification of the reference's shared send/recv buffers and
    in-place ``ManyPencilArray`` transposes (see
    ``parallel/multiarrays.py``).  After a donating call the source array
    is invalid.
    """
    pin = src.pencil
    R = assert_compatible(pin, dest)
    if isinstance(method, Auto):
        method = resolve_method(pin, dest, src.extra_dims, src.dtype, method)
    from ..ops.pallas_kernels import pallas_enabled

    import jax.core

    with timeit(pin.timer, "transpose!"):
        eager = not isinstance(src.data, jax.core.Tracer)
        # the hop tap observes EAGER dispatches only: under an outer
        # jit this call runs at trace time (once per compile), where a
        # "duration" would be lowering time, not a dispatch — it must
        # neither flood the journal per compile nor poison the drift
        # fit (use obs.profile for device-side visibility of jitted
        # programs).  The clock starts BEFORE the fault probe so a
        # `delay`-mode stall (the injected straggler) is part of the
        # measured dispatch — what the mesh straggler detector reads.
        t0 = time.perf_counter() if (obs.enabled() and eager) else None
        # the SDC drill point: eager dispatches only (a traced hop is
        # one compile, not an exchange), gated on armed() so the
        # no-faults hot path pays one cached env probe
        act = None
        if eager and faults.armed("hop.exchange"):
            act = faults.fire("hop.exchange", r=R,
                              method=_method_label(method))
            if act == "torn":   # this site cannot tear: treat as kill
                faults.kill_now()
        if eager and guard.enabled():
            # guarded path: probes ride the SAME program; a corrupt
            # drill rides between exchange and post-probe
            out = _dispatch_guarded_hop(
                pin, dest, R, src.ndims_extra, method, src.data, donate,
                src.dtype,
                corrupt_hit=(faults.hit_count("hop.exchange")
                             if act == "corrupt" else None))
        else:
            fn = _metered_cached(_compiled_transpose, "hop", pin, dest, R,
                                 src.ndims_extra, method, donate,
                                 pallas_enabled())
            out = fn(src.data)
            if act == "corrupt":
                # guard off: the poke flows through UNDETECTED — the
                # silent garbage the guard exists to catch (chaos tests
                # pin both behaviors)
                from ..guard import integrity as gi

                out = gi.corrupt_eager(
                    out, faults.hit_count("hop.exchange") - 1)
        if t0 is not None:
            _obs_record_hop(pin, dest, R, method, src.extra_dims,
                            src.dtype, time.perf_counter() - t0)
    return PencilArray(dest, out, src.extra_dims)


def reshard(src: PencilArray, dest: Pencil, *,
            method: AbstractTransposeMethod = Auto(),
            donate: bool = False,
            hbm_limit: Optional[int] = None) -> PencilArray:
    """Unrestricted redistribution between *any* two pencils sharing a
    topology and global shape — capability beyond the reference's
    single-slot transpose.

    By default the **route planner** (``parallel/routing.py``) searches
    the pencil graph for a chain of single-axis exchanges the cost
    model prices cheaper than the one opaque GSPMD exchange, and
    executes the winner as ONE fused jitted chain (per-hop dispatch
    and intermediates are compiler-owned); it falls back to the GSPMD
    partitioner when no cheaper route exists (``arXiv:2112.01075``'s
    searched-decomposition redistribution).  ``method=Gspmd()`` forces
    the legacy single-exchange path; an explicit exchange method
    (``AllToAll()``/``Ring()``/``Pipelined(...)``) forces the ROUTED
    path with that method on every edge (falling back to Gspmd only
    when no single-slot chain exists at all).  Results are
    bit-identical either way (test-pinned) — only scheduling differs.

    ``donate=True`` donates the source buffer to the executable (``src``
    becomes invalid), as with ``transpose(donate=True)``.

    ``hbm_limit`` bounds every hop's charged per-chip footprint
    (memory-bounded redistribution, ``arXiv:2112.01075``): hops that
    would bust the limit are time-sliced into chunked collectives by
    the planner (bit-identical, count ×K), ``donate=True`` shrinks the
    charge further (the retiring-source accounting — see
    :func:`~pencilarrays_tpu.parallel.routing.plan_reshard_route`),
    and the bound is honored or the call fails typed: when no
    admissible route exists at all, a
    :class:`~pencilarrays_tpu.analysis.errors.HbmBoundError` is raised
    instead of silently running the unbounded GSPMD exchange.
    """
    import jax.core

    pin = src.pencil
    if pin.topology != dest.topology:
        raise ValueError("reshard: pencil topologies differ")
    if pin.size_global() != dest.size_global():
        raise ValueError("reshard: global shapes differ")
    if hbm_limit is not None and isinstance(method, Gspmd):
        raise ValueError(
            "reshard(hbm_limit=) cannot bound method=Gspmd(): the "
            "partitioner owns its collectives and intermediates, so no "
            "peak-HBM claim is checkable; use Auto() or an explicit "
            "exchange method")
    if pin == dest:
        return src  # nothing to move (transpose() passthrough parity)
    eager = not isinstance(src.data, jax.core.Tracer)
    don = donate and eager
    if not isinstance(method, Gspmd):
        from .routing import (_obs_record_route_plan, execute_route,
                              plan_reshard_route)

        route = plan_reshard_route(pin, dest, src.extra_dims, src.dtype,
                                   method=method, hbm_limit=hbm_limit,
                                   donate=don)
        if obs.enabled() and eager:
            _obs_record_route_plan(route, src.extra_dims, src.dtype)
        if route.use_route:
            if obs.enabled() and eager:
                obs.counter("reshard.dispatches", path="routed").inc()
            return execute_route(src, route, donate=don)
        if hbm_limit is not None:
            # the caller asked for a bound the planner cannot honor:
            # the GSPMD fallback's peak is unboundable, so fail typed
            # (report the cheapest unbounded route's footprint so the
            # error names the actual need, not just the miss)
            from ..analysis.errors import HbmBoundError

            unbounded = plan_reshard_route(pin, dest, src.extra_dims,
                                           src.dtype, method=method,
                                           donate=don)
            raise HbmBoundError(
                "reshard",
                f"{pin.decomposition}->{dest.decomposition}",
                unbounded.peak_hbm_bytes or 0, int(hbm_limit))
    # only an ACTUAL gspmd dispatch is counted (the typed hbm raise
    # above dispatches nothing, and must not leave phantom metrics)
    if obs.enabled() and eager:
        obs.counter("reshard.dispatches", path="gspmd").inc()
    # the GSPMD fallback is pure data movement too: with the guard
    # armed, eager dispatches run probe-instrumented (same invariant,
    # same watchdog) — and the SDC drill point covers this path
    act = None
    if eager and faults.armed("hop.exchange"):
        act = faults.fire("hop.exchange", kind="reshard-gspmd")
        if act == "torn":
            faults.kill_now()
    if eager and guard.enabled():
        out = _dispatch_guarded_hop(
            pin, dest, "gspmd", src.ndims_extra, Gspmd(), src.data, don,
            src.dtype,
            corrupt_hit=(faults.hit_count("hop.exchange")
                         if act == "corrupt" else None))
        return PencilArray(dest, out, src.extra_dims)
    fn = _metered_cached(_compiled_reshard, "reshard", pin, dest,
                         src.ndims_extra, don)
    out = fn(src.data)
    if act == "corrupt":
        from ..guard import integrity as gi

        out = gi.corrupt_eager(out, faults.hit_count("hop.exchange") - 1)
    return PencilArray(dest, out, src.extra_dims)


class Transposition:
    """Object API for parity with the reference's two-step
    ``Transposition(Ao, Ai)`` + ``transpose!(t)`` + ``MPI.Waitall(t)``
    (``Transpositions.jl:70-131``).

    Under XLA there is nothing to wait on — collectives are scheduled by
    the compiler — so :meth:`waitall` is a no-op kept for source parity,
    and :meth:`execute` returns the destination array.
    """

    def __init__(self, dest: Pencil, src: PencilArray,
                 method: AbstractTransposeMethod = AllToAll()):
        self.dest_pencil = dest
        self.src = src
        self.method = method
        self.dim = assert_compatible(src.pencil, dest)
        self._result: Optional[PencilArray] = None

    def execute(self) -> PencilArray:
        if self._result is None:
            self._result = transpose(self.src, self.dest_pencil,
                                     method=self.method)
        return self._result

    def waitall(self) -> None:
        """No-op (XLA latency-hiding scheduler owns completion)."""
        self.execute()
