"""Reduced-precision wire format — the sanctioned pack/unpack choke point.

Every transpose hop in the stack is bandwidth-bound by the validated
byte model (AccFFT, arXiv:1506.07933: redistribution time is wire bytes
over bisection bandwidth), so the cheapest bytes are the ones never
sent.  This module owns the OPT-IN reduced-precision exchange payload
(``wire_dtype="bf16" | "f16"`` on the explicit transpose methods and on
``PencilFFTPlan``): shards are cast-packed immediately before the
collective and restored immediately after, *inside the same traced
program*, so XLA fuses the casts into the exchange boundaries and the
collective itself moves half the bytes.  Accumulation and transform
math stay in full precision — only the wire narrows.

Three contracts, all enforced here so no caller can drift:

* **packing** — :func:`pack` / :func:`unpack` are the ONLY functions
  allowed to change an exchange payload's element type (``pa-lint``'s
  ``wire-cast`` check forbids direct ``.astype(`` in the
  exchange-program modules).  Real payloads cast elementwise; complex
  payloads (c64/c128) use SPLIT-COMPLEX packing — re/im stacked along a
  new trailing axis — so each component downcasts through a clean
  real→real cast instead of a complex cast (which XLA would reject or
  round through an intermediate).  The trailing axis rides the exchange
  like an extra dim: the split/concat dims' indices are untouched, so
  the same pack serves ``AllToAll``, ``Ring`` tiles and every
  ``Pipelined`` chunk;
* **byte accounting** — :func:`wire_itemsize` / :func:`wire_bytes` are
  the ONE definition of per-element wire cost shared by
  ``transpose_cost``, ``PencilFFTPlan.collective_costs`` and the route
  planner's peak-HBM bound (they used to each re-derive ``itemsize``).
  bf16/f16 carry 2 bytes per real component, so f32/c64 payloads halve
  and f64/c128 quarter — and the compiled HLO's collective shapes
  really are ``bf16[...]``, so the HLO-pinned prediction==measurement
  equality holds with the wire on;
* **tolerance model** — :func:`wire_rtol` is the per-dtype quantization
  error bound the guard's content-sum probes compare against
  (``guard/integrity.py``): a restored payload may differ from its
  source by at most ~half a wire-dtype ULP per element.  Exceedance is
  a typed :class:`~pencilarrays_tpu.guard.errors.WirePrecisionError`,
  never a silent wrong answer.  Override:
  ``PENCILARRAYS_TPU_GUARD_WIRE_RTOL``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "WIRE_DTYPES",
    "canonical_wire_dtype",
    "pack",
    "unpack",
    "wire_itemsize",
    "wire_bytes",
    "cast_score_bytes",
    "wire_rtol",
]

# canonical name -> numpy-compatible dtype constructor.  bf16 keeps the
# f32 exponent range (safe default for spectra spanning decades); f16
# carries 3 more mantissa bits but overflows beyond ~65504.
WIRE_DTYPES = ("bf16", "f16")

# machine epsilon of each wire format (2^-mantissa_bits): the per-element
# relative quantization error of one downcast is at most eps/2 (round to
# nearest even), and the guard's content-sum tolerance scales it.
_WIRE_EPS = {"bf16": 2.0 ** -8, "f16": 2.0 ** -11}

# Casts are HBM traffic, not ICI traffic: pack reads full + writes wire,
# unpack reads wire + writes full, and HBM bandwidth is roughly an order
# of magnitude above ICI on current TPUs — so the router's
# bytes-equivalent score discounts cast bytes by this factor (they must
# count, or a zero-cost cast would make the wire strictly free, but they
# must not be allowed to outweigh the ICI bytes they eliminate).
CAST_BYTES_WEIGHT = 0.125


def canonical_wire_dtype(wire_dtype) -> Optional[str]:
    """Normalize a ``wire_dtype`` spelling to ``"bf16"``/``"f16"``/
    ``None``.  Accepts the canonical strings, ``"bfloat16"``/
    ``"float16"``, and jnp/np dtype objects; anything else is a typed
    ``ValueError`` (an unsupported wire format must fail at
    construction, not dispatch)."""
    if wire_dtype is None:
        return None
    if isinstance(wire_dtype, str):
        name = wire_dtype.strip().lower()
    else:
        name = np.dtype(wire_dtype).name  # jnp.bfloat16 has an np dtype
    name = {"bfloat16": "bf16", "float16": "f16", "half": "f16"}.get(
        name, name)
    if name not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be None, 'bf16' or 'f16', got "
            f"{wire_dtype!r}")
    return name


def _jnp_wire(wire: str):
    import jax.numpy as jnp

    return jnp.bfloat16 if wire == "bf16" else jnp.float16


def pack(x, wire_dtype: str):
    """Cast one exchange payload down to its wire format (traced).

    Real inexact payloads cast elementwise; complex payloads split into
    re/im along a NEW trailing axis (split-complex packing) so each
    component downcasts real→real.  Exact dtypes (ints/bool) have no
    lossless narrow wire form and raise — the caller opted into a
    float wire for float data, not into corrupting indices.

    The payload ships as the wire format's raw 16-BIT PATTERN
    (``bitcast_convert_type`` to ``uint16`` — a free reinterpret, no
    value change): backends without native bf16 collective support
    (XLA:CPU — the virtual test mesh) would otherwise WIDEN a bf16
    collective back to f32 through the float-normalization pass,
    silently unhalving the wire, while an integer collective moves
    exactly 2 bytes per component on every backend.  :func:`unpack`
    bitcasts back before the restoring upcast."""
    import jax
    import jax.numpy as jnp

    wt = _jnp_wire(wire_dtype)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        parts = jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)
        return jax.lax.bitcast_convert_type(jnp.asarray(parts, wt),
                                            jnp.uint16)
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        raise TypeError(
            f"wire_dtype={wire_dtype!r} needs an inexact payload dtype; "
            f"got {x.dtype} (exact dtypes have no lossy wire form)")
    return jax.lax.bitcast_convert_type(jnp.asarray(x, wt), jnp.uint16)


def unpack(y, orig_dtype, wire_dtype: str):
    """Restore a packed payload to its original dtype (traced): the
    exact inverse of :func:`pack`'s bitcast + shape change — values
    carry the wire format's quantization, which the guard's tolerance
    model prices (:func:`wire_rtol`)."""
    import jax
    import jax.numpy as jnp

    orig = jnp.dtype(orig_dtype)
    w = jax.lax.bitcast_convert_type(y, _jnp_wire(wire_dtype))
    if jnp.issubdtype(orig, jnp.complexfloating):
        # host-side dtype math only (c64 -> f32, c128 -> f64)
        real_dt = np.empty(0, np.dtype(orig)).real.dtype
        parts = jnp.asarray(w, real_dt)
        return jnp.asarray(
            jax.lax.complex(parts[..., 0], parts[..., 1]), orig)
    return jnp.asarray(w, orig)


def wire_itemsize(dtype, wire_dtype) -> int:
    """Per-element wire bytes of one exchanged payload element: the
    dtype's own itemsize at full precision, 2 bytes per real component
    on a bf16/f16 wire (so c64/c128 split-complex packs carry 4)."""
    dt = np.dtype(dtype if dtype is not None else np.float32)
    if wire_dtype is None:
        return dt.itemsize
    canonical_wire_dtype(wire_dtype)  # validate spelling
    if dt.kind not in "fc":
        raise TypeError(
            f"wire_dtype={wire_dtype!r} needs an inexact payload dtype; "
            f"got {dt} (exact dtypes have no lossy wire form)")
    return 4 if dt.kind == "c" else 2


def wire_bytes(dtype, wire_dtype, shape: Sequence[int]) -> int:
    """Wire bytes of one exchanged operand of logical ``shape`` — the
    ONE byte-accounting definition ``transpose_cost``,
    ``collective_costs`` and ``routing.py`` share (they must never
    re-derive ``itemsize`` independently)."""
    elems = 1
    for n in shape:
        elems *= int(n)
    return elems * wire_itemsize(dtype, wire_dtype)


def cast_score_bytes(wire_nbytes: int, dtype, wire_dtype) -> int:
    """Bytes-equivalent toll of one hop's pack+unpack casts, for the
    planners' scoring currency (``routing._score`` and the FFT
    planner's ``_schedule_score``): each element is read full + written
    wire (pack) and read wire + written full (unpack), discounted by
    :data:`CAST_BYTES_WEIGHT` because the traffic is HBM, not ICI.
    Zero with the wire off."""
    if wire_dtype is None or wire_nbytes <= 0:
        return 0
    w = wire_itemsize(dtype, wire_dtype)
    full = np.dtype(dtype if dtype is not None else np.float32).itemsize
    elems = wire_nbytes // max(1, w)
    return int(2 * elems * (full + w) * CAST_BYTES_WEIGHT)


def wire_rtol(wire_dtype, count: int) -> float:
    """Relative tolerance of the guard's content-sum compare across one
    wire round trip: per-element quantization is bounded by half the
    wire format's epsilon, and the probe compares SUMS of ``count``
    elements whose errors accumulate against the abs-sum scale — so the
    bound is ``eps/2`` (worst case all same-signed) with a small
    reduction-depth safety margin, NOT ``eps * count`` (the errors are
    already measured against ``abs_sum``, which scales with count).
    Override: ``PENCILARRAYS_TPU_GUARD_WIRE_RTOL`` (see
    ``engine/config.py``)."""
    if wire_dtype is None:
        return 0.0
    from ..engine import config as _rtc

    override = _rtc.current().guard_wire_rtol
    if override is not None:
        return override
    eps = _WIRE_EPS[canonical_wire_dtype(wire_dtype)]
    return 0.5 * eps * (1.0 + 0.25 * math.log2(max(2, count)))
