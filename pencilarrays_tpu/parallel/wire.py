"""Reduced-precision wire format — the sanctioned pack/unpack choke point.

Every transpose hop in the stack is bandwidth-bound by the validated
byte model (AccFFT, arXiv:1506.07933: redistribution time is wire bytes
over bisection bandwidth), so the cheapest bytes are the ones never
sent.  This module owns the OPT-IN reduced-precision exchange payload
(``wire_dtype="bf16" | "f16"`` on the explicit transpose methods and on
``PencilFFTPlan``): shards are cast-packed immediately before the
collective and restored immediately after, *inside the same traced
program*, so XLA fuses the casts into the exchange boundaries and the
collective itself moves half the bytes.  Accumulation and transform
math stay in full precision — only the wire narrows.

Three contracts, all enforced here so no caller can drift:

* **packing** — :func:`pack` / :func:`unpack` are the ONLY functions
  allowed to change an exchange payload's element type (``pa-lint``'s
  ``wire-cast`` check forbids direct ``.astype(`` in the
  exchange-program modules).  Real payloads cast elementwise; complex
  payloads (c64/c128) use SPLIT-COMPLEX packing — re/im stacked along a
  new trailing axis — so each component downcasts through a clean
  real→real cast instead of a complex cast (which XLA would reject or
  round through an intermediate).  The trailing axis rides the exchange
  like an extra dim: the split/concat dims' indices are untouched, so
  the same pack serves ``AllToAll``, ``Ring`` tiles and every
  ``Pipelined`` chunk;
* **byte accounting** — :func:`wire_itemsize` / :func:`wire_bytes` are
  the ONE definition of per-element wire cost shared by
  ``transpose_cost``, ``PencilFFTPlan.collective_costs`` and the route
  planner's peak-HBM bound (they used to each re-derive ``itemsize``).
  bf16/f16 carry 2 bytes per real component, so f32/c64 payloads halve
  and f64/c128 quarter — and the compiled HLO's collective shapes
  really are ``bf16[...]``, so the HLO-pinned prediction==measurement
  equality holds with the wire on;
* **tolerance model** — :func:`wire_rtol` is the per-dtype quantization
  error bound the guard's content-sum probes compare against
  (``guard/integrity.py``): a restored payload may differ from its
  source by at most ~half a wire-dtype ULP per element.  Exceedance is
  a typed :class:`~pencilarrays_tpu.guard.errors.WirePrecisionError`,
  never a silent wrong answer.  Override:
  ``PENCILARRAYS_TPU_GUARD_WIRE_RTOL``.

PR 19 finishes the precision ladder with the fp8 formats
(``wire_dtype="fp8_e4m3" | "fp8_e5m2"``, ÷4 bytes on f32/c64 payloads)
using PER-TILE SCALING: fp8 has 3-4 significand bits and a few hundred
representable magnitudes, so a raw elementwise cast would flush or
saturate any payload whose dynamic range spans more than the format —
instead :func:`pack` tiles the shard along its largest FREE axis (one
not being split or concatenated by the exchange —
:func:`fp8_tile_axis`), computes a finite-masked max-abs per
:data:`FP8_TILE`-element window inside the same traced program, maps
each window onto the format's full range (``amax -> FP8_FMAX``),
quantizes, and ships the u8 BIT PATTERN with the f32 scale tensor
riding the SAME collective as a tiny side payload: the scales are
bitcast to u8 and concatenated onto the payload along the tile axis,
so one exchange moves both and no backend can widen either.  Because
the tile axis is untouched by the exchange (``AllToAll`` splits ``b``
/ concats ``a``; ``Ring`` slices ``b`` and merges into ``a``), every
payload slice travels WITH its scales and :func:`unpack` re-derives
the tile geometry from the pre-pack shape alone.  e4m3 is the
finite-only ``fn`` variant (max 448, NO inf — overflow and inf both
land on NaN, still nonfinite, so the guard's finite-tap census is
preserved); e5m2 trades two significand bits for f16's exponent range
(max 57344, keeps inf).  ``wire_bytes`` prices the scale overhead
exactly (``+4`` bytes per tile along the tile axis), so the HLO-pinned
prediction==measurement equality holds for fp8 too.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "WIRE_DTYPES",
    "FP8_WIRE_DTYPES",
    "FP8_TILE",
    "canonical_wire_dtype",
    "fp8_tile_axis",
    "pack",
    "unpack",
    "wire_itemsize",
    "wire_bytes",
    "cast_score_bytes",
    "wire_rtol",
]

# canonical name -> numpy-compatible dtype constructor.  bf16 keeps the
# f32 exponent range (safe default for spectra spanning decades); f16
# carries 3 more mantissa bits but overflows beyond ~65504.  The fp8
# pair quarters the wire instead of halving it: e4m3 carries 3
# significand bits over a finite-only ±448 range (per-tile scaling
# supplies the dynamic range the format lacks), e5m2 keeps two fewer
# bits but f16's exponent span — pick e5m2 only when single tiles
# legitimately span >2^8 of dynamic range.
WIRE_DTYPES = ("bf16", "f16", "fp8_e4m3", "fp8_e5m2")
FP8_WIRE_DTYPES = ("fp8_e4m3", "fp8_e5m2")

# machine epsilon of each wire format (2^-mantissa_bits): the per-element
# relative quantization error of one downcast is at most eps/2 (round to
# nearest even), and the guard's content-sum tolerance scales it.
_WIRE_EPS = {"bf16": 2.0 ** -8, "f16": 2.0 ** -11,
             "fp8_e4m3": 2.0 ** -3, "fp8_e5m2": 2.0 ** -2}

# fp8 format constants, hardcoded rather than derived: np.finfo rejects
# the ml_dtypes extension classes on this container's numpy, and the
# values are fixed by the OCP FP8 spec (e4m3fn: 1-4-3, max finite
# 0b0.1111.110 = 448, no inf; e5m2: 1-5-2, max finite 57344).
_FP8_FMAX = {"fp8_e4m3": 448.0, "fp8_e5m2": 57344.0}
# smallest positive subnormal (2^(1-bias-mantissa)): values below
# ~scale*sub/2 flush to zero on the wire — priced by wire_rtol's
# scale-granularity term.
_FP8_SUB = {"fp8_e4m3": 2.0 ** -9, "fp8_e5m2": 2.0 ** -16}

# per-tile scaling window (elements along the tile axis sharing one f32
# scale).  256 keeps the side payload at 4/256 = 1.6% of the wire while
# staying tight enough that one outlier only costs its own window's
# resolution.
FP8_TILE = 256

# Casts are HBM traffic, not ICI traffic: pack reads full + writes wire,
# unpack reads wire + writes full, and HBM bandwidth is roughly an order
# of magnitude above ICI on current TPUs — so the router's
# bytes-equivalent score discounts cast bytes by this factor (they must
# count, or a zero-cost cast would make the wire strictly free, but they
# must not be allowed to outweigh the ICI bytes they eliminate).
CAST_BYTES_WEIGHT = 0.125


_WIRE_ALIASES = {
    "bfloat16": "bf16", "float16": "f16", "half": "f16",
    "e4m3": "fp8_e4m3", "float8_e4m3": "fp8_e4m3",
    "float8_e4m3fn": "fp8_e4m3", "fp8-e4m3": "fp8_e4m3",
    "e5m2": "fp8_e5m2", "float8_e5m2": "fp8_e5m2",
    "fp8-e5m2": "fp8_e5m2",
}


def canonical_wire_dtype(wire_dtype) -> Optional[str]:
    """Normalize a ``wire_dtype`` spelling to one of
    :data:`WIRE_DTYPES` or ``None``.  Accepts the canonical strings,
    ``"bfloat16"``/``"float16"``, the fp8 spellings
    (``"e4m3"``/``"float8_e4m3fn"``/...), and jnp/np dtype objects;
    anything else is a typed ``ValueError`` (an unsupported wire format
    must fail at construction, not dispatch).  An fp8 spelling also
    resolves the element type eagerly
    (:func:`~pencilarrays_tpu.utils.jaxcompat.wire_fp8_dtype`), so a
    jax build without fp8 fails HERE with a typed ``WireDtypeError``
    naming the missing class."""
    if wire_dtype is None:
        return None
    if isinstance(wire_dtype, str):
        name = wire_dtype.strip().lower()
    else:
        name = np.dtype(wire_dtype).name  # jnp.bfloat16 has an np dtype
    name = _WIRE_ALIASES.get(name, name)
    if name not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be None or one of {WIRE_DTYPES}, got "
            f"{wire_dtype!r}")
    if name in FP8_WIRE_DTYPES:
        from ..utils.jaxcompat import wire_fp8_dtype

        wire_fp8_dtype(name)  # fail at construction if the build lacks it
    return name


def _jnp_wire(wire: str):
    import jax.numpy as jnp

    if wire in FP8_WIRE_DTYPES:
        from ..utils.jaxcompat import wire_fp8_dtype

        return wire_fp8_dtype(wire)
    return jnp.bfloat16 if wire == "bf16" else jnp.float16


def fp8_tile_axis(shape: Sequence[int], a: int, b: int) -> int:
    """THE tile-axis rule pack, unpack and ``wire_bytes`` share: the
    largest-extent axis of the pre-pack payload shape that is NOT one
    of the exchange axes (``a`` = concat dim, ``b`` = split dim), ties
    to the lowest index.  The exchange leaves this axis untouched on
    every method (AllToAll tiles over ``b``/``a``; Ring slices ``b``
    and merges into ``a``), so the scale windows laid along it travel
    intact with their payload elements and the receiver can re-derive
    the tile geometry from the pre-pack shape alone.  A payload with no
    free axis (pure 2-D ``(a, b)`` operand) cannot carry per-tile
    scales and raises — the planner must fall back to a 16-bit wire."""
    best, best_n = -1, -1
    for i, n in enumerate(shape):
        if i == a or i == b:
            continue
        if int(n) > best_n:
            best, best_n = i, int(n)
    if best < 0:
        raise ValueError(
            f"fp8 wire needs a tile axis outside the exchange axes "
            f"(a={a}, b={b}), but shape {tuple(shape)} has no free "
            f"axis — use a 16-bit wire for 2-D exchange operands")
    return best


def _fp8_geometry(shape: Sequence[int], a: int, b: int) -> Tuple[int, int, int]:
    """(tile_axis, n_t, ntiles) of one pre-pack payload shape."""
    t = fp8_tile_axis(shape, a, b)
    n_t = int(shape[t])
    return t, n_t, -(-n_t // FP8_TILE)


def _split_complex(x):
    """(parts, was_complex): re/im stacked along a NEW trailing axis
    for complex payloads, the payload itself otherwise.  Exact dtypes
    raise — the caller opted into a float wire for float data, not
    into corrupting indices."""
    import jax.numpy as jnp

    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1), True
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        raise TypeError(
            f"a reduced-precision wire needs an inexact payload dtype; "
            f"got {x.dtype} (exact dtypes have no lossy wire form)")
    return x, False


def _fp8_pack(x, wire: str, a: int, b: int):
    """Per-tile-scaled fp8 quantization (traced) — see module doc.

    The finite mask does double duty: nonfinite taps are excluded from
    the max-abs (one Inf must not zero out its whole window) AND pass
    through the quantizer unclipped, so Inf/NaN arrive nonfinite on
    the far side (under e4m3fn, Inf converts to NaN — still nonfinite,
    so the guard's finite-tap census is preserved; NaN==NaN passes the
    content compare).  All-zero (or all-nonfinite) windows take
    scale=1 so zero stays exactly zero.  The clip guards the one-ULP
    f32 rounding edge where ``amax/scale`` lands a hair above FMAX
    and would otherwise overflow the finite-only e4m3."""
    import jax
    import jax.numpy as jnp

    fdt = _jnp_wire(wire)
    fmax = _FP8_FMAX[wire]
    parts, _ = _split_complex(x)
    t, n_t, ntiles = _fp8_geometry(x.shape, a, b)

    finite = jnp.isfinite(parts)
    absx = jnp.where(finite, jnp.abs(parts), 0)
    pad = ntiles * FP8_TILE - n_t
    if pad:
        widths = [(0, 0)] * parts.ndim
        widths[t] = (0, pad)
        absx = jnp.pad(absx, widths)  # zeros never win a max-abs
    tiled = absx.reshape(
        parts.shape[:t] + (ntiles, FP8_TILE) + parts.shape[t + 1:])
    amax = tiled.max(axis=t + 1)
    scale = jnp.where(amax > 0, amax / fmax, 1).astype(jnp.float32)
    # per-element scale: repeat each window's scale and trim the tail
    # (cheaper than padding the payload itself through the quantizer)
    per = jax.lax.slice_in_dim(
        jnp.repeat(scale.astype(parts.dtype), FP8_TILE, axis=t),
        0, n_t, axis=t)
    scaled = parts / per
    q = jnp.where(finite, jnp.clip(scaled, -fmax, fmax),
                  scaled).astype(fdt)
    payload = jax.lax.bitcast_convert_type(q, jnp.uint8)
    su8 = jax.lax.bitcast_convert_type(scale, jnp.uint8)  # +trailing (4,)
    su8 = jnp.moveaxis(su8, -1, t + 1).reshape(
        scale.shape[:t] + (4 * ntiles,) + scale.shape[t + 1:])
    # ONE u8 array, ONE collective: scales ride the exchange folded
    # onto the tile axis — untouched by split/concat, so each payload
    # slice travels with exactly its own windows' scales.
    return jnp.concatenate([payload, su8], axis=t)


def _fp8_unpack(y, orig_dtype, wire: str, a: int, b: int,
                orig_shape: Sequence[int]):
    """Inverse of :func:`_fp8_pack`: re-derive the tile geometry from
    the PRE-PACK shape (the tile axis and its extent survive every
    exchange), split payload from scales, reverse both bitcasts, and
    rescale.  Non-tile extents are read off the received array — the
    exchange has resized ``a``/``b`` by then."""
    import jax
    import jax.numpy as jnp

    orig = jnp.dtype(orig_dtype)
    is_c = jnp.issubdtype(orig, jnp.complexfloating)
    # host-side dtype math only (c64 -> f32, c128 -> f64)
    real_dt = np.empty(0, np.dtype(orig)).real.dtype if is_c else orig
    t, n_t, ntiles = _fp8_geometry(orig_shape, a, b)

    payload = jax.lax.slice_in_dim(y, 0, n_t, axis=t)
    su8 = jax.lax.slice_in_dim(y, n_t, n_t + 4 * ntiles, axis=t)
    su8 = jnp.moveaxis(
        su8.reshape(su8.shape[:t] + (ntiles, 4) + su8.shape[t + 1:]),
        t + 1, -1)
    scale = jax.lax.bitcast_convert_type(su8, jnp.float32)
    per = jax.lax.slice_in_dim(
        jnp.repeat(scale, FP8_TILE, axis=t), 0, n_t, axis=t)
    vals = jax.lax.bitcast_convert_type(payload, _jnp_wire(wire))
    parts = jnp.asarray(vals, real_dt) * jnp.asarray(per, real_dt)
    if is_c:
        return jnp.asarray(
            jax.lax.complex(parts[..., 0], parts[..., 1]), orig)
    return jnp.asarray(parts, orig)


def pack(x, wire_dtype: str, *, axes: Optional[Tuple[int, int]] = None):
    """Cast one exchange payload down to its wire format (traced).

    Real inexact payloads cast elementwise; complex payloads split into
    re/im along a NEW trailing axis (split-complex packing) so each
    component downcasts real→real.  Exact dtypes (ints/bool) have no
    lossless narrow wire form and raise — the caller opted into a
    float wire for float data, not into corrupting indices.

    The payload ships as the wire format's raw BIT PATTERN
    (``bitcast_convert_type`` to ``uint16``/``uint8`` — a free
    reinterpret, no value change): backends without native bf16/fp8
    collective support (XLA:CPU — the virtual test mesh) would
    otherwise WIDEN the collective back to f32 through the
    float-normalization pass, silently un-narrowing the wire, while an
    integer collective moves exactly the wire bytes on every backend.
    :func:`unpack` bitcasts back before the restoring upcast.

    The fp8 formats additionally need ``axes=(a, b)`` — the exchange's
    concat/split dims — to lay their per-tile scale windows along an
    axis the exchange will not touch (:func:`fp8_tile_axis`)."""
    import jax
    import jax.numpy as jnp

    wire = canonical_wire_dtype(wire_dtype)
    if wire in FP8_WIRE_DTYPES:
        if axes is None:
            raise ValueError(
                f"wire_dtype={wire!r} needs axes=(a, b) to derive its "
                f"tile axis — fp8 pack is exchange-geometry aware")
        return _fp8_pack(x, wire, int(axes[0]), int(axes[1]))
    parts, _ = _split_complex(x)
    return jax.lax.bitcast_convert_type(
        jnp.asarray(parts, _jnp_wire(wire)), jnp.uint16)


def unpack(y, orig_dtype, wire_dtype: str, *,
           axes: Optional[Tuple[int, int]] = None,
           orig_shape: Optional[Sequence[int]] = None):
    """Restore a packed payload to its original dtype (traced): the
    exact inverse of :func:`pack`'s bitcast + shape change — values
    carry the wire format's quantization, which the guard's tolerance
    model prices (:func:`wire_rtol`).  The fp8 formats need the SAME
    ``axes`` pack used plus the pre-pack ``orig_shape`` to re-derive
    the tile geometry (both survive the exchange by construction)."""
    import jax
    import jax.numpy as jnp

    wire = canonical_wire_dtype(wire_dtype)
    if wire in FP8_WIRE_DTYPES:
        if axes is None or orig_shape is None:
            raise ValueError(
                f"wire_dtype={wire!r} unpack needs axes=(a, b) and the "
                f"pre-pack orig_shape to re-derive its tile geometry")
        return _fp8_unpack(y, orig_dtype, wire, int(axes[0]),
                           int(axes[1]), orig_shape)
    orig = jnp.dtype(orig_dtype)
    w = jax.lax.bitcast_convert_type(y, _jnp_wire(wire))
    if jnp.issubdtype(orig, jnp.complexfloating):
        # host-side dtype math only (c64 -> f32, c128 -> f64)
        real_dt = np.empty(0, np.dtype(orig)).real.dtype
        parts = jnp.asarray(w, real_dt)
        return jnp.asarray(
            jax.lax.complex(parts[..., 0], parts[..., 1]), orig)
    return jnp.asarray(w, orig)


def wire_itemsize(dtype, wire_dtype) -> int:
    """PAYLOAD wire bytes per exchanged logical element: the dtype's
    own itemsize at full precision, 2 bytes per real component on a
    bf16/f16 wire (so c64/c128 split-complex packs carry 4), 1 byte
    per real component on an fp8 wire (2 for complex).  fp8 totals
    additionally carry the per-tile scale side payload —
    :func:`wire_bytes` is the authoritative total; this is only the
    per-element factor."""
    dt = np.dtype(dtype if dtype is not None else np.float32)
    if wire_dtype is None:
        return dt.itemsize
    wire = canonical_wire_dtype(wire_dtype)  # validate spelling
    if dt.kind not in "fc":
        raise TypeError(
            f"wire_dtype={wire_dtype!r} needs an inexact payload dtype; "
            f"got {dt} (exact dtypes have no lossy wire form)")
    per = 1 if wire in FP8_WIRE_DTYPES else 2
    return 2 * per if dt.kind == "c" else per


def wire_bytes(dtype, wire_dtype, shape: Sequence[int], *,
               axes: Optional[Tuple[int, int]] = None) -> int:
    """Wire bytes of one exchanged operand of logical ``shape`` — the
    ONE byte-accounting definition ``transpose_cost``,
    ``collective_costs`` and ``routing.py`` share (they must never
    re-derive ``itemsize`` independently).

    On an fp8 wire the total is EXACT, scale side payload included:
    the packed operand's tile axis carries ``n_t + 4*ceil(n_t/TILE)``
    bytes per component per row (payload + f32 scales), so callers
    must pass the exchange ``axes=(a, b)`` — the same geometry
    :func:`pack` uses — or the accounting could not know which axis
    the windows lie along."""
    elems = 1
    for n in shape:
        elems *= int(n)
    w = wire_itemsize(dtype, wire_dtype)
    wire = canonical_wire_dtype(wire_dtype)
    if wire not in FP8_WIRE_DTYPES:
        return elems * w
    if axes is None:
        raise ValueError(
            f"wire_bytes on wire_dtype={wire!r} needs the exchange "
            f"axes=(a, b) to derive the tile axis — fp8 byte "
            f"accounting is exchange-geometry aware")
    t, n_t, ntiles = _fp8_geometry(shape, int(axes[0]), int(axes[1]))
    rows = elems // max(1, n_t)  # product of every non-tile extent
    return rows * (n_t + 4 * ntiles) * w


def cast_score_bytes(wire_nbytes: int, dtype, wire_dtype) -> int:
    """Bytes-equivalent toll of one hop's pack+unpack casts, for the
    planners' scoring currency (``routing._score`` and the FFT
    planner's ``_schedule_score``): each element is read full + written
    wire (pack) and read wire + written full (unpack), discounted by
    :data:`CAST_BYTES_WEIGHT` because the traffic is HBM, not ICI.
    Zero with the wire off."""
    if wire_dtype is None or wire_nbytes <= 0:
        return 0
    w = wire_itemsize(dtype, wire_dtype)
    full = np.dtype(dtype if dtype is not None else np.float32).itemsize
    # on an fp8 wire this slightly overcounts elements (the scale side
    # payload is ~1.6% of wire_nbytes) — acceptable for a weighted
    # score term; wire_bytes stays the exact accounting.
    elems = wire_nbytes // max(1, w)
    return int(2 * elems * (full + w) * CAST_BYTES_WEIGHT)


def wire_rtol(wire_dtype, count: int) -> float:
    """Relative tolerance of the guard's content-sum compare across one
    wire round trip: per-element quantization is bounded by half the
    wire format's epsilon, and the probe compares SUMS of ``count``
    elements whose errors accumulate against the abs-sum scale — so the
    bound is ``eps/2`` (worst case all same-signed) with a small
    reduction-depth safety margin, NOT ``eps * count`` (the errors are
    already measured against ``abs_sum``, which scales with count).
    The fp8 formats add a SCALE-GRANULARITY term: per-tile scaling
    fixes each window's absolute quantization grid at
    ``amax * sub / FMAX`` (the scaled subnormal spacing), so elements
    far below their window's max-abs flush toward zero with an
    absolute error the eps model does not see.  Worst case the window
    sum carries ``TILE`` such flushes against an abs-sum of order
    ``amax``, bounding the extra relative error by
    ``TILE * sub / (2 * FMAX)`` — e4m3 @ TILE=256 adds ~5.6e-4 on top
    of its eps/2 = 6.25e-2.  Override:
    ``PENCILARRAYS_TPU_GUARD_WIRE_RTOL`` (see ``engine/config.py``)."""
    if wire_dtype is None:
        return 0.0
    from ..engine import config as _rtc

    override = _rtc.current().guard_wire_rtol
    if override is not None:
        return override
    wire = canonical_wire_dtype(wire_dtype)
    base = 0.5 * _WIRE_EPS[wire]
    if wire in FP8_WIRE_DTYPES:
        base += FP8_TILE * _FP8_SUB[wire] / (2.0 * _FP8_FMAX[wire])
    return base * (1.0 + 0.25 * math.log2(max(2, count)))
