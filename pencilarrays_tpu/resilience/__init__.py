"""Resilience subsystem: crash-safe checkpoints, fault injection,
retry/backoff.

Three cooperating pieces (see ``docs/Resilience.md``):

* :class:`CheckpointManager` — atomic, checksummed, GC'd checkpoints
  layered over the I/O drivers (``checkpoint.py``);
* :mod:`~pencilarrays_tpu.resilience.faults` — deterministic named
  injection points consulted by the drivers and the distributed
  runtime (``faults.py``);
* :class:`RetryPolicy` — exponential backoff + jitter + deadline for
  every cross-process rendezvous (``retry.py``).

``checkpoint`` is imported lazily: the drivers and
``parallel/distributed.py`` import this package for its errors/faults/
retry pieces at module load, before ``pencilarrays_tpu.io`` exists.
"""

from .errors import (  # noqa: F401
    CheckpointNotFoundError,
    CorruptCheckpointError,
    CorruptSidecarError,
    InjectedFault,
    ResilienceError,
    RetryDeadlineExceeded,
)
from . import faults  # noqa: F401
from .retry import RetryPolicy, is_transient  # noqa: F401

__all__ = [
    "CheckpointManager",
    "Checkpoint",
    "CheckpointNotFoundError",
    "CorruptCheckpointError",
    "CorruptSidecarError",
    "InjectedFault",
    "ResilienceError",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "is_transient",
    "faults",
]

_LAZY = ("CheckpointManager", "Checkpoint")


def __getattr__(name):
    if name in _LAZY:
        from . import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
