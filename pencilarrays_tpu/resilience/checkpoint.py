"""Crash-safe checksummed checkpoints layered over the I/O drivers.

A production TPU job's dominant failure mode is *interruption*: a
preempted pod slice, a worker SIGKILLed mid-checkpoint, a filesystem
throwing transient errors.  :class:`CheckpointManager` makes the
checkpoint-restart story trustworthy under exactly those failures:

* **Atomic commit** — each checkpoint is written into a temp directory
  (``.tmp-step-N``); only after every process's data, the per-block
  checksum manifest and their fsyncs land is the directory renamed to
  its final name and a ``COMMIT`` marker atomically published via
  ``os.replace``.  A crash at ANY earlier point leaves only garbage
  that :meth:`latest_valid` skips — never a half-checkpoint that parses.
* **End-to-end verification** — per-block CRC32C checksums are computed
  during the drivers' own ``iter_local_blocks`` streaming (the
  ``block_observer`` hook: no extra host copy of the array) and recorded
  in ``MANIFEST.json`` keyed by each block's logical-order global
  corner, so a reader under ANY process count or decomposition re-reads
  exactly those ranges and verifies them.  A mismatch raises
  :class:`CorruptCheckpointError` naming the dataset and block.
* **Retention GC** — ``keep=N`` bounds disk: after each successful
  commit the oldest committed checkpoints beyond N, stale temp
  directories and torn uncommitted directories are removed.

Layout of one checkpoint::

    <directory>/step-00000012/
        data.bin  data.bin.json    # (driver-dependent) the datasets
        MANIFEST.json              # per-dataset block checksums
        COMMIT                     # atomic commit marker (last to appear)

The manager is multi-process aware: data writes go through the drivers'
existing collective protocols, per-process block checksums are merged by
process 0 (``blocks.r<p>.json`` scratch files), and every commit step is
ordered by the same cross-host barriers the drivers use.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from . import faults
from . import checksum
from .checksum import ALGO, BlockChecksums, crc_of_array
from .errors import (CheckpointNotFoundError, CorruptCheckpointError,
                     ResilienceError)
from .fsutil import atomic_write_json as _atomic_write_json
from .fsutil import atomic_write_text, fsync_dir as _fsync_dir
from .retry import RetryPolicy, logger

__all__ = ["CheckpointManager", "Checkpoint"]

MANIFEST_NAME = "MANIFEST.json"
COMMIT_NAME = "COMMIT"
MANIFEST_VERSION = "1.0"

_STEP_RE = re.compile(r"^step-(\d{8,})$")


def _data_filename(driver) -> str:
    """The datasets' container name inside a checkpoint directory."""
    name = type(driver).__name__
    return {"BinaryDriver": "data.bin", "HDF5Driver": "data.h5",
            "OrbaxDriver": "data"}.get(name, "data.bin")


def _supports_checksums(driver) -> bool:
    """Checksums need the logical-order ``block_observer`` streaming hook
    (binary discontiguous + HDF5); the Orbax driver stores padded device
    arrays through TensorStore, which carries its own integrity story."""
    return type(driver).__name__ in ("BinaryDriver", "HDF5Driver")


class CheckpointManager:
    """Save/restore/latest/retention-GC over a checkpoint directory.

    Parameters
    ----------
    directory:
        Root holding one ``step-N`` subdirectory per checkpoint.
    driver:
        Any :class:`~pencilarrays_tpu.io.core.ParallelIODriver`
        (default :class:`~pencilarrays_tpu.io.BinaryDriver`).
    keep:
        Retain at most this many committed checkpoints (None: keep all).
    checksums:
        Record + verify per-block CRCs (default True; requires a driver
        with the ``block_observer`` hook).
    retry:
        :class:`RetryPolicy` for the driver opens and metadata flushes
        (default: :meth:`RetryPolicy.from_env`).
    """

    def __init__(self, directory: str, driver=None, *,
                 keep: Optional[int] = None, checksums: bool = True,
                 timer=None, retry: Optional[RetryPolicy] = None):
        from ..io import BinaryDriver

        self.directory = os.fspath(directory)
        self.driver = BinaryDriver() if driver is None else driver
        if checksums and not _supports_checksums(self.driver):
            raise ValueError(
                f"{type(self.driver).__name__} does not stream logical-order "
                f"blocks, so manifest checksums cannot be computed; pass "
                f"checksums=False (the driver's own storage integrity still "
                f"applies)")
        if keep is not None and keep < 1:
            raise ValueError("keep must be >= 1 (or None to keep all)")
        self.keep = keep
        self.checksums = checksums
        self.timer = timer
        self.retry = retry or RetryPolicy.from_env()
        self._data_name = _data_filename(self.driver)
        os.makedirs(self.directory, exist_ok=True)

    # -- paths / process helpers ------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{step:08d}")

    def _tmp_dir(self, step: int) -> str:
        return os.path.join(self.directory, f".tmp-step-{step:08d}")

    @staticmethod
    def _is_proc0() -> bool:
        from ..parallel.distributed import process_index

        return process_index() == 0

    @staticmethod
    def _barrier(name: str) -> None:
        from ..parallel.distributed import sync_global_devices

        sync_global_devices(name)

    def _scan(self) -> Dict[int, str]:
        """All final-named step directories (committed or torn)."""
        out = {}
        for entry in os.listdir(self.directory):
            m = _STEP_RE.match(entry)
            if m and os.path.isdir(os.path.join(self.directory, entry)):
                out[int(m.group(1))] = os.path.join(self.directory, entry)
        return out

    def is_committed(self, step: int) -> bool:
        return os.path.exists(os.path.join(self._step_dir(step), COMMIT_NAME))

    def steps(self) -> List[int]:
        """Committed steps, ascending (commit marker present; contents
        not yet verified — see :meth:`verify` / :meth:`latest_valid`)."""
        return sorted(s for s in self._scan() if self.is_committed(s))

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Mapping, *, chunks: bool = False) -> str:
        """Write ``state`` (dataset name -> PencilArray or tuple of
        same-pencil arrays) as checkpoint ``step``; returns the committed
        directory.  Crash-safe: until the final barrier the previous
        checkpoints are untouched and the new one is invisible."""
        from ..io import open_file
        from ..io.core import pack_collection
        from ..parallel.pencil import LogicalOrder
        from ..utils.timers import timeit

        step = int(step)
        if step < 0:
            raise ValueError("step must be >= 0")
        if not state:
            raise ValueError("cannot checkpoint an empty state")
        if chunks and self.checksums:
            raise ValueError(
                "chunks=True stores memory-order rank blocks, which the "
                "logical-order manifest checksums cannot describe; pass "
                "checksums=False to combine them")
        if chunks and type(self.driver).__name__ != "BinaryDriver":
            raise ValueError(
                "chunks=True is a BinaryDriver layout option; "
                f"{type(self.driver).__name__} does not accept it")
        tmp, final = self._tmp_dir(step), self._step_dir(step)
        from .. import obs

        t_save0 = None
        if obs.enabled():
            t_save0 = time.perf_counter()
            obs.counter("ckpt.saves").inc()
            obs.record_event("ckpt.save", step=step, status="begin",
                             dir=self.directory,
                             driver=type(self.driver).__name__,
                             datasets=sorted(state),
                             checksums=self.checksums)
        if self._is_proc0():
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
        self._barrier("pa_ckpt_tmp")

        timer = self.timer
        crcs = BlockChecksums() if self.checksums else None
        entries: Dict[str, dict] = {}
        with timeit(timer, "checkpoint save"):
            data_path = os.path.join(tmp, self._data_name)
            with open_file(self.driver, data_path, write=True, create=True,
                           truncate=True, retry=self.retry) as f:
                for name, x in state.items():
                    view, ncomp = pack_collection(x)
                    entries[name] = {
                        "dtype": np.dtype(view.dtype).name,
                        "dims_logical": list(
                            view.pencil.size_global(LogicalOrder)),
                        "extra_dims": list(view.extra_dims),
                        "collection": ncomp,
                        "size_bytes": view.sizeof_global(),
                        "blocks": None,
                    }
                    if crcs is not None:
                        f.write(name, x, block_observer=crcs.observer(name))
                    elif chunks:
                        f.write(name, x, chunks=True)
                    else:
                        f.write(name, x)

            from ..parallel.distributed import process_index

            if crcs is not None:
                _atomic_write_json(
                    os.path.join(tmp, f"blocks.r{process_index()}.json"),
                    crcs.as_dict())
            self._barrier("pa_ckpt_blocks")

            if self._is_proc0():
                if crcs is not None:
                    merged: Dict[str, list] = {n: [] for n in entries}
                    for fname in sorted(os.listdir(tmp)):
                        if not re.match(r"^blocks\.r\d+\.json$", fname):
                            continue
                        with open(os.path.join(tmp, fname)) as bf:
                            for n, blocks in json.load(bf).items():
                                merged.setdefault(n, []).extend(blocks)
                        os.unlink(os.path.join(tmp, fname))
                    for n, blocks in merged.items():
                        entries[n]["blocks"] = sorted(
                            blocks, key=lambda b: tuple(b["start"]))
                from ..cluster import epoch as _epoch

                manifest = {
                    "format": "pencilarrays-tpu-checkpoint",
                    "version": MANIFEST_VERSION,
                    "step": step,
                    # recovery-epoch stamp: lets a post-mortem align this
                    # checkpoint with the journals/bundles of the recovery
                    # generation that produced it (docs/Cluster.md)
                    "epoch": _epoch.current(),
                    "driver": type(self.driver).__name__,
                    "data_file": self._data_name,
                    "algo": ALGO if self.checksums else None,
                    "datasets": entries,
                }
                self.retry.call(_atomic_write_json,
                                os.path.join(tmp, MANIFEST_NAME), manifest,
                                label="flush checkpoint manifest",
                                timer=timer)
            # the crash-before-commit injection point: a kill here leaves
            # a fully-written but never-visible temp directory
            faults.fire("ckpt.commit", step=step)
            if self._is_proc0():
                if os.path.exists(final):
                    # re-saving an existing step: move the old directory
                    # aside (into the GC'd temp namespace) instead of
                    # deleting it — a crash before the new COMMIT must
                    # not have destroyed the only copy
                    os.rename(final, f"{tmp}-replaced")
                os.rename(tmp, final)
                _fsync_dir(self.directory)
                # the one atomic commit point: COMMIT appears via replace
                atomic_write_text(os.path.join(final, COMMIT_NAME),
                                  f"step {step}\n")
                obs.record_event("ckpt.commit", step=step, dir=final)
            self._barrier("pa_ckpt_commit")
            if self._is_proc0():
                self._gc(current=step)
            self._barrier("pa_ckpt_done")
        if t_save0 is not None:
            dt = time.perf_counter() - t_save0
            obs.histogram("ckpt.save_seconds").observe(dt)
            obs.record_event("ckpt.save", step=step, status="committed",
                             seconds=dt)
        return final

    def save_async(self, step: int, state: Mapping, *,
                   chunks: bool = False, engine=None):
        """Serialize checkpoint ``step`` on the engine's HOST pool
        (:meth:`~pencilarrays_tpu.engine.Engine.host_task`) —
        :meth:`save`, overlapped with whatever the ordered dispatch
        queue runs next (the PR-12 host/device overlap, applied to the
        save path natively instead of callers hand-rolling futures).
        Returns a :class:`~pencilarrays_tpu.engine.StepFuture`
        resolving to the committed directory; failures surface as
        typed errors on the future.

        The ``state`` mapping is snapshotted shallowly at submit (jax
        arrays are immutable, so the serialized values are a stable
        snapshot even while later steps compute).  Concurrent saves on
        ONE manager are the caller's to order — chain on the returned
        future, or drive the loop through
        :func:`~pencilarrays_tpu.engine.run_steps_async`, which chains
        saves for you.  Single-controller meshes only (the save path
        barriers internally; a host-pool save on a multi-controller
        rank would barrier off the main thread)."""
        from ..engine import get_engine

        eng = engine if engine is not None else get_engine()
        state = dict(state)
        return eng.host_task(
            lambda: self.save(step, state, chunks=chunks),
            label=f"ckpt.save:{step}")

    def _recover_replaced(self) -> None:
        """A re-save of step N moves the old committed directory to
        ``.tmp-step-N-replaced`` before the new COMMIT lands; if the
        re-save crashed in that window, the replacement is torn and the
        moved-aside directory is the ONLY committed copy — put it back
        before anything could sweep it.  Best-effort and race-tolerant:
        ``os.rename`` is atomic, so under multi-process one process wins
        and the others' failures are ignored."""
        for entry in os.listdir(self.directory):
            m = re.match(r"^\.tmp-step-(\d{8,})-replaced$", entry)
            if not m:
                continue
            step = int(m.group(1))
            src = os.path.join(self.directory, entry)
            final = self._step_dir(step)
            if self.is_committed(step) \
                    or not os.path.exists(os.path.join(src, COMMIT_NAME)):
                continue  # replacement committed (src is garbage) or
                # src itself never was a committed checkpoint
            try:
                if os.path.exists(final):
                    shutil.rmtree(final)  # torn replacement wreckage
                os.rename(src, final)
                logger.warning(
                    "recovered checkpoint step %d from an interrupted "
                    "re-save (%s)", step, entry)
            except OSError:
                pass

    def _gc(self, current: Optional[int] = None) -> None:
        """Retention: drop oldest committed checkpoints beyond ``keep``,
        stale temp/replaced directories, and torn (uncommitted) step
        directories.  Runs only after the current step's COMMIT landed,
        so everything left in the temp namespace is garbage by then."""
        self._recover_replaced()
        removed = []
        for entry in os.listdir(self.directory):
            if entry.startswith(".tmp-"):
                removed.append(entry)
                shutil.rmtree(os.path.join(self.directory, entry),
                              ignore_errors=True)
        committed, torn = [], []
        for step, path in sorted(self._scan().items()):
            (committed if self.is_committed(step) else torn).append(path)
        for path in torn:
            if path != (self._step_dir(current) if current is not None
                        else None):
                logger.warning("GC removing torn checkpoint %s", path)
                removed.append(os.path.basename(path))
                shutil.rmtree(path, ignore_errors=True)
        if self.keep is not None:
            for path in committed[:-self.keep]:
                removed.append(os.path.basename(path))
                shutil.rmtree(path, ignore_errors=True)
        if removed:
            from .. import obs

            if obs.enabled():
                obs.counter("ckpt.gc_removed").inc(len(removed))
                obs.record_event("ckpt.gc", removed=sorted(removed),
                                 dir=self.directory)

    # -- verify / discover -------------------------------------------------
    def _load_manifest(self, step: int) -> dict:
        path = os.path.join(self._step_dir(step), MANIFEST_NAME)
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step}: manifest missing ({path})",
                step=step, path=path) from e
        except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step}: manifest unreadable ({e})",
                step=step, path=path) from e

    def verify(self, step: int) -> None:
        """Validate checkpoint ``step`` end-to-end: COMMIT marker,
        manifest, dataset presence, and (when recorded) every block's
        checksum.  Raises :class:`CorruptCheckpointError` naming the
        first failing dataset/block."""
        from .. import obs

        try:
            if not self.is_committed(step):
                raise CorruptCheckpointError(
                    f"checkpoint step {step} has no COMMIT marker "
                    f"(missing or torn write)", step=step,
                    path=self._step_dir(step))
            manifest = self._load_manifest(step)
            for name, ds in manifest["datasets"].items():
                self._verify_dataset(step, manifest, name, ds)
        except ResilienceError as e:
            if obs.enabled():
                obs.counter("ckpt.verify_failures").inc()
                obs.record_event("ckpt.verify", step=step, ok=False,
                                 error=str(e))
            raise
        if obs.enabled():
            obs.record_event("ckpt.verify", step=step, ok=True)

    def _checksum_blocks(self, step: int, manifest: dict, name: str,
                         ds: dict) -> Optional[List[dict]]:
        """Manifest blocks eligible for CRC verification, or ``None``
        when checksums are absent or the writer's algorithm is
        unavailable here.  A checkpoint is verified with the WRITER's
        algorithm; when this host cannot compute it, degrade to
        structural checks rather than falsely failing (or falsely
        passing) CRCs."""
        blocks = ds.get("blocks")
        algo = manifest.get("algo")
        if blocks is not None and not checksum.supported(algo):
            logger.warning(
                "checkpoint step %d: checksum algorithm %r unavailable on "
                "this host — skipping CRC verification of dataset %r",
                step, algo, name)
            return None
        return blocks

    def _verify_dataset(self, step: int, manifest: dict, name: str,
                        ds: dict) -> None:
        shape = tuple(ds["dims_logical"]) + tuple(ds["extra_dims"])
        blocks = self._checksum_blocks(step, manifest, name, ds)
        data_path = os.path.join(self._step_dir(step),
                                 manifest.get("data_file", self._data_name))
        if blocks is not None:
            covered = sum(int(np.prod(b["shape"], dtype=np.int64))
                          for b in blocks)
            if covered != int(np.prod(shape, dtype=np.int64)):
                raise CorruptCheckpointError(
                    f"checkpoint step {step} dataset {name!r}: manifest "
                    f"blocks cover {covered} elements of "
                    f"{int(np.prod(shape, dtype=np.int64))}",
                    step=step, dataset=name, path=data_path)
        if blocks is None:
            # checksums off (or algorithm unavailable): presence/metadata
            # check only — must NOT assume the discontiguous block-reader
            # layout (chunks-layout and Orbax checkpoints land here)
            self._check_dataset_present(step, data_path, name)
            return
        self._verify_block_list(step, manifest, name, ds, blocks)

    def _verify_block_list(self, step: int, manifest: dict, name: str,
                           ds: dict, blocks: List[dict]) -> None:
        """Checksum-verify ``blocks`` (any subset of the manifest's
        block list) against the stored data."""
        algo = manifest.get("algo")
        data_path = os.path.join(self._step_dir(step),
                                 manifest.get("data_file", self._data_name))
        try:
            with self._open_block_reader(manifest, data_path, name,
                                         ds) as read_block:
                for i, b in enumerate(blocks):
                    start, bshape = tuple(b["start"]), tuple(b["shape"])
                    try:
                        got = crc_of_array(read_block(start, bshape), algo)
                    except (OSError, ValueError, IndexError) as e:
                        raise CorruptCheckpointError(
                            f"checkpoint step {step} dataset {name!r} "
                            f"block {i} (start={start}, shape={bshape}): "
                            f"unreadable ({type(e).__name__}: {e})",
                            step=step, dataset=name, block=i,
                            path=data_path) from e
                    if got != b["crc"]:
                        raise CorruptCheckpointError(
                            f"checkpoint step {step} dataset {name!r} "
                            f"block {i} (start={start}, shape={bshape}): "
                            f"checksum mismatch ({manifest['algo']} "
                            f"{got:#010x} != recorded {b['crc']:#010x}) — "
                            f"the data file is corrupt",
                            step=step, dataset=name, block=i,
                            path=data_path)
        except ResilienceError:
            raise
        except (OSError, ValueError, KeyError) as e:
            # opening the container / locating the dataset failed: a
            # truncated data file, an unloadable sidecar, a dataset the
            # (possibly corrupted) metadata no longer names
            raise CorruptCheckpointError(
                f"checkpoint step {step} dataset {name!r}: data unreadable "
                f"({type(e).__name__}: {e})",
                step=step, dataset=name, path=data_path) from e

    def _verify_dataset_local(self, step: int, manifest: dict, name: str,
                              ds: dict, pencil) -> None:
        """Cross-decomposition restore verification: map the WRITER's
        global-corner block manifest onto the READER pencil's local
        extents and checksum-verify exactly the intersecting blocks.

        The manifest keys blocks by logical-order global corner — a
        deliberately decomposition-independent address — so a reformed
        mesh (different process count, different decomposition, even
        ``world == 1``) can restore a checkpoint written under a
        topology that no longer exists, verifying only the bytes this
        process is about to trust instead of re-reading the whole
        global array on every rank.  Degrades exactly like
        :meth:`_verify_dataset` when checksums are absent or the
        writer's algorithm is unavailable here."""
        blocks = self._checksum_blocks(step, manifest, name, ds)
        if blocks is None:
            data_path = os.path.join(
                self._step_dir(step),
                manifest.get("data_file", self._data_name))
            self._check_dataset_present(step, data_path, name)
            return
        self._verify_block_list(step, manifest, name, ds,
                                self._local_blocks(pencil, ds, blocks))

    @staticmethod
    def _local_blocks(pencil, ds: dict, blocks: List[dict]) -> List[dict]:
        """The manifest blocks whose logical-order global extents
        intersect any block of ``pencil`` owned by THIS process (every
        block, on a single-process mesh)."""
        import jax

        from ..parallel.pencil import LogicalOrder

        nd = len(ds["dims_logical"])
        proc = jax.process_index()
        topo = pencil.topology
        local_ranges = []
        for rank in range(len(topo)):
            coords = topo.coords(rank)
            if topo.device(coords).process_index != proc:
                continue
            local_ranges.append(pencil.range_local(coords, LogicalOrder))
        return CheckpointManager._blocks_intersecting(
            local_ranges, nd, blocks)

    @staticmethod
    def _blocks_intersecting(local_ranges, nd: int,
                             blocks: List[dict]) -> List[dict]:
        """Pure intersection: manifest blocks (logical-order global
        ``start``/``shape``, the first ``nd`` dims being the spatial
        ones) overlapping any of ``local_ranges`` (tuples of ``range``
        per spatial dim)."""
        out = []
        for b in blocks:
            start, bshape = b["start"], b["shape"]
            for rngs in local_ranges:
                if all(start[d] < rngs[d].stop
                       and start[d] + bshape[d] > rngs[d].start
                       for d in range(nd)):
                    out.append(b)
                    break
        return out

    def _check_dataset_present(self, step: int, data_path: str,
                               name: str) -> None:
        """Driver-agnostic structural check: the container opens and
        names the dataset (the checksums-off validation level)."""
        try:
            f = self.driver.open(data_path, read=True)
        except ResilienceError:
            raise
        except (OSError, ValueError, KeyError, RuntimeError) as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step} dataset {name!r}: container "
                f"unreadable ({type(e).__name__}: {e})",
                step=step, dataset=name, path=data_path) from e
        try:
            if hasattr(f, "dataset_meta"):       # binary: sidecar entry
                f.dataset_meta(name)
            else:                                # hdf5 / orbax: name list
                names = f.datasets() if callable(f.datasets) else [
                    d["name"] for d in f.datasets]
                if name not in names:
                    raise KeyError(name)
        except (OSError, ValueError, KeyError) as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step} dataset {name!r}: missing from "
                f"the data container ({type(e).__name__}: {e})",
                step=step, dataset=name, path=data_path) from e
        finally:
            f.close()

    def _open_block_reader(self, manifest: dict, data_path: str, name: str,
                           ds: dict):
        """Context manager yielding ``read_block(start, shape)`` over the
        dataset's logical-order global index space."""
        from contextlib import contextmanager

        shape = tuple(ds["dims_logical"]) + tuple(ds["extra_dims"])
        driver_name = manifest.get("driver", type(self.driver).__name__)
        if driver_name == "HDF5Driver":
            import h5py

            @contextmanager
            def h5_reader():
                with h5py.File(data_path, "r", locking=False) as hf:
                    dset = hf[name]
                    if tuple(dset.shape) != shape:
                        raise CorruptCheckpointError(
                            f"dataset {name!r}: stored shape "
                            f"{tuple(dset.shape)} != manifest {shape}",
                            dataset=name, path=data_path)

                    def read_block(start, bshape):
                        sl = tuple(slice(s, s + e)
                                   for s, e in zip(start, bshape))
                        return np.asarray(dset[sl])

                    yield read_block

            return h5_reader()

        # binary driver: sidecar gives the dataset offset; blocks are
        # strided views of the discontiguous logical-order region
        @contextmanager
        def bin_reader():
            f = self.driver.open(data_path, read=True)
            try:
                d = f.dataset_meta(name)
                if d.get("layout") != "discontiguous":
                    raise CorruptCheckpointError(
                        f"dataset {name!r}: layout {d.get('layout')!r} does "
                        f"not support manifest verification",
                        dataset=name, path=data_path)
                if tuple(d["dims_logical"]) != tuple(ds["dims_logical"]):
                    raise CorruptCheckpointError(
                        f"dataset {name!r}: sidecar dims "
                        f"{d['dims_logical']} != manifest "
                        f"{ds['dims_logical']}",
                        dataset=name, path=data_path)
                mm = np.memmap(data_path, dtype=np.dtype(d["dtype"]),
                               mode="r", offset=d["offset_bytes"],
                               shape=shape)

                def read_block(start, bshape):
                    sl = tuple(slice(s, s + e)
                               for s, e in zip(start, bshape))
                    return mm[sl]

                yield read_block
                del mm
            finally:
                f.close()

        return bin_reader()

    def latest_valid(self) -> Optional[int]:
        """Newest step that is committed AND passes verification;
        uncommitted, torn or checksum-failing checkpoints are skipped
        with a logged warning.  ``None`` when nothing valid exists.
        Also recovers a committed step parked in the ``-replaced``
        namespace by a re-save that crashed before its new COMMIT.

        This is a *per-process* answer — on a multi-process mesh where
        each host verifies its own storage, use
        :meth:`common_latest_valid` so every rank restores the SAME
        step."""
        self._recover_replaced()
        for step in sorted(self._scan(), reverse=True):
            if not self.is_committed(step):
                logger.warning(
                    "checkpoint step %d skipped: no COMMIT marker", step)
                continue
            try:
                self.verify(step)
            except ResilienceError as e:
                logger.warning("checkpoint step %d skipped: %s", step, e)
                continue
            return step
        return None

    def valid_steps(self) -> List[int]:
        """EVERY committed step that passes verification, ascending —
        the full restorable set this process can vouch for (the input
        to the mesh-wide checkpoint election)."""
        self._recover_replaced()
        out = []
        for step in sorted(self._scan()):
            if not self.is_committed(step):
                continue
            try:
                self.verify(step)
            except ResilienceError as e:
                logger.warning("checkpoint step %d skipped: %s", step, e)
                continue
            out.append(step)
        return out

    def common_latest_valid(self, *, coordinator=None) -> Optional[int]:
        """Newest step that is :meth:`latest_valid`-grade on **every**
        rank of the mesh — the agreed-checkpoint election.

        The divergent-restore hazard this removes: a torn write on one
        rank silently shifts that rank's ``latest_valid()`` to an older
        step, and per-rank restores then reload DIFFERENT steps — a
        mesh-wide state divergence no probe downstream can attribute.
        Here every rank publishes its full valid-step set over the
        cluster KV (one allgather round), the intersection is computed
        identically everywhere, and its maximum is the one step the
        whole mesh restores.  ``None`` when no step is valid on every
        rank.

        Cost: the election fully verifies every retained checkpoint on
        every rank (bounded by ``keep``) — deliberately ONE consensus
        round on a cold recovery path, instead of a cheaper
        newest-first protocol that would need a verify/exchange round
        per rejected candidate.  Set ``checksums=False`` (structural
        verification) if election latency on very large retained sets
        ever matters.

        With no coordinator (layer off, or a single-process mesh) this
        degrades to :meth:`latest_valid` exactly."""
        if coordinator is None:
            from .. import cluster

            coordinator = cluster.coordinator()
        if coordinator is None:
            return self.latest_valid()
        local = self.valid_steps()
        common = coordinator.agree_steps("ckpt-valid", local)
        agreed = max(common) if common else None
        from .. import obs
        from ..cluster import epoch as _epoch

        if obs.enabled():
            obs.record_event(
                "cluster.verdict", label="ckpt-elect", action="elect",
                epoch=_epoch.current(), step=agreed,
                local_steps=local, common_steps=common)
        if agreed is None:
            logger.warning(
                "no checkpoint step is valid on every rank (local valid "
                "steps here: %s)", local)
        elif local and agreed != local[-1]:
            logger.warning(
                "mesh-agreed checkpoint step %d is older than this "
                "rank's newest valid step %d (a peer's newer step is "
                "torn or missing)", agreed, local[-1])
        return agreed

    # -- restore -----------------------------------------------------------
    def restore(self, step: Optional[int] = None,
                *, verify: Optional[bool] = None) -> "Checkpoint":
        """Open checkpoint ``step`` (default: :meth:`latest_valid` —
        or, with the cluster layer armed on a multi-process mesh,
        :meth:`common_latest_valid`, so every rank opens the SAME
        agreed step) for reading.  ``verify`` (default: the manager's
        ``checksums`` setting) validates the requested datasets against
        the manifest before any bytes are trusted.  When the step comes
        from :meth:`latest_valid` it was fully verified moments ago, so
        the per-read verification defaults OFF for that path (pass
        ``verify=True`` to force it anyway)."""
        if step is None:
            step = self.common_latest_valid()
            if step is None:
                raise CheckpointNotFoundError(
                    f"no valid committed checkpoint under "
                    f"{self.directory!r}")
            if verify is None:
                verify = False  # just verified by latest_valid()
        step = int(step)
        if not self.is_committed(step):
            raise CheckpointNotFoundError(
                f"checkpoint step {step} is not committed under "
                f"{self.directory!r}")
        manifest = self._load_manifest(step)
        return Checkpoint(self, step, manifest,
                          verify=self.checksums if verify is None else verify)


class Checkpoint:
    """A committed checkpoint opened for restore."""

    def __init__(self, manager: CheckpointManager, step: int, manifest: dict,
                 *, verify: bool):
        self.manager = manager
        self.step = step
        self.manifest = manifest
        self.verify = verify
        self.path = manager._step_dir(step)

    @property
    def datasets(self) -> List[str]:
        return sorted(self.manifest["datasets"])

    def read(self, name: str, pencil, extra_dims: Optional[Tuple] = None,
             *, verify=None):
        """Read dataset ``name`` into ``pencil`` (any decomposition or
        process count — the drivers' restart contract).  With
        verification on, every manifest block is checksum-validated
        first; corruption raises :class:`CorruptCheckpointError` instead
        of returning garbage.  ``verify="local"`` is the
        cross-decomposition restore mode: only the writer's manifest
        blocks that intersect THIS process's local extents of
        ``pencil`` are verified — what an elastic reformation onto a
        smaller mesh wants, where re-verifying the whole global array
        on every surviving rank would multiply restore latency."""
        from ..io import open_file
        from ..utils.timers import timeit

        mf = self.manifest
        if name not in mf["datasets"]:
            raise KeyError(
                f"dataset {name!r} not in checkpoint step {self.step} "
                f"(has {self.datasets})")
        do_verify = self.verify if verify is None else verify
        from .. import obs

        t0 = None
        if obs.enabled():
            t0 = time.perf_counter()
        with timeit(self.manager.timer, "checkpoint restore"):
            if do_verify == "local":
                self.manager._verify_dataset_local(
                    self.step, mf, name, mf["datasets"][name], pencil)
            elif do_verify:
                self.manager._verify_dataset(self.step, mf, name,
                                             mf["datasets"][name])
            data_path = os.path.join(
                self.path, mf.get("data_file", self.manager._data_name))
            with open_file(self.manager.driver, data_path, read=True,
                           retry=self.manager.retry) as f:
                out = f.read(name, pencil, extra_dims)
            if faults.armed("ckpt.restore"):
                # the post-read SDC drill: data verified on disk, then
                # corrupted in flight — what the detect-and-recover
                # ladder (guard.guarded_step) and downstream invariant
                # probes exist to catch
                act = faults.fire("ckpt.restore", step=self.step,
                                  dataset=name)
                if act == "torn":   # cannot tear a read: treat as kill
                    faults.kill_now()
                if act == "corrupt":
                    from ..guard import integrity as _gi

                    out = type(out)(
                        out.pencil,
                        _gi.corrupt_eager(
                            out.data,
                            faults.hit_count("ckpt.restore") - 1),
                        out.extra_dims)
        if t0 is not None:
            dt = time.perf_counter() - t0
            obs.counter("ckpt.restores").inc()
            obs.histogram("ckpt.restore_seconds").observe(dt)
            obs.record_event("ckpt.restore", step=self.step, dataset=name,
                             seconds=dt, verified=do_verify)
        return out

    def read_state(self, pencil, names: Optional[List[str]] = None) -> Dict:
        """Restore several datasets (default: all) onto one pencil."""
        return {name: self.read(name, pencil)
                for name in (names or self.datasets)}
