"""Streaming block checksums for checkpoint manifests.

CRC32C (Castagnoli — the checksum of GCS, TensorStore and most storage
stacks) via ``google-crc32c`` or ``crc32c`` when available, falling back
to ``zlib.crc32``; the algorithm actually used travels in the manifest
(``"algo"``), so a checkpoint written with one is verified with the
same one.

Checksums are computed over each per-shard block's **logical-order
bytes** during the same ``iter_local_blocks`` streaming the drivers
write from — the block is already a host copy, and the CRC walks it in
bounded chunks, so checksumming adds no extra host copy of the array
(at most one transient ``_CHUNK``-sized buffer for the C bindings,
which require ``bytes``).
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, List

import numpy as np

__all__ = ["ALGO", "supported", "crc_update", "crc_of_array",
           "BlockChecksums"]

_CHUNK = 1 << 24  # 16 MiB: bounds the transient bytes copy per update


def _zlib_extend(crc: int, data: bytes) -> int:
    return zlib.crc32(data, crc)


# every backend this host can compute, keyed by the manifest algo name —
# a verifier uses the WRITER's algorithm, not its own default
_BACKENDS: Dict[str, Callable[[int, bytes], int]] = {"crc32": _zlib_extend}
try:
    import google_crc32c

    _BACKENDS["crc32c"] = google_crc32c.extend
except ImportError:
    try:
        import crc32c as _c

        _BACKENDS["crc32c"] = lambda crc, data: _c.crc32c(data, crc)
    except ImportError:
        pass

ALGO = "crc32c" if "crc32c" in _BACKENDS else "crc32"


def supported(algo: str) -> bool:
    return algo in _BACKENDS


def crc_update(crc: int, data: bytes, algo: str = ALGO) -> int:
    return _BACKENDS[algo](crc, data) & 0xFFFFFFFF


def crc_of_array(a: np.ndarray, algo: str = ALGO) -> int:
    """CRC of an array's C-order bytes, streamed in bounded chunks."""
    a = np.ascontiguousarray(a)
    flat = a.reshape(-1).view(np.uint8)
    crc = 0
    for i in range(0, flat.size, _CHUNK):
        crc = crc_update(crc, flat[i:i + _CHUNK].tobytes(), algo)
    return crc


class BlockChecksums:
    """Per-dataset block CRC accumulator fed by the drivers'
    ``block_observer`` hook: one entry per streamed block, keyed by its
    logical-order global corner (decomposition-independent — a verifier
    under ANY process layout can re-read exactly these ranges)."""

    def __init__(self):
        self._datasets: Dict[str, List[dict]] = {}

    def observer(self, dataset: str) -> Callable:
        blocks = self._datasets.setdefault(dataset, [])

        def observe(start, block):
            blocks.append({
                "start": [int(s) for s in start],
                "shape": [int(s) for s in block.shape],
                "crc": crc_of_array(block),
            })

        return observe

    def blocks(self, dataset: str) -> List[dict]:
        return sorted(self._datasets.get(dataset, []),
                      key=lambda b: tuple(b["start"]))

    def as_dict(self) -> Dict[str, List[dict]]:
        return {name: self.blocks(name) for name in sorted(self._datasets)}
