"""Typed failure taxonomy of the resilience subsystem.

Every failure the checkpoint/restore and fault-injection machinery can
surface derives from :class:`ResilienceError`, so callers (and the
truncation fuzz test) can assert "typed resilience error, never garbage
data" with a single ``except`` clause.  The I/O-shaped members also
derive from the matching builtin (``OSError``/``ValueError``) so
pre-existing handlers keep working.
"""

from __future__ import annotations

__all__ = [
    "ResilienceError",
    "CorruptCheckpointError",
    "CorruptSidecarError",
    "CheckpointNotFoundError",
    "InjectedFault",
    "RetryDeadlineExceeded",
]


class ResilienceError(Exception):
    """Base of every error raised by ``pencilarrays_tpu.resilience``."""


class CorruptCheckpointError(ResilienceError):
    """A checkpoint failed validation: missing COMMIT marker, unreadable
    manifest, or a dataset block whose bytes do not match the manifest
    checksum.  ``step``/``dataset``/``block`` pinpoint the failure."""

    def __init__(self, message: str, *, step=None, dataset=None, block=None,
                 path=None):
        super().__init__(message)
        self.step = step
        self.dataset = dataset
        self.block = block
        self.path = path


class CorruptSidecarError(ResilienceError, ValueError):
    """A driver's sidecar metadata (e.g. the binary driver's ``.json``)
    is truncated or corrupt — the data file is unreadable without it."""

    def __init__(self, message: str, *, path=None):
        super().__init__(message)
        self.path = path


class CheckpointNotFoundError(ResilienceError, FileNotFoundError):
    """No committed checkpoint exists at the requested step (or at all)."""


class InjectedFault(ResilienceError, OSError):
    """The deterministic error raised by a ``faults`` rule in ``error``
    mode — an ``OSError`` (errno EIO) so it walks the same transient-I/O
    retry paths a real filesystem error would."""

    def __init__(self, message: str, *, point=None, hit=None):
        import errno

        super().__init__(errno.EIO, message)
        self.point = point
        self.hit = hit


class RetryDeadlineExceeded(ResilienceError, TimeoutError):
    """A retried operation did not succeed within the policy deadline
    (or exhausted its attempts); ``__cause__`` is the last error."""
