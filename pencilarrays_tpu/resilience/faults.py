"""Deterministic fault injection for the distributed runtime.

The I/O drivers, the checkpoint manager and ``parallel/distributed.py``
consult named **injection points** at their failure-critical moments, so
tests (and chaos drills) can simulate torn writes, crash-before-commit
and transient ``OSError`` storms *without monkeypatching internals* —
and so a worker subprocess can be killed mid-write purely through its
environment.

Registered points (see ``docs/Resilience.md``):

========================  ====================================================
``io.open``               driver ``open`` (before the file is touched)
``io.write_block``        one per-shard block about to hit the data file
``io.flush_meta``         a sidecar/metadata flush (the commit point of a
                          driver-level write)
``ckpt.commit``           the checkpoint manager about to commit (rename +
                          COMMIT marker)
``ckpt.restore``          a dataset just restored from a checkpoint
                          (``corrupt`` pokes the restored array)
``dist.initialize``       the coordinator connection inside
                          ``distributed.initialize``
``barrier``               ``sync_global_devices`` (ctx carries the name)
``hop.exchange``          an eager transpose / routed-reshard dispatch
                          (``corrupt`` pokes the hop's output — the SDC
                          drill the ``guard`` probes must catch)
``serve.submit``          the plan service's admission boundary (every
                          ``submit``/``submit_reshard``, before quota/
                          SLO checks — ``error`` fails THIS submitter
                          typed, ``delay`` drags admission: the
                          overload and flaky-client drills)
``fleet.route``           the fleet's routed-admission path: once in
                          the router's ``submit`` and once on the
                          back-end mesh as it takes the routed
                          request — with ``%mesh<k>`` one shared spec
                          kills/delays/errors exactly ONE mesh's
                          admission path (the whole-mesh chaos drill)
``kv.get``                one KV wire read (each ``try_get`` and each
                          poll of a blocking ``get``, both backends) —
                          the ``drop``/``partition`` surface: a
                          partitioned rank's reads find nothing, so
                          its waits run out typed
``kv.set``                one KV wire write (``set``/``set_if``/
                          ``delete``, both backends) — ``drop``
                          silently loses the write, ``partition``
                          raises it unreachable; ``%rank<k>`` on only
                          one of ``kv.get``/``kv.set`` expresses an
                          *asymmetric* partition
========================  ====================================================

Rules are **counter-based, never random** — the same spec replays the
same failure.  Spec grammar (comma/semicolon-separated)::

    point:mode[%rank<k>|%mesh<k>][*times][@nth]

* ``mode`` — ``error`` (raise :class:`InjectedFault`), ``kill``
  (``SIGKILL`` this process: the un-catchable crash), ``torn``
  (cooperative: the call site writes a partial block, then dies),
  ``corrupt`` (cooperative: the call site applies the deterministic
  counter-addressed bitflip/NaN poke of
  ``guard.integrity.corrupt_block`` — silent data corruption on
  demand, so chaos tests can assert typed-error-or-bit-identical,
  never garbage), ``delay`` (sleep
  ``PENCILARRAYS_TPU_FAULTS_DELAY_S`` seconds — default 0.25 — at the
  point, then proceed normally: the deterministic *straggler*, e.g.
  ``hop.exchange:delay%rank1`` makes rank 1 drag every exchange
  without changing any value; guard/cluster semantics are untouched,
  which is exactly what the straggler-detection drill needs),
  ``drop`` (cooperative, KV wire only: the addressed operation is
  *silently lost* — a dropped read misses, a dropped write returns
  normally having written nothing: the lost-update drill), or
  ``partition`` (cooperative, KV wire only: the store is unreachable
  for the addressed process — reads find nothing until their bounded
  wait runs out typed, writes raise ``ConsensusTimeoutError``
  immediately.  ``kv.get:partition%rank1,kv.set:partition%rank1``
  cuts rank 1 off the wire entirely; arming only one direction
  expresses an asymmetric partition).
* ``%rank<k>`` — rank-addressed injection: the rule triggers only in
  the process whose mesh rank is ``k`` (``PENCILARRAYS_TPU_CLUSTER_RANK``,
  else the jax-assigned process id, else 0 — the cluster layer's
  identity resolution), so ONE spec shared by every worker's
  environment can kill/corrupt/hang a *specific* rank:
  ``hop.exchange:corrupt%rank1@2`` poisons rank 1's second hop and
  nobody else's.  ``@nth`` counts that rank's own local hits.
* ``%mesh<k>`` — mesh-addressed injection (the rank selector's fleet
  sibling): the rule triggers only in a process whose fleet mesh id
  is ``k`` (``PENCILARRAYS_TPU_FLEET_MESH``, set by the mesh worker's
  launcher; a non-fleet process answers -1 and never matches), so ONE
  spec shared by every mesh's environment addresses a *whole mesh*:
  ``fleet.route:kill%mesh1@4`` SIGKILLs mesh 1 as it takes its 4th
  routed request — the whole-mesh loss drill.
* ``*times`` — trigger on that many consecutive hits (default: ``error``
  and ``corrupt`` forever, ``kill``/``torn`` once).
* ``@nth`` — first trigger on the *nth* hit of the point (1-based,
  default 1): ``io.write_block:torn@3`` tears the third block.

Sources, in precedence order: rules installed programmatically
(:func:`install` / the :func:`active` context manager), else the
``PENCILARRAYS_TPU_FAULTS`` environment variable (re-read whenever it
changes, so a worker can arm itself after import).  Example::

    PENCILARRAYS_TPU_FAULTS="io.write_block:torn@3,dist.initialize:error*3"
"""

from __future__ import annotations

import os
import re
import signal
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .errors import InjectedFault

__all__ = [
    "POINTS",
    "Rule",
    "parse",
    "install",
    "clear",
    "reset_counters",
    "active",
    "armed",
    "fire",
    "hit_count",
    "block_write_hook",
    "kill_now",
    "delay_seconds",
    "ENV_VAR",
    "DELAY_S_VAR",
]

ENV_VAR = "PENCILARRAYS_TPU_FAULTS"

POINTS = frozenset({
    "io.open",
    "io.write_block",
    "io.flush_meta",
    "ckpt.commit",
    "ckpt.restore",
    "dist.initialize",
    "barrier",
    "hop.exchange",
    "serve.submit",
    "fleet.route",
    "kv.get",
    "kv.set",
})

MODES = frozenset({"error", "kill", "torn", "corrupt", "delay",
                   "drop", "partition"})

DELAY_S_VAR = "PENCILARRAYS_TPU_FAULTS_DELAY_S"
DEFAULT_DELAY_S = 0.25


def delay_seconds() -> float:
    """The injected-straggler sleep (``delay`` mode), env-tunable so a
    drill can scale the excess against its own hop durations."""
    try:
        return float(os.environ.get(DELAY_S_VAR, DEFAULT_DELAY_S))
    except ValueError:
        return DEFAULT_DELAY_S


@dataclass(frozen=True)
class Rule:
    point: str
    mode: str                  # one of MODES
    times: Optional[int]       # consecutive triggering hits (None = forever)
    first: int = 1             # 1-based hit index of the first trigger
    rank: Optional[int] = None   # %rank<k> selector (None = every rank)
    mesh: Optional[int] = None   # %mesh<k> selector (None = every mesh)

    def triggers(self, hit: int) -> bool:
        if hit < self.first:
            return False
        return self.times is None or hit < self.first + self.times


def parse(spec: str) -> List[Rule]:
    """Parse a spec string into rules (grammar in the module docstring)."""
    rules = []
    for raw in spec.replace(";", ",").split(","):
        raw = raw.strip()
        if not raw:
            continue
        try:
            point, rhs = raw.split(":", 1)
        except ValueError:
            raise ValueError(f"fault rule {raw!r}: expected point:mode")
        point = point.strip()
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; registered points: "
                f"{sorted(POINTS)}")
        first = 1
        if "@" in rhs:
            rhs, nth = rhs.rsplit("@", 1)
            first = int(nth)
            if first < 1:
                raise ValueError(f"fault rule {raw!r}: @nth is 1-based")
        times: Optional[int]
        if "*" in rhs:
            mode, n = rhs.split("*", 1)
            times = int(n)
        else:
            mode, times = rhs, None
        rank: Optional[int] = None
        mesh: Optional[int] = None
        if "%" in mode:
            mode, sel = mode.split("%", 1)
            m = re.match(r"^(rank|mesh)(\d+)$", sel.strip())
            if not m:
                raise ValueError(
                    f"fault rule {raw!r}: selector {sel!r} is not "
                    f"'rank<k>' or 'mesh<k>' (e.g. "
                    f"hop.exchange:corrupt%rank1@2, "
                    f"fleet.route:kill%mesh1@4)")
            if m.group(1) == "rank":
                rank = int(m.group(2))
            else:
                mesh = int(m.group(2))
        mode = mode.strip()
        if mode not in MODES:
            raise ValueError(
                f"fault rule {raw!r}: mode {mode!r} not in {sorted(MODES)}")
        if times is None and mode in ("kill", "torn"):
            times = 1  # a crash repeats at most per-process anyway
        rules.append(Rule(point, mode, times, first, rank, mesh))
    return rules


# programmatic rules (highest precedence) + per-point hit counters
_rules: Optional[List[Rule]] = None
_env_cache: Optional[str] = None
_env_rules: List[Rule] = []
_hits: Dict[str, int] = {}
# (point, mode) pairs already journaled for the high-rate cooperative
# modes (drop/partition fire once per wire poll: the journal gets the
# onset, the counter gets the rate)
_journaled: set = set()


def install(spec) -> None:
    """Install rules programmatically (a spec string or ``Rule`` list);
    takes precedence over the environment until :func:`clear`."""
    global _rules
    _rules = parse(spec) if isinstance(spec, str) else list(spec)
    reset_counters()


def clear() -> None:
    """Drop programmatic rules (environment rules apply again)."""
    global _rules
    _rules = None
    reset_counters()


def reset_counters() -> None:
    _hits.clear()
    _journaled.clear()


def hit_count(point: str) -> int:
    """Hits recorded so far at ``point`` (the counter ``corrupt`` call
    sites use to address the deterministic poke)."""
    return _hits.get(point, 0)


@contextmanager
def active(spec):
    """Scope rules to a ``with`` block (the test-friendly entry point)."""
    global _rules
    prev = _rules
    install(spec)
    try:
        yield
    finally:
        _rules = prev
        reset_counters()


def _current_rules() -> Sequence[Rule]:
    if _rules is not None:
        return _rules
    global _env_cache, _env_rules
    env = os.environ.get(ENV_VAR, "")
    if env != _env_cache:          # re-read on change: workers arm late
        _env_cache = env
        _env_rules = parse(env) if env else []
    return _env_rules


def armed(point: str) -> bool:
    """Cheap probe: does any current rule target ``point``?  Hot paths
    use this to keep their no-faults fast path untouched (e.g. the
    binary writer's in-thread block copies).  Deliberately ignores the
    ``%rank``/``%mesh`` selectors (resolving identity is not
    probe-cheap): a rule addressed to another rank or mesh makes this
    process take the instrumented path, where :func:`fire` then
    correctly does nothing."""
    return any(r.point == point for r in _current_rules())


def _self_rank() -> int:
    """This process's mesh rank for ``%rank<k>`` matching — delegated
    to the cluster layer's ONE identity-resolution rule (env override
    first, so FileKV drill workers are addressable before any jax
    state exists).  Resolved lazily: only rules that carry a rank
    selector ever pay for it."""
    from ..cluster import rank

    return rank()


def _self_mesh() -> int:
    """This process's fleet mesh id for ``%mesh<k>`` matching —
    delegated to the fleet layer's ONE identity rule (the
    ``PENCILARRAYS_TPU_FLEET_MESH`` env var a mesh worker's launcher
    sets; -1 = not a mesh worker, matches no selector).  Resolved
    lazily, like :func:`_self_rank`."""
    from ..fleet import mesh_id

    return mesh_id()


def kill_now() -> None:
    """SIGKILL this process — the un-catchable crash (no atexit, no
    flush): what a preempted TPU worker actually looks like."""
    os.kill(os.getpid(), signal.SIGKILL)


def block_write_hook(i, start, block, block_observer, put, *,
                     flush=None, in_flight=(), **ctx) -> None:
    """The per-block injection + checksum hook every driver write path
    shares (ONE implementation of the torn semantics).  Fires
    ``io.write_block``; on a ``torn`` rule it orders any in-flight
    writes, writes a prefix of the block's leading-dim rows via ``put``,
    flushes, and SIGKILLs — the mid-checkpoint crash the resilience
    tests drill.  Otherwise it feeds the optional ``block_observer``
    (the checkpoint manager's checksum tap)."""
    act = fire("io.write_block", block=i, **ctx)
    if act == "torn":
        for fu in in_flight:  # order the tear after earlier blocks
            fu.result()
        put(start, block[: max(1, block.shape[0] // 2)])
        if flush is not None:
            flush()
        kill_now()
    if block_observer is not None:
        block_observer(start, block)


def fire(point: str, **ctx) -> Optional[str]:
    """Consult the injection point.  Returns ``None`` (the overwhelmingly
    common no-fault case), raises :class:`InjectedFault` (``error``),
    never returns (``kill``), or returns a cooperative mode string the
    call site honors: ``"torn"`` (write a partial block, then call
    :func:`kill_now`; sites that cannot tear treat it as ``kill``) or
    ``"corrupt"`` (apply the deterministic counter-addressed poke —
    ``guard.integrity.corrupt_block`` — to the point's payload)."""
    rules = _current_rules()
    if not rules:
        return None
    matching = [r for r in rules if r.point == point]
    if not matching:
        return None
    hit = _hits.get(point, 0) + 1
    _hits[point] = hit
    for r in matching:
        if not r.triggers(hit):
            continue
        if r.rank is not None and r.rank != _self_rank():
            continue   # addressed to another rank; counters still tick
        if r.mesh is not None and r.mesh != _self_mesh():
            continue   # addressed to another mesh; counters still tick
        _obs_firing(point, r.mode, hit, ctx)
        if r.mode == "delay":
            # the deterministic straggler: stall, then proceed — the
            # point's semantics (and any LATER rule on it) are untouched
            import time

            time.sleep(delay_seconds())
            continue
        if r.mode == "kill":
            kill_now()
        if r.mode in ("torn", "corrupt", "drop", "partition"):
            return r.mode
        where = f" [{ctx}]" if ctx else ""
        raise InjectedFault(
            f"injected fault at {point} (hit {hit}){where}",
            point=point, hit=hit)
    return None


def _obs_firing(point: str, mode: str, hit: int, ctx: dict) -> None:
    """Journal a triggered rule through the obs flight recorder BEFORE
    the fault takes effect — for ``kill``/``torn`` the record is the
    only trace the dead process leaves (it is fsync'd: ``fault`` is a
    critical event), which is what makes the SIGKILL restart drill's
    timeline readable."""
    from ..obs import enabled, record_event
    from ..obs.metrics import counter

    if not enabled():
        return
    counter("faults.fired", point=point, mode=mode).inc()
    # drop/partition fire once per KV wire poll and never kill the
    # process: the journal records the ONSET (first firing) only —
    # every subsequent firing is visible through the counter — and
    # skips the per-record fsync a kill/torn firing rightly pays
    if mode in ("drop", "partition"):
        if (point, mode) in _journaled:
            return
        _journaled.add((point, mode))
        record_event("fault", point=point, mode=mode, hit=hit,
                     _fsync=False, **{
                         k: v for k, v in ctx.items()
                         if k not in ("point", "mode", "hit")})
        return
    record_event("fault", point=point, mode=mode, hit=hit, **{
        k: v for k, v in ctx.items() if k not in ("point", "mode", "hit")})
