"""Durable filesystem primitives shared by the I/O drivers and the
checkpoint manager — ONE implementation of the atomic fsync'd publish,
so every metadata commit point in the tree carries identical durability
guarantees (tmp write + data fsync + ``os.replace`` + directory fsync).
"""

from __future__ import annotations

import json
import os

__all__ = ["fsync_dir", "atomic_write_json", "atomic_write_text"]


def fsync_dir(path: str) -> None:
    """Durably order a rename/replace within its directory (best effort:
    not every FS supports directory fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_publish(path: str, write_body) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        write_body(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def atomic_write_json(path: str, obj) -> None:
    """Atomically publish ``obj`` as JSON at ``path``: a crash at any
    point leaves either the previous content or the new one, never a
    torn file."""
    _atomic_publish(path, lambda f: json.dump(obj, f, indent=1))


def atomic_write_text(path: str, text: str) -> None:
    _atomic_publish(path, lambda f: f.write(text))
