"""Retry/timeout/backoff policy for cross-process rendezvous and I/O.

Every cross-process rendezvous in the runtime — the coordinator
connection in ``distributed.initialize``, a driver ``open`` racing file
creation on a shared filesystem, a sidecar flush hitting a transient
``EIO`` — needs *bounded retries, not hangs and not crashes*.
:class:`RetryPolicy` is the one knob set: exponential backoff with
jitter under an overall wall-clock deadline.

Each retry is logged twice: through the ``pencilarrays_tpu.resilience``
logger (a visible warning naming the operation, attempt and delay) and
through the existing timer/trace channel — the backoff sleep is wrapped
in :func:`~pencilarrays_tpu.utils.timers.timeit`, so retries show up in
``TimerOutput`` reports and as ``jax.named_scope`` annotations exactly
like any other instrumented section.

Environment knobs (read by :meth:`RetryPolicy.from_env`):

=================================  =======  ==============================
``PENCILARRAYS_TPU_RETRIES``       5        max attempts
``PENCILARRAYS_TPU_RETRY_BASE``    0.05     first backoff delay (s)
``PENCILARRAYS_TPU_RETRY_MAX``     2.0      per-retry delay ceiling (s)
``PENCILARRAYS_TPU_RETRY_DEADLINE``  30.0   overall wall-clock budget (s)
=================================  =======  ==============================
"""

from __future__ import annotations

import errno
import logging
import os
import random
import time
from dataclasses import dataclass, replace
from typing import Callable, Optional, Tuple

from .errors import InjectedFault, RetryDeadlineExceeded

__all__ = ["RetryPolicy", "is_transient"]

logger = logging.getLogger("pencilarrays_tpu.resilience")

# OSError errnos worth retrying: resource pressure / interruption /
# shared-FS weather.  ENOENT and EACCES are deliberately NOT here — a
# missing file or bad permission is a program error, and retrying it
# would only turn a clear failure into a slow one.
_TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.EINTR, errno.EBUSY, errno.EIO, errno.ENOSPC,
    errno.ESTALE, errno.ETIMEDOUT, errno.ECONNREFUSED, errno.ECONNRESET,
    errno.EADDRINUSE,
})


def is_transient(e: BaseException) -> bool:
    """Default retryability test: connection/timeout errors, injected
    faults, and ``OSError`` with a transient errno."""
    if isinstance(e, (ConnectionError, TimeoutError, InterruptedError,
                      InjectedFault)):
        return True
    if isinstance(e, OSError):
        return e.errno in _TRANSIENT_ERRNOS
    return False


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + deadline.

    Delay before retry *n* (1-based) is
    ``min(base_delay * 2**(n-1), max_delay)`` scaled by a uniform jitter
    in ``[1 - jitter, 1 + jitter]``; the whole operation must land
    within ``deadline`` seconds of the first attempt or
    :class:`RetryDeadlineExceeded` is raised (chaining the last error).
    ``max_attempts=1`` disables retries entirely.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    deadline: float = 30.0
    jitter: float = 0.25
    retry_on: Optional[Tuple[type, ...]] = None  # None -> is_transient()

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        env = os.environ.get
        kw = dict(
            max_attempts=int(env("PENCILARRAYS_TPU_RETRIES", 5)),
            base_delay=float(env("PENCILARRAYS_TPU_RETRY_BASE", 0.05)),
            max_delay=float(env("PENCILARRAYS_TPU_RETRY_MAX", 2.0)),
            deadline=float(env("PENCILARRAYS_TPU_RETRY_DEADLINE", 30.0)),
        )
        kw.update(overrides)
        return cls(**kw)

    def replace(self, **kw) -> "RetryPolicy":
        return replace(self, **kw)

    def _retryable(self, e: BaseException) -> bool:
        if self.retry_on is not None:
            return isinstance(e, self.retry_on)
        return is_transient(e)

    def delay_for(self, attempt: int) -> float:
        """Jittered backoff delay before the retry following ``attempt``
        (1-based) — THE one definition of the backoff curve, shared
        with external retry loops (e.g. ``guard.guarded_step``)."""
        delay = min(self.base_delay * 2 ** (attempt - 1), self.max_delay)
        return delay * (1 + self.jitter * (2 * random.random() - 1))

    def call(self, fn: Callable, *args, label: str = "operation",
             timer=None, **kw):
        """Run ``fn(*args, **kw)`` under this policy.  Non-retryable
        errors propagate untouched on the first attempt; retryable ones
        are re-raised as-is once attempts are exhausted, or wrapped in
        :class:`RetryDeadlineExceeded` when the deadline cuts the loop
        short."""
        from ..utils.timers import timeit

        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kw)
            except BaseException as e:
                if not self._retryable(e) or attempt >= self.max_attempts:
                    raise
                delay = self.delay_for(attempt)
                elapsed = time.monotonic() - start
                if elapsed + delay > self.deadline:
                    raise RetryDeadlineExceeded(
                        f"{label}: attempt {attempt} failed and the "
                        f"{self.deadline:.1f}s retry deadline is exhausted "
                        f"({elapsed:.2f}s elapsed): {e}") from e
                # primary sink: the obs flight recorder (a durable
                # timeline the SIGKILL drills can read back); the logger
                # stays as the always-on operational fallback
                from ..obs import enabled as _obs_enabled

                if _obs_enabled():
                    from ..obs import counter, record_event

                    counter("retry.attempts", label=label).inc()
                    record_event(
                        "retry", label=label, attempt=attempt,
                        max_attempts=self.max_attempts, delay_s=delay,
                        error=f"{type(e).__name__}: {e}")
                logger.warning(
                    "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                    label, attempt, self.max_attempts, e, delay)
                with timeit(timer, f"retry {label}"):
                    time.sleep(delay)
