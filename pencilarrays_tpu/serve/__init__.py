"""serve/ — the multi-tenant plan service (PR 10, overload plane PR 15).

The transpose engine, the batched plan layer, the guard's recovery
ladder and the obs plane all exist to be *used* — this package is the
layer that serves them: concurrent FFT/reshard requests from multiple
logical tenants, executed on one resident mesh.

* :class:`PlanService` — submit/coalesce/dispatch loop with per-tenant
  quotas and typed isolation (``docs/Serving.md``);
* :class:`PlanRegistry` — fingerprint-keyed resident executables
  (keys are :meth:`~pencilarrays_tpu.ops.fft.PencilFFTPlan.plan_key`,
  deterministic across processes and restarts);
* :class:`AdmissionQueue` / :class:`TenantQuota` / :class:`Ticket` —
  the scheduling core and the client-side future;
* the overload-survival plane: :class:`SLO` (per-tenant deadlines +
  shed priorities + the PR-19 ``max_rel_l2`` accuracy budget, enforced
  at admission/take/completion), :class:`PressurePolicy` + the
  hysteretic load-shedding gate (``serve/shed.py``) with its
  precision-downgrade rung (``serve/precision.py``: sheddable traffic
  served on a cheaper wire — full -> bf16 -> fp8 — inside each
  tenant's calibrated error envelope, instead of shed), and the
  :class:`Autoscaler` closing the serve↔elastic loop (grow/shrink the
  mesh from the queue's own load projection — ``serve/autoscale.py``);
* typed errors: :class:`ServeError`, :class:`AdmissionError`,
  :class:`DeadlineError`, :class:`StaleRequestError`,
  :class:`ServiceClosedError`.

Everything here is plain Python over the public plan APIs: importing
the package is cheap (jax is only touched when a request dispatches),
and a process that never serves pays nothing.
"""

from .autoscale import Autoscaler, AutoscalePolicy, ScaleDecision  # noqa: F401
from .errors import (  # noqa: F401
    AdmissionError,
    DeadlineError,
    ServeError,
    ServiceClosedError,
    StaleRequestError,
)
from .precision import (  # noqa: F401
    PRECISION_LADDER,
    select_rung,
    wire_error_envelope,
)
from .queue import AdmissionQueue, Batch, TenantQuota, Ticket  # noqa: F401
from .registry import PlanRegistry  # noqa: F401
from .service import PlanService  # noqa: F401
from .shed import PressureGate, PressurePolicy  # noqa: F401
from .slo import SLO, LoadTracker  # noqa: F401

__all__ = [
    "PlanService",
    "PlanRegistry",
    "AdmissionQueue",
    "TenantQuota",
    "Ticket",
    "Batch",
    "SLO",
    "LoadTracker",
    "PressurePolicy",
    "PressureGate",
    "PRECISION_LADDER",
    "select_rung",
    "wire_error_envelope",
    "Autoscaler",
    "AutoscalePolicy",
    "ScaleDecision",
    "ServeError",
    "AdmissionError",
    "DeadlineError",
    "StaleRequestError",
    "ServiceClosedError",
]
