"""serve/ — the multi-tenant plan service (PR 10).

The transpose engine, the batched plan layer, the guard's recovery
ladder and the obs plane all exist to be *used* — this package is the
layer that serves them: concurrent FFT/reshard requests from multiple
logical tenants, executed on one resident mesh.

* :class:`PlanService` — submit/coalesce/dispatch loop with per-tenant
  quotas and typed isolation (``docs/Serving.md``);
* :class:`PlanRegistry` — fingerprint-keyed resident executables
  (keys are :meth:`~pencilarrays_tpu.ops.fft.PencilFFTPlan.plan_key`,
  deterministic across processes and restarts);
* :class:`AdmissionQueue` / :class:`TenantQuota` / :class:`Ticket` —
  the scheduling core and the client-side future;
* typed errors: :class:`ServeError`, :class:`AdmissionError`,
  :class:`StaleRequestError`, :class:`ServiceClosedError`.

Everything here is plain Python over the public plan APIs: importing
the package is cheap (jax is only touched when a request dispatches),
and a process that never serves pays nothing.
"""

from .errors import (  # noqa: F401
    AdmissionError,
    ServeError,
    ServiceClosedError,
    StaleRequestError,
)
from .queue import AdmissionQueue, Batch, TenantQuota, Ticket  # noqa: F401
from .registry import PlanRegistry  # noqa: F401
from .service import PlanService  # noqa: F401

__all__ = [
    "PlanService",
    "PlanRegistry",
    "AdmissionQueue",
    "TenantQuota",
    "Ticket",
    "Batch",
    "ServeError",
    "AdmissionError",
    "StaleRequestError",
    "ServiceClosedError",
]
