"""The serve↔elastic autoscaler — demand in, capacity out.

Everything this module composes already exists: the admission queue
meters load (:class:`~pencilarrays_tpu.serve.slo.LoadTracker` — the
ONE projection the shedding gate reads too), the elastic layer can
shrink (``announce_leave`` → reform) and grow (``request_join`` →
reform admits the joiner), and the persistent compile cache
(``PENCILARRAYS_TPU_COMPILE_CACHE``) can hand a joiner pre-compiled
plans.  Nothing connected them — an overload storm just grew the queue
until quota rejections.  The :class:`Autoscaler` is that connection:

* :meth:`Autoscaler.tick` is called by the application at **step /
  reformation boundaries only** (never mid-dispatch: mesh membership
  may only change where the elastic layer already changes it);
* a window is classified against the projection: **overload** when the
  projected queue drain time exceeds ``overload_drain_s``, **idle**
  when nothing is queued or in flight, **normal** otherwise;
* decisions require ``windows`` CONSECUTIVE classifications (a single
  spike never scales) and are rate-limited by ``cooldown_s`` (scaling
  is expensive — a reformation — and an oscillating controller is
  worse than none: no flapping, by construction);
* **sustained overload** → scale **up**: if a pre-warmed joiner is
  waiting (``request_join`` published under the base namespace), run a
  reformation with ``reason="scale-up"`` — the join-admission path the
  elastic layer already drills; with no joiner waiting the decision is
  still journaled (``acted=false``) as the demand signal an operator
  (or a joiner-spawning supervisor) acts on;
* **sustained idle** → scale **down**: the highest-rank member — the
  one whose departure keeps surviving ranks dense — calls
  ``announce_leave()``; the NEXT step boundary publishes the planned
  departure, survivors reform smaller, the leaver exits clean.  Every
  rank runs the same controller over the same projection inputs and
  journals the same decision; only the designated leaver acts;
* every decision journals fsync-critical ``serve.scale{direction,
  reason, projection}`` WITH the projection inputs, so ``pa-obs
  timeline`` can render *why* capacity moved.

Pre-warmed joining (:func:`join_prewarmed`): a replacement rank builds
and compiles its registered plans BEFORE publishing its join request —
with ``PENCILARRAYS_TPU_COMPILE_CACHE`` set, the XLA programs land in
(or come from) the persistent cache, so the post-join rebuild is a
cache hit instead of a full compile.  Warm-up is measured and
journaled; ``benchmarks/autoscale_bench.py`` prices it with vs without
the cache.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

__all__ = ["AutoscalePolicy", "ScaleDecision", "Autoscaler",
           "prewarm_plans", "join_prewarmed"]


@dataclass(frozen=True)
class AutoscalePolicy:
    """The controller's knobs.

    ``overload_drain_s``: projected drain above this classifies the
    window as overloaded.  ``windows``: consecutive windows required
    before a decision (no single-spike scaling).  ``cooldown_s``:
    minimum spacing between decisions.  ``min_world``/``max_world``:
    capacity bounds (``max_world=None``: unbounded growth requests)."""

    overload_drain_s: float = 1.0
    windows: int = 3
    cooldown_s: float = 30.0
    min_world: int = 1
    max_world: Optional[int] = None

    def __post_init__(self):
        if self.overload_drain_s <= 0:
            raise ValueError("overload_drain_s must be positive")
        if self.windows < 1:
            raise ValueError("windows must be >= 1")
        if self.min_world < 1:
            raise ValueError("min_world must be >= 1")


@dataclass
class ScaleDecision:
    """One tick's verdict.  ``direction`` ``"hold"`` means no decision
    fired (insufficient windows, cooldown, or nothing to do);
    ``acted`` says whether capacity actually moved from THIS process
    (an ``up`` with no joiner waiting, or a ``down`` on a non-leaver
    rank, journals but does not act)."""

    direction: str                  # "up" | "down" | "hold"
    reason: str
    projection: dict = field(default_factory=dict)
    acted: bool = False
    detail: Optional[str] = None
    gen: Optional[int] = None       # reformation generation, when acted


class Autoscaler:
    """The boundary-driven controller (module docstring).

    Parameters
    ----------
    service:
        The :class:`~pencilarrays_tpu.serve.PlanService` whose load
        projection drives decisions.
    coordinator:
        Explicit cluster coordinator (default: the process-global one
        at each tick — so a reformation's fresh coordinator is picked
        up without re-plumbing).
    policy:
        :class:`AutoscalePolicy` (default: defaults above).
    ckpt_mgr / restore:
        Passed through to the scale-up reformation so the join
        admission restores the agreed checkpoint across the grown
        decomposition, exactly like a failure reformation.
    """

    def __init__(self, service, *, coordinator=None,
                 policy: Optional[AutoscalePolicy] = None,
                 ckpt_mgr=None, restore: Optional[Callable] = None):
        self.service = service
        # a controller needs the projection FED: an SLO-less service
        # skips pricing entirely, which would leave this autoscaler
        # permanently blind to overload (down-only scaling)
        service.ensure_priced()
        self.policy = policy or AutoscalePolicy()
        self._coordinator = coordinator
        self.ckpt_mgr = ckpt_mgr
        self.restore = restore
        self._over = 0
        self._idle = 0
        self._last_decision = -float("inf")
        self._decisions = 0

    def coordinator(self):
        if self._coordinator is not None:
            return self._coordinator
        from .. import cluster

        return cluster.coordinator()

    @property
    def decisions(self) -> int:
        return self._decisions

    # -- the controller ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> ScaleDecision:
        """Feed one boundary window; returns the decision (and acts on
        it).  Call ONLY at step/reformation boundaries — an acted
        ``up`` runs a reformation right here."""
        now = time.monotonic() if now is None else now
        p = self.policy
        proj = self.service.load_projection()
        drain = proj.get("drain_s")
        overloaded = drain is not None and drain > p.overload_drain_s
        idle = (not overloaded and proj.get("queue_depth", 0) == 0
                and proj.get("inflight_requests", 0) == 0)
        if overloaded:
            self._over += 1
            self._idle = 0
        elif idle:
            self._idle += 1
            self._over = 0
        else:
            self._over = self._idle = 0
        if now - self._last_decision < p.cooldown_s:
            return ScaleDecision("hold", "cooldown", proj)
        if self._over >= p.windows:
            return self._decide(self._scale_up(proj), now)
        if self._idle >= p.windows:
            return self._decide(self._scale_down(proj), now)
        return ScaleDecision("hold", "window", proj)

    def _decide(self, d: ScaleDecision, now: float) -> ScaleDecision:
        # K consecutive windows CONSUMED by a decision (acted or not):
        # the streak restarts, so an unactionable overload journals
        # once per cooldown instead of once per tick
        self._over = self._idle = 0
        self._last_decision = now
        self._decisions += 1
        self._journal(d)
        return d

    def _scale_up(self, proj: dict) -> ScaleDecision:
        from ..cluster import elastic

        coord = self.coordinator()
        if coord is None or not elastic.enabled():
            return ScaleDecision(
                "up", "overload", proj, acted=False,
                detail="no-coordinator" if coord is None else
                "elastic-off")
        p = self.policy
        if p.max_world is not None and coord.world >= p.max_world:
            return ScaleDecision("up", "overload", proj, acted=False,
                                 detail="at-max-world")
        pending = self.pending_joiners(coord)
        if not pending:
            # the demand signal: journaled for the operator / the
            # joiner-spawning supervisor — nothing to admit yet
            return ScaleDecision("up", "overload", proj, acted=False,
                                 detail="no-joiner")
        r = elastic.reform(coord, reason="scale-up",
                           ckpt_mgr=self.ckpt_mgr, restore=self.restore)
        if self._coordinator is not None:
            self._coordinator = r.coordinator
        return ScaleDecision("up", "overload", proj, acted=True,
                             detail=f"admitted={pending}",
                             gen=r.membership.gen)

    def _scale_down(self, proj: dict) -> ScaleDecision:
        coord = self.coordinator()
        if coord is None:
            return ScaleDecision("down", "idle", proj, acted=False,
                                 detail="no-coordinator")
        floor = max(self.policy.min_world, 1)
        if coord.world <= floor:
            return ScaleDecision("down", "idle", proj, acted=False,
                                 detail="at-min-world")
        # the designated leaver: the HIGHEST rank — its departure keeps
        # the survivors' dense reindex an identity map.  Every rank
        # computes the same decision from the same projection; only the
        # leaver flags itself (announce_leave publishes the planned
        # departure at ITS next step boundary)
        if coord.rank != coord.world - 1:
            return ScaleDecision("down", "idle", proj, acted=False,
                                 detail="not-leaver")
        coord.announce_leave()
        return ScaleDecision("down", "idle", proj, acted=True,
                             detail=f"leaving-rank={coord.rank}")

    def pending_joiners(self, coord=None) -> list:
        """Join slots waiting under the base namespace (the
        ``request_join`` queue the next reformation admits — parsed by
        the elastic layer's ONE key parser)."""
        from ..cluster.elastic import pending_join_slots

        coord = coord if coord is not None else self.coordinator()
        if coord is None:
            return []
        try:
            return pending_join_slots(coord.kv, coord.ns)
        except Exception:
            return []

    @staticmethod
    def _journal(d: ScaleDecision) -> None:
        from .. import obs

        if not obs.enabled():
            return
        obs.counter("serve.scale_decisions", direction=d.direction,
                    acted=str(bool(d.acted)).lower()).inc()
        obs.record_event(
            "serve.scale", direction=d.direction, reason=d.reason,
            projection=d.projection, acted=d.acted,
            **({"detail": d.detail} if d.detail else {}),
            **({"gen": d.gen} if d.gen is not None else {}))

    def _reset_for_tests(self) -> None:
        self._over = self._idle = 0
        self._last_decision = -float("inf")
        self._decisions = 0


# ---------------------------------------------------------------------------
# pre-warmed joining
# ---------------------------------------------------------------------------

def prewarm_plans(factories: Dict[str, Callable],
                  extra_dims: tuple = ()) -> dict:
    """Build and COMPILE every factory's plan now, so a joiner arrives
    warm: with ``PENCILARRAYS_TPU_COMPILE_CACHE`` set the XLA programs
    populate (or come from) the persistent compilation cache, and the
    post-join rebuild of the same fingerprints is a cache hit instead
    of a full compile.  Returns the measured warm-up report (also
    journaled as ``serve.scale{reason="prewarm"}`` — capacity
    preparation is a scaling event)."""
    import os

    from .. import obs

    t0 = time.perf_counter()
    per_plan = {}
    for name, factory in factories.items():
        t1 = time.perf_counter()
        plan = factory(None)
        plan.compile(extra_dims)
        per_plan[name] = time.perf_counter() - t1
    report = {
        "plans": len(factories),
        "warm_s": time.perf_counter() - t0,
        "per_plan_s": per_plan,
        "compile_cache": os.environ.get(
            "PENCILARRAYS_TPU_COMPILE_CACHE") or None,
    }
    if obs.enabled():
        obs.record_event("serve.scale", direction="up", reason="prewarm",
                         projection=report, acted=False)
    return report


def join_prewarmed(kv, slot: str, *,
                   factories: Optional[Dict[str, Callable]] = None,
                   namespace: str = "pa",
                   timeout: Optional[float] = None):
    """The joiner-side flow: pre-warm the registered plans, publish the
    join request, block until a reformation admits this slot, and
    re-register the factories with the elastic layer so every LATER
    reformation rebuilds them too.  Returns ``(Reformation, warm
    report)`` — the reformation's coordinator is live and installed,
    ready for ``elastic_step``/``PlanService`` traffic."""
    from ..cluster import elastic

    warm = prewarm_plans(factories) if factories else None
    r = elastic.request_join(kv, slot, namespace=namespace,
                             timeout=timeout)
    if factories:
        for name, factory in factories.items():
            elastic.register_plan(name, factory)
    return r, warm
