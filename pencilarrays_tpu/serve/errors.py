"""Typed errors of the plan-service layer.

The serve contract mirrors the guard's: failures surface as *typed*
errors scoped to the narrowest unit they poison — an admission decision
rejects ONE tenant's request, a detected corruption fails ONE batch's
tickets — never as a torn service or an unattributed exception on some
other tenant's future.
"""

from __future__ import annotations

__all__ = ["ServeError", "AdmissionError", "DeadlineError",
           "StaleRequestError", "ServiceClosedError"]


class ServeError(RuntimeError):
    """Base class of every serve-layer error."""


class AdmissionError(ServeError):
    """A tenant's request was rejected at admission (quota exceeded).

    Carries ``tenant`` and ``reason`` (``"queue-depth"``,
    ``"inflight-bytes"``, ``"hbm-limit"`` — a whale reshard for
    which even the chunk-synthesized route planner found no admissible
    route under the service's per-chip peak-HBM bound — or ``"shed"``:
    the overload gate sacrificed this sheddable-priority request, at
    submit or by evicting it from the queue, see
    :mod:`~pencilarrays_tpu.serve.shed`) so a client can
    distinguish back-off from a bug.  Admission rejections never enter
    the queue: they cost the service one counter bump and the caller
    one typed exception.  The one exception is ``reason="shed"`` on an
    *evicted* request, which WAS queued — its ticket fails typed with
    this error instead of ever dispatching.
    """

    def __init__(self, msg: str, *, tenant: str, reason: str):
        super().__init__(msg)
        self.tenant = tenant
        self.reason = reason


class DeadlineError(ServeError):
    """A request cannot (or could not) meet its tenant's SLO deadline
    (:class:`~pencilarrays_tpu.serve.slo.SLO`).

    ``reason`` says which enforcement point fired:

    * ``"projected"`` — at admission: the queue's own load projection
      (measured service rate over the priced cost queued ahead) says
      the request would complete after its deadline, so it is rejected
      up front — never a silent late answer;
    * ``"expired"`` — at take: the request's deadline passed while it
      sat in the queue; it is shed before dispatch (its ticket fails
      with this error) instead of burning mesh time on an answer
      nobody can use.

    Carries ``tenant``, ``reason``, ``deadline_s`` (the tenant's
    budget) and ``projected_s`` (the projection that condemned it;
    ``None`` on the expired path)."""

    def __init__(self, msg: str, *, tenant: str, reason: str,
                 deadline_s: float, projected_s=None):
        super().__init__(msg)
        self.tenant = tenant
        self.reason = reason
        self.deadline_s = deadline_s
        self.projected_s = projected_s


class StaleRequestError(ServeError):
    """A queued request's device payload is bound to a mesh that no
    longer backs its plan — e.g. the plan was rebuilt by an elastic
    reformation while the request sat in the queue.  Host-array
    payloads submitted against a *named* plan re-bind and survive
    (see :meth:`~pencilarrays_tpu.serve.PlanService.register_plan`);
    device arrays cannot, and fail typed instead of dispatching onto
    dead devices."""


class ServiceClosedError(ServeError):
    """Submit after :meth:`~pencilarrays_tpu.serve.PlanService.close`."""
