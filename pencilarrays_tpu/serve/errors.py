"""Typed errors of the plan-service layer.

The serve contract mirrors the guard's: failures surface as *typed*
errors scoped to the narrowest unit they poison — an admission decision
rejects ONE tenant's request, a detected corruption fails ONE batch's
tickets — never as a torn service or an unattributed exception on some
other tenant's future.
"""

from __future__ import annotations

__all__ = ["ServeError", "AdmissionError", "StaleRequestError",
           "ServiceClosedError"]


class ServeError(RuntimeError):
    """Base class of every serve-layer error."""


class AdmissionError(ServeError):
    """A tenant's request was rejected at admission (quota exceeded).

    Carries ``tenant`` and ``reason`` (``"queue-depth"``,
    ``"inflight-bytes"``, or ``"hbm-limit"`` — a whale reshard for
    which even the chunk-synthesized route planner found no admissible
    route under the service's per-chip peak-HBM bound) so a client can
    distinguish back-off from a bug.  Admission rejections never enter
    the queue: they cost the service one counter bump and the caller
    one typed exception.
    """

    def __init__(self, msg: str, *, tenant: str, reason: str):
        super().__init__(msg)
        self.tenant = tenant
        self.reason = reason


class StaleRequestError(ServeError):
    """A queued request's device payload is bound to a mesh that no
    longer backs its plan — e.g. the plan was rebuilt by an elastic
    reformation while the request sat in the queue.  Host-array
    payloads submitted against a *named* plan re-bind and survive
    (see :meth:`~pencilarrays_tpu.serve.PlanService.register_plan`);
    device arrays cannot, and fail typed instead of dispatching onto
    dead devices."""


class ServiceClosedError(ServeError):
    """Submit after :meth:`~pencilarrays_tpu.serve.PlanService.close`."""
