"""The precision-downgrade ladder — wire precision as a serving lever.

Under overload the PR-15 pressure gate had exactly two sacrifices:
reject sheddable traffic at submit, or evict it queued.  PR 19 adds a
rung BEFORE both: serve the request anyway, on a cheaper wire format
(full -> ``bf16`` -> ``fp8_e4m3``), within an accuracy budget the
tenant declared up front (:class:`~pencilarrays_tpu.serve.slo.SLO.
max_rel_l2`).  A degraded answer inside the tenant's own tolerance
beats a typed rejection every time — but ONLY inside that tolerance,
which is why the rung selection is driven by a *calibrated* error
envelope, not by the wire format's nominal epsilon:

* the envelope for each rung is read from ``BENCH_WIRE.json`` (the
  measured wire-precision benchmark artifact at the repo root, same
  loader discipline as ``PIPELINE_SWEEP.json`` — env override
  ``PENCILARRAYS_TPU_BENCH_WIRE_PATH``, mtime-invalidated): the worst
  measured relative l2 error across every recorded section (plan
  roundtrip, Navier-Stokes and diffusion workloads), doubled as a
  safety margin;
* with no artifact captured yet, conservative fallback constants
  apply — deliberately pessimistic, so an uncalibrated service
  downgrades less, never out of tolerance;
* :func:`select_rung` picks the DEEPEST (cheapest-wire) rung whose
  envelope fits under the tenant's ``max_rel_l2`` and that is strictly
  cheaper than the plan's current wire — a plan already on ``bf16``
  either drops to fp8 (budget permitting) or is left alone.

The service journals every applied downgrade as a fsync-critical
``serve.precision`` record (schema v7) carrying the envelope it
promised and the budget it fit under, so ``pa-obs request <trace>``
reconstructs exactly what precision a degraded answer was served at
and why that was within contract.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

__all__ = ["PRECISION_LADDER", "wire_error_envelope", "select_rung",
           "wire_depth"]

# the default ladder, shallowest first.  e5m2 is omitted: it costs the
# same bytes as e4m3 with half the mantissa — there is no load level at
# which it is the right trade for served FFT traffic (it exists for
# gradient-shaped dynamic range, selectable via a custom ladder).
PRECISION_LADDER: Tuple[str, ...] = ("bf16", "fp8_e4m3")

# how "deep" (cheap) each wire is: full precision 0, 16-bit wires 1,
# fp8 wires 2.  select_rung only ever moves strictly deeper.
_WIRE_DEPTH = {None: 0, "bf16": 1, "f16": 1,
               "fp8_e4m3": 2, "fp8_e5m2": 2}

# calibrated-fallback envelopes (relative l2), used only when no
# BENCH_WIRE.json exists: ~2x the worst error measured on the dev CPU
# mesh across plan roundtrips and the NS/diffusion workloads.
_FALLBACK_ENVELOPE = {"bf16": 2.5e-2, "f16": 3.0e-3,
                      "fp8_e4m3": 8.0e-2, "fp8_e5m2": 1.6e-1}

_SAFETY = 2.0   # margin over the worst measured rel-l2 in the artifact


def wire_depth(wire_dtype: Optional[str]) -> int:
    """Ladder depth of a canonical wire spelling (0 = full precision)."""
    return _WIRE_DEPTH.get(wire_dtype, 0)


def wire_error_envelope(wire_dtype: str) -> Optional[float]:
    """The calibrated worst-case relative l2 error of serving on
    ``wire_dtype``: ``_SAFETY`` x the largest ``rel_err_l2`` recorded
    for that format anywhere in ``BENCH_WIRE.json`` (plan-roundtrip and
    workload sections alike), or the conservative fallback constant
    when no artifact has been captured.  ``None`` for a format with
    neither (never downgraded onto)."""
    from ..parallel.wire import canonical_wire_dtype
    from ..utils.artifacts import load_verdict_artifact

    wire = canonical_wire_dtype(wire_dtype)
    doc = load_verdict_artifact("BENCH_WIRE.json",
                                "PENCILARRAYS_TPU_BENCH_WIRE_PATH")
    worst = None
    if isinstance(doc, dict):
        for section in doc.values():
            if not isinstance(section, dict):
                continue
            rec = section.get(wire)
            if isinstance(rec, dict) and "rel_err_l2" in rec:
                err = float(rec["rel_err_l2"])
                worst = err if worst is None else max(worst, err)
    if worst is not None and worst > 0:
        return _SAFETY * worst
    return _FALLBACK_ENVELOPE.get(wire)


def select_rung(max_rel_l2: float, current_wire: Optional[str] = None,
                ladder: Sequence[str] = PRECISION_LADDER
                ) -> Optional[Tuple[str, float]]:
    """The deepest ladder rung whose calibrated envelope fits under
    ``max_rel_l2`` AND that is strictly deeper (cheaper wire) than
    ``current_wire``.  Returns ``(wire, envelope)`` or ``None`` when no
    admissible downgrade exists (budget too tight, plan already at its
    floor, or the fp8 element types missing on this jax build — a rung
    the backend cannot represent is silently skipped, never an
    admission-path crash)."""
    from ..parallel.wire import canonical_wire_dtype
    from ..utils.jaxcompat import WireDtypeError

    depth = wire_depth(current_wire)
    best = None
    for rung in ladder:
        try:
            wire = canonical_wire_dtype(rung)
        except (WireDtypeError, ValueError, TypeError):
            continue
        if wire_depth(wire) <= depth:
            continue
        envelope = wire_error_envelope(wire)
        if envelope is not None and envelope <= max_rel_l2:
            best = (wire, envelope)     # keep going: deepest rung wins
    return best
