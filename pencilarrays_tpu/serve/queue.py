"""Admission + coalescing queue — the service's scheduling brain.

Three responsibilities, all deterministic (a multi-controller mesh runs
one service instance per rank, and every rank must make IDENTICAL
batching and ordering decisions from the same submission sequence —
wall clocks only gate *when* a batch becomes ready, never how batches
are formed or ordered relative to each other):

* **admission** — per-tenant quotas (queue depth, in-flight logical
  bytes) checked at :meth:`offer`; violations raise typed
  :class:`~pencilarrays_tpu.serve.errors.AdmissionError` and never
  enter the queue;
* **coalescing** — same-fingerprint requests (same ``plan_key`` ×
  direction, or same reshard route) group along ``extra_dims`` into
  one batched dispatch: bytes ×B, collective count ×1 — the PR 9
  batched-plan amortization, applied to *traffic* instead of a
  caller-declared batch.  A group dispatches when it reaches
  ``max_batch`` or its oldest request has waited ``max_wait_s``
  (a flush takes everything, ragged final batch included);
* **cost ordering** — ready batches dispatch cheapest-first in the
  ``collective_costs`` currency (``count * latency_bytes + bytes``,
  the Auto/route-planner score), so a small tenant's request is never
  starved behind a huge plan's traffic.  Anti-starvation: a batch
  whose oldest request has waited ``starve_after_s`` jumps the cost
  order (FIFO among the starved), so expensive batches are delayed,
  never parked forever.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from .errors import AdmissionError, ServiceClosedError
from .slo import LoadTracker

__all__ = ["Ticket", "TenantQuota", "Batch", "AdmissionQueue"]

_ids = itertools.count(1)


class Ticket:
    """A submitted request's future: :meth:`result` blocks until the
    service fulfilled or failed it (typed errors re-raise here — an
    :class:`~pencilarrays_tpu.guard.IntegrityError` detected inside
    this request's batch surfaces on THIS ticket, nobody else's)."""

    def __init__(self, tenant: str, kind: str, key: str):
        self.id = next(_ids)
        self.tenant = tenant
        self.kind = kind
        self.key = key
        self.t_submit = time.monotonic()
        self.t_dispatch: Optional[float] = None
        self.t_done: Optional[float] = None
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """The request's output array; raises the request's typed error
        (or ``TimeoutError`` if the service has not resolved it yet)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} (tenant {self.tenant!r}) not done")
        if self._error is not None:
            raise self._error
        return self._result

    def error(self) -> Optional[BaseException]:
        """The failure, if the request failed (None while pending/ok)."""
        return self._error

    def _fulfill(self, result) -> None:
        self.t_done = time.monotonic()
        self._result = result
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self.t_done = time.monotonic()
        self._error = error
        self._event.set()


@dataclass(frozen=True)
class TenantQuota:
    """Admission limits of one tenant: pending+executing request count
    and pending+executing logical payload bytes (global, unpadded —
    what the tenant asked to move, not what the mesh pads it to)."""

    max_requests: int = 1024
    max_bytes: int = 1 << 34    # 16 GiB of queued traffic per tenant


@dataclass
class _Entry:
    """One queued request (internal)."""

    ticket: Ticket
    plan: object                  # PencilFFTPlan, or None for reshard
    direction: str                # "forward" | "backward" (fft)
    payload: object               # PencilArray | host array
    nbytes: int
    plan_name: Optional[str]      # named (elastic-rebindable) plans
    dest: object = None           # reshard destination Pencil
    method: object = None         # reshard method
    seq: int = 0                  # admission order (deterministic ties)
    deadline: Optional[float] = None  # absolute monotonic SLO deadline
    shed_priority: int = 0        # the tenant's SLO shed tier
    cost_bytes: int = 0           # priced B=1 cost (projection currency)
    departed: bool = False        # left _pending (lazy SLO-heap skip)
    trace: Optional[str] = None   # request trace context (schema v6)


@dataclass
class Batch:
    """A ready-to-dispatch coalesced group."""

    key: str
    kind: str                     # "fft" | "reshard"
    entries: List[_Entry]
    reason: str                   # "full" | "deadline" | "flush"
    cost: int = 0                 # bytes-equivalent score (set by queue)
    seq: int = 0                  # first entry's admission order
    resubmits: int = 0            # engine-reformation resubmission count
    # (a taken batch dropped typed by Engine.reform re-enters the
    # reformed engine instead of stranding its tickets — bounded)

    @property
    def tickets(self) -> List[Ticket]:
        return [e.ticket for e in self.entries]


class AdmissionQueue:
    """The deterministic admission/coalescing/ordering core (see module
    docstring).  Thread-safe; scheduling state never leaves the lock."""

    def __init__(self, *, max_batch: int = 8, max_wait_s: float = 0.002,
                 starve_after_s: float = 1.0,
                 default_quota: Optional[TenantQuota] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 hbm_limit: Optional[int] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.starve_after_s = float(starve_after_s)
        # per-chip peak-HBM bound the service's reshard traffic is
        # planned under (PlanService(hbm_limit=)): batch pricing plans
        # with it so the cost the scheduler orders by is the cost of
        # the route that will actually dispatch (chunk-synthesized
        # whale routes price their count xK)
        self.hbm_limit = int(hbm_limit) if hbm_limit is not None else None
        self.default_quota = default_quota or TenantQuota()
        self.quotas = dict(quotas or {})
        self._lock = threading.Lock()
        self._closed = False
        self._seq = itertools.count(1)
        # coalesce key -> entries in admission order
        self._pending: Dict[str, List[_Entry]] = {}
        # per-tenant accounting: requests/bytes admitted and not yet
        # completed (queued + executing)
        self._tenant_requests: Dict[str, int] = {}
        self._tenant_bytes: Dict[str, int] = {}
        # the queue's own arrival/cost/service history — THE load
        # projection admission deadlines, the shedding gate and the
        # autoscaler all read (serve/slo.py)
        self.load = LoadTracker()
        # per-coalesce-key B=1 price cache (the projection currency is
        # priced once per distinct traffic shape, not once per request)
        self._key_cost: Dict[str, int] = {}
        # entries shed at the take point (SLO deadline expired while
        # queued) — the service pops these and fails their tickets typed
        self._expired: List[_Entry] = []
        # -- the take-path index (depth-stress fix) --
        # The v1 take path rescanned EVERY pending group per tick:
        # O(groups) per call, superlinear across a burst (ROADMAP's
        # 10^4-entry flag; pinned by tests/test_serve_depth.py).  The
        # take now touches only groups that can actually yield work:
        # _full — groups at max_batch (maintained at offer/take);
        # _due_heap — (coalesce deadline, tiebreak, key), lazily
        # validated (a popped key whose LIVE head is due later is
        # re-pushed, a dead key is dropped); _slo_heap — (SLO deadline,
        # seq, entry), lazily skipping departed entries.  Batch
        # formation and dispatch order are untouched — the index
        # changes WHAT is scanned, never what is taken or how it sorts.
        self._full: set = set()
        self._due_heap: list = []
        self._slo_heap: list = []
        self._heap_seq = itertools.count(1)
        # scan accounting (the scaling assertion's deterministic pin)
        self._take_calls = 0
        self._groups_scanned = 0
        # -- the depth index (load-export fix) --
        # depth() sits on the fleet worker's 50ms load-export path
        # (service.load_projection -> publish_load): the v1 body
        # re-counted every queued entry per call — O(depth) per export,
        # superlinear across a burst.  Queued-entry counts (distinct
        # from _tenant_requests/_bytes, which also cover EXECUTING
        # work and release at completion) are now maintained at offer
        # and at every _pending departure; depth() just reads them.
        # depth_entries_scanned stays 0 on the O(1) path — the
        # scaling assertion's pin (reintroducing a scan must bump it).
        self._depth_total = 0
        self._depth_tenant: Dict[str, int] = {}
        self._depth_entries_scanned = 0

    # -- admission ---------------------------------------------------------
    def quota_for(self, tenant: str) -> TenantQuota:
        return self.quotas.get(tenant, self.default_quota)

    def offer(self, entry: _Entry) -> bool:
        """Admit one request or raise typed
        :class:`~pencilarrays_tpu.serve.errors.AdmissionError`.
        Returns True when this admission brought its coalesce group to
        a full ``max_batch`` — the streaming pump's fast-path signal
        (a full batch gains nothing by waiting out the deadline),
        known for free at append time."""
        t = entry.ticket.tenant
        q = self.quota_for(t)
        with self._lock:
            if self._closed:
                # checked under the SAME lock close_gate() takes, so a
                # submit racing close() is rejected typed — it can
                # never land after the service's final drain pass
                raise ServiceClosedError("service is closed")
            n = self._tenant_requests.get(t, 0)
            b = self._tenant_bytes.get(t, 0)
            if n + 1 > q.max_requests:
                raise AdmissionError(
                    f"tenant {t!r}: queue depth {n} at quota "
                    f"({q.max_requests} requests)", tenant=t,
                    reason="queue-depth")
            if b + entry.nbytes > q.max_bytes:
                raise AdmissionError(
                    f"tenant {t!r}: {b + entry.nbytes} in-flight bytes "
                    f"would exceed quota ({q.max_bytes})", tenant=t,
                    reason="inflight-bytes")
            entry.seq = next(self._seq)
            entry.departed = False
            self._tenant_requests[t] = n + 1
            self._tenant_bytes[t] = b + entry.nbytes
            self._depth_total += 1
            self._depth_tenant[t] = self._depth_tenant.get(t, 0) + 1
            group = self._pending.setdefault(entry.ticket.key, [])
            group.append(entry)
            if len(group) == 1:
                # the group's coalescing deadline enters the index once,
                # at formation; a remainder left by a take re-pushes
                heapq.heappush(self._due_heap, (
                    entry.ticket.t_submit + self.max_wait_s,
                    next(self._heap_seq), entry.ticket.key))
            if entry.deadline is not None:
                heapq.heappush(self._slo_heap,
                               (entry.deadline, entry.seq, entry))
            self.load.note_arrival(entry.cost_bytes)
            full = len(group) >= self.max_batch
            if full:
                self._full.add(entry.ticket.key)
            return full

    def close_gate(self) -> None:
        """Refuse all future :meth:`offer` calls (atomic with the offer
        path's lock — nothing can slip in after this returns)."""
        with self._lock:
            self._closed = True

    def _depart_locked(self, entry: _Entry) -> None:
        """One entry leaves ``_pending`` (taken, shed or evicted):
        flag it for the lazy heaps and settle the depth index.  Every
        departure path MUST come through here — the depth counters are
        only as honest as their bookkeeping.  Caller holds the lock."""
        entry.departed = True
        t = entry.ticket.tenant
        self._depth_total -= 1
        left = self._depth_tenant.get(t, 0) - 1
        if left > 0:
            self._depth_tenant[t] = left
        else:
            self._depth_tenant.pop(t, None)

    def release(self, entry: _Entry) -> None:
        """Return one request's quota (called at completion, ok or
        failed — the quota covers queued *and* executing work)."""
        t = entry.ticket.tenant
        with self._lock:
            self._tenant_requests[t] = max(
                0, self._tenant_requests.get(t, 0) - 1)
            self._tenant_bytes[t] = max(
                0, self._tenant_bytes.get(t, 0) - entry.nbytes)

    # -- batching ----------------------------------------------------------
    def take_ready(self, *, flush: bool = False,
                   now: Optional[float] = None) -> List[Batch]:
        """Pop every ready batch, ordered for dispatch.

        Readiness: a full ``max_batch`` group is always ready; a
        partial group is ready once its oldest member waited
        ``max_wait_s`` (or immediately under ``flush`` — the ragged
        final batch of a drain).  Ordering: starved batches first (in
        admission order), then ascending priced cost, admission order
        breaking ties — deterministic for identical submission
        sequences regardless of wall clocks.

        SLO take-point enforcement: entries whose deadline expired
        while queued are shed BEFORE batch formation (an expired
        request must not burn mesh time that makes its neighbors late
        too) — the service pops them via :meth:`pop_expired` and fails
        their tickets typed ``DeadlineError(reason="expired")``."""
        now = time.monotonic() if now is None else now
        out: List[Batch] = []
        with self._lock:
            self._take_calls += 1
            keys = (list(self._pending) if flush
                    else self._due_keys_locked(now))
            self._groups_scanned += len(keys)
            for key in keys:
                self._take_key_locked(key, now, flush, out)
        for b in out:
            b.cost = self._batch_cost(b)
            for e in b.entries:
                self.load.note_taken(e.cost_bytes)

        def order(b: Batch):
            starved = (now - b.entries[0].ticket.t_submit
                       >= self.starve_after_s)
            return (0, b.seq) if starved else (1, b.cost, b.seq)

        out.sort(key=order)
        return out

    def _take_key_locked(self, key: str, now: float, flush: bool,
                         out: List[Batch]) -> None:
        """The v1 per-group take body, verbatim semantics: shed
        deadline-expired members, split full batches, take the rest if
        due (or flushing).  Caller holds the lock and picked ``key``
        from the index (or the full scan, under flush)."""
        entries = self._pending.get(key)
        if entries is None:
            return
        live = [e for e in entries
                if e.deadline is None or now <= e.deadline]
        if len(live) != len(entries):
            for e in entries:
                if e.deadline is not None and now > e.deadline:
                    self._depart_locked(e)
                    self._expired.append(e)
                    self.load.note_removed(e.cost_bytes)
            entries = live
            self._pending[key] = entries
        while len(entries) >= self.max_batch:
            take, entries = (entries[: self.max_batch],
                             entries[self.max_batch:])
            self._pending[key] = entries
            for e in take:
                self._depart_locked(e)
            out.append(self._mk_batch(key, take, "full"))
        if entries and (flush or now - entries[0].ticket.t_submit
                        >= self.max_wait_s):
            del self._pending[key]
            for e in entries:
                self._depart_locked(e)
            out.append(self._mk_batch(
                key, entries, "flush" if flush else "deadline"))
        elif not entries:
            del self._pending[key]
        if key in self._full and \
                len(self._pending.get(key, ())) < self.max_batch:
            self._full.discard(key)
        remainder = self._pending.get(key)
        if remainder:
            # the survivors' coalescing deadline re-enters the index
            # (their original due entry was consumed popping this key)
            heapq.heappush(self._due_heap, (
                remainder[0].ticket.t_submit + self.max_wait_s,
                next(self._heap_seq), key))

    def _due_keys_locked(self, now: float) -> List[str]:
        """Every key that can yield work at ``now``: full groups,
        groups whose coalescing deadline passed, and groups holding an
        SLO-expired entry (the take-point shed must fire even when the
        group itself is not due).  O(due + full + log n), NOT
        O(groups) — the depth-stress fix.  Caller holds the lock."""
        keys: List[str] = []
        seen = set()
        while self._slo_heap and self._slo_heap[0][0] <= now:
            _, _, entry = heapq.heappop(self._slo_heap)
            if entry.departed:
                continue
            k = entry.ticket.key
            if k in self._pending and k not in seen:
                seen.add(k)
                keys.append(k)
        for k in self._full:
            if k not in seen:
                seen.add(k)
                keys.append(k)
        while self._due_heap and self._due_heap[0][0] <= now:
            _, _, k = heapq.heappop(self._due_heap)
            group = self._pending.get(k)
            if not group:
                continue        # stale: the group was fully taken
            actual = group[0].ticket.t_submit + self.max_wait_s
            if actual > now:
                # stale-but-live: the head that set this deadline left;
                # re-index at the live head's deadline
                heapq.heappush(self._due_heap,
                               (actual, next(self._heap_seq), k))
                continue
            if k not in seen:
                seen.add(k)
                keys.append(k)
        return keys

    def scan_stats(self) -> dict:
        """Take-path scan accounting — ``groups_scanned`` across
        ``take_calls`` is what the depth-stress scaling assertion pins
        (it must track DUE work, not queue breadth).
        ``depth_entries_scanned`` pins the depth-index fix the same
        way: it must stay 0 no matter how often :meth:`depth` is
        polled at depth (the load-export path reads counters, never
        rescans the queue)."""
        with self._lock:
            return {"take_calls": self._take_calls,
                    "groups_scanned": self._groups_scanned,
                    "depth_entries_scanned": self._depth_entries_scanned}

    @staticmethod
    def _mk_batch(key: str, entries: List[_Entry], reason: str) -> Batch:
        e0 = entries[0]
        kind = "reshard" if e0.plan is None else "fft"
        return Batch(key=key, kind=kind, entries=list(entries),
                     reason=reason, seq=e0.seq)

    def pop_expired(self) -> List[_Entry]:
        """Entries shed at the take point since the last pop (admission
        order) — the service fails their tickets typed."""
        with self._lock:
            out, self._expired = self._expired, []
        out.sort(key=lambda e: e.seq)
        return out

    def evict_sheddable(self, protected_priority: int) -> List[_Entry]:
        """The pressure gate's second rung: remove every queued entry
        whose ``shed_priority`` is strictly below the protected tier
        and return them in admission-sequence order — deterministic in
        the submission sequence (identical submissions evict identical
        sets; the clock only gates WHEN the rung fires).  The service
        fails their tickets typed ``AdmissionError(reason="shed")``."""
        evicted: List[_Entry] = []
        with self._lock:
            for key in list(self._pending):
                entries = self._pending[key]
                keep = [e for e in entries
                        if e.shed_priority >= protected_priority]
                if len(keep) != len(entries):
                    for e in entries:
                        if e.shed_priority < protected_priority:
                            self._depart_locked(e)
                            evicted.append(e)
                            self.load.note_removed(e.cost_bytes)
                    if keep:
                        self._pending[key] = keep
                    else:
                        del self._pending[key]
                    if len(keep) < self.max_batch:
                        self._full.discard(key)
        evicted.sort(key=lambda e: e.seq)
        return evicted

    def note_batch_done(self, batch: Batch, execute_s: float) -> None:
        """Feed one finished dispatch into the load tracker (ok or
        failed — the wall time was equally real either way)."""
        cost = sum(e.cost_bytes for e in batch.entries)
        self.load.note_completed(cost, len(batch.entries), execute_s)

    def note_entry_done(self, entry: _Entry) -> None:
        """Clear ONE taken entry's in-flight accounting without a rate
        sample (a validation loser fails before any device time is
        spent; leaving its cost in flight would inflate every drain
        projection forever)."""
        self.load.note_completed(entry.cost_bytes, 1, 0.0)

    def entry_cost(self, entry: _Entry) -> int:
        """Price one request in the projection currency (the B=1 batch
        score), cached per coalesce key — hbm-bounded solo reshards
        share their fingerprint prefix's price.  Traffic the router
        prices at zero (a single-device mesh moves no wire bytes)
        falls back to the logical payload bytes: the PROJECTION must
        stay meaningful on any mesh, while dispatch ordering keeps the
        router score untouched (zero-cost batches still tie
        head-of-line there)."""
        key = entry.ticket.key.split("#solo", 1)[0]
        with self._lock:
            cached = self._key_cost.get(key)
        if cached is not None:
            return cached
        cost = self._batch_cost(self._mk_batch(
            entry.ticket.key, [entry], "price"))
        if cost <= 0:
            cost = max(1, entry.nbytes)
        with self._lock:
            self._key_cost[key] = cost
        return cost

    # -- pricing -----------------------------------------------------------
    def _batch_cost(self, batch: Batch) -> int:
        """Bytes-equivalent dispatch cost of the whole batch — the
        mixed-traffic ordering currency (the route-planner score at the
        coalesced ``extra_dims``: ``count * latency_bytes +
        drift-corrected bytes``, for fft and reshard alike).  NEVER
        raises: unpriceable
        batches (Gspmd hops, any pricing failure) cost 0 and dispatch
        first — the model cannot rank what it cannot see, head-of-line
        is the safe default, and a pricing bug must not wedge the
        dispatch loop (``take_ready`` is on the service's only
        scheduling path)."""
        try:
            return self._batch_cost_inner(batch)
        except Exception:
            return 0

    def _batch_cost_inner(self, batch: Batch) -> int:
        from ..parallel.transpositions import Auto

        B = len(batch.entries)
        extra = (B,) if B > 1 else ()
        e0 = batch.entries[0]
        if batch.kind == "fft":
            # price with the decomposition scorer — the SAME
            # drift-corrected route-planner currency the reshard branch
            # gets from plan_reshard_route, at the plan's own configured
            # method latency; fft and reshard batches must sort in one
            # currency or cheapest-first inverts on mixed traffic
            from ..ops.fft import _schedule_score
            from ..parallel.routing import trusted_drift_hops

            method = e0.plan.method
            latency = (method.latency_bytes if isinstance(method, Auto)
                       else Auto().latency_bytes)
            entry = _schedule_score(e0.plan, extra, latency,
                                    trusted_drift_hops())
            return int(entry["score_bytes"])
        # reshard: the route planner's own score (drift-corrected,
        # HBM-bounded when the service carries a limit — a whale
        # batch's chunk-synthesized route prices its count xK), or the
        # priced GSPMD baseline on fallback
        from ..parallel.routing import plan_reshard_route

        route = plan_reshard_route(e0.payload.pencil, e0.dest, extra,
                                   e0.payload.dtype, method=e0.method,
                                   hbm_limit=self.hbm_limit)
        if route.use_route and route.score_bytes is not None:
            return int(route.score_bytes)
        return int(route.gspmd_score_bytes or 0)

    # -- introspection -----------------------------------------------------
    def next_ready_in(self, now: Optional[float] = None
                      ) -> Optional[float]:
        """Seconds until the OLDEST pending group's coalescing
        deadline (0.0 when already due; None when nothing is
        pending) — the streaming pump re-arms at this instead of a
        fresh full ``max_wait_s``, so a group admitted just after a
        tick never waits ~2x its deadline.  SLO deadlines feed the
        same bound (the deadline-aware pump tick): a queued entry
        about to expire wakes the pump so the take-point shed fails
        its ticket promptly instead of after a full coalescing wait."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._pending:
                return None
            due = None
            while self._due_heap:
                d, _, k = self._due_heap[0]
                group = self._pending.get(k)
                if not group:
                    heapq.heappop(self._due_heap)
                    continue
                actual = group[0].ticket.t_submit + self.max_wait_s
                if actual > d:
                    # stale head: re-index at the live head's deadline
                    heapq.heappop(self._due_heap)
                    heapq.heappush(self._due_heap,
                                   (actual, next(self._heap_seq), k))
                    continue
                due = d
                break
            while self._slo_heap and self._slo_heap[0][2].departed:
                heapq.heappop(self._slo_heap)
            if self._slo_heap:
                sd = self._slo_heap[0][0]
                due = sd if due is None else min(due, sd)
        # every nonempty group holds a due-heap entry (pushed at
        # formation and at every remainder), so due is None only when
        # _pending emptied between the check and the walk — impossible
        # under the lock; the guard is belt-and-braces
        return max(0.0, due - now) if due is not None else None

    def depth(self, tenant: Optional[str] = None) -> int:
        """Queued entries, total or for one tenant — O(1) from the
        depth index (this sits on the fleet load-export path, polled
        every 50ms per mesh; see ``_depart_locked``)."""
        with self._lock:
            if tenant is None:
                return self._depth_total
            return self._depth_tenant.get(tenant, 0)

    def tenants(self) -> Dict[str, dict]:
        """Per-tenant accounting snapshot (admitted, not yet done)."""
        with self._lock:
            names = set(self._tenant_requests) | set(self._tenant_bytes)
            return {t: {"requests": self._tenant_requests.get(t, 0),
                        "bytes": self._tenant_bytes.get(t, 0)}
                    for t in sorted(names)}

    def pending_entries(self) -> List[_Entry]:
        """Snapshot of queued entries (rebind support)."""
        with self._lock:
            return [e for v in self._pending.values() for e in v]
