"""Plan registry — one resident executable per plan fingerprint.

The service's tenants describe *what* they want transformed; the
registry makes sure equivalent descriptions share ONE compiled
executable.  Keys are :meth:`~pencilarrays_tpu.ops.fft.PencilFFTPlan.
plan_key` fingerprints — deterministic across processes and jax
restarts (the same digest family the obs journal stamps as ``plan_fp``
and the crash bundle records as ``schedule_sha256``), so two tenants
that each built their own ``PencilFFTPlan`` over the same
``(global_shape, dtype, topology, schedule)`` configuration resolve to
the same registry entry and the same ``CompiledPlan``.

Cache accounting rides the existing ``compile.cache_hits|misses``
counters with a ``cache="serve"`` label and a per-tenant dimension.
A registry hit short-circuits :meth:`PencilFFTPlan.compile` entirely,
and the miss path calls it with its own plan-level counter suppressed
(``_counters=False``) — one resolve, one count, never the
double-count a naive delegation would produce (plan-level ``cache=
"plan"`` counters keep counting direct ``plan.compile()`` callers
only).

Rebind semantics (the elastic-reformation contract): ``register(plan)``
dedups on the fingerprint — first registration wins and callers use the
returned *canonical* plan — while ``register(plan, replace=True)``
swaps the stored plan object AND drops every compiled executable under
that key: a rebuilt plan has the same fingerprint (same static
configuration) but lives on a NEW mesh, and a cached executable from
the dead mesh must never be dispatched again.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["PlanRegistry"]


class PlanRegistry:
    """Fingerprint-keyed store of plans and their compiled executables."""

    def __init__(self):
        self._lock = threading.Lock()
        # key -> plan (the canonical object for that fingerprint)
        self._plans: Dict[str, object] = {}
        # (key, extra_dims, donate) -> CompiledPlan
        self._compiled: Dict[tuple, object] = {}
        self._hits = 0
        self._misses = 0

    # -- plans -------------------------------------------------------------
    def register(self, plan, *, replace: bool = False):
        """Register ``plan`` under its :meth:`plan_key` and return the
        canonical plan for that key (the first-registered object, unless
        ``replace=True`` swaps it and invalidates the key's compiled
        executables — the elastic rebuild path)."""
        key = plan.plan_key()
        with self._lock:
            cur = self._plans.get(key)
            if cur is not None and not replace:
                return cur
            if cur is not None and cur is not plan:
                self._drop_compiled_locked(key)
            self._plans[key] = plan
            return plan

    def plan(self, key: str):
        """The canonical plan registered under ``key`` (None if absent)."""
        return self._plans.get(key)

    def keys(self) -> Tuple[str, ...]:
        return tuple(self._plans)

    def _drop_compiled_locked(self, key: str) -> int:
        stale = [k for k in self._compiled if k[0] == key]
        for k in stale:
            del self._compiled[k]
        return len(stale)

    def drop_executables(self, key: Optional[str] = None) -> int:
        """Drop compiled executables (all of them, or one key's) —
        refilled on demand.  Returns how many were discarded."""
        with self._lock:
            if key is not None:
                return self._drop_compiled_locked(key)
            n = len(self._compiled)
            self._compiled.clear()
            return n

    # -- executables -------------------------------------------------------
    def compiled(self, plan, extra_dims: Tuple[int, ...] = (), *,
                 donate: bool = False,
                 tenants: Sequence[str] = ()) -> object:
        """Resolve the ``CompiledPlan`` for ``(plan_key, extra_dims,
        donate)``, compiling on first use.  ``tenants`` attributes the
        hit/miss counters: one ``compile.cache_{hits|misses}{cache=
        "serve", tenant=...}`` bump per requesting tenant (a coalesced
        batch spans tenants; each of them experienced the hit)."""
        key = plan.plan_key()
        sub = (key, tuple(int(e) for e in extra_dims), bool(donate))
        with self._lock:
            self._plans.setdefault(key, plan)
            cp = self._compiled.get(sub)
        hit = cp is not None
        if not hit:
            # compile OUTSIDE the registry lock (an XLA trace+compile
            # can take seconds — another tenant's cache hit must not
            # queue behind it) and with the plan-level counter
            # suppressed: THIS resolve is the one cache event
            # (satellite fix — a serve miss used to count under
            # cache="plan" too).  A racing miss double-compiles once
            # (plan.compile's own per-plan cache dedups the executable)
            # and the first insert wins.
            new = plan.compile(sub[1], donate=donate, _counters=False)
            with self._lock:
                cp = self._compiled.setdefault(sub, new)
        with self._lock:
            self._hits += hit
            self._misses += not hit
        from .. import obs

        if obs.enabled():
            name = f"compile.cache_{'hits' if hit else 'misses'}"
            for t in (tenants or ("-",)):
                obs.counter(name, cache="serve", tenant=str(t)).inc()
        return cp

    def executables(self, key: Optional[str] = None) -> Tuple[object, ...]:
        """The resident :class:`~pencilarrays_tpu.ops.fft.CompiledPlan`
        executables (one key's, or all) — what a pre-flight
        certification sweep (``PlanService.certify()``) walks."""
        with self._lock:
            return tuple(cp for k, cp in self._compiled.items()
                         if key is None or k[0] == key)

    def stats(self) -> dict:
        with self._lock:
            return {"plans": len(self._plans),
                    "executables": len(self._compiled),
                    "hits": self._hits, "misses": self._misses}
