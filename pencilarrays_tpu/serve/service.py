"""The multi-tenant plan service — concurrent FFT/reshard workloads on
one resident mesh.

Every ingredient exists in the layers below; this module is the thin,
deterministic loop that composes them into a *service*:

* the **registry** (:mod:`~pencilarrays_tpu.serve.registry`) resolves
  each request's plan fingerprint to ONE resident
  :class:`~pencilarrays_tpu.ops.fft.CompiledPlan` executable, shared
  across tenants (``compile.cache_*{cache="serve"}`` counters);
* the **admission queue** (:mod:`~pencilarrays_tpu.serve.queue`)
  enforces per-tenant quotas, coalesces same-fingerprint requests
  along ``extra_dims`` into one batched dispatch (bytes ×B, collective
  count ×1 — the PR 9 amortization applied to live traffic), and
  orders mixed-plan batches by their ``collective_costs`` price so
  small requests are not starved behind huge ones;
* every batch dispatch runs under
  :func:`~pencilarrays_tpu.guard.recover.guarded_step` — the
  **isolation path**: a detected corruption (SDC probe mismatch, hang
  watchdog) inside one batch surfaces as a typed
  :class:`~pencilarrays_tpu.guard.IntegrityError` on THAT batch's
  tickets, after the ladder's retries; queued batches — other
  tenants' or the same tenant's later traffic — dispatch next,
  unpoisoned.  With the integrity guard armed
  (``PENCILARRAYS_TPU_GUARD``), dispatch takes the *eager* schedule
  (per-hop invariant probes, the instrumented path); with it off, the
  registry's single-dispatch compiled executable (the fast path);
* execution rides the per-mesh **engine**
  (:mod:`~pencilarrays_tpu.engine`): every batch becomes one ordered
  dispatch-queue task — the batch's host-side packing (the numpy
  stack of host payloads) runs on the engine's host pool, OVERLAPPED
  with the previous batch's device compute, and the device program is
  issued by the engine's single consumer thread in take-order, so the
  SPMD collective-ordering invariant holds by construction
  (``certify(engine=True)`` proves it post-hoc via
  :func:`~pencilarrays_tpu.analysis.spmd.verify_dispatch_log`).
  Streaming mode (:meth:`PlanService.start`) is an engine timer tick
  honoring the coalescing deadlines — the PR-10 polling daemon thread
  is gone.

Determinism contract (multi-controller meshes): one service instance
runs per rank; batching and ordering decisions are pure functions of
the submission sequence (see :class:`~pencilarrays_tpu.serve.queue.
AdmissionQueue`), so ranks that submit identically and drain at the
same points dispatch identical collective programs in identical order.

Elastic interop: plans registered by *name* via :meth:`PlanService.
register_plan` re-register their factory with
:func:`~pencilarrays_tpu.cluster.elastic.register_plan` — after a mesh
reformation the factory re-runs, the registry entry is swapped (stale
executables dropped), queued host-payload requests re-bind to the
rebuilt plan, and the service resumes draining its queue.  Queued
*device* payloads bound to the dead mesh fail typed
(:class:`~pencilarrays_tpu.serve.errors.StaleRequestError`).

The full request lifecycle is journaled (``serve.request`` →
``serve.coalesce`` → ``serve.dispatch`` → ``serve.complete``,
schema-registered in ``obs/schema.py``) and metered per tenant
(``serve.*`` counters/histograms/gauges), so ``pa-obs timeline``
renders a served run end to end.  Every record on one request's path
carries its **trace context** (schema v6, ``obs/requestflow.py``):
admission ADOPTS an inbound ambient trace (a fleet worker installs
the routed request's id — the trace-ctx lint forbids re-minting
mid-path) and mints one only for direct submissions, so ``pa-obs
request <trace_id>`` reconstructs the causal timeline across the
router's and every mesh's journals — coalesced batches journal the
B-way fan-in (``traces``) so one shared dispatch span is attributable
to each member request.  Completions also feed the per-tenant SLO
error-budget :class:`~pencilarrays_tpu.serve.slo.BurnRateMonitor`:
when a tenant's budget burns faster than the alert threshold, ONE
fsync-critical ``serve.burn_alert`` record fires per overload episode
(edge-triggered with hysteresis).
"""

from __future__ import annotations

import itertools
import math
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from functools import lru_cache

from .errors import (AdmissionError, DeadlineError, ServeError,
                     ServiceClosedError, StaleRequestError)
from .queue import AdmissionQueue, Batch, TenantQuota, Ticket, _Entry
from .registry import PlanRegistry
from .shed import PressureGate, PressurePolicy
from .slo import SLO, BurnRateMonitor

__all__ = ["PlanService"]

_solo_ids = itertools.count(1)      # per-request coalesce-key suffixes
# for hbm-bounded reshards (admitted at B=1, served at B=1)
_service_ids = itertools.count(1)   # dispatch-log attribution tokens:
# NEVER id(self) — a recycled address would pull a dead service's
# records into another service's certify(engine=True)


@lru_cache(maxsize=64)
def _split_fn(B: int):
    """Jitted B-way trailing-dim splitter (jit's own cache specializes
    per shape/dtype/sharding)."""
    import jax

    return jax.jit(lambda d: tuple(d[..., i] for i in range(B)))


class PlanService:
    """Accept concurrent FFT/reshard requests from logical tenants and
    execute them on the resident mesh (module docstring).

    Parameters
    ----------
    max_batch, max_wait_s, starve_after_s, quota, quotas:
        Queue knobs (:class:`~pencilarrays_tpu.serve.queue.
        AdmissionQueue`): coalescing width, partial-batch deadline,
        anti-starvation age, default and per-tenant admission quotas.
        ``max_batch=1`` is the serialized per-request baseline (the
        benchmark's control arm).
    retry:
        :class:`~pencilarrays_tpu.resilience.retry.RetryPolicy` for the
        per-batch ``guarded_step`` ladder (default: env-tuned
        ``from_env()`` — ``PENCILARRAYS_TPU_RETRIES`` etc.).
    registry:
        Share a :class:`~pencilarrays_tpu.serve.registry.PlanRegistry`
        across services (default: a private one).
    engine:
        Explicit :class:`~pencilarrays_tpu.engine.Engine` to dispatch
        through (default: the process's shared ``"default"`` engine —
        one mesh, ONE ordered dispatch queue, so concurrent services
        and app step loops cannot interleave collective launches).
    hbm_limit:
        Per-chip peak-HBM bound (bytes) the service's reshard traffic
        must fit under.  Whale requests whose every single-shot route
        busts the bound are no longer rejected: the route planner
        *synthesizes* a time-sliced chunked route
        (memory-bounded redistribution, arXiv:2112.01075 — see
        ``parallel/routing.py``) at admission, and the dispatch
        executes it.  Only a request for which even maximal chunking
        finds no admissible route fails, typed
        (:class:`~pencilarrays_tpu.serve.errors.AdmissionError`,
        ``reason="hbm-limit"``) at submit — never after queuing.
        ``None`` (default) keeps admission unbounded.
    slos:
        Per-tenant :class:`~pencilarrays_tpu.serve.slo.SLO` objectives
        (also settable later via :meth:`set_slo`).  A tenant with a
        ``deadline_s`` gets all three enforcement points (admission
        projection, take-point expiry shed, completion violation
        journaling — ``docs/Serving.md``); ``shed_priority`` orders the
        overload gate's sacrifices.  With no SLOs and no ``pressure``
        policy the service behaves exactly as before (the disabled
        path: no per-request pricing, no projections —
        ``BENCH_AUTOSCALE.json`` pins it within noise of PR-10/14
        serving).
    pressure:
        A :class:`~pencilarrays_tpu.serve.shed.PressurePolicy` arming
        the load-shedding gate (water marks on the projected queue
        drain time).  With ``degrade_water_s`` set, the gate's first
        rung serves sheddable traffic on a cheaper wire precision
        (full -> bf16 -> fp8) inside each tenant's declared
        ``SLO.max_rel_l2`` envelope instead of shedding it
        (``serve/precision.py``; every applied downgrade journals a
        fsync-critical ``serve.precision`` record, schema v7).
        ``None`` (default): no shedding, PR-10 admission semantics.
    burn:
        A :class:`~pencilarrays_tpu.serve.slo.BurnRateMonitor` for
        per-tenant SLO error-budget burn tracking (default: one with
        the monitor's own defaults).  Only tenants with a
        ``deadline_s`` SLO feed it; a threshold crossing journals ONE
        fsync-critical ``serve.burn_alert`` per overload episode.
    """

    def __init__(self, *, max_batch: int = 8, max_wait_s: float = 0.002,
                 starve_after_s: float = 1.0,
                 quota: Optional[TenantQuota] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 retry=None, registry: Optional[PlanRegistry] = None,
                 engine=None, hbm_limit: Optional[int] = None,
                 slos: Optional[Dict[str, SLO]] = None,
                 pressure: Optional[PressurePolicy] = None,
                 burn: Optional[BurnRateMonitor] = None):
        self.registry = registry or PlanRegistry()
        self.hbm_limit = int(hbm_limit) if hbm_limit is not None else None
        self.queue = AdmissionQueue(
            max_batch=max_batch, max_wait_s=max_wait_s,
            starve_after_s=starve_after_s, default_quota=quota,
            quotas=quotas, hbm_limit=self.hbm_limit)
        self.retry = retry
        self._lock = threading.Lock()
        self._named: Dict[str, object] = {}
        self._elastic_names: set = set()
        self._closed = False
        self._slos: Dict[str, SLO] = dict(slos or {})
        for t, s in self._slos.items():
            if not isinstance(s, SLO):
                raise TypeError(f"slos[{t!r}] is not an SLO: {s!r}")
        self._gate = PressureGate(pressure) if pressure is not None \
            else None
        self.burn = burn if burn is not None else BurnRateMonitor()
        self._force_priced = False      # ensure_priced(): an attached
        # Autoscaler needs the projection even with no SLOs/gate
        self._protected = max(
            (s.shed_priority for s in self._slos.values()), default=0)
        # batches taken from the queue but not yet finished: an elastic
        # rebind must re-point THESE plan references too (a reformation
        # can interrupt a batch mid-dispatch and rerun it)
        self._inflight: List[Batch] = []
        # batches dropped typed by an engine reformation, awaiting
        # resubmission onto the reformed engine — flushed only from
        # safe points (a finished dispatch, an explicit step/drain, the
        # engine's own post-reform hook off the consumer thread), so a
        # resubmitted batch can never dispatch concurrently with an
        # in-flight one (see _park_or_finish)
        self._parked: List[Batch] = []
        self._sid = next(_service_ids)
        self._engine_obj = engine
        self._streaming = False
        self._pump_scheduled = False
        self._pump_token = None     # (engine, generation) the pending
        # tick was scheduled against — a reform drops timers, so a
        # stale token means "scheduled" is a lie and must re-arm (the
        # ENGINE OBJECT, not id(): a recycled address on a swapped
        # engine must never collide into a false dedup)
        self._pump_deadline = 0.0   # when the armed tick fires — an
        # URGENT re-arm (full batch ready) may undercut it
        self._hooked_engines = weakref.WeakSet()    # engines whose
        # on_reform hook already re-arms this service's pump
        self._unhooks: List[Callable] = []  # their unsubscribes,
        # called at close() so a shared long-lived engine never
        # accumulates dead services' hooks
        self._dispatches = 0
        self._completed: Dict[str, int] = {}
        self._slo_violations = 0

    def engine(self):
        """The engine this service dispatches through (the explicit
        one, else the process's shared default — resolved per call so
        an elastic reformation's fresh engine is picked up without
        re-plumbing)."""
        if self._engine_obj is not None:
            return self._engine_obj
        from ..engine import get_engine

        return get_engine()

    # -- named (elastic-rebindable) plans ----------------------------------
    def register_plan(self, name: str, factory: Callable):
        """Build and register a named plan: ``factory(ctx)`` must return
        a :class:`~pencilarrays_tpu.ops.fft.PencilFFTPlan` (``ctx`` is
        ``None`` now, and the
        :class:`~pencilarrays_tpu.cluster.elastic.ReformContext` when a
        reformation re-invokes it).  The factory is re-registered with
        the elastic layer as ``serve:<name>`` so a reformed mesh
        rebuilds the plan, swaps the registry entry (stale executables
        dropped) and re-binds queued host-payload requests — the
        service then resumes draining its queue.  Returns the built
        plan."""
        plan = factory(None)
        with self._lock:
            self._named[name] = plan
        self.registry.register(plan, replace=True)

        from ..cluster import elastic

        def _rebuild(ctx=None):
            p = factory(ctx)
            self._rebind(name, p)
            return p

        elastic.register_plan(f"serve:{name}", _rebuild)
        with self._lock:
            self._elastic_names.add(f"serve:{name}")
        return plan

    def _rebind(self, name: str, plan) -> None:
        with self._lock:
            self._named[name] = plan
        self.registry.register(plan, replace=True)
        # re-point EVERY queued entry of this fingerprint, not just the
        # name= submissions: a plan= submission resolves to the same
        # canonical object (registry dedupe) and shares the coalesce
        # key, so leaving it on the dead-mesh plan would poison the
        # whole post-reform batch.  In-flight and reformation-parked
        # batches re-bind too: an elastic reformation can interrupt a
        # batch mid-dispatch and rerun it (elastic_step's reform rung),
        # and the rerun must execute on the rebuilt plan
        key = plan.plan_key()
        with self._lock:
            taken = [e for b in self._inflight for e in b.entries] + \
                    [e for b in self._parked for e in b.entries]
        for e in self.queue.pending_entries() + taken:
            if e.plan is not None and (
                    e.plan_name == name or e.plan.plan_key() == key):
                e.plan = plan

    def plan(self, name: str):
        """The current plan registered under ``name`` (post-reform this
        is the rebuilt one)."""
        return self._named.get(name)

    # -- SLOs + the load projection ----------------------------------------
    def set_slo(self, tenant: str, slo: SLO) -> None:
        """Attach (or replace) one tenant's
        :class:`~pencilarrays_tpu.serve.slo.SLO` — deadlines enforce
        from the next submission on."""
        if not isinstance(slo, SLO):
            raise TypeError(f"set_slo needs an SLO, got {slo!r}")
        with self._lock:
            self._slos[tenant] = slo
            self._protected = max(
                s.shed_priority for s in self._slos.values())

    def slo(self, tenant: str) -> Optional[SLO]:
        return self._slos.get(tenant)

    @property
    def _slo_armed(self) -> bool:
        """Any SLO, a pressure policy, or :meth:`ensure_priced` arms
        the projection machinery; without them, submissions skip
        pricing entirely (the disabled path — PR-10 behavior and
        overhead, bit-for-bit)."""
        return (bool(self._slos) or self._gate is not None
                or self._force_priced)

    def ensure_priced(self) -> None:
        """Arm request pricing + the load projection even with no SLOs
        and no pressure gate — the :class:`~pencilarrays_tpu.serve.
        autoscale.Autoscaler` calls this at attach: a controller
        watching a projection that is never fed would be permanently
        blind to overload (it could scale down but never up)."""
        self._force_priced = True

    def load_projection(self) -> dict:
        """The queue's live load projection (serve/slo.py snapshot plus
        the gate state) — what the shedding gate and the autoscaler
        read, exposed for operators and the bench."""
        snap = self.queue.load.snapshot()
        snap["queue_depth"] = self.queue.depth()
        snap["pressure"] = (self._gate.state if self._gate is not None
                            else None)
        snap["burn"] = self.burn.snapshot()
        return snap

    # -- submission --------------------------------------------------------
    def submit(self, tenant: str, u, *, plan=None, name: Optional[str] = None,
               direction: str = "forward") -> Ticket:
        """Submit one single-sample FFT request.

        ``u`` is the sample: a host array in the plan's *global logical*
        shape (scattered onto the mesh at dispatch — the rebind-safe
        form), or a :class:`~pencilarrays_tpu.parallel.arrays.
        PencilArray` already living on the plan's input (forward) /
        output (backward) pencil with ``extra_dims == ()``.  Pass the
        plan directly or by registered ``name``.  Returns a
        :class:`~pencilarrays_tpu.serve.queue.Ticket`; same-fingerprint
        submissions coalesce into one batched dispatch, bit-identical
        to sequential per-request execution (test-pinned)."""
        if direction not in ("forward", "backward"):
            raise ValueError(
                f"direction must be 'forward' or 'backward', "
                f"got {direction!r}")
        plan_name = None
        if name is not None:
            if plan is not None:
                raise ValueError("pass plan= or name=, not both")
            plan = self._named.get(name)
            if plan is None:
                raise ServeError(f"no plan registered under {name!r}")
            plan_name = name
        if plan is None:
            raise ValueError("submit needs plan= or name=")
        plan = self.registry.register(plan)
        self._check_payload(u)
        self._check_fft_shape(plan, direction, u)
        key = f"fft:{plan.plan_key()}:{direction}"
        nbytes = self._fft_nbytes(plan, direction)
        ticket = Ticket(tenant, "fft", key)
        entry = _Entry(ticket=ticket, plan=plan, direction=direction,
                       payload=u, nbytes=nbytes, plan_name=plan_name)
        self._stamp_slo(entry)
        self._admit(entry, direction=direction)
        return ticket

    def submit_reshard(self, tenant: str, u, dest, *,
                       method=None) -> Ticket:
        """Submit one reshard request: redistribute ``u`` (a
        :class:`PencilArray`, ``extra_dims == ()``) onto pencil
        ``dest`` via the cost-driven route planner (``method`` defaults
        to :class:`~pencilarrays_tpu.parallel.transpositions.Auto`).
        Same-route submissions coalesce like FFT traffic.

        With a service ``hbm_limit``, admission prices the request
        against the memory-bounded route planner: a whale whose
        single-shot routes all bust the bound is admitted on its
        *synthesized* chunked route; only a request with no admissible
        route at all (even maximally time-sliced) is rejected typed
        (:class:`~pencilarrays_tpu.serve.errors.AdmissionError`,
        ``reason="hbm-limit"``).  hbm-bounded reshards dispatch one
        per batch (no coalescing): a coalesced stack would multiply
        the un-chunkable footprint floor by B and could bust at
        dispatch what each request fit at admission."""
        from .. import obs
        from ..parallel.arrays import PencilArray
        from ..parallel.routing import reshard_key
        from ..parallel.transpositions import Auto, Gspmd

        if not isinstance(u, PencilArray):
            raise ServeError(
                "submit_reshard needs a PencilArray payload (a reshard "
                "is defined by where the data currently lives)")
        self._check_payload(u)
        method = method if method is not None else Auto()
        if self.hbm_limit is not None:
            from ..parallel.routing import plan_reshard_route

            if isinstance(method, Gspmd):
                raise ServeError(
                    "hbm-limited services cannot take method=Gspmd() "
                    "reshards: the partitioner's peak allocation is "
                    "unboundable")
            route = plan_reshard_route(u.pencil, dest, (), u.dtype,
                                       method=method,
                                       hbm_limit=self.hbm_limit)
            if not route.use_route:
                if obs.enabled():
                    obs.counter("serve.rejected", tenant=tenant,
                                reason="hbm-limit").inc()
                raise AdmissionError(
                    f"tenant {tenant!r}: no admissible reshard route "
                    f"under hbm_limit={self.hbm_limit} (even maximal "
                    f"time-slicing busts the bound)", tenant=tenant,
                    reason="hbm-limit")
        key = f"reshard:{reshard_key(u.pencil, dest, u.dtype, method)}"
        if self.hbm_limit is not None:
            # hbm-bounded reshards never coalesce: stacking B samples
            # multiplies the un-chunkable ``elems x itemsize`` floor by
            # B, so a batch of individually-admissible whales could
            # bust the bound at DISPATCH — violating the "rejected
            # typed at submit, never after queuing" contract the
            # admission check above just enforced.  One whale, one
            # batch (the key stays fingerprint-prefixed for journals)
            key += f"#solo{next(_solo_ids)}"
        nbytes = (math.prod(u.pencil.size_global())
                  * u.dtype.itemsize)
        ticket = Ticket(tenant, "reshard", key)
        entry = _Entry(ticket=ticket, plan=None, direction="forward",
                       payload=u, nbytes=nbytes, plan_name=None,
                       dest=dest, method=method)
        self._stamp_slo(entry)
        self._admit(entry)
        return ticket

    def _stamp_slo(self, entry: _Entry) -> None:
        slo = self._slos.get(entry.ticket.tenant)
        if slo is None:
            return
        entry.shed_priority = slo.shed_priority
        if slo.deadline_s is not None:
            # the admission-time deadline every later enforcement point
            # (take shed, completion accounting) measures against
            entry.deadline = entry.ticket.t_submit + slo.deadline_s

    @staticmethod
    def _check_payload(u) -> None:
        from ..parallel.arrays import PencilArray

        if isinstance(u, PencilArray) and u.extra_dims != ():
            raise ServeError(
                f"serve requests are single-sample (extra_dims=(), got "
                f"{u.extra_dims}); coalescing owns the batch dimension — "
                f"declare caller-side batches with PencilFFTPlan(batch=B) "
                f"instead")

    @staticmethod
    def _check_fft_shape(plan, direction: str, u) -> None:
        """Host payloads are shape-checked AT SUBMIT: a malformed
        sample must be a typed error on its own submitter, never a
        stack failure inside a coalesced batch that poisons other
        tenants' tickets."""
        import numpy as np

        from ..parallel.arrays import PencilArray

        if isinstance(u, PencilArray):
            return      # device payloads are validated per entry at
            # dispatch (the pencil may legitimately rebind by then)
        expected = tuple(plan.shape_physical if direction == "forward"
                         else plan.shape_spectral)
        got = tuple(np.shape(u))
        if got != expected:
            raise ServeError(
                f"payload shape {got} does not match the plan's "
                f"{'physical' if direction == 'forward' else 'spectral'} "
                f"global shape {expected}")
        dt = (plan.dtype_physical if direction == "forward"
              else plan.dtype_spectral)
        if np.iscomplexobj(u) and np.dtype(dt).kind != "c":
            raise ServeError(
                f"complex payload submitted where the plan expects "
                f"{np.dtype(dt).name} — the coalesced cast would "
                f"silently discard the imaginary part")

    @staticmethod
    def _fft_nbytes(plan, direction: str) -> int:
        if direction == "forward":
            return (math.prod(plan.shape_physical)
                    * plan.dtype_physical.itemsize)
        return (math.prod(plan.shape_spectral)
                * plan.dtype_spectral.itemsize)

    def _admit(self, entry: _Entry, *, direction: Optional[str] = None
               ) -> None:
        from .. import obs
        from ..obs import requestflow
        from ..resilience import faults

        if self._closed:
            raise ServiceClosedError("service is closed")
        t = entry.ticket.tenant
        # trace context: ADOPT the ambient inbound trace (a fleet
        # worker installed the routed request's id — re-minting here
        # would shear the cross-mesh causal chain; the trace-ctx lint
        # audits this site), mint only for direct submissions — the
        # serve layer is the second of the two admission points
        entry.trace = (requestflow.current_trace()
                       or requestflow.mint_trace())
        # the admission-boundary injection point: overload and
        # flaky-client drills inject here like at every other layer
        # (error raises InjectedFault to THIS submitter, delay drags
        # the admission path — docs/Resilience.md)
        faults.fire("serve.submit", tenant=t, kind=entry.ticket.kind)
        try:
            self._enforce_slo(entry)
            full = self.queue.offer(entry)
        except ServeError as e:
            if obs.enabled():
                obs.counter("serve.rejected", tenant=t,
                            reason=getattr(e, "reason", "error")).inc()
            raise
        if obs.enabled():
            obs.counter("serve.requests", tenant=t,
                        kind=entry.ticket.kind).inc()
            obs.gauge("serve.queue_depth", tenant=t).set(
                self.queue.depth(t))
            fields = dict(tenant=t, req=entry.ticket.id,
                          kind=entry.ticket.kind, key=entry.ticket.key,
                          nbytes=entry.nbytes, trace=entry.trace)
            if direction is not None:
                fields["direction"] = direction
            obs.record_event("serve.request", **fields)
        # streaming mode: EVERY admission (re)schedules the pump tick —
        # a request landing on an idle queue must not wait for a tick
        # that was never armed (an idle tick does not reschedule itself,
        # and an engine reform drops pending timers).  An admission
        # that COMPLETED a batch ticks at the minimum spacing: a full
        # batch gains nothing by waiting out the coalescing deadline
        if self._streaming:
            if full:
                self._schedule_pump(
                    delay_s=getattr(self, "_min_tick_s", 0.001))
            else:
                self._schedule_pump()

    # -- SLO / pressure enforcement ----------------------------------------
    def _enforce_slo(self, entry: _Entry) -> None:
        """The admission enforcement point (raises typed): feed the
        pressure gate, downgrade wire precision under its first rung
        (PR 19 — a sheddable tenant with an ``SLO.max_rel_l2`` budget
        is SERVED on a cheaper wire instead of rejected), evict under
        its last rung, shed sheddable priorities, and reject requests
        whose projected wait already busts their deadline.  A no-SLO
        no-pressure service returns on the first line — the disabled
        path does no pricing at all."""
        if not self._slo_armed:
            return
        t = entry.ticket.tenant
        if self._gate is not None:
            self._feed_gate()
            degraded = (
                self._gate.degrades(entry.shed_priority, self._protected)
                and self._maybe_degrade(entry))
            if not degraded and self._gate.sheds(
                    entry.shed_priority, self._protected):
                raise AdmissionError(
                    f"tenant {t!r}: shed under load (priority "
                    f"{entry.shed_priority} below the protected tier "
                    f"{self._protected}, gate {self._gate.state!r})",
                    tenant=t, reason="shed")
        # priced AFTER any downgrade: the projection must charge the
        # wire the request will actually move, or the autoscaler and
        # the gate would keep seeing the full-precision queue
        entry.cost_bytes = self.queue.entry_cost(entry)
        load = self.queue.load
        if entry.deadline is not None:
            projected = load.projected_wait_s()
            budget = entry.deadline - entry.ticket.t_submit
            # boundary contract (test-pinned): a projection EQUAL to
            # the deadline still admits — only a wait the model says
            # is strictly too long is rejected up front
            if projected is not None and projected > budget:
                raise DeadlineError(
                    f"tenant {t!r}: projected wait {projected:.3f}s "
                    f"exceeds the {budget:.3f}s deadline — rejected at "
                    f"admission, not answered late", tenant=t,
                    reason="projected", deadline_s=budget,
                    projected_s=projected)

    def _maybe_degrade(self, entry: _Entry) -> bool:
        """The precision-downgrade rung (PR 19): swap a sheddable fft
        entry onto the deepest wire-precision plan variant whose
        CALIBRATED error envelope (``serve/precision.py``,
        ``BENCH_WIRE.json``) fits under the tenant's declared
        ``SLO.max_rel_l2``.  Returns True when a downgrade was applied
        — the caller then skips the shed rung: served degraded beats
        shed.

        The swap happens BEFORE the entry is priced or queued: the
        coalesce key is rebuilt from the variant's ``plan_key()`` (wire
        dtype is part of schedule identity, so full/bf16/fp8 traffic
        can never coalesce into one batch), the registry holds the
        variant's own compiled executable, and the load projection
        charges the cheaper wire.  Tenants with no ``max_rel_l2`` —
        and reshard traffic, which has no per-precision plan variants —
        fall through untouched to the shed rung.  (An elastic
        reformation re-binds named-plan entries to the rebuilt FULL
        plan: a degraded-then-reformed request is served at better
        precision than promised, never worse.)"""
        from .. import obs
        from .precision import select_rung

        if entry.plan is None or entry.ticket.kind != "fft":
            return False
        t = entry.ticket.tenant
        slo = self._slos.get(t)
        if slo is None or slo.max_rel_l2 is None:
            return False
        rung = select_rung(slo.max_rel_l2, entry.plan.wire_dtype)
        if rung is None:
            return False
        wire, envelope = rung
        wire_from = entry.plan.wire_dtype or "full"
        plan = self.registry.register(entry.plan.with_wire_dtype(wire))
        entry.plan = plan
        entry.ticket.key = f"fft:{plan.plan_key()}:{entry.direction}"
        if obs.enabled():
            obs.counter("serve.degraded", tenant=t, wire=wire).inc()
            # fsync-critical: a precision decision changes the answer a
            # client receives — it must survive a crash, like the shed
            # and burn-alert records it sits between
            obs.record_event(
                "serve.precision", _fsync=True, tenant=t,
                req=entry.ticket.id, key=entry.ticket.key,
                trace=entry.trace, wire_from=wire_from, wire_to=wire,
                envelope=envelope, max_rel_l2=slo.max_rel_l2,
                gate=self._gate.state)
        return True

    def _slo_maintenance(self) -> None:
        """The take-side enforcement: re-feed the gate (pressure can
        cross a mark between admissions), run the evict rung, and fail
        take-point-expired entries typed.  Called by every dispatch
        path (step / streaming pump) around ``take_ready``."""
        if not self._slo_armed:
            return
        if self._gate is not None:
            self._feed_gate()

    def _feed_gate(self) -> None:
        """THE one gate-feed sequence (admission and take enforcement
        points must never diverge): update with the live drain
        projection, then run the evict rung if the gate escalated."""
        load = self.queue.load
        self._gate.update(load.drain_s(), load.snapshot)
        if self._gate.evicting():
            self._evict_sheddable()

    def _shed_expired(self) -> None:
        """Fail every entry ``take_ready`` shed as deadline-expired:
        typed ``DeadlineError(reason="expired")`` on its own ticket —
        never a silent late answer, never a dispatched corpse."""
        from .. import obs

        for e in self.queue.pop_expired():
            budget = (e.deadline - e.ticket.t_submit
                      if e.deadline is not None else 0.0)
            if obs.enabled():
                obs.counter("serve.shed", tenant=e.ticket.tenant,
                            reason="expired").inc()
            self._finish_one(
                e.ticket.key, e, error=DeadlineError(
                    f"tenant {e.ticket.tenant!r}: deadline "
                    f"({budget:.3f}s) expired while queued — shed "
                    f"before dispatch", tenant=e.ticket.tenant,
                    reason="expired", deadline_s=budget))

    def _evict_sheddable(self) -> None:
        """The pressure gate's second rung: evict queued sheddable
        entries (admission-sequence order, deterministic) and fail
        their tickets typed ``AdmissionError(reason="shed")``."""
        from .. import obs

        for e in self.queue.evict_sheddable(self._protected):
            if obs.enabled():
                obs.counter("serve.shed", tenant=e.ticket.tenant,
                            reason="evicted").inc()
            self._finish_one(
                e.ticket.key, e, error=AdmissionError(
                    f"tenant {e.ticket.tenant!r}: evicted from the "
                    f"queue under overload (priority {e.shed_priority} "
                    f"below the protected tier {self._protected})",
                    tenant=e.ticket.tenant, reason="shed"))

    # -- dispatch ----------------------------------------------------------
    def step(self, *, flush: bool = False) -> int:
        """Dispatch every ready batch through the engine (coalescing
        deadlines honored; ``flush=True`` takes partial groups too —
        the ragged final batch) and block until their futures resolve.
        Returns the number of batches TAKEN — dispatched, or failed
        typed at submission (a batch that left the queue always
        resolves its tickets, one way or the other).  Batches are
        submitted in take-order and the engine's single consumer issues
        them in submission order, so the dispatched collective sequence
        is identical to the pre-engine serialized loop (certifiable:
        :meth:`certify` with ``engine=True``).  Client-thread API —
        never call from inside engine-executed work."""
        self._slo_maintenance()
        taken = self.queue.take_ready(flush=flush)
        self._shed_expired()
        # batches dropped typed by an engine reformation resubmit ahead
        # of fresh traffic (they are older) — not re-counted: they were
        # already counted by the step/pump that first took them
        batches = self._take_parked() + taken
        futs = []
        interrupt = None
        for b in batches:
            f, err = self._submit_or_fail(b)
            futs.append(f)
            if interrupt is None and isinstance(
                    err, (KeyboardInterrupt, SystemExit)):
                interrupt = err
        for f in futs:
            if f is None:
                continue    # every entry failed validation: no dispatch
            f._event.wait()
            err = f.error()
            if interrupt is None and isinstance(
                    err, (KeyboardInterrupt, SystemExit)):
                # the tickets are failed (nobody waits on a dead
                # future) but the interrupt itself must reach the
                # caller — the pre-engine contract, preserved
                interrupt = err
        if interrupt is not None:
            raise interrupt
        return len(taken)

    def drain(self) -> int:
        """Flush-dispatch until the queue AND the reformation-parked
        backlog are empty; returns batches taken (see :meth:`step`).
        The deterministic entry point: tests and multi-controller
        meshes submit, then drain.  Parked batches count: a batch
        dropped typed by an engine reformation still holds unresolved
        tickets, and drain()'s contract is that nobody waits forever
        after it returns."""
        n = 0
        while True:
            with self._lock:
                parked = bool(self._parked)
            if not (self.queue.depth() or parked):
                break
            n += self.step(flush=True)
        return n

    def start(self, poll_s: float = 0.001) -> None:
        """Arm streaming mode (single-controller meshes only;
        multi-controller ranks must drain at agreed points, see the
        determinism contract): every admission schedules an engine
        timer honoring the coalescing deadline, whose tick takes ready
        batches into the ordered dispatch queue.  No thread is created
        and nothing polls — the PR-10 private daemon loop (poll, sleep,
        repeat, contending with the main thread for every dispatch) is
        deleted; ``poll_s`` is kept as the minimum tick spacing."""
        self._min_tick_s = float(poll_s)
        self._streaming = True
        self._schedule_pump()

    def stop(self) -> None:
        """Disarm streaming mode: queued work stays queued for an
        explicit :meth:`step`/:meth:`drain`.  A scheduled tick may
        still fire once but dispatches NOTHING once streaming is off —
        stop() means no further implicit dispatch, period."""
        self._streaming = False

    def _schedule_pump(self, *, delay_s: Optional[float] = None) -> None:
        """Schedule ONE pending pump tick (collapsing duplicates) at
        the coalescing deadline — or immediately when a full batch is
        already ready.  Never raises: the caller is the admission path
        (the request is already queued — a scheduling failure must not
        strip the submitter of a ticket that may still dispatch) or the
        pump tick itself."""
        from .. import obs

        if not self._streaming or self._closed:
            return
        eng = self.engine()
        self._hook_reform(eng)
        if not eng.accepting:
            return      # quiesced/reforming: the engine's reform/
            # resume hook (or the next submit) re-pumps
        if delay_s is None:
            # the deadline-aware tick: bound by the oldest pending
            # group's coalescing deadline AND any queued SLO deadline
            # (next_ready_in folds both) — a request whose deadline is
            # far inside the coalesce window must be shed at ITS
            # deadline, not discovered expired a full window later
            wait = self.queue.next_ready_in()
            delay_s = self.queue.max_wait_s if wait is None else wait
            delay_s = max(delay_s, getattr(self, "_min_tick_s", 0.001))
        token = (eng, eng.generation)
        now = time.monotonic()
        with self._lock:
            if (self._pump_scheduled and self._pump_token == token
                    and now + delay_s >= self._pump_deadline - 1e-4):
                return      # an armed tick already fires soon enough
            # re-arm when: the token is stale (the engine reformed —
            # dropping its timers — or was swapped, so "scheduled" is
            # a lie), OR an urgent deadline (a full batch) undercuts
            # the armed tick.  The superseded tick still fires and
            # drains harmlessly (take_ready dedups the work)
            self._pump_scheduled = True
            self._pump_token = token
            self._pump_deadline = now + delay_s
        try:
            eng.call_later(delay_s, self._pump, label="serve-pump")
        except Exception:
            # engine closed/reformed between the accepting check and
            # the call: queued work is NOT lost — the next admission
            # (or an explicit step/drain) re-pumps.  Clear the flag
            # only if OUR token still owns it: a concurrent admission
            # may have legitimately re-armed on the live generation
            with self._lock:
                if self._pump_token == token:
                    self._pump_scheduled = False
            if obs.enabled():
                obs.counter("serve.pump_schedule_errors").inc()

    def _hook_reform(self, eng) -> None:
        """Register (once per engine) a post-reform hook that re-arms
        the pump: a reform drops the armed tick, and ALREADY-QUEUED
        streaming traffic must drain even if no further admission ever
        arrives to notice the stale token.  The hook holds only a
        weakref to the service so a long-lived shared engine never
        keeps a closed service alive."""
        with self._lock:
            if eng in self._hooked_engines:
                return
            self._hooked_engines.add(eng)
        ref = weakref.ref(self)

        def _rearm(_eng):
            svc = ref()
            if svc is None:
                return
            # NOTHING dispatches from this hook while it runs on the
            # engine's own consumer thread (an elastic_step reforming
            # from inside an in-flight dispatch): neither a parked
            # flush nor a pump tick may put the new generation to work
            # concurrently with the old consumer's still-rerunning
            # interrupted batch — that dispatch's completion (_finish)
            # flushes and re-arms instead
            if _eng.on_consumer_thread():
                return
            svc._flush_parked()
            if svc._streaming and not svc._closed and svc.queue.depth():
                svc._schedule_pump()

        unhook = eng.on_reform(_rearm)
        with self._lock:
            # close() may have swapped _unhooks out while we were
            # registering: our entry would land in a list nobody ever
            # drains, leaving a dead service's hook on a shared engine
            late = self._closed
            if not late:
                self._unhooks.append(unhook)
        if late:
            unhook()

    def _pump(self) -> None:
        """The streaming tick (runs on the engine consumer thread):
        submit every ready batch, then reschedule while traffic
        remains.  Must never raise — a scheduling bug costs one tick,
        never the engine."""
        from .. import obs

        now = time.monotonic()
        with self._lock:
            # only the OWNING tick clears the flag: a superseded
            # later-deadline tick firing while a live one is still
            # armed (deadline in the future) must not clear it, or
            # every admission until that live tick re-arms redundantly
            if now >= self._pump_deadline - 1e-4:
                self._pump_scheduled = False
        if not self._streaming or self._closed:
            return
        try:
            self._slo_maintenance()
            batches = self.queue.take_ready()
            self._shed_expired()
        except Exception:
            batches = []
            if obs.enabled():
                obs.counter("serve.loop_errors").inc()
        for b in self._take_parked() + batches:
            self._submit_or_fail(b)
        if self.queue.depth():
            # re-arm at the oldest pending group's own deadline — a
            # fresh full max_wait_s from now would make a group that
            # just missed this tick wait up to ~2x its deadline
            wait = self.queue.next_ready_in()
            self._schedule_pump(delay_s=None if wait is None else max(
                wait, getattr(self, "_min_tick_s", 0.001)))

    def close(self, *, drain: bool = True) -> None:
        """Stop accepting work; by default drain what is queued.  The
        admission gate closes BEFORE the final drain and atomically
        with the queue's own offer lock, so a submit racing close() is
        a typed rejection — never a ticket stranded in a service nobody
        will ever drain again.  Elastic factories registered through
        :meth:`register_plan` are unregistered so a later reformation
        does not rebuild plans for (and keep alive) a dead service."""
        self.stop()
        self._closed = True             # fast-path rejection
        self.queue.close_gate()         # the airtight one
        if drain:
            self.drain()
        # reformation-parked batches must not strand their tickets in a
        # dead service: resubmit (or fail typed, if the engine is gone)
        self._flush_parked()
        from ..cluster import elastic
        with self._lock:
            names, self._elastic_names = self._elastic_names, set()
            unhooks, self._unhooks = self._unhooks, []
        for n in names:
            elastic.unregister_plan(n)
        for u in unhooks:       # drop our reform hooks from engines
            try:                # that outlive this service
                u()
            except Exception:
                pass

    # -- the batch executor ------------------------------------------------
    def _dispatch(self, batch: Batch) -> None:
        """Submit one batch through the engine and wait for it — the
        synchronous per-batch unit (callers that drive ``take_ready``
        themselves; :meth:`step` is the batched form).  A submission
        failure fails the batch's tickets typed (interrupts still
        propagate)."""
        fut, serr = self._submit_or_fail(batch)
        if isinstance(serr, (KeyboardInterrupt, SystemExit)):
            raise serr
        if fut is None:
            return
        fut._event.wait()
        err = fut.error()
        if isinstance(err, (KeyboardInterrupt, SystemExit)):
            raise err

    def _submit_or_fail(self, batch: Batch):
        """:meth:`_submit_batch`, but a submission failure (engine
        closed/reformed between ``take_ready`` and submit, a scheduling
        bug) fails THIS batch's tickets typed instead of propagating —
        once a batch left the queue, nobody but us will ever resolve
        its tickets.  NEVER raises (the streaming pump runs on the
        engine consumer thread, where an escaped exception kills the
        consumer and strands every queued future).  Returns ``(future,
        error)``: the future is ``None`` when nothing dispatched, the
        error is the submission failure so synchronous callers can
        re-raise interrupts after the tickets are failed."""
        from .. import obs

        try:
            return self._submit_batch(batch), None
        except BaseException as e:
            try:
                self._finish(batch, None, e, 0.0)
            except Exception:
                pass
            if obs.enabled():
                obs.counter("serve.submit_errors").inc()
            return None, e

    def _submit_batch(self, batch: Batch):
        """Turn one ready batch into one ordered engine dispatch.

        Runs on the submitting thread (a :meth:`step` caller or the
        streaming pump tick): journals the batch formation, fails
        blame-one validation losers typed, then submits ONE engine
        task — host-payload packing (the numpy stack) as the task's
        ``pack`` stage on the host pool (overlapped with earlier
        batches' device compute), the ``guarded_step``-wrapped device
        dispatch as its ``run`` stage on the consumer thread.  Returns
        the batch's :class:`~pencilarrays_tpu.engine.StepFuture` (or
        ``None`` when every entry failed validation and nothing
        dispatches).  Tickets are fulfilled by the future's completion
        callback, so streaming mode needs no waiter."""
        from .. import obs
        from ..guard.recover import elastic_step

        B = len(batch.entries)
        resubmit = batch.resubmits > 0
        t_dispatch = time.monotonic()
        for e in batch.entries:
            e.ticket.t_dispatch = t_dispatch
        wait_s = t_dispatch - batch.entries[0].ticket.t_submit
        if obs.enabled() and not resubmit:
            # the formation record: what the queue coalesced (validation
            # losses below journal their own non-ok serve.complete).
            # ONE logical dispatch = one coalesce/dispatch record —
            # a reformation-parked resubmission re-enters here but
            # must not double-journal or double-count
            # the fan-in record: the leader's trace plus every
            # member's (one dispatch span is SHARED by B requests —
            # pa-obs request finds this record through either field)
            obs.record_event(
                "serve.coalesce", key=batch.key, n=B,
                reqs=[e.ticket.id for e in batch.entries],
                reason=batch.reason, wait_s=wait_s,
                trace=batch.entries[0].trace,
                traces=[e.trace for e in batch.entries])
            obs.histogram("serve.batch_size", kind=batch.kind).observe(B)
        # per-entry payload validation BEFORE the shared dispatch: a
        # problem only one request can be blamed for (a stale device
        # payload after an elastic rebuild) fails THAT ticket typed and
        # the rest of the batch proceeds — the isolation contract holds
        # inside a batch too, for every blame-one failure we can detect
        # up front (host payload shapes were already checked at submit)
        survivors = []
        for e in batch.entries:
            err = self._validate_entry(batch, e)
            if err is None:
                survivors.append(e)
            else:
                # take_ready counted this entry in flight: clear it
                # (no rate sample — nothing dispatched for it), or the
                # drain projection inflates forever and the pressure
                # gate / autoscaler wedge on phantom load
                self.queue.note_entry_done(e)
                self._finish_one(batch.key, e, error=err)
        if not survivors:
            return None     # nothing actually dispatches: no
            # serve.dispatch record, no dispatch count
        batch.entries = survivors
        tenants = sorted({e.ticket.tenant for e in survivors})
        writes = self._batch_resources(batch)
        lane = self._lane_for(batch)
        if obs.enabled() and not resubmit:
            obs.record_event(
                "serve.dispatch", key=batch.key, n=len(survivors),
                tenants=tenants, score_bytes=batch.cost,
                reason=batch.reason, lane=lane,
                chain="|".join(writes) if writes else "*",
                trace=survivors[0].trace,
                traces=[e.trace for e in survivors])
        with self._lock:
            if not resubmit:
                self._dispatches += 1
            self._inflight.append(batch)
        pack = self._host_pack_fn(batch)
        timing = {"s": 0.0}
        meta = self._dispatch_meta(batch)

        def run(host_operand=None):
            # elastic_step, not guarded_step: when the elastic layer is
            # armed a PeerFailureError/PeerLeftError mid-batch reforms
            # the mesh (the service's registered factories rebuild its
            # plans, _rebind re-points this batch's entries) and the
            # batch reruns under the reformed mesh — with the gate off
            # this IS guarded_step, bit-for-bit (elastic test pin)
            t0 = time.perf_counter()
            try:
                return elastic_step(
                    lambda: self._run_batch(batch, host_operand),
                    retry=self.retry, label=f"serve:{batch.key}",
                    meta={"tenants": tenants,
                          "reqs": [e.ticket.id for e in batch.entries]})
            finally:
                timing["s"] = time.perf_counter() - t0

        fut = self.engine().submit(
            run, pack=pack, label=f"serve:{batch.key}", meta=meta,
            writes=writes, lane=lane)
        fut.add_done_callback(
            lambda f: self._complete_or_park(batch, f, timing))
        return fut

    def _batch_resources(self, batch: Batch) -> tuple:
        """The batch's declared engine write set — its dependency
        chain.  One fingerprint = one chain: every dispatch of the
        same plan (either direction — a backward may consume a
        forward's output, so they are conservatively chained) orders
        FIFO, while different tenants' different plans overlap.
        Reshard batches chain on their coalesce route key (the
        ``#solo`` suffix stripped: a solo-cost split still contends
        for the same route)."""
        if batch.kind == "fft":
            return (f"plan:{batch.entries[0].plan.plan_key()}",)
        return ("route:" + batch.key.split("#solo", 1)[0],)

    def _lane_for(self, batch: Batch) -> int:
        """The batch's engine priority lane: the max ``shed_priority``
        among its entries' SLOs (the tier the shedding gate already
        protects), plus one **urgency boost** when any member's
        remaining deadline slack is under the queue's projected wait —
        the batch that will MISS its SLO if it queues normally jumps
        first.  Unpriced traffic (no SLOs armed) rides lane 0, where
        the engine's FIFO tiebreak is exactly the v1 order."""
        if not self._slo_armed:
            return 0
        lane = max((e.shed_priority for e in batch.entries), default=0)
        deadlines = [e.deadline for e in batch.entries
                     if e.deadline is not None]
        if deadlines:
            slack = min(deadlines) - time.monotonic()
            projected = self.queue.load.projected_wait_s()
            # projected is None until the tracker has a completion rate
            # — no projection, no urgency verdict, no boost
            if projected is not None and slack < projected:
                lane += 1
        return lane

    def _complete_or_park(self, batch: Batch, f, timing: dict) -> None:
        """A batch whose queued engine task was dropped typed by an
        engine reformation (:class:`EngineReformedError`) is PARKED for
        resubmission onto the reformed engine instead of failing its
        tickets — host payloads re-bind to the rebuilt plans, so the
        program it will dispatch is a live-mesh one.  Parked batches
        are flushed only from safe points (a finished dispatch's
        completion, an explicit step/drain, the engine's post-reform
        hook off the consumer thread), so a resubmission can never
        dispatch concurrently with a still-running in-flight batch.
        Bounded: the 4th consecutive reformation drop fails the batch
        typed — reformation storms must not hide tickets forever."""
        from .. import obs
        from ..engine.errors import EngineReformedError

        err = f.error()
        if (isinstance(err, EngineReformedError) and not self._closed
                and batch.resubmits < 3):
            batch.resubmits += 1
            with self._lock:
                # parked ≠ in flight: resubmission re-appends it, and
                # _rebind already walks _parked separately
                self._inflight = [b for b in self._inflight
                                  if b is not batch]
                self._parked.append(batch)
            if obs.enabled():
                obs.counter("serve.reform_requeues").inc()
            return
        self._finish(batch, f._result, err, timing["s"])

    def _take_parked(self) -> List[Batch]:
        with self._lock:
            out, self._parked = self._parked, []
        return out

    def _flush_parked(self) -> None:
        for b in self._take_parked():
            self._submit_or_fail(b)

    def _host_pack_fn(self, batch: Batch):
        """The batch's host-pool pack stage: for an all-host FFT batch,
        the numpy dtype-cast + stack (ONE ``from_global`` scatter later
        on the consumer — the PR 10 coalescing shape, now overlapped
        with the previous dispatch's compute).  Device payloads have
        nothing to pack on the host (``None``: materialize + stack run
        on the consumer thread with the device program — device work
        never leaves the ordered queue)."""
        import numpy as np

        from ..parallel.arrays import PencilArray

        if batch.kind != "fft" or any(
                isinstance(e.payload, PencilArray)
                for e in batch.entries):
            return None
        e0 = batch.entries[0]
        plan, direction = e0.plan, e0.direction
        entries = list(batch.entries)

        def pack():
            dt = (plan.dtype_physical if direction == "forward"
                  else plan.dtype_spectral)
            if len(entries) == 1:
                return np.asarray(entries[0].payload, dtype=dt)
            return np.stack(
                [np.asarray(e.payload, dtype=dt) for e in entries],
                axis=-1)

        return pack

    def _dispatch_meta(self, batch: Batch) -> dict:
        """What ``certify(engine=True)`` needs to re-verify this
        dispatch against its ``collective_costs`` prediction — wire
        dtype and priced wire bytes included, so a dispatch whose
        logged payload size disagrees with the plan's (possibly
        reduced-precision) schedule fails ``verify_dispatch_log``
        typed instead of certifying cleanly, and mixed-precision
        traffic is auditable per dispatch."""
        B = len(batch.entries)
        # "trace" (the leader's) rides the engine task meta: the
        # executor installs it as ambient context around the dispatch,
        # so engine/guard/retry records journal under the request's id
        # (trace-ctx lint: this dict must carry the inbound trace)
        meta = {"service": self._sid, "kind": batch.kind,
                "key": batch.key, "n": B, "cost": batch.cost,
                "trace": batch.entries[0].trace}
        if batch.kind == "fft":
            e0 = batch.entries[0]
            extra = (B,) if B > 1 else ()
            meta.update(plan=e0.plan, direction=e0.direction,
                        extra_dims=extra,
                        wire_dtype=e0.plan.wire_dtype,
                        wire_bytes=e0.plan.predicted_wire_bytes(extra))
        return meta

    def _validate_entry(self, batch: Batch, entry: _Entry
                        ) -> Optional[BaseException]:
        from ..parallel.arrays import PencilArray

        u = entry.payload
        if not isinstance(u, PencilArray):
            return None
        if batch.kind == "fft":
            e0 = batch.entries[0]
            pen = (e0.plan.input_pencil if e0.direction == "forward"
                   else e0.plan.output_pencil)
            if u.pencil != pen:
                return StaleRequestError(
                    f"request {entry.ticket.id}: payload lives on "
                    f"{u.pencil!r}, plan expects {pen!r} (a device "
                    f"payload cannot follow a rebuilt plan; submit "
                    f"host arrays against a named plan to survive "
                    f"reformation)")
        elif u.pencil != batch.entries[0].payload.pencil:
            # reshard coalescing stacks payloads: every member must
            # live on the SAME pencil (same mesh incarnation)
            return StaleRequestError(
                f"request {entry.ticket.id}: reshard payload pencil "
                f"differs from its coalesce group's")
        return None

    def _run_batch(self, batch: Batch,
                   host_operand=None) -> List[object]:
        """Build the coalesced operand, execute ONE dispatch, split the
        results per request.  Runs inside ``guarded_step`` on the
        engine's consumer thread — re-runnable by construction (inputs
        are never donated on the serve path, and ``host_operand`` — the
        pool-packed host stack, when the batch had one — re-scatters
        cleanly on every retry)."""
        from .. import guard

        entries = batch.entries
        B = len(entries)
        if batch.kind == "reshard":
            from ..parallel.transpositions import reshard

            xs = [self._materialize_reshard(e) for e in entries]
            arr = xs[0] if B == 1 else self._stack(xs)
            # the service's hbm_limit rides the dispatch: a coalesced
            # whale batch replans at its coalesced extra_dims, so the
            # synthesized chunking scales with the batch (and a batch
            # for which nothing fits fails THESE tickets typed — the
            # isolation contract, not an unbounded dispatch)
            out = reshard(arr, entries[0].dest, method=entries[0].method,
                          hbm_limit=self.hbm_limit)
            return self._split(out, B)
        e0 = entries[0]
        plan, direction = e0.plan, e0.direction
        arr = self._coalesce_fft(plan, direction, entries,
                                 host_operand=host_operand)
        if guard.enabled():
            # isolation path: the EAGER schedule — per-hop invariant
            # probes inside each exchange program, hang watchdog per
            # dispatch; a corrupted hop raises typed IntegrityError
            # scoped to this batch (the fast path below runs the whole
            # chain as one opaque program the probes cannot see into)
            out = (plan.forward(arr) if direction == "forward"
                   else plan.backward(arr))
        else:
            cp = self.registry.compiled(
                plan, arr.extra_dims,
                tenants=[e.ticket.tenant for e in entries])
            out = (cp.forward(arr) if direction == "forward"
                   else cp.backward(arr))
        return self._split(out, B)

    @staticmethod
    def _stack(xs) -> object:
        """Coalesce B single-sample arrays along one trailing batch dim
        (``extra_dims == (B,)``) — each hop's single collective then
        carries the whole batch (bytes ×B, count ×1)."""
        import jax
        import jax.numpy as jnp

        from ..parallel.arrays import PencilArray

        pen = xs[0].pencil
        data = jnp.stack([x.data for x in xs], axis=-1)
        data = jax.device_put(data, pen.sharding(1))
        return PencilArray(pen, data, (len(xs),))

    @staticmethod
    def _split(out, B: int) -> List[object]:
        from ..parallel.arrays import PencilArray

        if B == 1:
            return [out]
        # ONE jitted dispatch slices all B samples (an eager sharded
        # getitem per sample costs ~10x the whole batched transform on
        # the virtual mesh — measured; the jitted splitter keeps every
        # slice sharded in place)
        parts = _split_fn(B)(out.data)
        return [PencilArray(out.pencil, p, ()) for p in parts]

    def _coalesce_fft(self, plan, direction: str, entries: List[_Entry],
                      *, host_operand=None):
        """The batch operand: an all-host batch is stacked ON THE HOST
        (by the engine's host pool — ``host_operand``, built while the
        previous batch's device program ran — or inline on a cold
        path) and scattered in ONE ``from_global`` (one pad/permute/
        device_put for the whole batch — B per-sample scatters plus a
        device-side restack would eat the coalescing win); any device
        payload in the batch falls back to per-sample materialize +
        device stack."""
        import numpy as np

        from ..parallel.arrays import PencilArray

        pen = (plan.input_pencil if direction == "forward"
               else plan.output_pencil)
        dt = (plan.dtype_physical if direction == "forward"
              else plan.dtype_spectral)
        B = len(entries)
        if host_operand is not None:
            return PencilArray.from_global(
                pen, host_operand, extra_ndims=0 if B == 1 else 1)
        if not any(isinstance(e.payload, PencilArray) for e in entries):
            if B == 1:
                return PencilArray.from_global(
                    pen, np.asarray(entries[0].payload, dtype=dt))
            host = np.stack(
                [np.asarray(e.payload, dtype=dt) for e in entries],
                axis=-1)
            return PencilArray.from_global(pen, host, extra_ndims=1)
        xs = [self._materialize_fft(plan, pen, dt, e) for e in entries]
        return xs[0] if B == 1 else self._stack(xs)

    def _materialize_fft(self, plan, pen, dt, entry: _Entry):
        # stale-pencil detection lives in _validate_entry (the
        # per-entry pre-dispatch check) — by here every device payload
        # was validated against this batch's pencil
        import jax.numpy as jnp

        from ..parallel.arrays import PencilArray

        u = entry.payload
        if isinstance(u, PencilArray):
            if u.dtype != dt:
                u = PencilArray(u.pencil, u.data.astype(dt), u.extra_dims)
            return u
        return PencilArray.from_global(pen, jnp.asarray(u, dtype=dt))

    @staticmethod
    def _materialize_reshard(entry: _Entry):
        return entry.payload

    def _finish(self, batch: Batch, outs: Optional[List[object]],
                err: Optional[BaseException], execute_s: float) -> None:
        from .. import obs

        with self._lock:
            self._inflight = [b for b in self._inflight
                              if b is not batch]
        for i, e in enumerate(batch.entries):
            self._finish_one(batch.key, e,
                             result=None if err is not None else outs[i],
                             error=err)
        # feed the load tracker: the dispatch's measured wall time IS
        # the service-rate sample every projection reads (ok or failed
        # — the time was equally real)
        self.queue.note_batch_done(batch, execute_s)
        if obs.enabled():
            obs.histogram("serve.execute_seconds",
                          kind=batch.kind).observe(execute_s)
        # a reformation may have parked dropped batches while this one
        # was in flight: with the dispatch done, resubmission is safe —
        # and a streaming pump disarmed by a consumer-thread
        # self-reform (the _rearm hook refuses to act there) is
        # re-armed HERE, where the in-flight dispatch provably ended
        self._flush_parked()
        if self._streaming and not self._closed and self.queue.depth():
            self._schedule_pump()

    def _finish_one(self, batch_key: str, e: _Entry, *, result=None,
                    error: Optional[BaseException] = None) -> None:
        from .. import obs

        outcome = "ok" if error is None else type(error).__name__
        self.queue.release(e)
        t = e.ticket
        if error is None:
            t._fulfill(result)
        else:
            t._fail(error)
        late = (error is None and e.deadline is not None
                and t.t_done > e.deadline)
        if obs.enabled():
            obs.counter("serve.completed", tenant=t.tenant,
                        outcome=outcome).inc()
            obs.histogram("serve.wait_seconds", tenant=t.tenant).observe(
                max(0.0, (t.t_dispatch or t.t_submit) - t.t_submit))
            obs.gauge("serve.queue_depth", tenant=t.tenant).set(
                self.queue.depth(t.tenant))
            # a non-ok completion gates a client-visible failure:
            # fsync-critical via the per-record override
            obs.record_event(
                "serve.complete", _fsync=(error is not None),
                tenant=t.tenant, req=t.id, outcome=outcome,
                seconds=t.t_done - t.t_submit, key=batch_key,
                trace=e.trace,
                **({"error": str(error)} if error is not None else {}))
            if late:
                # the completion enforcement point: the answer is
                # returned (the work is done) but the violation is on
                # the record, fsync-critical — an SLO breach must
                # survive even a crash right after it
                obs.counter("serve.slo_violations",
                            tenant=t.tenant).inc()
                obs.record_event(
                    "serve.slo_violation", tenant=t.tenant, req=t.id,
                    deadline_s=e.deadline - t.t_submit,
                    late_s=t.t_done - e.deadline, key=batch_key,
                    trace=e.trace)
        slo = self._slos.get(t.tenant)
        if slo is not None and slo.deadline_s is not None:
            # every deadline-carrying completion is a burn sample: a
            # late answer and a deadline-typed failure (expired /
            # projected shed) both spend the tenant's error budget
            alert = self.burn.note(
                t.tenant, late or isinstance(error, DeadlineError))
            if obs.enabled():
                obs.gauge("serve.burn_rate", tenant=t.tenant).set(
                    self.burn.burn_rate(t.tenant) or 0.0)
                if alert is not None:
                    # the page: the budget is burning threshold-x too
                    # fast — fsync-critical (an overload episode must
                    # be on the record even if the process dies in it)
                    obs.counter("serve.burn_alerts",
                                tenant=t.tenant).inc()
                    obs.record_event("serve.burn_alert", _fsync=True,
                                     **alert)
        with self._lock:
            self._completed[outcome] = self._completed.get(outcome, 0) + 1
            if late:
                self._slo_violations += 1

    # -- pre-flight certification ------------------------------------------
    def certify(self, *, hbm_limit: Optional[int] = None,
                raise_on_error: bool = True, engine: bool = False) -> dict:
        """Statically certify every resident plan BEFORE it serves
        traffic: each registered fingerprint's compiled executables
        (forward AND backward, every resident ``extra_dims``/donate
        variant — or a fresh default-batch trace when nothing has
        compiled yet) are extracted with
        :mod:`pencilarrays_tpu.analysis.spmd` and proved equal,
        op-for-op, to the plan's ``collective_costs`` prediction;
        ``hbm_limit`` additionally bounds each certified variant's
        static peak-HBM at that variant's OWN ``extra_dims`` (a
        coalesced-batch executable is priced at its batch).

        One ``analysis.check`` journal record per certified target
        (non-ok fsync-critical).  Returns the sweep report; with
        ``raise_on_error`` the first divergence re-raises its typed
        error (:class:`~pencilarrays_tpu.analysis.errors.
        ScheduleMismatchError` naming the offending op, ...) after the
        report entry is journaled — the pre-flight gate.

        ``engine=True`` additionally certifies the PIPELINED execution
        this service actually ran: the engine's issued dispatch log
        (filtered to this service's batches) is proved equal to the
        serialized schedule — issue order == enqueue order, and each
        dispatched program's compiled collective trace == its plan's
        ``collective_costs`` prediction op-for-op
        (:func:`~pencilarrays_tpu.analysis.spmd.verify_dispatch_log`;
        typed :class:`~pencilarrays_tpu.analysis.errors.
        DispatchOrderError` / :class:`~pencilarrays_tpu.analysis.
        errors.ScheduleMismatchError` naming the first divergence).
        The result rides the report under ``"engine"``."""
        from ..analysis.errors import AnalysisError
        from ..analysis.spmd import certify_plan

        t0 = time.perf_counter()
        report: dict = {"plans": [], "ok": True}
        for key in self.registry.keys():
            plan = self.registry.plan(key)
            if plan is None:
                continue
            compiled = self.registry.executables(key)
            targets = ([(cp, cp.extra_dims) for cp in compiled]
                       or [(None, None)])
            for cp, extra in targets:
                # hbm_limit rides each variant's certification: a
                # resident coalesced-batch executable is bounded at ITS
                # extra_dims, not the plan's default batch
                try:
                    rec = certify_plan(plan, extra, compiled=cp,
                                       hbm_limit=hbm_limit,
                                       target=f"serve:{key}")
                except AnalysisError as e:
                    if raise_on_error:
                        raise
                    rec = {"target": f"serve:{key}",
                           "outcome": type(e).__name__,
                           "error": str(e),
                           "extra_dims": list(
                               extra if extra is not None
                               else plan.batch_dims)}
                    report["ok"] = False
                report["plans"].append(rec)
        if engine:
            from ..analysis.spmd import verify_dispatch_log

            eng = self.engine()
            mine = [r for r in eng.dispatch_log()
                    if r.meta.get("service") == self._sid]
            try:
                report["engine"] = verify_dispatch_log(
                    mine, source=f"serve-engine:{eng.name}")
                # the log is a bounded window: a certification that did
                # not see the whole run must say so, never imply it did
                report["engine"]["log_truncated"] = \
                    eng.stats()["log_truncated"]
            except AnalysisError as e:
                if raise_on_error:
                    raise
                report["engine"] = {"outcome": type(e).__name__,
                                    "error": str(e)}
                report["ok"] = False
        report["seconds"] = time.perf_counter() - t0
        report["certified"] = len(report["plans"])
        return report

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        """Service snapshot: registry hit/miss, per-tenant accounting,
        queue depth, dispatch/completion counts, SLO violation count
        and the pressure-gate state (``None`` when no gate is
        armed)."""
        with self._lock:
            completed = dict(self._completed)
            violations = self._slo_violations
        return {"registry": self.registry.stats(),
                "tenants": self.queue.tenants(),
                "queue_depth": self.queue.depth(),
                "dispatches": self._dispatches,
                "completed": completed,
                "slo_violations": violations,
                "pressure": (self._gate.state
                             if self._gate is not None else None)}
