"""Load shedding + backpressure — the overload gate.

When offered load exceeds service capacity, SOMETHING gives.  Without
this module it was the admission queue (growing until per-tenant quota
rejections hit arbitrary tenants) and every tenant's latency (the queue
drains in cost order, so the storm's own traffic starves everyone).
The pressure gate makes the sacrifice explicit, ordered, and journaled:

* the gate watches the ONE load projection
  (:class:`~pencilarrays_tpu.serve.slo.LoadTracker`): the projected
  **queue drain time** in the router's bytes-equivalent currency;
* one rung BEFORE shedding (``degrade_water_s``, PR 19, opt-in): the
  gate enters ``degrade`` — sheddable-tier requests from tenants that
  declared an accuracy budget (:class:`~pencilarrays_tpu.serve.slo.
  SLO.max_rel_l2`) are still served, on a cheaper wire precision
  (full -> bf16 -> fp8) within that budget; served degraded beats
  shed, and tenants without a budget fall through untouched;
* when drain crosses ``high_water_s`` the gate enters ``shed``:
  requests from tenants below the protected priority tier (the highest
  ``shed_priority`` among registered SLOs) are rejected typed at
  submit (:class:`~pencilarrays_tpu.serve.errors.AdmissionError`,
  ``reason="shed"``) — the cheapest possible rejection, one counter
  bump and a typed exception, nothing queued;
* one rung further (``evict_water_s``, default ``2 x high_water_s``)
  the gate enters ``evict``: already-queued sheddable entries are
  evicted — failed typed with the same ``reason="shed"`` — in
  admission-sequence order (deterministic: identical submission
  sequences evict identical sets, wall clocks only gate *when* the
  rung fires);
* recovery is **hysteretic**: the gate returns to ``ok`` only when
  drain falls below ``low_water_s`` — a storm hovering at the high
  water mark must not flap the gate open/shut per request;
* every state transition journals ``serve.pressure`` (fsync-critical —
  a shedding decision gates client-visible failures) with the full
  projection snapshot, so ``pa-obs timeline`` renders why.

The gate only arms when at least one registered SLO declares a
non-default ``shed_priority`` tier *below* another — with no SLOs (or
one uniform tier) nothing is sheddable and the service keeps PR-10
behavior bit-for-bit (the ``BENCH_AUTOSCALE.json`` disabled-path
discipline).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

__all__ = ["PressurePolicy", "PressureGate"]


@dataclass(frozen=True)
class PressurePolicy:
    """The gate's water marks (seconds of projected queue drain).

    ``low_water_s < high_water_s <= evict_water_s`` is enforced;
    ``evict_water_s=None`` defaults to ``2 x high_water_s``.
    ``degrade_water_s`` (PR 19, optional) arms the precision-downgrade
    rung strictly between the hysteresis band's low mark and the shed
    mark: ``low_water_s < degrade_water_s < high_water_s``.  ``None``
    (default) keeps the PR-15 three-state machine bit-for-bit."""

    high_water_s: float = 1.0
    low_water_s: float = 0.5
    evict_water_s: Optional[float] = None
    degrade_water_s: Optional[float] = None

    def __post_init__(self):
        if self.high_water_s <= 0:
            raise ValueError(
                f"high_water_s must be positive, got {self.high_water_s}")
        if not (0 <= self.low_water_s < self.high_water_s):
            raise ValueError(
                f"hysteresis needs 0 <= low_water_s < high_water_s, got "
                f"low={self.low_water_s} high={self.high_water_s}")
        evict = self.evict_water_s
        if evict is not None and evict < self.high_water_s:
            raise ValueError(
                f"evict_water_s ({evict}) below high_water_s "
                f"({self.high_water_s}): the evict rung is an escalation")
        deg = self.degrade_water_s
        if deg is not None and not (
                self.low_water_s < deg < self.high_water_s):
            raise ValueError(
                f"degrade_water_s ({deg}) must sit strictly inside the "
                f"hysteresis band (low_water_s={self.low_water_s}, "
                f"high_water_s={self.high_water_s}): the downgrade rung "
                f"fires BEFORE shedding and recovers with it")

    @property
    def evict_at(self) -> float:
        return (self.evict_water_s if self.evict_water_s is not None
                else 2.0 * self.high_water_s)


class PressureGate:
    """The hysteretic overload state machine (module docstring).

    States: ``ok`` -> ``degrade`` (serve sheddable on a cheaper wire
    precision, when armed) -> ``shed`` (reject sheddable at submit) ->
    ``evict`` (also evict queued sheddable); back to ``ok`` only below
    the low water mark.  Thread-safe; :meth:`update` is called with a
    fresh drain projection on every admission and every take."""

    STATES = ("ok", "degrade", "shed", "evict")

    def __init__(self, policy: Optional[PressurePolicy] = None):
        self.policy = policy or PressurePolicy()
        self._lock = threading.Lock()
        self._state = "ok"
        self._transitions = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def transitions(self) -> int:
        """How many state changes the gate has made (the no-flap
        drill's assertion: storm -> recover is exactly two)."""
        with self._lock:
            return self._transitions

    def update(self, drain_s: Optional[float],
               projection=None) -> str:
        """Feed one drain projection; returns the (possibly new) state
        and journals the transition when it changed.  ``None`` (a blind
        tracker) never changes state: no measurement, no verdict.
        ``projection`` may be a dict OR a zero-arg callable producing
        one — called only when a transition actually journals, so the
        per-admission hot path never builds the full snapshot."""
        if drain_s is None:
            return self.state
        p = self.policy
        with self._lock:
            prev = self._state
            if drain_s >= p.evict_at:
                nxt = "evict"
            elif drain_s >= p.high_water_s:
                # escalation is immediate; de-escalation from evict to
                # shed happens here too (the evict rung fired, queued
                # sheddable work is gone, drain fell between the marks)
                nxt = "shed"
            elif (p.degrade_water_s is not None
                  and drain_s >= p.degrade_water_s):
                # the downgrade rung: an open gate escalates to
                # "degrade"; a gate already shedding HOLDS (shed
                # recovers through the full hysteresis at low water,
                # not at the degrade mark — no shed/degrade flap) and
                # evict de-escalates one rung (drain provably < high)
                nxt = ("degrade" if prev == "ok"
                       else "shed" if prev == "evict" else prev)
            elif drain_s <= p.low_water_s:
                # at-or-below low water recovers: a fully-drained queue
                # projects EXACTLY 0.0, which must reopen a gate even
                # when low_water_s is 0 (legal per the policy check)
                nxt = "ok"
            else:
                # the hysteresis band (below high water, at/above low):
                # hold the current state — an "ok" gate stays open
                # until HIGH water, a shedding gate stays shut until
                # LOW water, and an "evict" gate de-escalates to shed
                # (its drain is provably below high, hence below evict)
                nxt = "shed" if prev == "evict" else prev
            changed = nxt != prev
            if changed:
                self._state = nxt
                self._transitions += 1
        if changed:
            self._journal(prev, nxt, drain_s, projection)
        return nxt

    @staticmethod
    def _journal(prev: str, state: str, drain_s: float,
                 projection) -> None:
        from .. import obs

        if not obs.enabled():
            return
        if callable(projection):
            projection = projection()
        obs.counter("serve.pressure_transitions", state=state).inc()
        obs.record_event("serve.pressure", state=state, prev=prev,
                         drain_s=drain_s,
                         **({"projection": projection}
                            if projection else {}))

    def sheds(self, shed_priority: int, protected_priority: int) -> bool:
        """Would the gate reject a request of ``shed_priority`` right
        now?  Sheddable = strictly below the protected tier (the
        highest registered priority — with one uniform tier nothing is
        ever shed).  The ``degrade`` state does NOT shed: its whole
        point is serving sheddable traffic (cheaper) instead."""
        if shed_priority >= protected_priority:
            return False
        return self.state in ("shed", "evict")

    def degrades(self, shed_priority: int,
                 protected_priority: int) -> bool:
        """Would the gate downgrade a request of ``shed_priority`` to a
        cheaper wire precision right now?  Same sheddability rule as
        :meth:`sheds`; true in EVERY pressure state — under ``shed`` /
        ``evict`` the downgrade rung is what keeps a budget-declaring
        tenant (:class:`~pencilarrays_tpu.serve.slo.SLO.max_rel_l2`)
        served where a budget-less one is rejected."""
        if shed_priority >= protected_priority:
            return False
        return self.state != "ok"

    def evicting(self) -> bool:
        return self.state == "evict"

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._state = "ok"
            self._transitions = 0
