"""Service-level objectives — per-tenant deadlines, priorities, and the
load projection they are enforced against.

A tenant with a latency budget has, until now, no way to express it:
an overload storm just grows the admission queue until quota rejections,
and a request that will *obviously* miss its deadline still burns a
dispatch.  This module adds the vocabulary:

* :class:`SLO` — what a tenant declares at registration
  (:meth:`~pencilarrays_tpu.serve.PlanService.set_slo`): a per-request
  completion ``deadline_s``, an advisory ``p99_budget_s``, and the
  ``shed_priority`` the load-shedding gate
  (:mod:`~pencilarrays_tpu.serve.shed`) orders sacrifices by;
* :class:`LoadTracker` — the admission queue's own arrival / cost /
  service history in the router's **bytes-equivalent currency** (the
  same ``count x latency_bytes + bytes`` score the cost-ordered
  scheduler already prices batches with).  Everything downstream — the
  admission-time deadline projection, the shedding gate's drain
  estimate, the autoscaler's grow/shrink windows — reads ONE
  projection, so they can never disagree about how loaded the service
  is.

Deadlines are enforced at THREE points (see ``docs/Serving.md``):

1. **admission** — a request whose *projected* wait (queued cost ahead
   of it divided by the measured service rate) already exceeds its
   deadline is rejected typed
   (:class:`~pencilarrays_tpu.serve.errors.DeadlineError`,
   ``reason="projected"``) — never a silent late answer;
2. **take** — entries that expired while queued are shed before
   dispatch (``reason="expired"``): an expired request must not burn
   the mesh time that would make its *neighbors* late too;
3. **completion** — a request that was dispatched in time but finished
   late journals a fsync-critical ``serve.slo_violation`` record and
   ticks ``serve.slo_violations{tenant=}`` — the result is still
   returned (the work is done), but the violation is on the record.

The tracker is deliberately conservative while blind: with no completed
dispatch in its window it projects ``None`` and admission lets
everything through — a service that has never measured itself has no
basis to reject, and the completion-point accounting will seed the
window within one batch.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

__all__ = ["SLO", "LoadTracker"]


@dataclass(frozen=True)
class SLO:
    """One tenant's service-level objective.

    Parameters
    ----------
    deadline_s:
        Per-request completion budget, measured from admission
        (``None``: no deadline — the tenant keeps PR-10 semantics).
    p99_budget_s:
        Advisory p99 latency budget.  Not enforced per request (a p99
        is a population property); it rides the tenant's
        ``serve.slo_violation`` accounting and the autoscale bench
        report so operators can tune capacity against it.
    shed_priority:
        Load-shedding order: under pressure the gate sheds lower
        priorities first, and tenants of the HIGHEST registered
        priority are never shed (see
        :class:`~pencilarrays_tpu.serve.shed.PressureGate`).  Default 0
        — an SLO-less tenant is maximally sheddable.
    """

    deadline_s: Optional[float] = None
    p99_budget_s: Optional[float] = None
    shed_priority: int = 0

    def __post_init__(self):
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be positive, got {self.deadline_s}")
        if self.p99_budget_s is not None and self.p99_budget_s <= 0:
            raise ValueError(
                f"p99_budget_s must be positive, got {self.p99_budget_s}")


class LoadTracker:
    """Arrival / cost / service history in the bytes-equivalent
    currency — THE load projection every overload decision reads.

    Thread-safe.  ``window`` bounds the completion history (service
    rate = total priced cost / total measured seconds over the
    window — a ratio of sums, so one tiny batch cannot dominate the
    estimate the way a mean-of-ratios would let it)."""

    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        self._completions: deque = deque(maxlen=max(1, int(window)))
        self._arrivals: deque = deque(maxlen=max(1, int(window)))
        self._queued_cost = 0       # admitted, not yet taken
        self._inflight_cost = 0     # taken, not yet completed
        self._queued_n = 0
        self._inflight_n = 0
        # the rate is read on EVERY admission (hot path) but changes
        # only at completions: cache it per completion-window version
        self._version = 0
        self._rate_cache = (-1, None)

    # -- feeding (the queue's accounting hooks) ----------------------------
    def note_arrival(self, cost_bytes: int,
                     now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._arrivals.append((now, int(cost_bytes)))
            self._queued_cost += int(cost_bytes)
            self._queued_n += 1

    def note_taken(self, cost_bytes: int) -> None:
        """An entry left the queue for dispatch (still counts toward
        drain until its batch completes)."""
        with self._lock:
            self._queued_cost = max(0, self._queued_cost - int(cost_bytes))
            self._queued_n = max(0, self._queued_n - 1)
            self._inflight_cost += int(cost_bytes)
            self._inflight_n += 1

    def note_removed(self, cost_bytes: int) -> None:
        """An entry left the queue WITHOUT dispatching (expired shed,
        pressure eviction): its cost stops weighing on the drain
        projection immediately."""
        with self._lock:
            self._queued_cost = max(0, self._queued_cost - int(cost_bytes))
            self._queued_n = max(0, self._queued_n - 1)

    def note_completed(self, cost_bytes: int, n: int,
                       execute_s: float) -> None:
        """One dispatched batch finished: ``cost_bytes`` priced cost,
        ``n`` requests, ``execute_s`` measured wall seconds.  Failed
        dispatches feed the window too — their time was just as real."""
        with self._lock:
            self._inflight_cost = max(
                0, self._inflight_cost - int(cost_bytes))
            self._inflight_n = max(0, self._inflight_n - int(n))
            if execute_s > 0:
                self._completions.append((int(cost_bytes),
                                          float(execute_s)))
                self._version += 1

    # -- the projection ----------------------------------------------------
    def rate_bytes_per_s(self) -> Optional[float]:
        """Measured service rate (priced cost per wall second) over the
        completion window; ``None`` until the first measurable
        completion — a never-measured service projects nothing."""
        with self._lock:
            ver, cached = self._rate_cache
            if ver == self._version:
                return cached
            if not self._completions:
                rate = None
            else:
                cost = sum(c for c, _ in self._completions)
                secs = sum(s for _, s in self._completions)
                rate = (cost / secs if secs > 0 and cost > 0 else None)
            self._rate_cache = (self._version, rate)
        return rate

    def projected_wait_s(self, ahead_cost_bytes: Optional[int] = None
                         ) -> Optional[float]:
        """Seconds a request admitted NOW would wait before its own
        dispatch completes: everything queued and in flight (or the
        explicit ``ahead_cost_bytes``) divided by the measured rate.
        ``None`` while the tracker is blind."""
        rate = self.rate_bytes_per_s()
        if rate is None:
            return None
        if ahead_cost_bytes is None:
            with self._lock:
                ahead_cost_bytes = self._queued_cost + self._inflight_cost
        return ahead_cost_bytes / rate

    def drain_s(self) -> Optional[float]:
        """Projected time to drain everything queued + in flight — the
        shedding gate's water-mark currency."""
        return self.projected_wait_s()

    def arrival_cost_per_s(self) -> Optional[float]:
        """Offered load over the arrival window (bytes-equivalent per
        second); ``None`` with fewer than two arrivals."""
        with self._lock:
            if len(self._arrivals) < 2:
                return None
            t0, _ = self._arrivals[0]
            t1, _ = self._arrivals[-1]
            cost = sum(c for _, c in self._arrivals)
        if t1 <= t0:
            return None
        return cost / (t1 - t0)

    def snapshot(self) -> dict:
        """The projection record journaled with every pressure
        transition and scale decision — the inputs, so ``pa-obs
        timeline`` can render WHY."""
        with self._lock:
            queued = self._queued_cost
            inflight = self._inflight_cost
            queued_n = self._queued_n
            inflight_n = self._inflight_n
        rate = self.rate_bytes_per_s()
        drain = (None if rate is None
                 else (queued + inflight) / rate)
        return {
            "queued_cost_bytes": queued,
            "inflight_cost_bytes": inflight,
            "queued_requests": queued_n,
            "inflight_requests": inflight_n,
            "rate_bytes_per_s": rate,
            "arrival_cost_per_s": self.arrival_cost_per_s(),
            "drain_s": drain,
        }

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._completions.clear()
            self._arrivals.clear()
            self._queued_cost = self._inflight_cost = 0
            self._queued_n = self._inflight_n = 0
            self._version += 1
            self._rate_cache = (-1, None)
