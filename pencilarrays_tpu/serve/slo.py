"""Service-level objectives — per-tenant deadlines, priorities, and the
load projection they are enforced against.

A tenant with a latency budget has, until now, no way to express it:
an overload storm just grows the admission queue until quota rejections,
and a request that will *obviously* miss its deadline still burns a
dispatch.  This module adds the vocabulary:

* :class:`SLO` — what a tenant declares at registration
  (:meth:`~pencilarrays_tpu.serve.PlanService.set_slo`): a per-request
  completion ``deadline_s``, an advisory ``p99_budget_s``, and the
  ``shed_priority`` the load-shedding gate
  (:mod:`~pencilarrays_tpu.serve.shed`) orders sacrifices by;
* :class:`LoadTracker` — the admission queue's own arrival / cost /
  service history in the router's **bytes-equivalent currency** (the
  same ``count x latency_bytes + bytes`` score the cost-ordered
  scheduler already prices batches with).  Everything downstream — the
  admission-time deadline projection, the shedding gate's drain
  estimate, the autoscaler's grow/shrink windows — reads ONE
  projection, so they can never disagree about how loaded the service
  is.

Deadlines are enforced at THREE points (see ``docs/Serving.md``):

1. **admission** — a request whose *projected* wait (queued cost ahead
   of it divided by the measured service rate) already exceeds its
   deadline is rejected typed
   (:class:`~pencilarrays_tpu.serve.errors.DeadlineError`,
   ``reason="projected"``) — never a silent late answer;
2. **take** — entries that expired while queued are shed before
   dispatch (``reason="expired"``): an expired request must not burn
   the mesh time that would make its *neighbors* late too;
3. **completion** — a request that was dispatched in time but finished
   late journals a fsync-critical ``serve.slo_violation`` record and
   ticks ``serve.slo_violations{tenant=}`` — the result is still
   returned (the work is done), but the violation is on the record.

The tracker is deliberately conservative while blind: with no completed
dispatch in its window it projects ``None`` and admission lets
everything through — a service that has never measured itself has no
basis to reject, and the completion-point accounting will seed the
window within one batch.

PR 18 adds :class:`BurnRateMonitor` — the SLO **error-budget burn
rate**: each tenant's budget allows a fraction of completions to bust
their deadline (``budget``, e.g. 0.01 = 1%); the monitor tracks the
observed violation fraction over a sliding time window and reports it
as a multiple of the budget (burn rate 1.0 = burning exactly at
budget; 4.0 = the budget will be gone in a quarter of the period).
``PlanService`` feeds it at completion, exports per-tenant
``serve.burn_rate`` gauges into the metrics snapshot (and so the
mesh/fleet fold), and journals a fsync-critical ``serve.burn_alert``
the moment a tenant crosses the alert threshold — edge-triggered with
hysteresis, so an overload window produces ONE durable alert record,
not one per completion.

Every projection here is O(1) per call: the arrival window keeps a
running cost sum (maintained against the deque's own evictions) and
the burn windows keep running violation counts — the loadgen harness
(``benchmarks/loadgen.py``) drives these paths at 10⁴–10⁵ depth,
where a per-call window scan would quietly turn the admission hot
path superlinear (``scan_stats`` pins that in
``tests/test_serve_depth.py``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["SLO", "LoadTracker", "BurnRateMonitor"]


@dataclass(frozen=True)
class SLO:
    """One tenant's service-level objective.

    Parameters
    ----------
    deadline_s:
        Per-request completion budget, measured from admission
        (``None``: no deadline — the tenant keeps PR-10 semantics).
    p99_budget_s:
        Advisory p99 latency budget.  Not enforced per request (a p99
        is a population property); it rides the tenant's
        ``serve.slo_violation`` accounting and the autoscale bench
        report so operators can tune capacity against it.
    shed_priority:
        Load-shedding order: under pressure the gate sheds lower
        priorities first, and tenants of the HIGHEST registered
        priority are never shed (see
        :class:`~pencilarrays_tpu.serve.shed.PressureGate`).  Default 0
        — an SLO-less tenant is maximally sheddable.
    max_rel_l2:
        Accuracy floor for the precision-downgrade rung (PR 19): the
        worst relative l2 error this tenant tolerates on a served
        result.  Under ``degrade`` pressure the service may swap a
        sheddable tenant's plan to a cheaper wire precision, but only
        onto rungs whose *calibrated* error envelope
        (``BENCH_WIRE.json``) fits under this bound — served degraded
        beats shed, but never silently out of tolerance.  ``None``
        (default): the tenant opted out; its requests are never
        downgraded (and so reach the shed rung first under pressure).
    """

    deadline_s: Optional[float] = None
    p99_budget_s: Optional[float] = None
    shed_priority: int = 0
    max_rel_l2: Optional[float] = None

    def __post_init__(self):
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be positive, got {self.deadline_s}")
        if self.p99_budget_s is not None and self.p99_budget_s <= 0:
            raise ValueError(
                f"p99_budget_s must be positive, got {self.p99_budget_s}")
        if self.max_rel_l2 is not None and self.max_rel_l2 <= 0:
            raise ValueError(
                f"max_rel_l2 must be positive, got {self.max_rel_l2}")


class LoadTracker:
    """Arrival / cost / service history in the bytes-equivalent
    currency — THE load projection every overload decision reads.

    Thread-safe.  ``window`` bounds the completion history (service
    rate = total priced cost / total measured seconds over the
    window — a ratio of sums, so one tiny batch cannot dominate the
    estimate the way a mean-of-ratios would let it)."""

    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        self._completions: deque = deque(maxlen=max(1, int(window)))
        self._arrivals: deque = deque(maxlen=max(1, int(window)))
        self._queued_cost = 0       # admitted, not yet taken
        self._inflight_cost = 0     # taken, not yet completed
        self._queued_n = 0
        self._inflight_n = 0
        # running sum of the arrival window — arrival_cost_per_s is
        # read on the load-export path (every 50 ms under a fleet
        # router), so it must not re-scan the window per call
        self._arrival_cost_sum = 0
        self._arrivals_scanned = 0  # scan_stats: pins the O(1) claim
        # the rate is read on EVERY admission (hot path) but changes
        # only at completions: cache it per completion-window version
        self._version = 0
        self._rate_cache = (-1, None)

    # -- feeding (the queue's accounting hooks) ----------------------------
    def note_arrival(self, cost_bytes: int,
                     now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            # the deque evicts its oldest element when appended at
            # capacity: the running sum must shed that element first
            if len(self._arrivals) == self._arrivals.maxlen:
                self._arrival_cost_sum -= self._arrivals[0][1]
            self._arrivals.append((now, int(cost_bytes)))
            self._arrival_cost_sum += int(cost_bytes)
            self._queued_cost += int(cost_bytes)
            self._queued_n += 1

    def note_taken(self, cost_bytes: int) -> None:
        """An entry left the queue for dispatch (still counts toward
        drain until its batch completes)."""
        with self._lock:
            self._queued_cost = max(0, self._queued_cost - int(cost_bytes))
            self._queued_n = max(0, self._queued_n - 1)
            self._inflight_cost += int(cost_bytes)
            self._inflight_n += 1

    def note_removed(self, cost_bytes: int) -> None:
        """An entry left the queue WITHOUT dispatching (expired shed,
        pressure eviction): its cost stops weighing on the drain
        projection immediately."""
        with self._lock:
            self._queued_cost = max(0, self._queued_cost - int(cost_bytes))
            self._queued_n = max(0, self._queued_n - 1)

    def note_completed(self, cost_bytes: int, n: int,
                       execute_s: float) -> None:
        """One dispatched batch finished: ``cost_bytes`` priced cost,
        ``n`` requests, ``execute_s`` measured wall seconds.  Failed
        dispatches feed the window too — their time was just as real."""
        with self._lock:
            self._inflight_cost = max(
                0, self._inflight_cost - int(cost_bytes))
            self._inflight_n = max(0, self._inflight_n - int(n))
            if execute_s > 0:
                self._completions.append((int(cost_bytes),
                                          float(execute_s)))
                self._version += 1

    # -- the projection ----------------------------------------------------
    def rate_bytes_per_s(self) -> Optional[float]:
        """Measured service rate (priced cost per wall second) over the
        completion window; ``None`` until the first measurable
        completion — a never-measured service projects nothing."""
        with self._lock:
            ver, cached = self._rate_cache
            if ver == self._version:
                return cached
            if not self._completions:
                rate = None
            else:
                cost = sum(c for c, _ in self._completions)
                secs = sum(s for _, s in self._completions)
                rate = (cost / secs if secs > 0 and cost > 0 else None)
            self._rate_cache = (self._version, rate)
        return rate

    def projected_wait_s(self, ahead_cost_bytes: Optional[int] = None
                         ) -> Optional[float]:
        """Seconds a request admitted NOW would wait before its own
        dispatch completes: everything queued and in flight (or the
        explicit ``ahead_cost_bytes``) divided by the measured rate.
        ``None`` while the tracker is blind."""
        rate = self.rate_bytes_per_s()
        if rate is None:
            return None
        if ahead_cost_bytes is None:
            with self._lock:
                ahead_cost_bytes = self._queued_cost + self._inflight_cost
        return ahead_cost_bytes / rate

    def drain_s(self) -> Optional[float]:
        """Projected time to drain everything queued + in flight — the
        shedding gate's water-mark currency."""
        return self.projected_wait_s()

    def arrival_cost_per_s(self) -> Optional[float]:
        """Offered load over the arrival window (bytes-equivalent per
        second); ``None`` with fewer than two arrivals.  O(1): the
        window sum is maintained at arrival, never re-scanned — this
        is on the 50 ms load-export path a fleet router polls."""
        with self._lock:
            if len(self._arrivals) < 2:
                return None
            t0, _ = self._arrivals[0]
            t1, _ = self._arrivals[-1]
            cost = self._arrival_cost_sum
        if t1 <= t0:
            return None
        return cost / (t1 - t0)

    def scan_stats(self) -> dict:
        """Work counters for the scaling-pin tests
        (``tests/test_serve_depth.py``): ``arrivals_scanned`` counts
        arrival-window elements walked by the projection — the fixed
        running-sum path never walks any, so it stays 0 at any
        depth."""
        with self._lock:
            return {"arrivals_scanned": self._arrivals_scanned}

    def snapshot(self) -> dict:
        """The projection record journaled with every pressure
        transition and scale decision — the inputs, so ``pa-obs
        timeline`` can render WHY."""
        with self._lock:
            queued = self._queued_cost
            inflight = self._inflight_cost
            queued_n = self._queued_n
            inflight_n = self._inflight_n
        rate = self.rate_bytes_per_s()
        drain = (None if rate is None
                 else (queued + inflight) / rate)
        return {
            "queued_cost_bytes": queued,
            "inflight_cost_bytes": inflight,
            "queued_requests": queued_n,
            "inflight_requests": inflight_n,
            "rate_bytes_per_s": rate,
            "arrival_cost_per_s": self.arrival_cost_per_s(),
            "drain_s": drain,
        }

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._completions.clear()
            self._arrivals.clear()
            self._queued_cost = self._inflight_cost = 0
            self._queued_n = self._inflight_n = 0
            self._arrival_cost_sum = 0
            self._arrivals_scanned = 0
            self._version += 1
            self._rate_cache = (-1, None)


class BurnRateMonitor:
    """Per-tenant SLO error-budget burn rate over a sliding window.

    ``budget`` is the violation fraction a tenant's error budget
    allows (0.01 = 1% of completions may bust their deadline).  The
    observed violation fraction over the trailing ``window_s`` seconds,
    divided by the budget, is the **burn rate**: 1.0 = burning exactly
    at budget, ``threshold`` (default 4x) = alert.  Below
    ``min_events`` completions in the window the monitor reports
    ``None`` — a two-request sample must not page anyone.

    Alerts are edge-triggered with 2x hysteresis: :meth:`note` returns
    the alert payload exactly once when a tenant's rate crosses the
    threshold, and re-arms only after the rate falls below half of it
    — an overload window produces ONE durable ``serve.burn_alert``
    record, not one per completion.  Thread-safe; every operation is
    O(1) amortized (running counts, each window element evicted once).
    """

    def __init__(self, budget: float = 0.01, threshold: float = 4.0,
                 window_s: float = 30.0, min_events: int = 16):
        if budget <= 0:
            raise ValueError(f"budget must be positive, got {budget}")
        if threshold <= 0:
            raise ValueError(
                f"threshold must be positive, got {threshold}")
        self.budget = float(budget)
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.min_events = max(1, int(min_events))
        self._lock = threading.Lock()
        self._win: Dict[str, deque] = {}      # tenant -> (t, violated)
        self._n: Dict[str, int] = {}
        self._viol: Dict[str, int] = {}
        self._alerting: Dict[str, bool] = {}

    def _evict_locked(self, tenant: str, now: float) -> None:
        win = self._win[tenant]
        cutoff = now - self.window_s
        while win and win[0][0] < cutoff:
            _, violated = win.popleft()
            self._n[tenant] -= 1
            if violated:
                self._viol[tenant] -= 1

    def _rate_locked(self, tenant: str) -> Optional[float]:
        n = self._n.get(tenant, 0)
        if n < self.min_events:
            return None
        return (self._viol.get(tenant, 0) / n) / self.budget

    def note(self, tenant: str, violated: bool,
             now: Optional[float] = None) -> Optional[dict]:
        """Feed one completion.  Returns the ``serve.burn_alert``
        payload exactly once per threshold crossing, else ``None``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            win = self._win.setdefault(tenant, deque())
            win.append((now, bool(violated)))
            self._n[tenant] = self._n.get(tenant, 0) + 1
            if violated:
                self._viol[tenant] = self._viol.get(tenant, 0) + 1
            self._evict_locked(tenant, now)
            rate = self._rate_locked(tenant)
            if rate is None:
                return None
            if not self._alerting.get(tenant, False) \
                    and rate >= self.threshold:
                self._alerting[tenant] = True
                return {"tenant": tenant, "burn_rate": rate,
                        "threshold": self.threshold,
                        "window_s": self.window_s}
            if self._alerting.get(tenant, False) \
                    and rate < 0.5 * self.threshold:
                self._alerting[tenant] = False
        return None

    def burn_rate(self, tenant: str,
                  now: Optional[float] = None) -> Optional[float]:
        """The tenant's current burn rate (``None``: unknown tenant or
        too few completions in the window)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if tenant not in self._win:
                return None
            self._evict_locked(tenant, now)
            return self._rate_locked(tenant)

    def snapshot(self, now: Optional[float] = None
                 ) -> Dict[str, Optional[float]]:
        """Every tracked tenant's burn rate — what the service folds
        into its stats and the per-tenant gauges ride."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out = {}
            for t in list(self._win):
                self._evict_locked(t, now)
                out[t] = self._rate_locked(t)
            return out

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._win.clear()
            self._n.clear()
            self._viol.clear()
            self._alerting.clear()
