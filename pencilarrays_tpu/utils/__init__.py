from .permutations import (
    AbstractPermutation,
    NO_PERMUTATION,
    NoPermutation,
    Permutation,
    as_permutation,
    identity_permutation,
)
from .timers import (
    TimerOutput,
    disable_debug_timings,
    enable_debug_timings,
    timeit,
    timings_enabled,
)

__all__ = [
    "TimerOutput",
    "disable_debug_timings",
    "enable_debug_timings",
    "timeit",
    "timings_enabled",
    "AbstractPermutation",
    "NO_PERMUTATION",
    "NoPermutation",
    "Permutation",
    "as_permutation",
    "identity_permutation",
]
