from .permutations import (
    AbstractPermutation,
    NO_PERMUTATION,
    NoPermutation,
    Permutation,
    as_permutation,
    identity_permutation,
)

__all__ = [
    "AbstractPermutation",
    "NO_PERMUTATION",
    "NoPermutation",
    "Permutation",
    "as_permutation",
    "identity_permutation",
]
