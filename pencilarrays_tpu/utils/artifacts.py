"""Measured-verdict artifact loading — one shared discipline.

Hand-written fast paths in this tree (the Pallas permute, the flash
kernels, the pipelined FFT hops) must justify their default routing with
a NUMBER measured on the real chip, persisted as a JSON artifact at the
repo root (``PALLAS_FLASH_SWEEP.json``, ``PIPELINE_SWEEP.json``, ...).
This module is the one loader for those artifacts:

* default location: the repo root (three dirnames above this package) —
  a source-checkout convention;
* an env-var override points anywhere (installed/site-packages layouts,
  experiment sandboxes);
* results are cached per resolved path and invalidated by file mtime, so
  a sweep that writes the artifact MID-process is picked up without a
  restart (the lru_cache-pins-None failure mode of the round-5 advisor
  finding).
"""

from __future__ import annotations

import json
import os
from typing import Optional

__all__ = ["load_verdict_artifact", "repo_root"]

_CACHE: dict = {}  # resolved path -> (mtime, parsed doc | None)


def repo_root() -> str:
    """Source-checkout repo root (three levels above this file)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_verdict_artifact(filename: str, env_var: str = None
                          ) -> Optional[dict]:
    """Parsed JSON artifact ``filename`` (repo root, or the ``env_var``
    override path), or ``None`` when absent/unreadable.  Cached per
    path, invalidated when the file's mtime changes."""
    path = None
    if env_var:
        path = os.environ.get(env_var) or None
    if path is None:
        path = os.path.join(repo_root(), filename)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        _CACHE.pop(path, None)
        return None
    hit = _CACHE.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = None
    _CACHE[path] = (mtime, doc)
    return doc
