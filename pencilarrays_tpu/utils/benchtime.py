"""Device-time measurement for benchmarks — the one shared protocol.

Remote TPU tunnels do not synchronize on ``block_until_ready``, so naive
wall-clock timing measures dispatch, not kernels.  The protocol here:

1. run K iterations of the body inside ONE jitted ``lax.fori_loop`` with
   a scalar readback (forces real completion);
2. take the MINIMUM over several repeats per K arm (BenchmarkTools-style,
   suppresses tunnel jitter);
3. difference two K values to cancel dispatch/compile overhead;
4. guard the slope: non-positive or implausibly small slopes (noise
   swamping the difference) fall back to the conservative per-iteration
   upper bound ``t(k1)/k1`` instead of reporting absurd throughput.

The observed per-repeat spread rides along (``last_spread``): single
numbers through a shared tunnel are only trustworthy with their
variance attached, so the bench artifact records it per metric and
parity/speedup claims can be checked against the noise floor.

Used by ``bench.py`` and ``benchmarks/suite.py``.
"""

from __future__ import annotations

import time
from typing import Callable

__all__ = ["device_seconds_per_iter", "last_spread"]

_LAST_SPREAD: dict = {"k1_worst_over_best": None, "slope_fallback": None}


def last_spread() -> dict:
    """Per-repeat variance of the most recent measurement: the k1 arm's
    worst/best wall-clock ratio (1.0 = perfectly stable; tunnel noise
    shows up here first) plus ``slope_fallback`` — whether the slope
    guard rejected the K-differenced slope and reported the conservative
    ``t(k1)/k1`` upper bound instead.  Bench artifacts attach this per
    metric so every number carries its own noise floor; with
    observability enabled it also lands in the metrics snapshot
    (``obs.snapshot()["benchtime"]``)."""
    return dict(_LAST_SPREAD)


def device_seconds_per_iter(body: Callable, x0, *, k0: int, k1: int,
                            repeats: int = 5) -> float:
    """Seconds per iteration of ``body`` (a data->data traceable fn)."""
    import jax
    import jax.numpy as jnp

    def timed(K):
        @jax.jit
        def run(d):
            out = jax.lax.fori_loop(0, K, lambda i, a: body(a), d)
            return jnp.sum(jnp.abs(out)).astype(jnp.float32)

        float(run(x0))  # compile + warm
        best, worst = float("inf"), 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(run(x0))
            dt = time.perf_counter() - t0
            best = min(best, dt)
            worst = max(worst, dt)
        return best, worst

    t_k0, _ = timed(k0)
    t_k1, w_k1 = timed(k1)
    spread = round(w_k1 / t_k1, 3) if t_k1 else None
    _LAST_SPREAD["k1_worst_over_best"] = spread
    slope = (t_k1 - t_k0) / (k1 - k0)
    upper = t_k1 / k1  # includes amortized dispatch: always >= true slope
    fallback = slope <= 0 or slope < 1e-3 * upper
    _LAST_SPREAD["slope_fallback"] = fallback
    if fallback:
        # noise swamped the difference (a stalled k0 arm, or jitter larger
        # than the loop): report the upper bound rather than an absurdity
        slope = upper
    from ..obs import enabled as _obs_enabled

    if _obs_enabled():
        from ..obs import counter, gauge

        counter("benchtime.measurements").inc()
        if fallback:
            counter("benchtime.slope_fallbacks").inc()
        if spread is not None:
            gauge("benchtime.last_spread").set(spread)
    return slope
