"""HLO-text introspection: collective op counts and byte-volume accounting.

The reference's cost model for a transposition is "bytes on the wire per
rank per hop" (``Transpositions.jl`` sends exactly the intersection ranges;
``benchmarks/`` report per-process timings).  On TPU the compiled artifact
is the ground truth, so we parse the partitioned HLO instead: each
collective *application* (``all-to-all(...)``, ``collective-permute(...)``,
async ``*-start`` forms) is counted once, and its result shape is priced in
bytes.  Under SPMD partitioning the compiled module is per-device, so the
byte volumes reported here are **per chip per application** — the unit the
ICI cost model wants.

Used by the driver gate (``__graft_entry__.dryrun_multichip``) to turn the
multichip correctness check into a perf-model artifact, and by tests as a
budget regression guard.
"""

from __future__ import annotations

import re

_DTYPE_BYTES = {
    "pred": 1,
    "s4": 1, "u4": 1,
    "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

COLLECTIVE_OPS = (
    "all-to-all",
    "all-gather",
    "reduce-scatter",
    "all-reduce",
    "collective-permute",
)

# Matches an opcode *application*: `= <shape> all-to-all(`, including the
# async `-start` form (the `-done` half is deliberately excluded so async
# pairs count once).  The shape is taken non-greedily up to the opcode
# token: TPU layouts embed parenthesized tile specs (`{1,0:T(8,128)}`)
# inside tuple shapes, so balanced-paren matching is not an option.  Name
# *references* (`%all-to-all.3`) never match: they are preceded by `%`,
# not whitespace, and are never followed directly by `(`.
_APP_RE = re.compile(
    r"=\s*(?P<shape>\S.*?)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_OPS) + r")(?:-start)?\("
)

# Each dim may carry a bounded-dynamic `<=` prefix (e.g. ``f32[<=8,4]``);
# pricing uses the bound, which upper-bounds the wire bytes.
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[((?:<=|[0-9,])*)\]")


def _dim_elems(dims_str: str) -> int:
    """Element count of one ``[dims]`` string (bounded-dynamic ``<=``
    prefixes priced at their bound)."""
    n = 1
    for d in dims_str.split(","):
        if d:
            n *= int(d.lstrip("<="))
    return n


def shape_bytes(shape_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` component in an HLO shape string."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue  # token[] etc. — zero-cost control types
        total += _dim_elems(dims) * _DTYPE_BYTES[dtype]
    return total


def largest_tensor_elems(hlo: str) -> int:
    """Element count of the largest single shape component anywhere in
    the HLO text — the memory-contract probe the attention tests use to
    assert a flash program never materializes an ``S x S`` score
    matrix."""
    return max((_dim_elems(dims) for _, dims in _SHAPE_RE.findall(hlo)),
               default=0)


def collective_stats(hlo: str) -> dict:
    """Per-collective ``{op: {"count": n, "bytes": total_result_bytes}}``.

    ``bytes`` prices each application's *result* shape (per device —
    partitioned-HLO shapes are per-shard).  For async ``-start`` ops the
    tuple result includes the operand alias, so async bytes are an upper
    bound; count semantics are exact either way.
    """
    stats: dict = {}
    for m in _APP_RE.finditer(hlo):
        entry = stats.setdefault(m.group("op"), {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += shape_bytes(m.group("shape"))
    return stats
