"""JAX API compatibility shims.

The framework targets the current JAX surface (``jax.shard_map`` with
``check_vma``, ``jax.sharding.AxisType``), but must also run on older
jaxlib builds (this container ships 0.4.37) where:

* ``shard_map`` still lives in ``jax.experimental.shard_map`` and its
  static-check kwarg is ``check_rep`` (the varying-mesh-axes check's
  predecessor);
* ``jax.sharding.AxisType`` does not exist (all mesh axes behave as the
  later ``Auto`` type).

Import :func:`shard_map` / :data:`AxisType` from here instead of from
``jax`` so every call site stays version-agnostic.  The shims resolve at
import time — zero per-call overhead.

PR 19 adds :func:`wire_fp8_dtype` — gated resolution of the fp8 wire
element types (``float8_e4m3fn`` / ``float8_e5m2``).  The pinned jax
ships them on ``jax.numpy``; older builds fall back to ``ml_dtypes``
(jaxlib's own dtype-extension dependency, so present wherever jaxlib
is); a build with neither raises a typed :class:`WireDtypeError`
naming the missing dtype AT PLAN CONSTRUCTION — an fp8 wire the
backend cannot represent must fail before any collective is traced,
not mid-dispatch.
"""

from __future__ import annotations

import os

import jax

__all__ = ["shard_map", "AxisType", "configure_compilation_cache",
           "COMPILE_CACHE_VAR", "wire_fp8_dtype", "WireDtypeError"]

COMPILE_CACHE_VAR = "PENCILARRAYS_TPU_COMPILE_CACHE"


def configure_compilation_cache(env_var: str = COMPILE_CACHE_VAR):
    """Wire jax's persistent compilation cache from one env knob:
    ``PENCILARRAYS_TPU_COMPILE_CACHE=<dir>`` points
    ``jax_compilation_cache_dir`` at ``<dir>`` (thresholds zeroed so
    every executable persists — the in-process hop/plan/route caches
    already dedupe, the disk cache's job is surviving process restarts).
    Called at package import; a no-op when the variable is unset, and
    best-effort on jax versions lacking a threshold knob.  Returns the
    resolved directory (or None)."""
    d = os.environ.get(env_var)
    if not d:
        return None
    try:
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(d))
    except Exception:
        return None  # ancient jax: knob absent — feature degrades away
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # threshold knobs vary by version; the dir is what matters
    return os.path.abspath(d)

class WireDtypeError(TypeError):
    """A requested wire element type does not exist on this jax build.

    Raised by :func:`wire_fp8_dtype` when neither ``jax.numpy`` nor
    ``ml_dtypes`` provides the fp8 class — typed so plan construction
    can fail fast and name exactly what is missing."""

    def __init__(self, message: str, *, dtype_name: str):
        super().__init__(message)
        self.dtype_name = dtype_name


# canonical wire spelling -> the class name both jax.numpy and ml_dtypes
# use for it.  e4m3 is the "fn" (finite-only) variant everywhere that
# matters: it has NO inf — overflow and inf both land on NaN — which the
# pack path's finite-masked amax is designed around (parallel/wire.py).
_FP8_CLASS_NAMES = {
    "fp8_e4m3": "float8_e4m3fn",
    "fp8_e5m2": "float8_e5m2",
}


def wire_fp8_dtype(name: str):
    """Resolve a canonical fp8 wire spelling (``"fp8_e4m3"`` /
    ``"fp8_e5m2"``) to its element type class, preferring ``jax.numpy``
    (the pinned 0.4.37 ships both) and falling back to ``ml_dtypes``.
    Raises :class:`WireDtypeError` naming the missing class when
    neither has it, so ``canonical_wire_dtype`` accepts fp8 spellings
    portably across jax builds without an unconditional import."""
    cls = _FP8_CLASS_NAMES.get(name)
    if cls is None:
        raise ValueError(
            f"not an fp8 wire dtype: {name!r} "
            f"(expected one of {tuple(_FP8_CLASS_NAMES)})")
    import jax.numpy as jnp

    dt = getattr(jnp, cls, None)
    if dt is not None:
        return dt
    try:  # jaxlib depends on ml_dtypes, so this is the natural fallback
        import ml_dtypes

        dt = getattr(ml_dtypes, cls, None)
    except ImportError:
        dt = None
    if dt is None:
        raise WireDtypeError(
            f"wire_dtype={name!r} needs the {cls!r} element type, but "
            f"neither jax.numpy nor ml_dtypes provides it on this build "
            f"— upgrade jax/ml_dtypes or drop to a 16-bit wire",
            dtype_name=cls)
    return dt


try:  # modern surface: jax.sharding.AxisType (Auto/Explicit/Manual)
    from jax.sharding import AxisType  # type: ignore
except ImportError:  # pre-AxisType jax: every axis is implicitly Auto
    AxisType = None

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Legacy adapter: ``check_vma`` maps onto ``check_rep`` (the
        older static replication check the vma check superseded)."""
        return _legacy_shard_map(f, mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
