"""JAX API compatibility shims.

The framework targets the current JAX surface (``jax.shard_map`` with
``check_vma``, ``jax.sharding.AxisType``), but must also run on older
jaxlib builds (this container ships 0.4.37) where:

* ``shard_map`` still lives in ``jax.experimental.shard_map`` and its
  static-check kwarg is ``check_rep`` (the varying-mesh-axes check's
  predecessor);
* ``jax.sharding.AxisType`` does not exist (all mesh axes behave as the
  later ``Auto`` type).

Import :func:`shard_map` / :data:`AxisType` from here instead of from
``jax`` so every call site stays version-agnostic.  The shims resolve at
import time — zero per-call overhead.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "AxisType"]

try:  # modern surface: jax.sharding.AxisType (Auto/Explicit/Manual)
    from jax.sharding import AxisType  # type: ignore
except ImportError:  # pre-AxisType jax: every axis is implicitly Auto
    AxisType = None

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Legacy adapter: ``check_vma`` maps onto ``check_rep`` (the
        older static replication check the vma check superseded)."""
        return _legacy_shard_map(f, mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
