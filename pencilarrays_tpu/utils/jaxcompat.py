"""JAX API compatibility shims.

The framework targets the current JAX surface (``jax.shard_map`` with
``check_vma``, ``jax.sharding.AxisType``), but must also run on older
jaxlib builds (this container ships 0.4.37) where:

* ``shard_map`` still lives in ``jax.experimental.shard_map`` and its
  static-check kwarg is ``check_rep`` (the varying-mesh-axes check's
  predecessor);
* ``jax.sharding.AxisType`` does not exist (all mesh axes behave as the
  later ``Auto`` type).

Import :func:`shard_map` / :data:`AxisType` from here instead of from
``jax`` so every call site stays version-agnostic.  The shims resolve at
import time — zero per-call overhead.
"""

from __future__ import annotations

import os

import jax

__all__ = ["shard_map", "AxisType", "configure_compilation_cache",
           "COMPILE_CACHE_VAR"]

COMPILE_CACHE_VAR = "PENCILARRAYS_TPU_COMPILE_CACHE"


def configure_compilation_cache(env_var: str = COMPILE_CACHE_VAR):
    """Wire jax's persistent compilation cache from one env knob:
    ``PENCILARRAYS_TPU_COMPILE_CACHE=<dir>`` points
    ``jax_compilation_cache_dir`` at ``<dir>`` (thresholds zeroed so
    every executable persists — the in-process hop/plan/route caches
    already dedupe, the disk cache's job is surviving process restarts).
    Called at package import; a no-op when the variable is unset, and
    best-effort on jax versions lacking a threshold knob.  Returns the
    resolved directory (or None)."""
    d = os.environ.get(env_var)
    if not d:
        return None
    try:
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(d))
    except Exception:
        return None  # ancient jax: knob absent — feature degrades away
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # threshold knobs vary by version; the dir is what matters
    return os.path.abspath(d)

try:  # modern surface: jax.sharding.AxisType (Auto/Explicit/Manual)
    from jax.sharding import AxisType  # type: ignore
except ImportError:  # pre-AxisType jax: every axis is implicitly Auto
    AxisType = None

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Legacy adapter: ``check_vma`` maps onto ``check_rep`` (the
        older static replication check the vma check superseded)."""
        return _legacy_shard_map(f, mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
