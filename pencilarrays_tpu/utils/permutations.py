"""Compile-time index-permutation algebra.

TPU-native re-design of the permutation layer of the reference
(``src/Permutations.jl:1-7`` + the external StaticPermutations.jl package it
re-exports, see ``README.md:44``).  In the reference, permutations are
compile-time tuples whose algebra (``perm * x``, ``perm \\ x``, ``inv``,
``append``) is resolved by the Julia compiler into zero-cost tuple shuffles.

Under JAX the analogous property holds automatically: a :class:`Permutation`
is a frozen, hashable Python object used only at *trace time* — it selects
which ``jnp.transpose`` / axis bookkeeping is emitted, and XLA folds layout
changes into adjacent ops.  Nothing here ever touches device data.

Conventions (0-based, matching Julia's StaticPermutations semantics shifted
down by one):

* ``Permutation(2, 0, 1).apply(t) == (t[2], t[0], t[1])`` — i.e. entry ``k``
  of the result is ``t[perm[k]]``.  This mirrors the reference where
  ``Permutation(2,3,1) * (x1,x2,x3) == (x2,x3,x1)``.
* ``invapply`` is the reference's ``perm \\ x``: the unique ``s`` with
  ``apply(perm, s) == x``.
* ``mul`` composes: ``(p * q).apply(t) == p.apply(q.apply(t))``.

:class:`NoPermutation` is the identity singleton, kept distinct (like the
reference's ``NoPermutation``) so "no permutation" is representable and cheap
to test for.
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple, Union

__all__ = [
    "AbstractPermutation",
    "Permutation",
    "NoPermutation",
    "NO_PERMUTATION",
    "as_permutation",
    "identity_permutation",
]


class AbstractPermutation:
    """Common interface for :class:`Permutation` and :class:`NoPermutation`."""

    __slots__ = ()

    # -- queries ---------------------------------------------------------
    def is_identity(self) -> bool:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    # -- algebra ---------------------------------------------------------
    def apply(self, t: Sequence) -> tuple:
        """Reference ``perm * x`` — permute a tuple into *memory* order."""
        raise NotImplementedError

    def invapply(self, t: Sequence) -> tuple:
        """Reference ``perm \\ x`` — undo :meth:`apply` (memory → logical)."""
        raise NotImplementedError

    def inverse(self) -> "AbstractPermutation":
        raise NotImplementedError

    def __mul__(self, other: "AbstractPermutation") -> "AbstractPermutation":
        raise NotImplementedError

    def __truediv__(self, other: "AbstractPermutation") -> "AbstractPermutation":
        """Relative permutation ``self / other``: the ``r`` with
        ``r * other == self`` (cf. ``Transpositions.jl:506`` where the unpack
        kernel applies ``perm_o / perm_i``)."""
        return self * other.inverse()

    def append(self, n_extra: int) -> "AbstractPermutation":
        """Identity-extend by ``n_extra`` trailing axes (reference ``append``;
        used for PencilArray *extra dims*, which are never permuted,
        ``src/arrays.jl:34-47``)."""
        raise NotImplementedError

    def prepend(self, n_extra: int) -> "AbstractPermutation":
        """Identity-extend by ``n_extra`` leading axes."""
        raise NotImplementedError

    # -- misc ------------------------------------------------------------
    def axes(self) -> Tuple[int, ...]:
        """The permutation as an axes tuple usable by ``jnp.transpose``."""
        raise NotImplementedError


class Permutation(AbstractPermutation):
    """A concrete compile-time permutation of ``N`` indices (0-based)."""

    __slots__ = ("_perm",)

    def __init__(self, *perm: int):
        if len(perm) == 1 and isinstance(perm[0], (tuple, list)):
            perm = tuple(perm[0])
        p = tuple(int(i) for i in perm)
        if sorted(p) != list(range(len(p))):
            raise ValueError(f"invalid permutation of 0..{len(p)-1}: {p}")
        self._perm = p

    # -- queries ---------------------------------------------------------
    @property
    def indices(self) -> Tuple[int, ...]:
        return self._perm

    def is_identity(self) -> bool:
        return self._perm == tuple(range(len(self._perm)))

    def __len__(self) -> int:
        return len(self._perm)

    def __iter__(self) -> Iterator[int]:
        return iter(self._perm)

    def __getitem__(self, i: int) -> int:
        return self._perm[i]

    # -- algebra ---------------------------------------------------------
    def apply(self, t: Sequence) -> tuple:
        if len(t) != len(self._perm):
            raise ValueError(
                f"length mismatch: permutation of {len(self._perm)} applied to "
                f"tuple of length {len(t)}"
            )
        return tuple(t[i] for i in self._perm)

    def invapply(self, t: Sequence) -> tuple:
        if len(t) != len(self._perm):
            raise ValueError(
                f"length mismatch: permutation of {len(self._perm)} applied to "
                f"tuple of length {len(t)}"
            )
        out = [None] * len(t)
        for k, i in enumerate(self._perm):
            out[i] = t[k]
        return tuple(out)

    def inverse(self) -> "Permutation":
        return Permutation(self.invapply(tuple(range(len(self._perm)))))

    def __mul__(self, other: AbstractPermutation) -> AbstractPermutation:
        if isinstance(other, NoPermutation):
            return self
        if not isinstance(other, Permutation):
            return NotImplemented
        # (p * q).apply(t) == p.apply(q.apply(t)):
        #   p.apply(q.apply(t))[k] = t[q[p[k]]]  =>  (p*q)[k] = q[p[k]]
        return Permutation(self.apply(other._perm))

    def append(self, n_extra: int) -> "Permutation":
        n = len(self._perm)
        return Permutation(self._perm + tuple(range(n, n + n_extra)))

    def prepend(self, n_extra: int) -> "Permutation":
        return Permutation(
            tuple(range(n_extra)) + tuple(i + n_extra for i in self._perm)
        )

    def axes(self) -> Tuple[int, ...]:
        return self._perm

    # -- misc ------------------------------------------------------------
    def __eq__(self, other) -> bool:
        if isinstance(other, Permutation):
            return self._perm == other._perm
        if isinstance(other, NoPermutation):
            return self.is_identity()
        return NotImplemented

    def __hash__(self) -> int:
        # eq/hash contract: identity Permutation == NoPermutation, so they
        # must hash identically.
        if self.is_identity():
            return hash("NoPermutation")
        return hash(("Permutation", self._perm))

    def __repr__(self) -> str:
        return f"Permutation{self._perm}"


class NoPermutation(AbstractPermutation):
    """Identity permutation of unspecified length (reference
    ``NoPermutation``).  Applying it returns its argument unchanged."""

    __slots__ = ()
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def is_identity(self) -> bool:
        return True

    def __len__(self) -> int:
        raise TypeError("NoPermutation has no fixed length")

    def apply(self, t: Sequence) -> tuple:
        return tuple(t)

    def invapply(self, t: Sequence) -> tuple:
        return tuple(t)

    def inverse(self) -> "NoPermutation":
        return self

    def __mul__(self, other: AbstractPermutation) -> AbstractPermutation:
        return other

    def append(self, n_extra: int) -> "NoPermutation":
        return self

    def prepend(self, n_extra: int) -> "NoPermutation":
        return self

    def axes(self) -> Tuple[int, ...]:
        raise TypeError("NoPermutation has no fixed length; use as_permutation")

    def __eq__(self, other) -> bool:
        if isinstance(other, NoPermutation):
            return True
        if isinstance(other, Permutation):
            return other.is_identity()
        return NotImplemented

    def __hash__(self) -> int:
        return hash("NoPermutation")

    def __repr__(self) -> str:
        return "NoPermutation()"


NO_PERMUTATION = NoPermutation()

PermutationLike = Union[AbstractPermutation, Sequence[int], None]


def identity_permutation(n: int) -> Permutation:
    return Permutation(tuple(range(n)))


def as_permutation(p: PermutationLike, ndim: int) -> AbstractPermutation:
    """Normalize ``None`` / tuples / AbstractPermutation to an
    :class:`AbstractPermutation` valid for ``ndim`` axes."""
    if p is None:
        return NO_PERMUTATION
    if isinstance(p, NoPermutation):
        return p
    if isinstance(p, Permutation):
        if len(p) != ndim:
            raise ValueError(f"permutation {p} incompatible with ndim={ndim}")
        # Normalize: identity permutations collapse to the singleton so that
        # descriptors differing only in identity-spelling are identical.
        return NO_PERMUTATION if p.is_identity() else p
    return as_permutation(Permutation(tuple(p)), ndim)
