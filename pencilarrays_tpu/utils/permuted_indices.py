"""Index iterators that walk in memory order while presenting logical
indices.

Reference ``src/PermutedIndices/PermutedIndices.jl``: default Cartesian
iteration over a permuted array walks out of memory order — a perf trap
the reference fixes with ``PermutedLinearIndices`` (``:17-49``) and
``PermutedCartesianIndices`` (``:51-93``), converting logical -> memory
via ``perm * I`` and memory -> logical via ``perm \\ I``.

On TPU, per-element host loops are never the compute path (broadcasting
and ``jnp`` ops are), so these utilities exist for *host-side* tasks that
genuinely enumerate indices — test assertions, debug dumps, building
scatter maps for I/O — with the same memory-order-walk guarantee.
"""

from __future__ import annotations

import math
from typing import Iterator, Sequence, Tuple

import numpy as np

from .permutations import AbstractPermutation

__all__ = ["PermutedLinearIndices", "PermutedCartesianIndices"]


class PermutedCartesianIndices:
    """Iterate logical index tuples in *memory* order
    (reference ``PermutedCartesianIndices``, ``PermutedIndices.jl:51-93``).

    ``shape`` is the logical shape; iteration visits elements so that the
    underlying memory-order array is walked contiguously (last memory dim
    fastest), yielding each position's *logical* index tuple.
    """

    def __init__(self, shape: Sequence[int], perm: AbstractPermutation):
        self.shape = tuple(int(n) for n in shape)
        self.perm = perm
        self.shape_mem = perm.apply(self.shape)

    def __len__(self) -> int:
        return math.prod(self.shape)

    def __iter__(self) -> Iterator[Tuple[int, ...]]:
        for mem_idx in np.ndindex(*self.shape_mem):
            # memory -> logical: perm \ I  (PermutedIndices.jl:72)
            yield self.perm.invapply(tuple(int(i) for i in mem_idx))

    def __getitem__(self, linear: int) -> Tuple[int, ...]:
        """Logical index of the ``linear``-th element in memory order."""
        mem_idx = np.unravel_index(linear, self.shape_mem)
        return self.perm.invapply(tuple(int(i) for i in mem_idx))


class PermutedLinearIndices:
    """Memory-order linear index of logical positions
    (reference ``PermutedLinearIndices``, ``PermutedIndices.jl:17-49``)."""

    def __init__(self, shape: Sequence[int], perm: AbstractPermutation):
        self.shape = tuple(int(n) for n in shape)
        self.perm = perm
        self.shape_mem = perm.apply(self.shape)

    def __len__(self) -> int:
        return math.prod(self.shape)

    def __iter__(self) -> Iterator[int]:
        return iter(range(len(self)))

    def __getitem__(self, logical_idx: Sequence[int]) -> int:
        """Linear (memory-order) position of a logical index tuple:
        logical -> memory via ``perm * I`` (PermutedIndices.jl:46)."""
        mem_idx = self.perm.apply(tuple(logical_idx))
        return int(np.ravel_multi_index(mem_idx, self.shape_mem))
