"""Hierarchical timers + profiler annotation — the TimerOutputs subsystem.

Reference: every ``Pencil`` owns (or shares) a ``TimerOutput``
(``Pencils.jl:191,434``) and the hot sections are wrapped in
``@timeit_debug timer "label"`` — "transpose!", "pack data", "unpack data",
I/O ops (``Transpositions.jl:173-177``, ``mpi_io.jl:338-424``).  Timings
are compiled out by default and enabled with
``TimerOutputs.enable_debug_timings`` (``docs/src/PencilArrays_timers.md``).

TPU re-design, two complementary channels:

* :func:`jax.named_scope` annotations are ALWAYS emitted inside traced
  code — they are free at runtime (trace-time metadata) and make the
  transpose/FFT phases visible in XLA/jax profiler traces, which is where
  on-device time must be read (host wall-clocks cannot see into an XLA
  program, and dispatch is async).
* A host-side hierarchical :class:`TimerOutput` measuring *dispatch+trace*
  wall time, attached to pencils via ``Pencil(timer=...)`` and disabled by
  default exactly like the reference's ``@timeit_debug``; enable with
  :func:`enable_debug_timings`.

THREAD SAFETY: one :class:`TimerOutput` may be entered concurrently from
several threads (the resilience subsystem's checksum thread pool, user
dispatch threads).  Each thread times into its OWN tree rooted at a
per-thread root — the section stack is thread-local state, so concurrent
``timeit`` blocks can never corrupt each other's nesting — and
:meth:`report`/:meth:`snapshot` merge the per-thread trees on demand.
:meth:`merge` folds another timer (or a :meth:`snapshot` dict, e.g. one
shipped from a peer process) into this one for cross-timer and
cross-process aggregation.

See ``docs/Observability.md`` for how these timers compose with the
``pencilarrays_tpu.obs`` metrics/journal/profiler layers.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Dict, Optional, Union

import jax

__all__ = [
    "TimerOutput",
    "timeit",
    "enable_debug_timings",
    "disable_debug_timings",
    "timings_enabled",
]

_ENABLED = False


def enable_debug_timings() -> None:
    """Reference ``TimerOutputs.enable_debug_timings(PencilArrays)``."""
    global _ENABLED
    _ENABLED = True


def disable_debug_timings() -> None:
    global _ENABLED
    _ENABLED = False


def timings_enabled() -> bool:
    return _ENABLED


class _Node:
    __slots__ = ("ncalls", "total", "children")

    def __init__(self):
        self.ncalls = 0
        self.total = 0.0
        self.children: Dict[str, "_Node"] = {}


def _merge_node(dst: _Node, src: _Node) -> None:
    dst.ncalls += src.ncalls
    dst.total += src.total
    # src may be a LIVE per-thread tree another thread is extending
    # (timing threads never take a lock — that is what keeps the hot
    # path free).  Snapshot the child list with a bounded retry: a
    # concurrent setdefault during the copy raises RuntimeError, never
    # corrupts.  Totals of in-flight sections read slightly stale, which
    # is inherent to reporting while timing.
    items = None
    for _ in range(100):
        try:
            items = list(src.children.items())
            break
        except RuntimeError:
            continue  # caught mid-insert; the next pass sees a superset
    if items is None:
        # pathological insert churn outlived every retry: take one
        # last C-level copy rather than silently dropping the subtree
        try:
            items = list(dict(src.children).items())
        except RuntimeError:
            items = []
    for label, child in items:
        _merge_node(dst.children.setdefault(label, _Node()), child)


def _node_to_dict(node: _Node) -> dict:
    return {
        "ncalls": node.ncalls,
        "seconds": node.total,
        "children": {label: _node_to_dict(c)
                     for label, c in node.children.items()},
    }


def _merge_dict(dst: _Node, d: dict) -> None:
    dst.ncalls += int(d.get("ncalls", 0))
    dst.total += float(d.get("seconds", 0.0))
    for label, c in (d.get("children") or {}).items():
        _merge_dict(dst.children.setdefault(label, _Node()), c)


class TimerOutput:
    """Hierarchical wall timer (host-side dispatch/trace time).

    Safe for concurrent use: the active-section stack lives in
    thread-local storage (a shared stack was the pre-obs corruption bug:
    two threads interleaving push/pop detached whole subtrees), and each
    thread accumulates into its own root.  Reporting merges the
    per-thread trees; :meth:`merge` aggregates across timers/processes.
    Reporting WHILE other threads are timing is crash-free (racy child
    lists are re-snapshotted) but reads in-flight sections slightly
    stale — a wall-clock report, not a consistent cut.
    """

    def __init__(self, name: str = "root"):
        self.name = name
        self._lock = threading.Lock()
        self._tls = threading.local()
        # (thread, root) per live timing thread; exited threads' trees
        # are folded into _retired on the next merge — thread-pool churn
        # (the I/O layer spawns pools per write) must not grow state or
        # report cost without bound, and must not LOSE finished timings
        self._roots: list = []
        self._retired = _Node()
        self._gen = 0            # bumped by reset(): stale stacks rebuild

    def _stack(self) -> list:
        tls = self._tls
        if getattr(tls, "gen", None) != self._gen:
            root = _Node()
            with self._lock:
                self._roots.append((threading.current_thread(), root))
            tls.stack = [root]
            tls.gen = self._gen
        return tls.stack

    @contextmanager
    def __call__(self, label: str):
        stack = self._stack()
        node = stack[-1].children.setdefault(label, _Node())
        stack.append(node)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            node.total += time.perf_counter() - t0
            node.ncalls += 1
            stack.pop()

    def reset(self) -> None:
        with self._lock:
            self._roots.clear()
            self._retired = _Node()
            self._gen += 1

    def _merged_root(self) -> _Node:
        out = _Node()
        with self._lock:
            live = []
            for thread, root in self._roots:
                if thread.is_alive():
                    live.append((thread, root))
                else:
                    # quiescent (its thread ran to completion): fold the
                    # finished tree into the retired accumulator once
                    _merge_node(self._retired, root)
            self._roots = live
            _merge_node(out, self._retired)
            roots = [r for _, r in live]
        for r in roots:
            _merge_node(out, r)
        return out

    @property
    def _root(self) -> _Node:
        """Merged view over the per-thread trees (kept for callers that
        predate the thread-local redesign; read-only by construction —
        mutations would land on a throwaway merge)."""
        return self._merged_root()

    def merge(self, other: Union["TimerOutput", dict]) -> "TimerOutput":
        """Fold ``other`` — another :class:`TimerOutput`, or a
        :meth:`snapshot` dict (the cross-process wire format: a peer
        JSON-ships its snapshot and process 0 merges) — into this
        timer.  Returns ``self`` for chaining."""
        src = other.snapshot() if isinstance(other, TimerOutput) else other
        root = self._stack()[0]
        for label, c in (src.get("children") or {}).items():
            _merge_dict(root.children.setdefault(label, _Node()), c)
        return self

    def snapshot(self) -> dict:
        """JSON-serializable merged tree ``{ncalls, seconds, children}``
        — the :meth:`merge` wire format, also embedded in obs metrics
        snapshots."""
        return _node_to_dict(self._merged_root())

    # -- reporting ---------------------------------------------------------
    def _lines(self, node: _Node, depth: int, out):
        for label, child in sorted(node.children.items(),
                                   key=lambda kv: -kv[1].total):
            out.append(
                f"{'  ' * depth}{label:<{40 - 2 * depth}} "
                f"{child.ncalls:>8} {child.total * 1e3:>12.3f} ms"
            )
            self._lines(child, depth + 1, out)

    def report(self) -> str:
        out = [f"TimerOutput({self.name})  —  host dispatch/trace wall time",
               f"{'section':<40} {'ncalls':>8} {'time':>15}"]
        self._lines(self._merged_root(), 0, out)
        return "\n".join(out)

    def __repr__(self) -> str:
        return self.report()


@contextmanager
def timeit(timer: Optional[TimerOutput], label: str):
    """``@timeit_debug timer label`` analog: always emits a
    ``jax.named_scope`` (visible in device profiles); additionally records
    host wall time when debug timings are enabled and a timer is present."""
    ctx = timer(label) if (_ENABLED and timer is not None) else nullcontext()
    with jax.named_scope(label.replace(" ", "_")):
        with ctx:
            yield
