"""Hierarchical timers + profiler annotation — the TimerOutputs subsystem.

Reference: every ``Pencil`` owns (or shares) a ``TimerOutput``
(``Pencils.jl:191,434``) and the hot sections are wrapped in
``@timeit_debug timer "label"`` — "transpose!", "pack data", "unpack data",
I/O ops (``Transpositions.jl:173-177``, ``mpi_io.jl:338-424``).  Timings
are compiled out by default and enabled with
``TimerOutputs.enable_debug_timings`` (``docs/src/PencilArrays_timers.md``).

TPU re-design, two complementary channels:

* :func:`jax.named_scope` annotations are ALWAYS emitted inside traced
  code — they are free at runtime (trace-time metadata) and make the
  transpose/FFT phases visible in XLA/jax profiler traces, which is where
  on-device time must be read (host wall-clocks cannot see into an XLA
  program, and dispatch is async).
* A host-side hierarchical :class:`TimerOutput` measuring *dispatch+trace*
  wall time, attached to pencils via ``Pencil(timer=...)`` and disabled by
  default exactly like the reference's ``@timeit_debug``; enable with
  :func:`enable_debug_timings`.
"""

from __future__ import annotations

import time
from contextlib import contextmanager, nullcontext
from typing import Dict, Optional

import jax

__all__ = [
    "TimerOutput",
    "timeit",
    "enable_debug_timings",
    "disable_debug_timings",
    "timings_enabled",
]

_ENABLED = False


def enable_debug_timings() -> None:
    """Reference ``TimerOutputs.enable_debug_timings(PencilArrays)``."""
    global _ENABLED
    _ENABLED = True


def disable_debug_timings() -> None:
    global _ENABLED
    _ENABLED = False


def timings_enabled() -> bool:
    return _ENABLED


class _Node:
    __slots__ = ("ncalls", "total", "children")

    def __init__(self):
        self.ncalls = 0
        self.total = 0.0
        self.children: Dict[str, _Node] = {}


class TimerOutput:
    """Hierarchical wall timer (host-side dispatch/trace time)."""

    def __init__(self, name: str = "root"):
        self.name = name
        self._root = _Node()
        self._stack = [self._root]

    @contextmanager
    def __call__(self, label: str):
        node = self._stack[-1].children.setdefault(label, _Node())
        self._stack.append(node)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            node.total += time.perf_counter() - t0
            node.ncalls += 1
            self._stack.pop()

    def reset(self) -> None:
        self._root = _Node()
        self._stack = [self._root]

    # -- reporting ---------------------------------------------------------
    def _lines(self, node: _Node, depth: int, out):
        for label, child in sorted(node.children.items(),
                                   key=lambda kv: -kv[1].total):
            out.append(
                f"{'  ' * depth}{label:<{40 - 2 * depth}} "
                f"{child.ncalls:>8} {child.total * 1e3:>12.3f} ms"
            )
            self._lines(child, depth + 1, out)

    def report(self) -> str:
        out = [f"TimerOutput({self.name})  —  host dispatch/trace wall time",
               f"{'section':<40} {'ncalls':>8} {'time':>15}"]
        self._lines(self._root, 0, out)
        return "\n".join(out)

    def __repr__(self) -> str:
        return self.report()


@contextmanager
def timeit(timer: Optional[TimerOutput], label: str):
    """``@timeit_debug timer label`` analog: always emits a
    ``jax.named_scope`` (visible in device profiles); additionally records
    host wall time when debug timings are enabled and a timer is present."""
    ctx = timer(label) if (_ENABLED and timer is not None) else nullcontext()
    with jax.named_scope(label.replace(" ", "_")):
        with ctx:
            yield
