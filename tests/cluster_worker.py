"""Worker for the coordinated mesh-recovery drills.

Unlike ``restart_worker.py`` (which exercises the ``jax.distributed``
data path), these workers drill the **cluster coordination layer** over
its filesystem KV backend: N plain OS processes, each a self-contained
single-process jax (1 local device, no cross-process collectives — so
the drill runs on any backend), joined ONLY through a shared
``FileKV`` directory.  That isolates exactly what PR 6 adds: status
consensus, checkpoint election, health leases and epochs — the
machinery that must behave identically over the jax distributed KV
store on a real pod.

Phases (launched by ``test_multiprocess.py``; each phase gets a fresh
KV namespace — a KV root is one job incarnation):

* ``sdc`` — every rank commits checkpoint steps 1 (ground truth) and 2
  (diverged), then rank 0's step-2 data file is torn (bitflip).  All
  ranks run a distributed ``guarded_step`` whose exchange is corrupted
  on rank 1 only (``hop.exchange:corrupt%rank1*2`` — the SAME spec in
  every worker's env; the ``%rank`` selector does the addressing).
  The mesh must agree: retry (rank 1 corrupt again) → coordinated
  restore of step **1** — the newest step valid on EVERY rank, even
  though rank 1's own ``latest_valid()`` is 2 — → rerun, bit-identical
  to ground truth, no deadlock.
* ``kill`` — every rank commits step 1, then runs a guarded step in
  which rank ``<world-2>`` is SIGKILLed by ``hop.exchange:kill%rank<v>``
  mid-step.  Survivors must exit with a typed ``PeerFailureError``
  naming the dead rank (crash bundle written) within the lease
  deadline — NOT hang until the watchdog/verdict timeout.
* ``restore`` — fresh processes (all ranks, including the previous
  victim's slot) elect ``common_latest_valid()`` and restore it: the
  coordinated-restore rerun must be bit-identical to ground truth.
* ``elastic`` / ``elastic_ref`` — the ISSUE 8 elastic-reformation
  drill: every rank runs ``nsteps`` checkpointed ``elastic_step``
  iterations of the same deterministic state evolution.  In
  ``elastic``, rank ``world-1`` is SIGKILLed mid-step-3
  (``hop.exchange:kill%rank<v>``): survivors must detect the loss by
  lease expiry, run the membership consensus, reform to ``world-1``
  ranks (dense reindex, generation-suffixed namespace), re-plan,
  restore the agreed step-2 checkpoint through the cross-decomposition
  read path, rerun the killed step and FINISH — printing a
  ``FINAL=<sha256>`` digest that must be bit-identical to the
  never-killed ``elastic_ref`` run's.  A ``serve.PlanService`` with a
  named plan and two pre-kill queued host-payload requests rides
  along: the reformation re-invokes the service's registered factory,
  the queue re-binds, and the post-loop drain must complete both
  requests bit-identically (``SERVE_RESUMED=2``).
* ``storm`` — the ISSUE 15 overload drill: each rank's ``PlanService``
  (SLOs: protected priority 10 with a deadline, sheddable priority 0;
  pressure gate armed) takes an overload storm — every sheddable
  submission is rejected typed ``AdmissionError(reason="shed")`` while
  the protected tier queues; rank 1 is then SIGKILLed mid-storm
  (``hop.exchange:kill%rank1``) and the survivor's serve dispatch
  (``elastic_step``) reforms to world-1 and resumes draining — every
  protected ticket resolves bit-identical to direct (unloaded)
  execution, under deadline, exactly once.
* ``scale`` — the ISSUE 15 autoscaler round trip: both ranks' windowed
  controllers agree the mesh is idle (``serve.scale`` down journaled
  everywhere, only the highest rank acts via ``announce_leave``), the
  survivor reforms down; the departed process pre-warms its plans
  through the persistent compile cache and rejoins
  (``join_prewarmed``), admitted by the survivor's overload-driven
  scale-up reformation; a post-join aligned ``guarded_step`` proves
  the re-grown mesh coordinates.
* ``partition`` — the ISSUE 20 split-brain drill: an asymmetric KV
  partition cuts the highest rank off the wire (``kv.get:partition,
  kv.set:partition`` self-armed mid-run — its reads find nothing, its
  writes raise, its renewals fail).  The minority side must exit its
  reformation attempt typed ``QuorumLossError`` (1 voter of 3, strict
  majority needs 2) — NEVER form a rival mesh; the majority reforms
  around it on fresh evidence (the stale lease) and agrees in the new
  namespace; and when the partition heals, the evicted rank's writes
  through :class:`FencedKV` are rejected typed ``FencedWriteError``
  by the fence the new generation's rank 0 advanced — the zombie can
  read, never corrupt.
* ``straggle`` / ``control`` — the PR 7 straggler drill: every rank
  runs the same guarded transpose steps, with rank 1 dragged by the
  deterministic ``hop.exchange:delay%rank1`` fault (``straggle``) or
  undelayed (``control``); every rank publishes its metrics snapshot
  over the KV and rank 0 folds the mesh view + runs straggler
  detection.  The test asserts exactly one ``cluster.straggler``
  event naming rank 1 in the delayed run and zero in the control.

Usage::

    python cluster_worker.py <kvroot> <world> <rank> <tmpdir> <phase>
"""

import json
import os
import sys
import time


def main():
    kvroot, world, rank, tmpdir, phase = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5])
    # one local device per worker: the drill exercises coordination,
    # not collectives — each rank's compute is self-contained
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1")
    # arm the cluster layer BEFORE importing anything heavy: identity
    # and gate are env-read (the late-arming contract), and the obs
    # journal attributes records to this mesh rank
    os.environ["PENCILARRAYS_TPU_CLUSTER"] = os.path.join(kvroot, phase)
    os.environ["PENCILARRAYS_TPU_CLUSTER_RANK"] = str(rank)
    os.environ["PENCILARRAYS_TPU_CLUSTER_WORLD"] = str(world)
    os.environ.setdefault("PENCILARRAYS_TPU_CLUSTER_LEASE_TTL", "2.0")
    os.environ.setdefault("PENCILARRAYS_TPU_CLUSTER_VERDICT_TIMEOUT", "60")
    os.environ["PENCILARRAYS_TPU_OBS"] = os.path.join(tmpdir, "obs")
    # tight aggregation cadence: the drill exercises the live mesh
    # publish/fold loop, not just the explicit fold at the end
    os.environ.setdefault("PENCILARRAYS_TPU_OBS_AGG_S", "0.5")
    if phase == "scale":
        # the pre-warmed-join story: the joiner compiles its plans
        # through the PERSISTENT compile cache before joining, so the
        # post-join rebuild is a cache hit (must be set before the
        # package import wires jax_compilation_cache_dir)
        os.environ.setdefault("PENCILARRAYS_TPU_COMPILE_CACHE",
                              os.path.join(tmpdir, "xla-cache"))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import pencilarrays_tpu as pa
    from pencilarrays_tpu import guard
    from pencilarrays_tpu.cluster import PeerFailureError
    from pencilarrays_tpu.resilience import CheckpointManager, RetryPolicy

    guard.enable(os.path.join(tmpdir, "bundles", f"r{rank}"))
    shape = (11, 9, 13)
    truth = np.random.default_rng(11).standard_normal(shape)
    topo = pa.Topology((1,))
    pen = pa.Pencil(topo, shape, (1,))
    pen2 = pa.Pencil(topo, shape, (0,))
    ckdir = os.path.join(tmpdir, f"ck-{'kill' if phase == 'restore' else phase}.r{rank}")
    mgr = CheckpointManager(ckdir, keep=4)
    victim = max(0, world - 2)  # the rank the kill drill SIGKILLs

    if phase == "sdc":
        mgr.save(1, {"u": pa.PencilArray.from_global(pen, truth)})
        mgr.save(2, {"u": pa.PencilArray.from_global(pen, truth + 5.0)})
        if rank == 0:
            # tear rank 0's NEWEST step: the divergent-restore hazard —
            # rank 1's latest_valid() is still 2, the mesh must agree on 1
            path = os.path.join(ckdir, "step-00000002", "data.bin")
            with open(path, "r+b") as f:
                f.seek(64)
                b = f.read(1)
                f.seek(64)
                f.write(bytes([b[0] ^ 0xFF]))
        # the SAME fault spec in every worker: %rank1 does the addressing
        os.environ["PENCILARRAYS_TPU_FAULTS"] = \
            "hop.exchange:corrupt%rank1*2"
        state = {"u": pa.PencilArray.from_global(pen, truth + 1000.0)}

        def step():
            return pa.transpose(state["u"], pen2)

        def restore_cb(ckpt):
            state["u"] = ckpt.read("u", pen)

        out = guard.guarded_step(
            step, ckpt_mgr=mgr, restore=restore_cb,
            retry=RetryPolicy(max_attempts=2, base_delay=0.01),
            label="cluster-sdc")
        assert np.array_equal(pa.gather(out), truth), \
            "coordinated recovery is not bit-identical to ground truth"
    elif phase == "kill":
        mgr.save(1, {"u": pa.PencilArray.from_global(pen, truth)})
        os.environ["PENCILARRAYS_TPU_FAULTS"] = \
            f"hop.exchange:kill%rank{victim}@1"
        state = {"u": pa.PencilArray.from_global(pen, truth)}

        def step():
            return pa.transpose(state["u"], pen2)

        t0 = time.monotonic()
        try:
            guard.guarded_step(step, ckpt_mgr=mgr,
                               restore=lambda c: None,
                               retry=RetryPolicy(max_attempts=2,
                                                 base_delay=0.01),
                               label="cluster-kill")
        except PeerFailureError as e:
            detect_s = time.monotonic() - t0
            assert e.rank == victim, f"wrong peer named: {e.rank}"
            assert e.bundle and os.path.isdir(e.bundle), \
                f"no crash bundle on PeerFailureError: {e.bundle!r}"
            with open(os.path.join(e.bundle, "MANIFEST.json")) as f:
                man = json.load(f)
            assert man["reason"] == "peer-failure", man["reason"]
            print(f"CLUSTER_OK phase=kill rank={rank} "
                  f"peerfail={e.rank} detect_s={detect_s:.2f}")
            return
        raise SystemExit(
            f"rank {rank}: expected SIGKILL (rank {victim}) or "
            f"PeerFailureError (survivors) — got a clean step")
    elif phase == "restore":
        # fresh incarnation after the kill: EVERY rank (including the
        # victim's replacement) elects the common step and restores it
        step = mgr.common_latest_valid()
        assert step == 1, f"expected agreed step 1, got {step}"
        back = mgr.restore(step).read("u", pen)
        assert np.array_equal(pa.gather(back), truth), \
            "coordinated restore is not bit-identical to ground truth"
    elif phase in ("elastic", "elastic_ref"):
        import hashlib

        from pencilarrays_tpu.cluster import elastic

        os.environ["PENCILARRAYS_TPU_ELASTIC"] = "1"
        nsteps, kill_step = 4, 3
        if phase == "elastic":
            # 2 hop.exchange hits per step (the two transposes of the
            # step body): the victim dies on the FIRST transpose of
            # step `kill_step`
            os.environ["PENCILARRAYS_TPU_FAULTS"] = (
                f"hop.exchange:kill%rank{world - 1}"
                f"@{2 * (kill_step - 1) + 1}")

        # ISSUE 9 satellite: a BATCHED plan in the elastic registry must
        # come back from the reformation with its batch intact — the
        # factory is re-invoked post-reform and rebuilds the same
        # batch=3 throughput plan (each drill rank has 1 local device,
        # so the rebuilt topology is (1,) in every generation)
        def batched_plan_factory(ctx=None):
            return pa.PencilFFTPlan(pa.Topology((1,)), shape, real=True,
                                    batch=3)

        elastic.register_plan("batched-fft", batched_plan_factory)

        # ISSUE 10 satellite: a SERVED plan registered by name must ride
        # the reformation too — the service re-registers its factory as
        # serve:<name>, the reform re-invokes it, queued host-payload
        # requests re-bind to the rebuilt plan, and the service resumes
        # draining its queue.  Requests are submitted BEFORE the kill
        # step and drained only after the loop (post-reform on the
        # elastic phase), so they provably cross the reformation.
        from pencilarrays_tpu.serve import PlanService

        def served_plan_factory(ctx=None):
            return pa.PencilFFTPlan(pa.Topology((1,)), shape, real=True)

        svc = PlanService(max_batch=4, max_wait_s=60.0)
        svc.register_plan("served-fft", served_plan_factory)
        serve_rng = np.random.default_rng(23)
        serve_payloads = [
            serve_rng.standard_normal(shape).astype(np.float32)
            for _ in range(2)]
        serve_tickets = [svc.submit("client", u, name="served-fft")
                         for u in serve_payloads]

        state = {"u": pa.PencilArray.from_global(pen, truth)}

        def evolve(x):
            return type(x)(x.pencil, x.data * 1.25 - 0.5, x.extra_dims)

        def estep():
            return pa.transpose(pa.transpose(state["u"], pen2), pen)

        def erestore(ckpt):
            # the cross-decomposition restore path: the writer's
            # global-corner block manifest mapped onto THIS (possibly
            # reformed) mesh's local extents, checksum-verified
            state["u"] = ckpt.read("u", pen, verify="local")

        mgr.save(0, {"u": state["u"]})
        for k in range(1, nsteps + 1):
            out = guard.elastic_step(
                estep, ckpt_mgr=mgr, restore=erestore,
                retry=RetryPolicy(max_attempts=2, base_delay=0.01),
                label=f"estep{k}")
            state["u"] = evolve(out)
            mgr.save(k, {"u": state["u"]})
        if phase == "elastic":
            # survivors went through exactly one reformation: the
            # registry factory must have rebuilt the batched plan with
            # its batch (and batched execution path) intact
            bp = elastic.plan("batched-fft")
            assert bp is not None, \
                "reformation did not re-invoke the batched plan factory"
            assert bp.batch == 3 and bp.batch_dims == (3,), \
                f"rebuilt plan lost its batch: {bp.batch!r}"
            bout = bp.forward(bp.allocate_input())
            assert bout.extra_dims == (3,), bout.extra_dims
            print(f"REPLAN_BATCH={bp.batch}")
            # the served plan was rebuilt through the SAME registry pass
            sp = elastic.plan("serve:served-fft")
            assert sp is not None, \
                "reformation did not re-invoke the served plan factory"
            assert svc.plan("served-fft") is sp, \
                "service did not re-bind to the rebuilt served plan"
        # resume draining: the pre-kill queue completes on the (possibly
        # rebuilt) plan, bit-identical to direct compiled execution
        assert svc.drain() >= 1, "service had nothing queued to drain"
        cur = svc.plan("served-fft")
        scp = cur.compile(())
        ok = 0
        for u, t in zip(serve_payloads, serve_tickets):
            ref = scp.forward(pa.PencilArray.from_global(
                cur.input_pencil, u))
            got = t.result(5)
            assert np.array_equal(np.asarray(pa.gather(got)),
                                  np.asarray(pa.gather(ref))), \
                "served request not bit-identical after reformation"
            ok += 1
        print(f"SERVE_RESUMED={ok}")
        final = np.ascontiguousarray(np.asarray(pa.gather(state["u"])))
        print(f"FINAL={hashlib.sha256(final.tobytes()).hexdigest()}")
    elif phase == "storm":
        # ISSUE 15 tentpole drill: an overload storm against the
        # 2-rank FileKV mesh sheds EXACTLY the sheddable tenants
        # (typed, at submit), rank 1 is SIGKILLed mid-storm, and the
        # survivor's serve dispatch reforms + resumes draining — every
        # submitted request ends in exactly one of: result / typed
        # DeadlineError / typed AdmissionError; protected results are
        # bit-identical to direct (unloaded) execution.
        from pencilarrays_tpu.resilience import faults as _faults
        from pencilarrays_tpu.serve import (
            AdmissionError, PlanService, PressurePolicy, SLO)

        os.environ["PENCILARRAYS_TPU_ELASTIC"] = "1"
        svc = PlanService(
            max_batch=4, max_wait_s=60.0,
            slos={"prot": SLO(deadline_s=120.0, shed_priority=10),
                  "bulk": SLO(shed_priority=0)},
            pressure=PressurePolicy(high_water_s=1e-4, low_water_s=5e-5),
            retry=RetryPolicy(max_attempts=2, base_delay=0.01))
        payloads = [np.random.default_rng(100 + i).standard_normal(shape)
                    for i in range(4)]
        # warmup: one aligned boundary that compiles the reshard and
        # seeds the service-rate window the projections read
        w = svc.submit_reshard(
            "prot", pa.PencilArray.from_global(pen, truth), pen2)
        assert svc.drain() == 1
        w.result(120)
        # the storm: 4 protected requests queue (the drain projection
        # crosses the water marks)...
        prot_tickets = [
            svc.submit_reshard(
                "prot", pa.PencilArray.from_global(pen, p), pen2)
            for p in payloads]
        # ...then 4 sheddable requests — ALL shed typed at submit, and
        # nothing else is (the protected tier keeps flowing)
        shed = 0
        for i in range(4):
            try:
                svc.submit_reshard(
                    "bulk",
                    pa.PencilArray.from_global(pen, payloads[i]), pen2)
            except AdmissionError as e:
                assert e.reason == "shed", e.reason
                shed += 1
        assert shed == 4, f"expected 4 shed, got {shed}"
        print(f"STORM_SHED={shed}")
        # mid-storm SIGKILL: rank 1 dies on its NEXT exchange — inside
        # the storm batch's dispatch.  The survivor's serve dispatch
        # (elastic_step) detects the loss by lease expiry, reforms to
        # world-1, reruns the batch and resumes draining.
        k = _faults.hit_count("hop.exchange")
        os.environ["PENCILARRAYS_TPU_FAULTS"] = \
            f"hop.exchange:kill%rank1@{k + 1}"
        assert svc.drain() >= 1
        import hashlib

        digest = hashlib.sha256()
        for p, t in zip(payloads, prot_tickets):
            out = t.result(120)
            ref = pa.reshard(pa.PencilArray.from_global(pen, p), pen2)
            a = np.ascontiguousarray(np.asarray(pa.gather(out)))
            b = np.ascontiguousarray(np.asarray(pa.gather(ref)))
            assert np.array_equal(a, b), \
                "protected result differs from unloaded execution"
            assert (t.t_done - t.t_submit) < 120.0, "deadline busted"
            digest.update(a.tobytes())
        st = svc.stats()
        assert st["completed"] == {"ok": 5}, st["completed"]
        assert st["slo_violations"] == 0, st
        assert st["pressure"] in ("shed", "evict"), st
        print(f"STORM_OK={len(prot_tickets)}")
        print(f"FINAL={digest.hexdigest()}")
    elif phase == "scale":
        # ISSUE 15: the scale-down -> scale-up round trip through a
        # REAL joiner.  Both ranks run the same windowed controller;
        # the highest rank announces its departure, survivors reform
        # down; the departed process comes back as a pre-warmed joiner
        # admitted by the survivor's scale-up reformation.
        from pencilarrays_tpu import cluster
        from pencilarrays_tpu.serve import (
            AutoscalePolicy, Autoscaler, PlanService, SLO)
        from pencilarrays_tpu.serve.autoscale import join_prewarmed

        os.environ["PENCILARRAYS_TPU_ELASTIC"] = "1"
        policy = RetryPolicy(max_attempts=2, base_delay=0.01)
        svc = PlanService(max_batch=4, max_wait_s=60.0,
                          slos={"prot": SLO(shed_priority=1)})
        asc = Autoscaler(svc, policy=AutoscalePolicy(
            overload_drain_s=0.05, windows=2, cooldown_s=0.0,
            min_world=1))
        state = {"u": pa.PencilArray.from_global(pen, truth)}

        def tick_step():
            return pa.transpose(state["u"], pen2)

        # (1) DOWN: two idle windows -> every rank journals the same
        # decision; only the highest rank flags itself
        asc.tick()
        d = asc.tick()
        assert d.direction == "down", d
        coord = cluster.coordinator()
        if rank == world - 1:
            assert d.acted and coord.leaving, d
            out = guard.guarded_step(tick_step, retry=policy,
                                     label="scale-boundary")
            assert out is not None    # the leaver exits WITH its result
            kv = coord.kv
            coord.leave()
            # wait until the survivor's scale-DOWN reformation commits
            # (its gen-1 lease appears) before requesting the rejoin —
            # otherwise the join request races the departure and the
            # SAME reformation re-admits us (legal, but then the drill
            # never exercises the scale-up decision)
            t_wait = time.monotonic() + 60
            while time.monotonic() < t_wait:
                if kv.try_get("pa.g1/lease/r0") is not None:
                    break
                time.sleep(0.1)
            else:
                raise SystemExit("scale-down reformation never landed")

            # (2) ...and returns as a PRE-WARMED joiner: plans compiled
            # through the persistent cache BEFORE the join request
            def factory(ctx=None):
                return pa.PencilFFTPlan(pa.Topology((1,)), shape,
                                        real=True)

            r, warm = join_prewarmed(coord.kv, f"s{rank}",
                                     factories={"scale-plan": factory},
                                     timeout=180)
            print(f"SCALE_JOINED gen={r.membership.gen} "
                  f"rank={r.membership.new_rank} "
                  f"warm_s={warm['warm_s']:.3f}")
            out = guard.guarded_step(lambda: "post-join", retry=policy,
                                     label="post-join",
                                     coordinator=r.coordinator)
            assert out == "post-join"
        else:
            assert not d.acted and d.detail == "not-leaver", d
            # the survivor's boundary turns the announced departure
            # into a reformation down
            out = guard.elastic_step(tick_step, retry=policy,
                                     label="scale-boundary")
            assert out is not None
            coord = cluster.coordinator()
            assert coord.world == world - 1, coord.world
            print(f"SCALE_DOWN world={coord.world}")
            # (3) UP: sustained overload + a pending joiner -> the
            # controller reforms to admit it.  The backlog is fed to
            # the projection directly (the storm drill covers organic
            # serve traffic; this drill is the capacity round trip).
            svc.queue.load.note_completed(1000, 1, 1.0)  # 1000 B-eq/s
            svc.queue.load.note_arrival(10_000)          # 10 s backlog
            deadline_t = time.monotonic() + 120
            acted = None
            while time.monotonic() < deadline_t:
                dd = asc.tick()
                if dd.direction == "up" and dd.acted:
                    acted = dd
                    break
                time.sleep(0.25)
            assert acted is not None, "scale-up never admitted a joiner"
            print(f"SCALE_UP gen={acted.gen} detail={acted.detail}")
            newc = cluster.coordinator()
            assert newc.world == world, newc.world
            out = guard.guarded_step(lambda: "post-join", retry=policy,
                                     label="post-join",
                                     coordinator=newc)
            assert out == "post-join"
    elif phase == "partition":
        from pencilarrays_tpu import cluster
        from pencilarrays_tpu.cluster import (FencedWriteError,
                                              QuorumLossError, elastic)
        from pencilarrays_tpu.cluster.kv import FencedKV

        os.environ["PENCILARRAYS_TPU_ELASTIC"] = "1"
        coord = cluster.coordinator()
        assert coord is not None, "cluster layer did not arm"
        ok = {"status": "ok", "can_retry": True, "can_restore": False}
        # prove the healthy 3-rank mesh first: one agreed verdict
        assert coord.agree("pre", ok)["action"] == "ok"
        victim_rank = world - 1
        if rank == victim_rank:
            # the partition: THIS rank loses the KV wire in both
            # directions — reads find nothing, writes raise, and the
            # heartbeat's renewals fail (caught in the renew loop), so
            # from the majority's side this lease simply goes stale
            os.environ["PENCILARRAYS_TPU_FAULTS"] = (
                "kv.get:partition,kv.set:partition")
            t0 = time.monotonic()
            try:
                elastic.reform(coord, reason="partition",
                               install=False, timeout=3.0)
            except QuorumLossError as e:
                print(f"MINORITY_TYPED have={len(e.have)} "
                      f"need={e.need} of={len(e.of)} "
                      f"detect_s={time.monotonic() - t0:.2f}",
                      flush=True)
            else:
                raise SystemExit(
                    "minority side formed a rival mesh — split brain")
            coord.shutdown()   # stop renewing into a mesh we left
            # the partition heals: the zombie wakes up still holding
            # its gen-0 token, finds the fence the majority's new
            # rank 0 advanced, and every write is rejected typed
            # BEFORE touching the store
            os.environ["PENCILARRAYS_TPU_FAULTS"] = ""
            zombie = FencedKV(coord.kv, namespace=coord.ns,
                              generation=0, epoch=0)
            t_wait = time.monotonic() + 120
            while zombie.fence() is None:
                if time.monotonic() >= t_wait:
                    raise SystemExit("majority fence never landed")
                time.sleep(0.1)
            try:
                zombie.set(f"{coord.ns}/poison/r{rank}", "stale")
            except FencedWriteError as e:
                print(f"ZOMBIE_FENCED token={e.token} "
                      f"fence={e.fence}", flush=True)
            else:
                raise SystemExit(
                    "zombie write landed in the live namespace")
            assert coord.kv.try_get(
                f"{coord.ns}/poison/r{rank}") is None
        else:
            # majority: wait for fresh evidence (the victim's lease
            # aging past ttl), then reform together around it
            t0 = time.monotonic()
            while victim_rank in coord.leases.live_ranks():
                if time.monotonic() - t0 > 60:
                    raise SystemExit(
                        "victim lease never went stale")
                time.sleep(0.1)
            r = elastic.reform(coord, reason="partition",
                               install=False,
                               detect_s=time.monotonic() - t0)
            m = r.membership
            assert m.members == list(range(world - 1)), m.members
            assert m.new_world == world - 1, m.new_world
            # the reformed majority coordinates in the new namespace
            post = r.coordinator.agree("post", ok)
            assert post["action"] == "ok", post
            print(f"REFORMED gen={m.gen} world={m.new_world} "
                  f"ns={m.namespace}", flush=True)
            r.coordinator.shutdown()
            coord.shutdown()
    elif phase in ("straggle", "control"):
        from pencilarrays_tpu import cluster

        if phase == "straggle":
            # the deterministic straggler: rank 1 drags EVERY exchange
            # by a fixed 0.3 s; values, guard and consensus semantics
            # are untouched (every verdict stays `ok`)
            os.environ["PENCILARRAYS_TPU_FAULTS_DELAY_S"] = "0.3"
            os.environ["PENCILARRAYS_TPU_FAULTS"] = \
                "hop.exchange:delay%rank1"
        state = {"u": pa.PencilArray.from_global(pen, truth)}
        for _ in range(4):
            guard.guarded_step(lambda: pa.transpose(state["u"], pen2),
                               label="straggle-step")
        coord = cluster.coordinator()
        assert coord is not None and coord.aggregator is not None, \
            "obs+cluster armed but no mesh aggregator"
        agg = coord.aggregator
        assert agg.publish_once(), "snapshot publish failed"
        # barrier: rank 0 must not fold before every rank published
        coord.allgather("straggle-published", {"rank": rank})
        if rank == 0:
            fold = agg.fold_once(wait=True, timeout=60)
            assert fold is not None and not fold["missing_ranks"], fold
    else:
        raise SystemExit(f"unknown phase {phase!r}")
    print(f"CLUSTER_OK phase={phase} rank={rank}")


if __name__ == "__main__":
    main()
