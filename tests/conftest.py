"""Test harness: simulate an 8-device TPU pod on CPU.

Mirrors the reference's test strategy (``test/runtests.jl:48-53``) of
simulating multi-node by multi-process on one box: here the analog is a
single process with 8 virtual XLA host devices
(``--xla_force_host_platform_device_count=8``), the JAX equivalent of the
JLArray fake-GPU trick (``test/array_types.jl:13``).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The axon TPU plugin (when present) re-forces its own platform; override.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache (works for the CPU backend too): the
# suite is compile-dominated on this image's single core, so repeat runs
# reuse every compile above the threshold.  Repo-local dir, gitignored.
jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Skip ``slow``-marked tests in the default run, mirroring the
    reference clamping its test nprocs (``test/runtests.jl:29-32``) —
    but never silently: an explicit ``-m`` expression (including
    ``-m ""`` for the full suite) or an explicit ``::node`` selection
    takes full control."""
    argv = list(config.invocation_params.args)
    if "-m" in argv or any(a.startswith(("-m=", "--markexpr")) for a in argv):
        return
    if any("::" in a for a in argv):
        return
    skip = pytest.mark.skip(
        reason='slow-marked: run with -m "" (or name the node id)')
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
