"""Tests-only stub of the minimal diffrax surface our interop uses.

``interop/diffrax_ext.py`` wires ``PIDController(norm=global_wrms_norm)``
into ``diffrax.diffeqsolve``; the real package is not installed in this
image (no network), so this stub implements just enough of the API —
``ODETerm``, ``Heun``, ``SaveAt``, ``PIDController``, ``diffeqsolve`` —
for the wrapper to execute end-to-end: a host-side adaptive Heun loop
whose accept/reject decision and dt control go through the controller's
``norm`` hook, exactly the seam the reference extension overloads
(``ext/PencilArraysDiffEqExt.jl:5-9``).  Installed into ``sys.modules``
by ``tests/test_diffrax_interop.py``; never shipped.

This is an API-shape stand-in, not a reimplementation of diffrax: one
solver, one controller law, dense output ignored.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

__version__ = "0.0-pencilarrays-test-stub"


@dataclasses.dataclass
class ODETerm:
    vector_field: Callable  # (t, y, args) -> dy/dt pytree


class Heun:
    """Marker for the 2nd-order explicit trapezoidal pair."""


@dataclasses.dataclass
class SaveAt:
    t1: bool = False


@dataclasses.dataclass
class PIDController:
    rtol: float
    atol: float
    norm: Callable[[Any], jax.Array]


@dataclasses.dataclass
class Solution:
    ts: Any
    ys: Any
    stats: dict


def diffeqsolve(terms, solver, *, t0, t1, dt0, y0,
                stepsize_controller: PIDController,
                saveat: Optional[SaveAt] = None, max_steps: int = 1000,
                args=None):
    if not isinstance(solver, Heun):
        raise NotImplementedError("stub only implements Heun")
    f = terms.vector_field
    rtol = stepsize_controller.rtol
    atol = stepsize_controller.atol
    norm = stepsize_controller.norm

    def scaled_error(err, y_a, y_b):
        return jax.tree_util.tree_map(
            lambda e, a, b: e / (atol + rtol * jnp.maximum(jnp.abs(a),
                                                           jnp.abs(b))),
            err, y_a, y_b)

    t, dt, y = float(t0), float(dt0), y0
    accepted = rejected = 0
    while t < t1 - 1e-12 and accepted + rejected < max_steps:
        h = min(dt, t1 - t)
        k1 = f(t, y, args)
        y_eul = jax.tree_util.tree_map(lambda a, b: a + h * b, y, k1)
        k2 = f(t + h, y_eul, args)
        y_new = jax.tree_util.tree_map(
            lambda a, b, c: a + (0.5 * h) * (b + c), y, k1, k2)
        err = jax.tree_util.tree_map(
            lambda b, c: (0.5 * h) * (c - b), k1, k2)
        enorm = float(norm(scaled_error(err, y, y_new)))
        if enorm <= 1.0:
            y, t = y_new, t + h
            accepted += 1
        else:
            rejected += 1
        dt = h * min(5.0, max(0.2, 0.9 * max(enorm, 1e-10) ** -0.5))
    if t < t1 - 1e-12:
        raise RuntimeError(
            f"stub diffeqsolve exhausted max_steps={max_steps} at t={t} "
            f"(tolerances too tight for the step budget?)")
    return Solution(ts=jnp.asarray([t]), ys=y,
                    stats={"num_accepted_steps": accepted,
                           "num_rejected_steps": rejected})
