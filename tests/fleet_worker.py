"""Worker for the fleet federation chaos drills.

One OS process = one back-end MESH: a self-contained single-process
jax (1 local device, no cross-process collectives — the drill runs on
any backend) wrapping a :class:`~pencilarrays_tpu.serve.PlanService`
in a :class:`~pencilarrays_tpu.fleet.MeshWorker`, joined to the
front-end router ONLY through a shared ``FileKV`` directory.  That
isolates exactly what the fleet layer adds: placement, health leases,
whole-mesh failover — the machinery that must behave identically over
the jax distributed KV store across real slices.

Identity is the environment: the launcher sets
``PENCILARRAYS_TPU_FLEET_MESH=<k>`` so one fault spec shared by every
process addresses a single mesh — the acceptance drill's
``fleet.route:kill%mesh1@4`` SIGKILLs exactly mesh 1 as it takes its
4th routed request, and ``PENCILARRAYS_TPU_CLUSTER_RANK=<k>`` so each
mesh's journal lands in its own ``journal.r<k>.jsonl`` for the
cross-process timeline merge.

Usage::

    python fleet_worker.py <kvroot> <mesh> <tmpdir> [max_seconds]
"""

import os
import sys


def main():
    kvroot, mesh, tmpdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    max_seconds = float(sys.argv[4]) if len(sys.argv) > 4 else 60.0
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1")
    # mesh identity BEFORE importing anything heavy: the %mesh fault
    # selector and the journal attribution are env-read
    os.environ["PENCILARRAYS_TPU_FLEET_MESH"] = str(mesh)
    os.environ.setdefault("PENCILARRAYS_TPU_CLUSTER_RANK", str(mesh))
    os.environ.setdefault("PENCILARRAYS_TPU_OBS",
                          os.path.join(tmpdir, "obs"))
    ttl = float(os.environ.get("PA_FLEET_TEST_TTL", "2.0"))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.cluster.kv import FileKV
    from pencilarrays_tpu.fleet import MeshWorker
    from pencilarrays_tpu.ops.fft import PencilFFTPlan
    from pencilarrays_tpu.serve import PlanService

    topo = pa.Topology((1,), devices=jax.devices()[:1])
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    svc.register_plan("minnow",
                      lambda ctx: PencilFFTPlan(topo, (8, 6, 4)))
    svc.register_plan("whale",
                      lambda ctx: PencilFFTPlan(topo, (16, 12, 8)))
    worker = MeshWorker(FileKV(kvroot), mesh, service=svc, ttl=ttl)
    worker.prewarm(["minnow", "whale"])
    worker.start()
    print(f"READY mesh={mesh} pid={os.getpid()}", flush=True)
    try:
        worker.run(poll_s=0.01, max_seconds=max_seconds)
    finally:
        print(f"EXITED mesh={mesh} handled={worker.handled}",
              flush=True)


if __name__ == "__main__":
    main()
