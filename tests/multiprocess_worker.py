"""Worker for the multi-process (multi-host analog) integration test.

The reference runs every functional test under ``mpiexec -n N``
(``test/runtests.jl:48-53``); the JAX analog is N OS processes joined by
``jax.distributed.initialize``, each owning a slice of the device pool.
This worker is launched by ``test_multiprocess.py`` with::

    python multiprocess_worker.py <coordinator> <nprocs> <pid> <tmpdir>

and exercises the cross-process surface: a topology spanning all
processes' devices, sharded fills, transpose, padding-masked global
reductions, multihost gather, and per-process collective binary IO with
a cross-process barrier.
"""

import os
import sys


def main():
    coordinator, nprocs, pid, tmpdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    n_local = 8 // nprocs  # 8 devices total, split across processes
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(coordinator, num_processes=nprocs,
                               process_id=pid)
    import jax.numpy as jnp
    import numpy as np

    import pencilarrays_tpu as pa
    from pencilarrays_tpu.io import BinaryDriver, open_file

    assert jax.process_count() == nprocs
    assert len(jax.devices()) == 8
    assert len(jax.local_devices()) == n_local

    topo = pa.Topology((2, 4))
    shape = (11, 9, 13)  # ragged on purpose
    pen_x = pa.Pencil(topo, shape, (1, 2), permutation=pa.Permutation(2, 0, 1))
    pen_y = pa.Pencil(topo, shape, (0, 2))

    # sharded fill spans both processes; reductions are global
    u = pa.ops.normal(pen_x, jax.random.key(7), dtype=jnp.float64)
    total = float(pa.ops.sum(u))
    mx = float(pa.ops.maximum(u))

    # gather returns the full array on EVERY process (process_allgather)
    g = pa.gather(u)
    assert g.shape == shape
    assert np.isclose(g.sum(), total, rtol=1e-10)
    assert np.isclose(g.max(), mx, rtol=1e-12)

    # transpose across the pod; ground truth agreement on every process
    v = pa.transpose(u, pen_y)
    gv = pa.gather(v)
    assert np.array_equal(gv, g), "transpose mismatch across processes"

    # collective binary write: each process writes only its shards;
    # deterministic offsets + barrier make the file complete
    path = os.path.join(tmpdir, "mp.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", u)
    with open_file(BinaryDriver(), path, read=True) as f:
        back = f.read("u", pen_y)  # different decomposition on re-read
    assert np.array_equal(pa.gather(back), g), "IO round trip mismatch"

    # collective HDF5 write (round 3): per-process shard files + the
    # virtual-dataset master; re-read under a different decomposition,
    # and as one plain h5py dataset
    from pencilarrays_tpu.io import HDF5Driver, has_hdf5

    if has_hdf5():
        h5path = os.path.join(tmpdir, "mp.h5")
        with open_file(HDF5Driver(), h5path, write=True, create=True) as f:
            f.write("u", u)
        with open_file(HDF5Driver(), h5path, read=True) as f:
            hback = f.read("u", pen_y)
        assert np.array_equal(pa.gather(hback), g), "HDF5 round trip"
        # collection-level I/O across processes: two fields, ONE dataset
        w = u * 2.0
        with open_file(HDF5Driver(), h5path, append=True, write=True) as f:
            f.write("uw", (u, w))
        with open_file(HDF5Driver(), h5path, read=True) as f:
            u2, w2 = f.read("uw", pen_x)
        assert np.array_equal(pa.gather(u2), g), "collection comp 0"
        assert np.array_equal(pa.gather(w2), 2.0 * g), "collection comp 1"
        if pid == 0:
            import h5py

            with h5py.File(h5path, "r") as mf:  # ecosystem-readable
                assert np.array_equal(mf["u"][...], g), "h5py direct read"
        pa.distributed.sync_global_devices("h5_done")

    # full FFT plan across the pod: hops ride collectives that cross the
    # process boundary; result matches numpy on every process, and the
    # measured Auto winner is broadcast so all processes agree
    plan = pa.PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64,
                            method=pa.Auto(mode="measure"))
    uf = pa.PencilArray.from_global(plan.input_pencil, g)
    uh = plan.forward(uf)
    expect_f = np.fft.fftn(np.fft.rfft(g, axis=0), axes=(1, 2))
    assert np.allclose(pa.gather(uh), expect_f, rtol=1e-9, atol=1e-8), \
        "cross-process FFT forward"
    assert np.allclose(pa.gather(plan.backward(uh)), g,
                       rtol=1e-10, atol=1e-10), "cross-process FFT inverse"

    # sequence-parallel attention spanning the processes: the ring's
    # ppermute rounds and ulysses' all_to_all cross the process boundary
    from pencilarrays_tpu.models import (
        dense_attention, ring_attention, ulysses_attention)

    topo_seq = pa.Topology((8,))
    pen_s = pa.Pencil(topo_seq, (32, 8), (0,))
    rng = np.random.default_rng(3)  # same seed -> same data every process
    qn, kn, vn = (rng.standard_normal((32, 8, 8)).astype(np.float32)
                  for _ in range(3))
    qa, ka, va = (pa.PencilArray.from_global(pen_s, x)
                  for x in (qn, kn, vn))
    expect = np.asarray(dense_attention(jnp.asarray(qn), jnp.asarray(kn),
                                        jnp.asarray(vn)))
    out_r = pa.gather(ring_attention(qa, ka, va))
    out_u = pa.gather(ulysses_attention(qa, ka, va))
    assert np.allclose(out_r, expect, rtol=2e-4, atol=2e-5), "ring attn"
    assert np.allclose(out_u, expect, rtol=2e-4, atol=2e-5), "ulysses attn"

    # zigzag causal ring across the process boundary (round 3)
    from pencilarrays_tpu.models import from_zigzag, to_zigzag

    expect_c = np.asarray(dense_attention(
        jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn), causal=True))
    out_z = pa.gather(from_zigzag(ring_attention(
        to_zigzag(qa), to_zigzag(ka), to_zigzag(va),
        causal=True, zigzag=True)))
    assert np.allclose(out_z, expect_c, rtol=2e-4, atol=2e-5), "zigzag attn"

    pa.distributed.sync_global_devices("done")
    print(f"WORKER_OK pid={pid} sum={total:.6f}")


if __name__ == "__main__":
    main()
