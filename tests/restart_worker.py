"""Worker for the cross-process-count restart tests.

The reference's discontiguous MPI-IO layout exists precisely so a file
can be "read back using a different number or distribution of MPI
processes" (``src/PencilIO/mpi_io.jl:159-167``).  The TPU analog must
hold across *process counts*, not just decompositions — and, for the
resilience subsystem, across *crashes*: a worker SIGKILLed mid-write
must leave the previous committed checkpoint restorable bit-for-bit.

Phases (launched by ``test_multiprocess.py``):

* ``write`` under 4 processes (2 devices each): binary + HDF5 (shard
  files + virtual-dataset master), pencil decomposed (1, 2) with a
  non-trivial permutation;
* ``read2`` under 2 processes (4 devices each): re-read both files onto
  a DIFFERENT decomposition (0, 2) on a different mesh shape;
* ``read1`` single-process (8 local devices, no ``jax.distributed``):
  re-read onto a 1-D slab decomposition;
* ``ckpt``: commit checkpoint step 1 (ground truth) through
  ``resilience.CheckpointManager`` (checksummed manifest + COMMIT);
* ``killwrite``: arm the ``io.write_block:torn@3`` fault and attempt
  checkpoint step 2 — the process tears the third block and SIGKILLs
  itself mid-write (the launcher asserts the signal death);
* ``recover``: assert ``latest_valid()`` skips the torn step-2 temp
  wreckage, restores step 1, and the recovered global array is
  bit-identical to the deterministic ground truth.  The single-process
  variant additionally runs the guard's detect-and-recover ladder
  (``guard.guarded_step`` + a deterministic ``hop.exchange:corrupt``
  drill): corrupted exchanges are detected as typed ``IntegrityError``,
  retries exhaust, the last committed checkpoint restores the state and
  the re-run step is bit-identical — journaled as ``guard.recover``
  events the launcher asserts.

Every phase checks gathered global arrays bit-for-bit against the
ground truth regenerated from the shared seed.

Usage::

    python restart_worker.py <coordinator|-> <nprocs> <pid> <tmpdir> <phase>
"""

import os
import sys


def main():
    coordinator, nprocs, pid, tmpdir, phase = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5])
    n_local = 8 // nprocs
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import pencilarrays_tpu as pa
    from pencilarrays_tpu.io import (BinaryDriver, HDF5Driver, has_hdf5,
                                     open_file)

    # idempotent bootstrap: a no-op when nprocs == 1, a retried
    # coordinator connection otherwise — restart workers call this
    # unconditionally instead of tracking whether init already happened
    pa.distributed.ensure_initialized(
        None if coordinator == "-" else coordinator,
        num_processes=nprocs, process_id=pid)

    assert len(jax.devices()) == 8
    shape = (11, 9, 13)  # ragged: every mesh below pads some dim
    truth = np.random.default_rng(11).standard_normal(shape)
    bpath = os.path.join(tmpdir, "restart.bin")
    hpath = os.path.join(tmpdir, "restart.h5")
    ckdir = os.path.join(tmpdir, "ckpts")

    if phase == "write":
        topo = pa.Topology((2, 4))
        pen = pa.Pencil(topo, shape, (1, 2),
                        permutation=pa.Permutation(2, 0, 1))
        u = pa.PencilArray.from_global(pen, truth)
        with open_file(BinaryDriver(), bpath, write=True, create=True) as f:
            f.write("u", u)
        if has_hdf5():
            with open_file(HDF5Driver(), hpath, write=True,
                           create=True) as f:
                f.write("u", u)
        if nprocs > 1:
            pa.distributed.sync_global_devices("write_done")
    elif phase in ("ckpt", "killwrite"):
        from pencilarrays_tpu.resilience import CheckpointManager, faults

        # arm the flight recorder: the SIGKILL drill must leave a
        # readable event timeline (journal under <tmpdir>/obs; env is
        # re-read on change, so arming after import works — the same
        # late-arming contract as the faults env)
        os.environ["PENCILARRAYS_TPU_OBS"] = os.path.join(tmpdir, "obs")
        topo = pa.Topology((2, 4))
        pen = pa.Pencil(topo, shape, (1, 2),
                        permutation=pa.Permutation(2, 0, 1))
        u = pa.PencilArray.from_global(pen, truth)
        mgr = CheckpointManager(ckdir, keep=3)
        if phase == "ckpt":
            mgr.save(1, {"u": u})
            assert mgr.latest_valid() == 1
            if nprocs > 1:
                pa.distributed.sync_global_devices("ckpt_done")
        else:
            # arm AFTER import (the env is re-read on change) and tear
            # a mid-stream block: SIGKILL mid-checkpoint-write.  Each
            # process streams 8/nprocs blocks, so pick a tear point that
            # exists for every process.
            tear = 3 if nprocs == 1 else 2
            os.environ[faults.ENV_VAR] = f"io.write_block:torn@{tear}"
            garbage = pa.PencilArray.from_global(
                pen, truth + 1000.0)  # step 2 must NOT survive
            mgr.save(2, {"u": garbage})
            raise SystemExit("unreachable: torn injection did not kill")
    elif phase == "recover":
        from pencilarrays_tpu.resilience import CheckpointManager

        os.environ["PENCILARRAYS_TPU_OBS"] = os.path.join(tmpdir, "obs")
        mgr = CheckpointManager(ckdir, keep=3)
        # the torn step-2 attempt must be invisible: only its temp
        # directory (never renamed, never committed) may remain
        assert mgr.latest_valid() == 1, mgr.steps()
        topo = pa.Topology((8,))
        pen = pa.Pencil(topo, shape, (1,))
        back = mgr.restore().read("u", pen)
        assert np.array_equal(pa.gather(back), truth), \
            "recovered checkpoint is not bit-identical to ground truth"
        if nprocs == 1:
            # the detect-and-recover ladder, end to end: in-memory state
            # diverged (as after a crash), the first two step attempts
            # hit injected exchange corruption (typed IntegrityError,
            # never garbage), escalation restores the committed step 1
            # and the re-run step is bit-identical — the full
            # guard.recover timeline lands in the same obs journal the
            # launcher lints
            from pencilarrays_tpu import guard
            from pencilarrays_tpu.resilience import RetryPolicy, faults

            guard.enable(os.path.join(tmpdir, "bundles"))
            pen2 = pa.Pencil(topo, shape, (0,))
            state = {"u": pa.PencilArray.from_global(pen, truth + 1000.0)}

            def step():
                return pa.transpose(state["u"], pen2)

            def restore_cb(ckpt):
                state["u"] = ckpt.read("u", pen)

            with faults.active("hop.exchange:corrupt*2"):
                out = guard.guarded_step(
                    step, ckpt_mgr=mgr, restore=restore_cb,
                    retry=RetryPolicy(max_attempts=2, base_delay=0.01),
                    label="restart-recover")
            assert np.array_equal(pa.gather(out), truth), \
                "guarded_step recovery is not bit-identical"
        if nprocs > 1:
            pa.distributed.sync_global_devices("recover_done")
    else:
        if phase == "read2":
            topo = pa.Topology((4, 2))
            pen = pa.Pencil(topo, shape, (0, 2))
        elif phase == "read1":
            topo = pa.Topology((8,))
            pen = pa.Pencil(topo, shape, (1,))
        else:
            raise SystemExit(f"unknown phase {phase!r}")
        with open_file(BinaryDriver(), bpath, read=True) as f:
            back = f.read("u", pen)
        assert np.array_equal(pa.gather(back), truth), \
            f"binary restart mismatch in {phase}"
        if has_hdf5() and os.path.exists(hpath):
            with open_file(HDF5Driver(), hpath, read=True) as f:
                hback = f.read("u", pen)
            assert np.array_equal(pa.gather(hback), truth), \
                f"hdf5 restart mismatch in {phase}"
        if nprocs > 1:
            pa.distributed.sync_global_devices("read_done")
    print(f"RESTART_OK phase={phase} pid={pid}")


if __name__ == "__main__":
    main()
