"""Worker for the cross-process-count restart test.

The reference's discontiguous MPI-IO layout exists precisely so a file
can be "read back using a different number or distribution of MPI
processes" (``src/PencilIO/mpi_io.jl:159-167``).  The TPU analog must
hold across *process counts*, not just decompositions: this worker is
launched by ``test_multiprocess.py::test_restart_across_process_counts``
in three phases —

* ``write`` under 4 processes (2 devices each): binary + HDF5 (shard
  files + virtual-dataset master), pencil decomposed (1, 2) with a
  non-trivial permutation;
* ``read2`` under 2 processes (4 devices each): re-read both files onto
  a DIFFERENT decomposition (0, 2) on a different mesh shape;
* ``read1`` single-process (8 local devices, no ``jax.distributed``):
  re-read onto a 1-D slab decomposition.

Every phase checks the gathered global array bit-for-bit against the
deterministic ground truth regenerated from the shared seed.

Usage::

    python restart_worker.py <coordinator|-> <nprocs> <pid> <tmpdir> <phase>
"""

import os
import sys


def main():
    coordinator, nprocs, pid, tmpdir, phase = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5])
    n_local = 8 // nprocs
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if nprocs > 1:
        jax.distributed.initialize(coordinator, num_processes=nprocs,
                                   process_id=pid)
    import numpy as np

    import pencilarrays_tpu as pa
    from pencilarrays_tpu.io import (BinaryDriver, HDF5Driver, has_hdf5,
                                     open_file)

    assert len(jax.devices()) == 8
    shape = (11, 9, 13)  # ragged: every mesh below pads some dim
    truth = np.random.default_rng(11).standard_normal(shape)
    bpath = os.path.join(tmpdir, "restart.bin")
    hpath = os.path.join(tmpdir, "restart.h5")

    if phase == "write":
        topo = pa.Topology((2, 4))
        pen = pa.Pencil(topo, shape, (1, 2),
                        permutation=pa.Permutation(2, 0, 1))
        u = pa.PencilArray.from_global(pen, truth)
        with open_file(BinaryDriver(), bpath, write=True, create=True) as f:
            f.write("u", u)
        if has_hdf5():
            with open_file(HDF5Driver(), hpath, write=True,
                           create=True) as f:
                f.write("u", u)
        if nprocs > 1:
            pa.distributed.sync_global_devices("write_done")
    else:
        if phase == "read2":
            topo = pa.Topology((4, 2))
            pen = pa.Pencil(topo, shape, (0, 2))
        elif phase == "read1":
            topo = pa.Topology((8,))
            pen = pa.Pencil(topo, shape, (1,))
        else:
            raise SystemExit(f"unknown phase {phase!r}")
        with open_file(BinaryDriver(), bpath, read=True) as f:
            back = f.read("u", pen)
        assert np.array_equal(pa.gather(back), truth), \
            f"binary restart mismatch in {phase}"
        if has_hdf5() and os.path.exists(hpath):
            with open_file(HDF5Driver(), hpath, read=True) as f:
                hback = f.read("u", pen)
            assert np.array_equal(pa.gather(hback), truth), \
                f"hdf5 restart mismatch in {phase}"
        if nprocs > 1:
            pa.distributed.sync_global_devices("read_done")
    print(f"RESTART_OK phase={phase} pid={pid}")


if __name__ == "__main__":
    main()
