"""Front-end router process for the ISSUE 20 WAL chaos drill.

One OS process = the fleet FRONT-END: a
:class:`~pencilarrays_tpu.fleet.FleetRouter` with a durable WAL over
the shared ``FileKV`` wire, submitting a deterministic storm of seeded
requests against the subprocess meshes of ``fleet_worker.py``.  The
launcher arms ``fleet.route:kill@<n>`` in THIS process's environment,
so the router SIGKILLs itself at its n-th admission — the
un-catchable front-end crash the WAL exists to survive.  The parent
then replays the WAL into a fresh router and proves the exactly-once
contract across router incarnations: every admission the log
committed resolves exactly once, nothing is lost, nothing doubles.

Payloads are derived from the request index (``default_rng(1000+i)``)
so the parent can regenerate any of them without a side channel.

Usage::

    python router_worker.py <kvroot> <waldir> <nreq> <meshes-csv>
"""

import os
import sys


def main():
    kvroot, waldir, nreq, meshes = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), sys.argv[4])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1")
    ttl = float(os.environ.get("PA_FLEET_TEST_TTL", "2.0"))
    import numpy as np

    from pencilarrays_tpu.cluster.kv import FileKV
    from pencilarrays_tpu.fleet import FleetRouter

    router = FleetRouter(FileKV(kvroot), ttl=ttl, wal_dir=waldir)
    for m in meshes.split(","):
        router.register_mesh(int(m))
    print(f"ROUTER_READY pid={os.getpid()}", flush=True)
    for i in range(nreq):
        rng = np.random.default_rng(1000 + i)
        u = (rng.standard_normal((8, 6, 4))
             + 1j * rng.standard_normal((8, 6, 4))).astype(np.complex64)
        router.submit("acme", u, name="minnow")  # armed kill fires here
        router.pump()
    left = router.drain(120.0)
    print(f"ROUTER_DRAINED left={left} "
          f"completed={router.stats()['completed']}", flush=True)
    router.close()


if __name__ == "__main__":
    main()
