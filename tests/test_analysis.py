"""Static-analysis layer (``pencilarrays_tpu/analysis/``, ISSUE 11).

Pillar 1 — SPMD program verifier: ``CollectiveTrace`` extraction across
methods x transforms x batch, typed rejection of corrupted schedules
(naming the offending op), HBM bounds, donation elision, guard-on/off
consistency, and the ``PlanService.certify()`` registry sweep with its
``analysis.check`` journal records.

Pillar 2 — AST repo linter: each check proven to FIRE on a
deliberately-broken fixture tree and to stay quiet on a clean one,
plus the allowlist round-trip (suppression, stale-entry detection,
unjustified entries are findings) and the real repo linting clean.

The ``pa-lint`` CLI is shelled over the repo and must exit 0 — the CI
gate of both pillars.
"""

import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.analysis.errors import (
    DonationError,
    HbmBoundError,
    ScheduleMismatchError,
    TraceDivergenceError,
)
from pencilarrays_tpu.analysis.lint import (
    Allowlist,
    Finding,
    lint_tree,
    run_lint,
)
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.parallel.routing import plan_reshard_route
from pencilarrays_tpu.parallel.transpositions import (
    AllToAll,
    Pipelined,
    Ring,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# pillar 1: trace extraction
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method", [AllToAll(), Ring(),
                                    Pipelined(chunks=2)],
                         ids=["alltoall", "ring", "pipelined"])
@pytest.mark.parametrize("extra", [(), (3,)], ids=["plain", "batched"])
def test_trace_transpose_matches_cost_model(devices, method, extra):
    """The extractor reproduces the validated byte model for every
    explicit method, batched and plain — the pin every refactored test
    file now routes through."""
    topo = pa.Topology((4,), devices=devices[:4])
    pin = pa.Pencil(topo, (16, 12, 20), (1,))
    pout = pa.Pencil(topo, (16, 12, 20), (0,))
    tr = spmd.trace_transpose(pin, pout, extra, np.complex64, method)
    assert tr.stats() == pa.transpose_cost(pin, pout, extra,
                                           np.complex64, method)
    # ordered, typed ops with replica groups and positive bytes
    assert all(o.bytes > 0 for o in tr.ops)
    assert [o.index for o in tr.ops] == list(range(len(tr.ops)))
    assert any(o.replica_groups for o in tr.ops)


@pytest.mark.parametrize("dims", [(4,), (2, 2)], ids=["slab", "pencil"])
@pytest.mark.parametrize("real", [False, True], ids=["c2c", "r2c"])
@pytest.mark.parametrize("extra", [(), (3,)], ids=["plain", "batched"])
def test_verify_plan_whole_matrix(devices, dims, real, extra):
    """Acceptance: every plan type's compiled trace == the
    ``collective_costs`` prediction — slab/pencil x c2c/r2c x batched,
    forward AND backward."""
    n = int(np.prod(dims))
    topo = pa.Topology(dims, devices=devices[:n])
    plan = PencilFFTPlan(topo, (8, 8, 4), real=real)
    fwd = spmd.verify_plan(plan, extra, "forward")
    bwd = spmd.verify_plan(plan, extra, "backward")
    assert len(fwd) > 0 and len(bwd) > 0


def test_verify_routed_reshard(devices):
    """Acceptance: the routed-reshard chain verifies too, and the
    trace is the executable's (``_compiled_route``), not a re-trace."""
    topo = pa.Topology((2, 4), devices=devices)
    pin = pa.Pencil(topo, (16, 12, 8), (1, 2))
    dest = pa.Pencil(topo, (16, 12, 8), (0, 1))
    route = plan_reshard_route(pin, dest, (), np.float32)
    assert route.hops
    tr = spmd.verify_route(route, (), np.float32)
    assert len(tr) == sum(
        c["count"] for h in route.hops for c in h.cost.values())


def test_trace_compiled_plan_is_residents_trace(devices):
    """``trace_compiled_plan`` inspects the resident ``CompiledPlan``
    executable's own jitted callable — certification covers what will
    actually dispatch."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64, batch=3)
    cp = plan.compile()
    tr = spmd.trace_compiled_plan(cp, "forward")
    assert tr.stats() == plan.collective_costs((3,))
    assert spmd.trace_compiled_plan(cp, "backward").stats() \
        == plan.collective_costs((3,))


# ---------------------------------------------------------------------------
# pillar 1: typed rejection
# ---------------------------------------------------------------------------


def test_corrupted_schedule_rejected_naming_op(devices):
    """Acceptance: a deliberately corrupted schedule is rejected with a
    typed error NAMING the offending op — both a dropped collective in
    the trace and a tampered prediction."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64)
    good = spmd.trace_plan(plan, ())
    assert good.ops, "plan must move bytes for this drill"
    # drop the last collective from the compiled trace
    corrupted = spmd.CollectiveTrace(
        source="corrupted", ops=good.ops[:-1],
        donated_params=good.donated_params)
    with pytest.raises(ScheduleMismatchError) as ei:
        spmd.verify_plan(plan, (), "forward", trace=corrupted)
    assert ei.value.op == good.ops[-1].kind
    assert ei.value.predicted is not None
    assert good.ops[-1].kind in str(ei.value)

    # tamper the prediction instead (the plan lies about its costs)
    class Tampered(PencilFFTPlan):
        def collective_costs(self, extra_dims=None, **kw):
            costs = PencilFFTPlan.collective_costs(self, extra_dims,
                                                   **kw)
            op = next(iter(costs))
            costs[op] = {"count": costs[op]["count"] + 1,
                         "bytes": costs[op]["bytes"]}
            return costs

    plan.__class__ = Tampered
    try:
        with pytest.raises(ScheduleMismatchError) as ei:
            spmd.verify_plan(plan, (), "forward", trace=good)
        assert ei.value.op in good.stats()
    finally:
        plan.__class__ = PencilFFTPlan


def test_hbm_bound_violation_names_hop(devices):
    """Check (c): a static peak-HBM prediction over the limit raises a
    typed error naming the offending hop, for plans AND routes."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64)
    peak, label = spmd.predicted_peak_hbm(plan)
    assert peak > 0 and label.startswith("hop[")
    assert spmd.verify_hbm(plan, peak) == peak  # at the bound: fits
    with pytest.raises(HbmBoundError) as ei:
        spmd.verify_hbm(plan, peak - 1, source="drill")
    assert ei.value.hop == label
    assert ei.value.peak_bytes == peak
    assert "drill" in str(ei.value) and label in str(ei.value)

    topo8 = pa.Topology((2, 4), devices=devices)
    pin = pa.Pencil(topo8, (16, 12, 8), (1, 2))
    dest = pa.Pencil(topo8, (16, 12, 8), (0, 1))
    route = plan_reshard_route(pin, dest, (), np.float32)
    rpeak, rlabel = spmd.predicted_peak_hbm(route)
    assert rpeak == max(h.peak_hbm_bytes for h in route.hops)
    with pytest.raises(HbmBoundError) as ei:
        spmd.verify_hbm(route, rpeak - 1)
    assert ei.value.hop == rlabel and rlabel.startswith("route[")


def test_consistency_checks(devices):
    """Check (b): batched-vs-unbatched (count x1, bytes xB) and
    guard-on-vs-off (same exchange collectives; probe all-reduces
    excluded by kind) — plus a typed divergence drill."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64)
    t1 = spmd.trace_plan(plan, ())
    t3 = spmd.trace_plan(plan, (3,))
    spmd.verify_consistent(t1, t3, bytes_ratio=3)
    with pytest.raises(TraceDivergenceError) as ei:
        spmd.verify_consistent(t1, t3, bytes_ratio=1)  # wrong ratio
    assert ei.value.op in t1.stats()
    # guard-on vs guard-off hop bodies compile the same exchanges
    from pencilarrays_tpu.ops.pallas_kernels import pallas_enabled
    from pencilarrays_tpu.parallel import transpositions as tr

    pin = pa.Pencil(topo, (8, 8, 4), (1, 2))
    pout = pa.Pencil(topo, (8, 8, 4), (0, 2))
    R = tr.assert_compatible(pin, pout)
    m = AllToAll()
    aval = spmd._input_aval(pin, (), np.dtype(np.float32))
    off = spmd.trace_fn(
        tr._compiled_transpose(pin, pout, R, 0, m, False,
                               pallas_enabled()),
        aval, source="guard-off")
    on = spmd.trace_fn(
        tr._compiled_guarded_transpose(pin, pout, R, 0, m, False,
                                       pallas_enabled(), False),
        aval, source="guard-on")
    spmd.verify_consistent(off, on)


def test_donation_verified_and_missing_donation_typed(devices):
    """Check (c), donation half: a donate-compiled route carries the
    input/output alias; a non-donating program fails typed."""
    topo = pa.Topology((2, 4), devices=devices)
    pin = pa.Pencil(topo, (16, 12, 8), (1, 2))
    dest = pa.Pencil(topo, (16, 12, 8), (0, 1))
    route = plan_reshard_route(pin, dest, (), np.float32)
    donated = spmd.trace_route(route, (), np.float32, donate=True)
    spmd.verify_donation(donated)
    assert 0 in donated.donated_params
    plain = spmd.trace_route(route, (), np.float32, donate=False)
    with pytest.raises(DonationError):
        spmd.verify_donation(plain)


# ---------------------------------------------------------------------------
# pillar 1: certification sweep + journal
# ---------------------------------------------------------------------------


def test_plan_service_certify_sweep(devices, tmp_path, monkeypatch):
    """``PlanService.certify()`` certifies every resident executable
    pre-flight, journaled as schema-clean ``analysis.check`` events."""
    from pencilarrays_tpu.obs.schema import lint_journal
    from pencilarrays_tpu.serve.service import PlanService

    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    svc = PlanService(max_batch=4)
    topo = pa.Topology((2, 2), devices=devices[:4])
    svc.register_plan("c2c", lambda ctx: PencilFFTPlan(
        topo, (8, 8, 4), dtype=np.complex64))
    svc.register_plan("r2c", lambda ctx: PencilFFTPlan(
        topo, (8, 8, 4), real=True))
    # one resident executable (the other plan stays trace-certified)
    svc.registry.compiled(svc.plan("c2c"), (2,))
    try:
        report = svc.certify()
    finally:
        svc.close()
        from pencilarrays_tpu.cluster import elastic

        elastic.unregister_plan("serve:c2c")
        elastic.unregister_plan("serve:r2c")
    assert report["ok"] and report["certified"] >= 2
    assert all(r["outcome"] == "ok" for r in report["plans"])
    targets = {r["target"] for r in report["plans"]}
    assert {f"serve:{svc.plan('c2c').plan_key()}",
            f"serve:{svc.plan('r2c').plan_key()}"} <= targets
    events = [e for e in obs.read_journal(jdir)
              if e["ev"] == "analysis.check"]
    assert len(events) == report["certified"]
    assert all(e["outcome"] == "ok" and e["seconds"] >= 0
               for e in events)
    assert lint_journal(obs.read_journal(jdir)) == []


def test_certify_hbm_bounds_resident_batched_variant(devices):
    """Review regression: ``certify(hbm_limit=)`` bounds each resident
    executable at ITS extra_dims — a coalesced-batch variant must not
    escape the limit through the plan's default batch, and the
    non-raising report names the typed error and the variant."""
    from pencilarrays_tpu.serve.service import PlanService

    svc = PlanService()
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64)  # batch=()
    svc.registry.register(plan)
    svc.registry.compiled(plan, (16,))      # resident batched variant
    try:
        unbatched_peak, _ = spmd.predicted_peak_hbm(plan, ())
        batched_peak, _ = spmd.predicted_peak_hbm(plan, (16,))
        assert batched_peak > unbatched_peak
        # a limit the default batch fits but the resident batch blows
        with pytest.raises(HbmBoundError):
            svc.certify(hbm_limit=unbatched_peak)
        report = svc.certify(hbm_limit=unbatched_peak,
                             raise_on_error=False)
        assert not report["ok"]
        bad = [r for r in report["plans"]
               if r["outcome"] == "HbmBoundError"]
        assert len(bad) == 1
        assert bad[0]["extra_dims"] == [16]
        assert "error" in bad[0]
        # at the true batched peak everything certifies
        assert svc.certify(hbm_limit=batched_peak)["ok"]
    finally:
        svc.close()


def test_certify_failure_journaled_and_raised(devices, tmp_path,
                                              monkeypatch):
    """A corrupted resident schedule fails certification with the
    typed error AND an fsync-critical non-ok ``analysis.check``."""
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 8, 4), dtype=np.complex64)
    real_costs = plan.collective_costs
    good = spmd.trace_plan(plan, ())
    op = next(iter(good.stats()))

    def tampered(extra_dims=None, **kw):
        costs = real_costs(extra_dims, **kw)
        costs[op] = {"count": costs[op]["count"] + 1,
                     "bytes": costs[op]["bytes"]}
        return costs

    monkeypatch.setattr(plan, "collective_costs", tampered)
    with pytest.raises(ScheduleMismatchError) as ei:
        spmd.certify_plan(plan, (), target="drill")
    assert ei.value.op == op
    events = [e for e in obs.read_journal(jdir)
              if e["ev"] == "analysis.check"]
    assert len(events) == 1
    assert events[0]["outcome"] == "ScheduleMismatchError"
    assert events[0]["target"] == "drill"


# ---------------------------------------------------------------------------
# pillar 2: AST linter on broken fixture trees
# ---------------------------------------------------------------------------


_SCHEMA_PY = """
EVENT_TYPES = {"hop": ("method",), "run.start": ("pid",)}
"""

_FAULTS_PY = """
POINTS = frozenset({"io.open", "hop.exchange"})
"""

_ELASTIC_PY = """
def clear_plan_caches():
    from ..ops import fft as _fft

    for mod, names in ((_fft, ("_stage_fn",)),):
        pass
"""


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))
    return path


def _fixture_repo(tmp_path, extra_files=()):
    """A minimal parseable repo skeleton: schema/faults/elastic source
    registries + docs corpus; ``extra_files`` adds the snippets under
    test."""
    root = str(tmp_path / "repo")
    _write(root, "pencilarrays_tpu/obs/schema.py", _SCHEMA_PY)
    _write(root, "pencilarrays_tpu/resilience/faults.py", _FAULTS_PY)
    _write(root, "pencilarrays_tpu/cluster/elastic.py", _ELASTIC_PY)
    _write(root, "pencilarrays_tpu/ops/fft.py", """
        from functools import lru_cache
        import jax

        @lru_cache(maxsize=8)
        def _stage_fn(k):
            return jax.jit(lambda x: x)
        """)
    _write(root, "docs/Resilience.md", "| `io.open` | `hop.exchange` |")
    _write(root, "README.md", "PENCILARRAYS_TPU_OBS is documented here")
    for rel, content in extra_files:
        _write(root, rel, content)
    return root


def test_lint_clean_fixture_has_no_findings(tmp_path):
    root = _fixture_repo(tmp_path)
    assert lint_tree(root) == []


def test_lint_unregistered_journal_event(tmp_path):
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/ops/thing.py", """
            def f(obs):
                obs.record_event("hop", method="x")       # registered
                obs.record_event("made.up", method="x")   # NOT
            """)])
    found = [f for f in lint_tree(root) if f.check == "journal-event"]
    assert len(found) == 1
    assert found[0].ident == "made.up"
    assert "EVENT_TYPES" in found[0].message


def test_lint_undocumented_env_knob(tmp_path):
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/knobs.py", """
            import os
            A = os.environ.get("PENCILARRAYS_TPU_OBS")       # documented
            B = os.environ.get("PENCILARRAYS_TPU_SECRET_K")  # NOT
            """)])
    found = [f for f in lint_tree(root) if f.check == "env-knob"]
    assert [f.ident for f in found] == ["PENCILARRAYS_TPU_SECRET_K"]


def test_lint_unregistered_plan_cache(tmp_path):
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/serve/extra.py", """
            from functools import lru_cache
            import jax

            @lru_cache(maxsize=4)
            def _rogue_fn(n):
                return jax.jit(lambda x: x * n)

            @lru_cache(maxsize=4)
            def _pure_table(n):
                return {"n": n}   # no jit: not an executable factory
            """)])
    found = [f for f in lint_tree(root) if f.check == "plan-cache"]
    assert [f.ident for f in found] == ["serve.extra._rogue_fn"]
    assert "clear_plan_caches" in found[0].message


def test_lint_fault_point_checks(tmp_path):
    root = _fixture_repo(tmp_path, [
        # consults an unregistered point
        ("pencilarrays_tpu/io/x.py", """
            from ..resilience import faults

            def f():
                faults.fire("io.open")
                faults.fire("io.bogus")
            """),
        # a registered point missing from the docs table
        ("pencilarrays_tpu/resilience/faults2.py", "")])
    # drop hop.exchange from the docs
    _write(root, "docs/Resilience.md", "| `io.open` |")
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "fault-point")
    assert found == ["hop.exchange", "io.bogus"]


def test_lint_unlocked_daemon_state(tmp_path):
    broken = """
        _pending = {}

        def note(k, v):
            _pending[k] = v
        """
    locked = """
        import threading

        _lock = threading.Lock()
        _pending = {}

        def note(k, v):
            with _lock:
                _pending[k] = v
        """
    readonly = """
        _TABLE = {"a": 1}

        def get(k):
            return _TABLE[k]
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/obs/broken.py", broken),
        ("pencilarrays_tpu/serve/lockedmod.py", locked),
        ("pencilarrays_tpu/cluster/tables.py", readonly),
        # same mutated state OUTSIDE the daemon packages: out of scope
        ("pencilarrays_tpu/parallel/free.py", broken)])
    found = [f for f in lint_tree(root) if f.check == "unlocked-state"]
    assert [f.ident for f in found] == ["obs.broken._pending"]


def test_lint_thread_spawn_outside_engine(tmp_path):
    """Raw Thread construction is an engine/ monopoly: a rogue daemon
    anywhere else is a finding (stable-ident'd by its enclosing def),
    spawn_thread call sites and engine-internal construction are not,
    and a justified allowlist entry suppresses it."""
    rogue = """
        import threading

        def start_daemon():
            t = threading.Thread(target=print, daemon=True)
            t.start()
        """
    from_import = """
        from threading import Thread

        def sneaky():
            Thread(target=print).start()
        """
    clean = """
        def start(self):
            from ..engine.threads import spawn_thread

            self._t = spawn_thread(self._loop, name="pa-x")
        """
    engine_own = """
        import threading

        def spawn_thread(target, *, name):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            return t
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/obs/rogue.py", rogue),
        ("pencilarrays_tpu/io/sneak.py", from_import),
        ("pencilarrays_tpu/cluster/ok.py", clean),
        ("pencilarrays_tpu/engine/threads.py", engine_own)])
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "thread-spawn")
    assert found == ["io.sneak.sneaky", "obs.rogue.start_daemon"]
    allow = _write(root, "pa-lint.allow", """
        thread-spawn obs.rogue.start_daemon  # drill-only daemon
        thread-spawn io.sneak.sneaky  # legacy, tracked in ISSUE-99
        """)
    findings, _ = run_lint(root, Allowlist.load(allow))
    assert [f for f in findings if f.check == "thread-spawn"] == []


def test_lint_hop_peak_outside_sanctioned_modules(tmp_path):
    """``_hop_peak_bytes`` references (import, attribute, bare call)
    anywhere but ``parallel/routing.py``/``analysis/spmd.py`` are
    findings — the footprint accounting stays ONE function; everyone
    else bounds through analysis.spmd."""
    rogue_import = """
        from ..parallel.routing import _hop_peak_bytes

        def my_own_bound(pin, pout, R):
            return _hop_peak_bytes(pin, pout, R, (), None)
        """
    rogue_attr = """
        def sneaky(routing, pin, pout):
            return routing._hop_peak_bytes(pin, pout, None, (), None)
        """
    sanctioned = """
        def _hop_peak_bytes(pin, pout, R, extra, dtype, method=None):
            return 0

        def edge(pin, pout):
            return _hop_peak_bytes(pin, pout, 0, (), None)
        """
    clean = """
        def bound(plan, limit):
            from ..analysis.spmd import step_hop_peak

            return step_hop_peak(plan, ())
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/ops/rogue_fft.py", rogue_import),
        ("pencilarrays_tpu/serve/sneak.py", rogue_attr),
        ("pencilarrays_tpu/parallel/routing.py", sanctioned),
        ("pencilarrays_tpu/analysis/spmd.py", sanctioned),
        ("pencilarrays_tpu/io/ok.py", clean)])
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "hop-peak")
    # the import AND the call site are each findings (stable idents)
    assert found == ["ops.rogue_fft.<module>",
                     "ops.rogue_fft.my_own_bound",
                     "serve.sneak.sneaky"]
    allow = _write(root, "pa-lint.allow", """
        hop-peak ops.rogue_fft.<module>  # migration, tracked
        hop-peak ops.rogue_fft.my_own_bound  # migration, tracked
        hop-peak serve.sneak.sneaky  # migration, tracked
        """)
    findings, _ = run_lint(root, Allowlist.load(allow))
    assert [f for f in findings if f.check == "hop-peak"] == []


def test_lint_trace_ctx_mint_choke_point(tmp_path):
    """``mint_trace`` references outside the two admission points (and
    the definition site) are findings — a mid-path mint shears the
    request's causal chain."""
    rogue = """
        from ..obs.requestflow import mint_trace

        def helper():
            return mint_trace()
        """
    sanctioned = """
        def submit(requestflow):
            return requestflow.mint_trace()
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/io/rogue.py", rogue),
        ("pencilarrays_tpu/fleet/router.py", sanctioned),
        ("pencilarrays_tpu/serve/service.py", sanctioned)])
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "trace-ctx")
    assert found == ["io.rogue.<module>", "io.rogue.helper"]


def test_lint_trace_ctx_wire_and_worker_propagation(tmp_path):
    """Cross-wire ``encode_request`` calls in fleet/ must pass
    ``trace=``, and fleet/worker.py service admissions must run under
    ``requestflow.installed(...)`` — each violation is its own stable
    finding; a ``**kwargs`` splat is statically unknowable and passes."""
    router = """
        from . import wire

        def place(kv, tid, payload, trace):
            kv.set("k", wire.encode_request(
                tid, tenant="t", payload=payload, trace=trace))

        def rebind(kv, tid, payload):
            kv.set("k", wire.encode_request(
                tid, tenant="t", payload=payload))   # drops the trace

        def dynamic(kv, tid, kw):
            kv.set("k", wire.encode_request(tid, **kw))  # unknowable
        """
    worker = """
        from ..obs import requestflow

        def take_good(service, req):
            with requestflow.installed(req.get("trace")):
                return service.submit(req["tenant"], req["payload"])

        def take_bad(service, req):
            return service.submit(req["tenant"], req["payload"])
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/fleet/router.py", router),
        ("pencilarrays_tpu/fleet/worker.py", worker)])
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "trace-ctx")
    assert found == ["fleet.router.rebind", "fleet.worker.take_bad"]


def test_lint_trace_ctx_dispatch_meta_key(tmp_path):
    """serve/service.py's ``_dispatch_meta`` must build a dict carrying
    the ``"trace"`` key (the engine installs it around the run); a
    fixture repo without the function skips silently (the clean-fixture
    test pins that)."""
    missing = """
        def _dispatch_meta(batch):
            return {"kind": batch.kind, "n": len(batch.entries)}
        """
    carrying = """
        def _dispatch_meta(batch):
            return {"kind": batch.kind, "trace": batch.entries[0].trace}
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/serve/service.py", missing)])
    found = [f.ident for f in lint_tree(root) if f.check == "trace-ctx"]
    assert found == ["serve.service._dispatch_meta"]

    root2 = _fixture_repo(tmp_path / "ok", [
        ("pencilarrays_tpu/serve/service.py", carrying)])
    assert [f for f in lint_tree(root2) if f.check == "trace-ctx"] == []


def test_lint_fp8_wire_casts_package_wide(tmp_path):
    """The fp8/u8 family rule (PR 19) is package-WIDE, not confined to
    the transpose modules: ``bitcast_convert_type`` (attribute or bare
    name) and fp8/u8-targeted ``.astype`` anywhere outside
    ``parallel/wire.py`` are findings; wire.py itself is exempt, and a
    vanilla f32 ``.astype`` elsewhere is not the fp8 rule's business."""
    rogue = """
        import jax
        import jax.numpy as jnp
        from jax.lax import bitcast_convert_type

        def homebrew_pack(x):
            q = x.astype(jnp.float8_e4m3fn)
            return jax.lax.bitcast_convert_type(q, jnp.uint8)

        def homebrew_scales(s):
            return bitcast_convert_type(s, jnp.uint8)

        def string_spelling(x):
            return x.astype("float8_e5m2")
        """
    sanctioned = """
        import jax
        import jax.numpy as jnp

        def _pack_fp8(x):
            q = x.astype(jnp.float8_e4m3fn)
            return jax.lax.bitcast_convert_type(q, jnp.uint8)
        """
    benign = """
        import jax.numpy as jnp

        def widen(x):
            return x.astype(jnp.float32)
        """
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/ops/quant.py", rogue),
        ("pencilarrays_tpu/parallel/wire.py", sanctioned),
        ("pencilarrays_tpu/io/benign.py", benign)])
    found = sorted(f.ident for f in lint_tree(root)
                   if f.check == "wire-cast")
    # homebrew_pack fires TWICE (the astype and the bitcast), each
    # line a separate finding; wire.py and the f32 cast are silent
    assert found == ["ops.quant.homebrew_pack",
                     "ops.quant.homebrew_pack",
                     "ops.quant.homebrew_scales",
                     "ops.quant.string_spelling"]

    # the grandfather allowlist is empty ON PURPOSE — no site in the
    # package needs it, and this assertion keeps it that way
    from pencilarrays_tpu.analysis.lint import WIRE_CAST_ALLOWLIST
    assert WIRE_CAST_ALLOWLIST == ()

    # the standard justified-allowlist machinery still applies for a
    # downstream fork mid-migration
    allow = _write(root, "pa-lint.allow", """
        wire-cast ops.quant.homebrew_pack  # migration, tracked
        wire-cast ops.quant.homebrew_scales  # migration, tracked
        wire-cast ops.quant.string_spelling  # migration, tracked
        """)
    findings, _ = run_lint(root, Allowlist.load(allow))
    assert [f for f in findings if f.check == "wire-cast"] == []


def test_allowlist_roundtrip(tmp_path):
    """Allowlist round-trip: a justified entry suppresses its finding,
    stale entries are reported unused, unjustified/malformed lines are
    findings themselves."""
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/knobs.py",
         'import os\nB = os.environ.get("PENCILARRAYS_TPU_SECRET_K")\n')])
    allow = _write(root, "pa-lint.allow", """
        # comment lines are fine
        env-knob PENCILARRAYS_TPU_SECRET_K  # internal-only drill knob
        env-knob PENCILARRAYS_TPU_NEVER_READ  # stale entry
        """)
    findings, al = run_lint(root, Allowlist.load(allow))
    assert findings == []
    assert al.unused() == ["env-knob PENCILARRAYS_TPU_NEVER_READ"]

    # an entry without a justification is itself a finding
    allow2 = _write(root, "pa-lint.allow", """
        env-knob PENCILARRAYS_TPU_SECRET_K
        """)
    findings, _ = run_lint(root, Allowlist.load(allow2))
    checks = {f.check for f in findings}
    assert "allowlist" in checks          # the bad line
    assert "env-knob" in checks           # and the finding is NOT hidden


def test_real_repo_lints_clean():
    """The tree itself: zero findings, empty allowlist hits — the
    satellite contract ('the linter lands green, not allowlisted')."""
    findings, al = run_lint(REPO_ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert al.unused() == []


def test_finding_identity_is_stable():
    f = Finding("env-knob", "a/b.py", 12, "PENCILARRAYS_TPU_X", "msg")
    assert f.key == "env-knob PENCILARRAYS_TPU_X"
    assert "a/b.py:12" in str(f)


# ---------------------------------------------------------------------------
# the CLI gate
# ---------------------------------------------------------------------------


def test_pa_lint_cli_exits_zero_on_repo():
    """CI gate: shell the real CLI over the repo — both pillars — and
    require exit 0.  Runs in a subprocess exactly as CI/a console
    script would."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # share the suite's persistent compile cache: the sweep re-lowers
    # tiny programs only
    env.setdefault("PENCILARRAYS_TPU_COMPILE_CACHE",
                   os.path.join(REPO_ROOT, ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, "-m", "pencilarrays_tpu.analysis", REPO_ROOT],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "pa-lint: clean" in proc.stdout
    assert "0 lint finding(s)" in proc.stdout


def test_pa_lint_cli_reports_findings(tmp_path):
    """A broken tree exits 1 and prints the finding."""
    root = _fixture_repo(tmp_path, [
        ("pencilarrays_tpu/knobs.py",
         'import os\nB = os.environ.get("PENCILARRAYS_TPU_SECRET_K")\n')])
    proc = subprocess.run(
        [sys.executable, "-m", "pencilarrays_tpu.analysis", root,
         "--no-spmd"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO_ROOT)
    assert proc.returncode == 1
    assert "PENCILARRAYS_TPU_SECRET_K" in proc.stdout
