"""PencilArray tests — parity with reference ``test/pencils.jl`` array
sections and ``src/arrays.jl`` semantics (construction validation, extra
dims, index-order guarantees, similar)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    MemoryOrder,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    global_view,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def pen(topo):
    return Pencil(topo, (12, 11, 10), (1, 2))


def global_ref(shape, extra=(), dtype=np.float64):
    """Deterministic distinguishable global array (the analog of the
    reference's seeded per-rank data, ``test/transpose.jl:38-42``)."""
    n = int(np.prod(shape + extra))
    return np.arange(n, dtype=dtype).reshape(shape + extra) / 7.0


def test_construction_validation(pen):
    # wrong shape rejected (arrays.jl:108-114)
    with pytest.raises(ValueError):
        PencilArray(pen, jnp.zeros((12, 11, 10)))  # unpadded
    ok = PencilArray(pen, jnp.zeros((12, 12, 12)))  # padded (11->12, 10->12)
    assert ok.shape == (12, 11, 10)
    assert ok.size_local((0, 0)) == (12, 6, 3)


def test_zeros_and_shape(pen):
    x = PencilArray.zeros(pen)
    assert x.shape == (12, 11, 10)
    assert x.dtype == jnp.float32
    assert x.ndims_space == 3 and x.ndims_extra == 0
    assert x.data.shape == (12, 12, 12)
    assert x.sizeof_global() == 12 * 11 * 10 * 4
    # sharded as the pencil dictates
    assert x.data.sharding.spec == pen.partition_spec()


def test_from_global_roundtrip(pen):
    u = global_ref((12, 11, 10))
    x = PencilArray.from_global(pen, u)
    assert np.array_equal(gather(x), u)
    assert np.array_equal(np.asarray(x), u)


def test_from_global_permuted(topo):
    perm = Permutation(2, 0, 1)
    pen = Pencil(topo, (12, 11, 10), (1, 2), permutation=perm)
    u = global_ref((12, 11, 10))
    x = PencilArray.from_global(pen, u)
    # memory-order storage: padded shape permuted
    assert x.data.shape == perm.apply((12, 12, 12))
    assert np.array_equal(gather(x), u)


def test_getitem_logical_global(topo):
    for perm in (None, Permutation(2, 0, 1), Permutation(1, 2, 0)):
        pen = Pencil(topo, (12, 11, 10), (1, 2), permutation=perm)
        u = global_ref((12, 11, 10))
        x = PencilArray.from_global(pen, u)
        assert float(x[3, 4, 5]) == u[3, 4, 5]
        assert float(x[-1, -1, -1]) == u[-1, -1, -1]
        np.testing.assert_array_equal(np.asarray(x[2]), u[2])
        np.testing.assert_array_equal(np.asarray(x[:, 3, :]), u[:, 3, :])
        np.testing.assert_array_equal(np.asarray(x[1:5, ..., 2]), u[1:5, ..., 2])
        np.testing.assert_array_equal(np.asarray(x[:, 1:11:2, 3]), u[:, 1:11:2, 3])
        np.testing.assert_array_equal(np.asarray(x[::-1, 0, 0]), u[::-1, 0, 0])
        np.testing.assert_array_equal(np.asarray(x[0, 8::-2, :]), u[0, 8::-2, :])
    with pytest.raises(IndexError):
        x[50, 0, 0]
    with pytest.raises(IndexError):
        x[0, 0, 0, 0]


def test_extra_dims(topo):
    # vector field: 3 trailing components (arrays.jl:34-47)
    pen = Pencil(topo, (12, 11, 10), (1, 2), permutation=Permutation(2, 0, 1))
    u = global_ref((12, 11, 10), extra=(3,))
    x = PencilArray.from_global(pen, u)
    assert x.extra_dims == (3,)
    assert x.ndims_extra == 1
    assert x.shape == (12, 11, 10, 3)
    assert x.size_global(MemoryOrder) == (10, 12, 11, 3)
    assert np.array_equal(gather(x), u)
    np.testing.assert_array_equal(np.asarray(x[2, 3, 4]), u[2, 3, 4])
    np.testing.assert_array_equal(np.asarray(x[:, 3, :, 1]), u[:, 3, :, 1])


def test_local_block(topo):
    perm = Permutation(1, 2, 0)
    pen = Pencil(topo, (12, 11, 10), (1, 2), permutation=perm)
    u = global_ref((12, 11, 10))
    x = PencilArray.from_global(pen, u)
    for rank in range(8):
        coords = topo.coords(rank)
        blk = np.asarray(x.local_block(coords))
        rr = pen.range_local(coords)
        np.testing.assert_array_equal(blk, u[np.ix_(*[list(r) for r in rr])])
        blk_m = np.asarray(x.local_block(coords, MemoryOrder))
        assert blk_m.shape == perm.apply(blk.shape)


def test_arithmetic_memory_order(pen):
    u = global_ref((12, 11, 10))
    x = PencilArray.from_global(pen, u)
    y = (x + x) * 2.0 - x / 2.0
    expect = (u + u) * 2.0 - u / 2.0
    assert np.allclose(gather(y), expect)
    assert y.pencil == pen
    z = x.map(jnp.sin)
    assert np.allclose(gather(z), np.sin(u))
    neg = -x
    assert np.allclose(gather(neg), -u)
    # scalar arithmetic touches padding; logical comparison must mask it
    pen_r = pen.replace()
    w = PencilArray.from_global(pen_r, u) + 1.0
    v = PencilArray.from_global(pen_r, u + 1.0)
    assert w == v and w.allclose(v)
    # extra-dims mismatch rejected
    a3 = PencilArray.from_global(pen, np.zeros((12, 11, 10, 3)))
    a1 = PencilArray.from_global(pen, np.zeros((12, 11, 10, 1)))
    with pytest.raises(ValueError, match="extra_dims"):
        _ = a3 + a1
    # mismatched pencils rejected
    pen2 = pen.replace(decomp_dims=(0, 2))
    w = PencilArray.zeros(pen2, dtype=x.dtype)
    with pytest.raises(ValueError):
        _ = x + w


def test_pytree_jit(pen):
    u = global_ref((12, 11, 10))
    x = PencilArray.from_global(pen, u)

    @jax.jit
    def f(a):
        return a.map(lambda d: jnp.cos(d) + 1.0)

    y = f(x)
    assert isinstance(y, PencilArray)
    assert y.pencil == pen
    assert np.allclose(gather(y), np.cos(u) + 1.0)


def test_similar(pen):
    x = PencilArray.zeros(pen, dtype=jnp.float64)
    y = x.similar()
    assert y.pencil == pen and y.dtype == x.dtype
    pen_y = pen.replace(decomp_dims=(0, 2))
    z = x.similar(pencil=pen_y, dtype=jnp.complex64)
    assert z.pencil == pen_y and z.dtype == jnp.complex64


def test_global_view_identity(pen):
    x = PencilArray.zeros(pen)
    assert global_view(x) is x


def test_fill_and_eq(pen):
    x = PencilArray.zeros(pen)
    y = x.fill(3.0)
    assert float(y[5, 5, 5]) == 3.0
    assert y == y
    assert not (x == y)
    assert x.allclose(x)

def test_equals_traced(pen):
    """``==`` is eager-only with a clear error under tracing; ``equals()``
    is the jit-safe traced form (cf. ADVICE r1: TracerBoolConversionError
    trap for a registered pytree)."""
    x = PencilArray.zeros(pen)
    y = x.fill(2.0)

    @jax.jit
    def f(a, b):
        return a.equals(b)

    assert bool(f(x, x))
    assert not bool(f(x, y))

    @jax.jit
    def g(a, b):
        return a == b

    with pytest.raises(TypeError, match="equals"):
        g(x, y)
