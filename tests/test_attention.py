"""Sequence-parallel attention on pencil primitives (SURVEY §2.3: the
pencil transpose IS the Ulysses head/sequence all-to-all reshard).

Ground truth is dense softmax attention on gathered arrays; both
distributed schemes must match it and each other, with HLO-pinned
collective budgets (2 all-to-alls for Ulysses, P-1 ppermute-pair rounds
for ring) and decomposition independence.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Topology, gather
from pencilarrays_tpu.models import (
    dense_attention, ring_attention, ulysses_attention,
)

S, H, D = 64, 8, 16


@pytest.fixture
def topo(devices):
    return Topology((8,))


def make_qkv(topo, seed=0):
    pen = Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(seed)
    qkv = [rng.standard_normal((S, H, D)).astype(np.float32)
           for _ in range(3)]
    wrapped = [PencilArray.from_global(pen, x) for x in qkv]
    return pen, qkv, wrapped


def test_ulysses_matches_dense(topo):
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo)
    out = ulysses_attention(qw, kw, vw)
    assert isinstance(out, PencilArray) and out.pencil == qw.pencil
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v))))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_ring_matches_dense(topo):
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo, seed=1)
    out = ring_attention(qw, kw, vw)
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v))))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_schemes_agree_and_decomposition_independent(topo, devices):
    pen8, _, (qw, kw, vw) = make_qkv(topo, seed=2)
    out_u = gather(ulysses_attention(qw, kw, vw))
    out_r = gather(ring_attention(qw, kw, vw))
    np.testing.assert_allclose(out_u, out_r, rtol=2e-4, atol=2e-5)

    topo1 = Topology((1,), devices=jax.devices()[:1])
    pen1 = Pencil(topo1, (S, H), (0,))
    qkv1 = [PencilArray.from_global(pen1, gather(x))
            for x in (qw, kw, vw)]
    out_1 = gather(ring_attention(*qkv1))
    np.testing.assert_allclose(out_r, out_1, rtol=2e-4, atol=2e-5)


def test_collective_budgets(topo):
    """Ulysses = exactly 2 all-to-alls (qkv stacked into ONE exchange,
    output in the second); ring = P-1 rounds x k&v ppermutes, zero
    all-to-alls, zero all-gathers."""
    pen, _, (qw, kw, vw) = make_qkv(topo, seed=3)

    def f_u(a, b, c):
        return ulysses_attention(PencilArray(pen, a), PencilArray(pen, b),
                                 PencilArray(pen, c)).data

    hlo = jax.jit(f_u).lower(qw.data, kw.data, vw.data).compile().as_text()
    assert len(re.findall(r" all-to-all\(", hlo)) == 2
    assert not re.findall(r" all-gather\(", hlo)

    def f_r(a, b, c):
        return ring_attention(PencilArray(pen, a), PencilArray(pen, b),
                              PencilArray(pen, c)).data

    hlo = jax.jit(f_r).lower(qw.data, kw.data, vw.data).compile().as_text()
    n_pp = len(re.findall(r" collective-permute\(", hlo))
    assert n_pp == 8 - 1, n_pp  # ONE k+v buffer per round, P-1 rounds
    assert not re.findall(r" all-to-all\(", hlo)
    assert not re.findall(r" all-gather\(", hlo)


def test_validation(topo):
    pen = Pencil(topo, (S, H), (0,))
    q = PencilArray.zeros(pen, (D,))
    pen_h = Pencil(topo, (S, H), (1,))
    kh = PencilArray.zeros(pen_h, (D,))
    with pytest.raises(ValueError, match="share q's pencil"):
        ulysses_attention(q, kh, kh)
    # ragged sequence rejected (softmax must not see padding)
    pen_r = Pencil(topo, (S - 3, H), (0,))
    qr = PencilArray.zeros(pen_r, (D,))
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(qr, qr, qr)
    # head-decomposed input rejected
    qh = PencilArray.zeros(pen_h, (D,))
    with pytest.raises(ValueError, match="sequence-decomposed"):
        ring_attention(qh, qh, qh)


@pytest.mark.parametrize("scheme", ["ulysses", "ring"])
def test_causal_matches_dense(topo, scheme):
    """causal=True masks by GLOBAL positions (ring must map its rotating
    block back to global kv indices)."""
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo, seed=4)
    fn = ulysses_attention if scheme == "ulysses" else ring_attention
    out = fn(qw, kw, vw, causal=True)
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v)),
                                        causal=True))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_causal_decomposition_independent(topo, devices):
    pen8, _, (qw, kw, vw) = make_qkv(topo, seed=5)
    out8 = gather(ring_attention(qw, kw, vw, causal=True))
    topo1 = Topology((1,), devices=jax.devices()[:1])
    pen1 = Pencil(topo1, (S, H), (0,))
    q1, k1, v1 = (PencilArray.from_global(pen1, gather(x))
                  for x in (qw, kw, vw))
    out1 = gather(ring_attention(q1, k1, v1, causal=True))
    np.testing.assert_allclose(out8, out1, rtol=2e-4, atol=2e-5)
