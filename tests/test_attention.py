"""Sequence-parallel attention on pencil primitives (SURVEY §2.3: the
pencil transpose IS the Ulysses head/sequence all-to-all reshard).

Ground truth is dense softmax attention on gathered arrays; both
distributed schemes must match it and each other, with HLO-pinned
collective budgets (2 all-to-alls for Ulysses, P-1 ppermute-pair rounds
for ring) and decomposition independence.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Topology, gather
from pencilarrays_tpu.models import (
    dense_attention, ring_attention, ulysses_attention,
)

S, H, D = 64, 8, 16


@pytest.fixture
def topo(devices):
    return Topology((8,))


def make_qkv(topo, seed=0):
    pen = Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(seed)
    qkv = [rng.standard_normal((S, H, D)).astype(np.float32)
           for _ in range(3)]
    wrapped = [PencilArray.from_global(pen, x) for x in qkv]
    return pen, qkv, wrapped


def test_ulysses_matches_dense(topo):
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo)
    out = ulysses_attention(qw, kw, vw)
    assert isinstance(out, PencilArray) and out.pencil == qw.pencil
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v))))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_ring_matches_dense(topo):
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo, seed=1)
    out = ring_attention(qw, kw, vw)
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v))))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_schemes_agree_and_decomposition_independent(topo, devices):
    pen8, _, (qw, kw, vw) = make_qkv(topo, seed=2)
    out_u = gather(ulysses_attention(qw, kw, vw))
    out_r = gather(ring_attention(qw, kw, vw))
    np.testing.assert_allclose(out_u, out_r, rtol=2e-4, atol=2e-5)

    topo1 = Topology((1,), devices=jax.devices()[:1])
    pen1 = Pencil(topo1, (S, H), (0,))
    qkv1 = [PencilArray.from_global(pen1, gather(x))
            for x in (qw, kw, vw)]
    out_1 = gather(ring_attention(*qkv1))
    np.testing.assert_allclose(out_r, out_1, rtol=2e-4, atol=2e-5)


def test_collective_budgets(topo):
    """Ulysses = exactly 2 all-to-alls (qkv stacked into ONE exchange,
    output in the second); ring = P-1 rounds x k&v ppermutes, zero
    all-to-alls, zero all-gathers."""
    pen, _, (qw, kw, vw) = make_qkv(topo, seed=3)

    def f_u(a, b, c):
        return ulysses_attention(PencilArray(pen, a), PencilArray(pen, b),
                                 PencilArray(pen, c)).data

    hlo = jax.jit(f_u).lower(qw.data, kw.data, vw.data).compile().as_text()
    assert len(re.findall(r" all-to-all\(", hlo)) == 2
    assert not re.findall(r" all-gather\(", hlo)

    def f_r(a, b, c):
        return ring_attention(PencilArray(pen, a), PencilArray(pen, b),
                              PencilArray(pen, c)).data

    hlo = jax.jit(f_r).lower(qw.data, kw.data, vw.data).compile().as_text()
    n_pp = len(re.findall(r" collective-permute\(", hlo))
    assert n_pp == 8 - 1, n_pp  # ONE k+v buffer per round, P-1 rounds
    assert not re.findall(r" all-to-all\(", hlo)
    assert not re.findall(r" all-gather\(", hlo)


def test_validation(topo):
    pen = Pencil(topo, (S, H), (0,))
    q = PencilArray.zeros(pen, (D,))
    pen_h = Pencil(topo, (S, H), (1,))
    kh = PencilArray.zeros(pen_h, (D,))
    with pytest.raises(ValueError, match="share q's pencil"):
        ulysses_attention(q, kh, kh)
    # ragged sequence rejected (softmax must not see padding)
    pen_r = Pencil(topo, (S - 3, H), (0,))
    qr = PencilArray.zeros(pen_r, (D,))
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(qr, qr, qr)
    # head-decomposed input rejected
    qh = PencilArray.zeros(pen_h, (D,))
    with pytest.raises(ValueError, match="sequence-decomposed"):
        ring_attention(qh, qh, qh)


@pytest.mark.parametrize("scheme", [
    "ulysses", pytest.param("ring", marks=pytest.mark.slow)])  # ring ~12 s
def test_causal_matches_dense(topo, scheme):
    """causal=True masks by GLOBAL positions (ring must map its rotating
    block back to global kv indices)."""
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo, seed=4)
    fn = ulysses_attention if scheme == "ulysses" else ring_attention
    out = fn(qw, kw, vw, causal=True)
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v)),
                                        causal=True))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_causal_decomposition_independent(topo, devices):
    pen8, _, (qw, kw, vw) = make_qkv(topo, seed=5)
    out8 = gather(ring_attention(qw, kw, vw, causal=True))
    topo1 = Topology((1,), devices=jax.devices()[:1])
    pen1 = Pencil(topo1, (S, H), (0,))
    q1, k1, v1 = (PencilArray.from_global(pen1, gather(x))
                  for x in (qw, kw, vw))
    out1 = gather(ring_attention(q1, k1, v1, causal=True))
    np.testing.assert_allclose(out8, out1, rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# round 3: flash local attention, batch dims, zigzag causal ring
# ---------------------------------------------------------------------------

from pencilarrays_tpu.models import (  # noqa: E402
    flash_attention, from_zigzag, to_zigzag, zigzag_indices,
)
from pencilarrays_tpu.models.attention import _neg_value  # noqa: E402


def test_flash_matches_dense_cross_length():
    """Chunked flash == dense, including ragged chunking (Skv not a
    multiple of chunk) and cross-length q/kv with explicit offsets."""
    rng = np.random.default_rng(10)
    q = jnp.asarray(rng.standard_normal((37, 3, 5)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((53, 3, 5)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((53, 3, 5)).astype(np.float32))
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal, chunk=8)
        expect = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
    # end-aligned cross-length convention via offsets
    out = flash_attention(q, k, v, causal=True, chunk=16,
                          q_offset=53 - 37)
    expect = dense_attention(q, k, v, causal=True, q_offset=53 - 37)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_flash_batch_dims():
    rng = np.random.default_rng(11)
    shape = (24, 2, 3, 2, 5)  # (S, H, B1, B2, D)
    q, k, v = (jnp.asarray(rng.standard_normal(shape).astype(np.float32))
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True, chunk=8)
    assert out.shape == shape
    # per-batch-element independence vs dense on each slice
    for b1 in range(3):
        for b2 in range(2):
            expect = dense_attention(q[:, :, b1, b2], k[:, :, b1, b2],
                                     v[:, :, b1, b2], causal=True)
            np.testing.assert_allclose(np.asarray(out[:, :, b1, b2]),
                                       np.asarray(expect),
                                       rtol=2e-4, atol=2e-5)


def test_flash_never_materializes_score_matrix():
    """The compiled flash program contains no S x S-sized tensor — the
    memory contract that makes long-context Ulysses usable (a dense
    local step would OOM at real sequence lengths)."""
    from pencilarrays_tpu.utils.hlo import largest_tensor_elems

    S, chunk = 4096, 256
    q = jnp.zeros((S, 1, 1, 8), jnp.float32)
    hlo = (jax.jit(lambda a: flash_attention(a, a, a, causal=True,
                                             chunk=chunk))
           .lower(q).compile().as_text())
    biggest = largest_tensor_elems(hlo)
    assert biggest <= 4 * S * chunk, biggest  # far below S*S


@pytest.mark.slow  # ~40 s: long-sequence flash sweep
def test_ulysses_long_sequence_flash(topo):
    """Long-S Ulysses (flash local step) matches the ring path closely;
    the dense S x S score matrix would be 64x larger than anything the
    flash program allocates."""
    S_long = 4096
    pen = Pencil(topo, (S_long, 8), (0,))
    rng = np.random.default_rng(12)
    qw, kw, vw = (PencilArray.from_global(
        pen, rng.standard_normal((S_long, 8, 4)).astype(np.float32))
        for _ in range(3))
    out_u = gather(ulysses_attention(qw, kw, vw, causal=True, chunk=256))
    out_r = gather(ring_attention(qw, kw, vw, causal=True))
    np.testing.assert_allclose(out_u, out_r, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("scheme", [
    "ulysses", pytest.param("ring", marks=pytest.mark.slow)])  # ring ~25 s
def test_batched_attention_matches_dense(topo, scheme):
    """extra_dims=(*batch, D): leading extra dims are independent batch
    elements in both distributed schemes."""
    pen = Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(13)
    shape = (S, H, 2, D)
    raw = [rng.standard_normal(shape).astype(np.float32) for _ in range(3)]
    qw, kw, vw = (PencilArray.from_global(pen, x) for x in raw)
    fn = ulysses_attention if scheme == "ulysses" else ring_attention
    out = gather(fn(qw, kw, vw, causal=True))
    expect = np.asarray(dense_attention(*map(jnp.asarray, raw),
                                        causal=True))
    np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)


def test_zigzag_roundtrip(topo):
    idx = zigzag_indices(S, 8)
    assert sorted(idx.tolist()) == list(range(S))
    pen = Pencil(topo, (S, H), (0,))
    u = np.random.default_rng(14).standard_normal((S, H, D)) \
        .astype(np.float32)
    x = PencilArray.from_global(pen, u)
    np.testing.assert_array_equal(gather(to_zigzag(x)), u[idx])
    np.testing.assert_array_equal(gather(from_zigzag(to_zigzag(x))), u)


@pytest.mark.slow  # ~40 s: zigzag x causal x dense cross-check
def test_zigzag_causal_matches_dense(topo):
    """Zigzag-placed causal ring == dense causal (after undoing the
    placement)."""
    _, (q, k, v), (qw, kw, vw) = make_qkv(topo, seed=15)
    qz, kz, vz = map(to_zigzag, (qw, kw, vw))
    out = from_zigzag(ring_attention(qz, kz, vz, causal=True, zigzag=True))
    expect = np.asarray(dense_attention(*map(jnp.asarray, (q, k, v)),
                                        causal=True))
    np.testing.assert_allclose(gather(out), expect, rtol=2e-4, atol=2e-5)


def test_zigzag_halves_causal_flops(topo):
    """The zigzag schedule's FLOP count is ~(4P+2)/(8P) of the naive
    ring's (~P/2 effective rounds): measured from the compiled programs'
    cost analysis, so a schedule regression fails loudly."""
    P, S_f, H_f, D_f = 8, 512, 4, 32
    pen = Pencil(topo, (S_f, H_f), (0,))
    q = PencilArray.zeros(pen, (D_f,))

    def flops(fn):
        c = jax.jit(lambda a, b, d: fn(
            PencilArray(pen, a, (D_f,)), PencilArray(pen, b, (D_f,)),
            PencilArray(pen, d, (D_f,))).data).lower(
            q.data, q.data, q.data).compile()
        ca = c.cost_analysis()
        # older jax returns a per-partition list of dicts
        return (ca[0] if isinstance(ca, (list, tuple)) else ca)["flops"]

    naive = flops(lambda a, b, c: ring_attention(a, b, c, causal=True))
    zz = flops(lambda a, b, c: ring_attention(a, b, c, causal=True,
                                              zigzag=True))
    ratio = zz / naive
    assert 0.40 < ratio < 0.65, ratio  # ideal (4P+2)/(8P) = 0.53


def test_f16_masked_attention_finite():
    """float16 q/k/v: the masked-score value derives from the dtype's
    finite range (a fixed -1e9 would overflow f16 to -inf and NaN the
    accumulation for fully-masked rows)."""
    assert _neg_value(jnp.float16) > float(jnp.finfo(jnp.float16).min)
    rng = np.random.default_rng(16)
    q, k, v = (jnp.asarray(rng.standard_normal((16, 2, 4))
                           .astype(np.float16)) for _ in range(3))
    for fn in (dense_attention,
               lambda *a, **kw: flash_attention(*a, chunk=4, **kw)):
        out = np.asarray(fn(q, k, v, causal=True))
        assert np.isfinite(out).all()
        assert out.dtype == np.float16
