"""Automatic transpose-method selection (``Auto``).

The reference leaves the ``PointToPoint()`` vs ``Alltoallv()`` choice to
the caller (``Transpositions.jl:17-24``); PencilFFTs users sweep it by
hand.  Here the framework can choose — ``mode="estimate"`` from the
validated analytic byte model, ``mode="measure"`` FFTW_MEASURE-style on
the actual configuration.  These tests pin the decision rule and that
Auto never changes results.
"""

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import (
    AllToAll,
    Auto,
    Pencil,
    PencilArray,
    Ring,
    Topology,
    gather,
    resolve_method,
    transpose,
)
from pencilarrays_tpu.parallel.transpositions import _measured_choice


def _pair(topo, shape):
    pin = Pencil(topo, shape, (0,))
    pout = pin.replace(decomp_dims=(1,))
    return pin, pout


def test_estimate_dense_prefers_all_to_all(devices):
    # divisible extents: G == P, ring moves the same bytes in P-1
    # serialized rounds -> one fused collective wins at any latency toll
    topo = Topology((8,))
    pin, pout = _pair(topo, (32, 32, 4))
    assert resolve_method(pin, pout, (), np.float32,
                          Auto(latency_bytes=0)) == AllToAll()
    assert resolve_method(pin, pout, (), np.float32, Auto()) == AllToAll()


def test_estimate_ragged_prefers_ring_when_bytes_dominate(devices):
    # n = 9 over P = 8: only G = 5 ceil-blocks are nonempty, the ring
    # runs 4 rounds vs 7 tiles of all_to_all wire -> Ring wins once the
    # per-round latency toll is off
    topo = Topology((8,))
    pin, pout = _pair(topo, (9, 9, 4))
    assert resolve_method(pin, pout, (), np.float32,
                          Auto(latency_bytes=0)) == Ring()
    # same configuration, latency-dominant regime (tiles are ~64 bytes):
    # serializing 4 rounds cannot pay for itself
    assert resolve_method(pin, pout, (), np.float32,
                          Auto(latency_bytes=128 * 1024)) == AllToAll()


def test_estimate_concrete_methods_pass_through(devices):
    topo = Topology((8,))
    pin, pout = _pair(topo, (9, 9, 4))
    assert resolve_method(pin, pout, (), np.float32, Ring()) == Ring()
    assert resolve_method(pin, pout, (), np.float32,
                          AllToAll()) == AllToAll()


def test_auto_transpose_matches_ground_truth(devices):
    topo = Topology((8,))
    shape = (9, 9, 4)
    u = (np.arange(np.prod(shape), dtype=np.float64).reshape(shape) + 1) / 3
    pin, pout = _pair(topo, shape)
    x = PencilArray.from_global(pin, u)
    for method in (Auto(), Auto(latency_bytes=0)):
        y = transpose(x, pout, method=method)
        np.testing.assert_array_equal(gather(y), u)


def test_auto_validates_mode():
    with pytest.raises(ValueError, match="estimate"):
        Auto(mode="guess")


def test_measure_mode_picks_and_caches(devices):
    from pencilarrays_tpu.parallel.transpositions import (
        Pipelined, _method_label)

    topo = Topology((4, 2))
    shape = (12, 10, 8)
    pin = Pencil(topo, shape, (1, 2))
    pout = pin.replace(decomp_dims=(0, 2))
    m = resolve_method(pin, pout, (), np.float32, Auto(mode="measure"))
    assert isinstance(m, (AllToAll, Ring, Pipelined))
    # cached: same configuration resolves to the same object without
    # re-measuring
    before = _measured_choice.cache_info().hits
    m2 = resolve_method(pin, pout, (), np.float32, Auto(mode="measure"))
    assert m2 == m
    assert _measured_choice.cache_info().hits == before + 1
    # and the measured choice produces correct data
    u = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
    x = PencilArray.from_global(pin, u)
    y = transpose(x, pout, method=Auto(mode="measure"))
    np.testing.assert_array_equal(gather(y), u)
    # every decision leaves a variance-aware audit record: every
    # candidate timed (the two explicit exchanges PLUS the Pipelined
    # K in {2,4,8} sweep on chunkable configurations), their k1
    # spreads, and the winner's margin relative to the observed noise
    # (VERDICT r3 weak #7)
    from pencilarrays_tpu.parallel.transpositions import (
        last_measure_reports)

    reports = last_measure_reports()
    assert reports, "measure decision left no audit record"
    rep = reports[-1]
    assert rep["winner"] == _method_label(m)
    assert len(rep["seconds"]) == len(rep["candidates"]) >= 2
    assert all(t > 0 for t in rep["seconds"])
    assert len(rep["k1_spreads"]) == len(rep["candidates"])
    # this configuration has chunkable dims -> the K sweep must appear
    assert any(c.startswith("Pipelined") for c in rep["candidates"])


def test_pipelined_cost_multiplies_count_not_bytes(devices):
    """transpose_cost for Pipelined(K): K_eff launches of the base
    exchange, identical total wire bytes (ceil chunks partition the
    block exactly) — the schema the HLO measurement reproduces."""
    from pencilarrays_tpu import Pipelined, Ring

    topo = Topology((8,))
    pin, pout = _pair(topo, (32, 32, 8))
    base = pa.transpose_cost(pin, pout, (), np.float32, AllToAll())
    c4 = pa.transpose_cost(pin, pout, (), np.float32, Pipelined(chunks=4))
    assert c4["all-to-all"]["bytes"] == base["all-to-all"]["bytes"]
    assert c4["all-to-all"]["count"] == 4 * base["all-to-all"]["count"]
    # ring base: rounds multiply, bytes stay
    br = pa.transpose_cost(pin, pout, (), np.float32, Ring())
    cr = pa.transpose_cost(pin, pout, (), np.float32,
                           Pipelined(chunks=2, base=Ring()))
    assert cr["collective-permute"]["bytes"] == \
        br["collective-permute"]["bytes"]
    assert cr["collective-permute"]["count"] == \
        2 * br["collective-permute"]["count"]
    # chunk-dim extent clamps K_eff
    c_big = pa.transpose_cost(pin, pout, (), np.float32,
                              Pipelined(chunks=64))
    assert c_big["all-to-all"]["count"] == 8  # extent of the spare dim


def test_transpose_cost_resolves_auto(devices):
    topo = Topology((8,))
    pin, pout = _pair(topo, (9, 9, 4))
    c_auto = pa.transpose_cost(pin, pout, (), np.float32,
                               Auto(latency_bytes=0))
    c_ring = pa.transpose_cost(pin, pout, (), np.float32, Ring())
    assert c_auto == c_ring


def test_fft_plan_accepts_auto(devices):
    from pencilarrays_tpu import PencilFFTPlan

    topo = Topology((4, 2))
    plan = PencilFFTPlan(topo, (12, 10, 8), real=True,
                         method=Auto(latency_bytes=0))
    u = np.random.default_rng(7).standard_normal((12, 10, 8)).astype(
        np.float32)
    x = PencilArray.from_global(plan.input_pencil, u)
    uh = plan.forward(x)
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(uh), expect, rtol=2e-4, atol=2e-4)
    back = plan.backward(uh)
    np.testing.assert_allclose(gather(back), u, rtol=2e-4, atol=2e-4)
