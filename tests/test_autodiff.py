"""Differentiability of the whole stack — a TPU-native capability with
no reference analog (MPI send/recv buffers cannot be differentiated
through; XLA collectives and traced data movement can).

Pins: ``jax.grad`` through every transpose method, reshard, FFT plans
(incl. finite-difference agreement), masked reductions, and a full
Navier-Stokes spectral step; PencilArray as a first-class grad argument
(pytree: the cotangent comes back ON the pencil); linearity
(jvp == primal application) of transposes; and ``jax.checkpoint``
(rematerialization — the HBM/FLOPs trade the brief calls out) through a
plan round trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Ring,
    Topology,
    gather,
    reshard,
    transpose,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


SHAPE = (12, 10, 8)


def _mk(topo, shape=SHAPE, seed=0, perm=Permutation(2, 0, 1)):
    pen = Pencil(topo, shape, (1, 2), permutation=perm)
    u = np.random.default_rng(seed).standard_normal(shape)
    return pen, u, PencilArray.from_global(pen, u)


@pytest.mark.parametrize("method", [AllToAll(), Ring(), Gspmd()])
def test_grad_through_transpose(topo, method):
    """d/du sum((T u)^2) = 2u for any data-movement T: the cotangent is
    routed back through the inverse exchange."""
    pen, u, x = _mk(topo)
    pen_y = pen.replace(decomp_dims=(0, 2))

    def loss(data):
        v = transpose(PencilArray(pen, data), pen_y, method=method)
        return pa.ops.sum(v * v)

    g = jax.grad(loss)(x.data)
    np.testing.assert_allclose(gather(PencilArray(pen, g)), 2 * u,
                               rtol=1e-12)


def test_grad_through_reshard(topo):
    pen, u, x = _mk(topo)
    pen_b = Pencil(topo, SHAPE, (0, 1), permutation=Permutation(1, 2, 0))

    def loss(data):
        v = reshard(PencilArray(pen, data), pen_b)
        return pa.ops.sum(v * v)

    g = jax.grad(loss)(x.data)
    np.testing.assert_allclose(gather(PencilArray(pen, g)), 2 * u,
                               rtol=1e-12)


def test_transpose_is_linear_jvp(topo):
    """jvp of a linear op is the op itself (and vjp is its inverse
    routing): tangents ride the same collectives."""
    pen, u, x = _mk(topo)
    pen_y = pen.replace(decomp_dims=(0, 2))
    t = np.random.default_rng(1).standard_normal(SHAPE)
    tx = PencilArray.from_global(pen, t)

    f = lambda d: transpose(PencilArray(pen, d), pen_y).data
    y, dy = jax.jvp(f, (x.data,), (tx.data,))
    np.testing.assert_array_equal(np.asarray(dy), np.asarray(f(tx.data)))


def test_grad_through_fft_plan_fd(topo):
    """Finite-difference agreement of d/du sum|F u|^2 through a
    distributed r2c plan (multi-hop, multi-collective)."""
    plan = PencilFFTPlan(topo, SHAPE, real=True, dtype=np.float64)
    u = np.random.default_rng(2).standard_normal(SHAPE)
    x = PencilArray.from_global(plan.input_pencil, u)

    def loss(data):
        uh = plan.forward(PencilArray(plan.input_pencil, data))
        return pa.ops.sum(PencilArray(uh.pencil, jnp.abs(uh.data) ** 2,
                                      uh.extra_dims))

    g = gather(PencilArray(plan.input_pencil, jax.grad(loss)(x.data)))

    def np_loss(uu):
        return np.sum(np.abs(np.fft.fftn(np.fft.rfft(uu, axis=0),
                                         axes=(1, 2))) ** 2)

    eps = 1e-6
    for (i, j, k) in [(0, 0, 0), (3, 4, 5), (11, 9, 7)]:
        up, un = u.copy(), u.copy()
        up[i, j, k] += eps
        un[i, j, k] -= eps
        fd = (np_loss(up) - np_loss(un)) / (2 * eps)
        np.testing.assert_allclose(g[i, j, k], fd, rtol=1e-4)


def test_fft_roundtrip_grad_identity(topo):
    """backward(forward(u)) == u is exactly differentiated: the grad of
    sum(roundtrip(u) * w) is w."""
    plan = PencilFFTPlan(topo, SHAPE, real=True, dtype=np.float64)
    u = np.random.default_rng(3).standard_normal(SHAPE)
    w = np.random.default_rng(4).standard_normal(SHAPE)
    x = PencilArray.from_global(plan.input_pencil, u)
    wx = PencilArray.from_global(plan.input_pencil, w)

    def loss(data):
        rt = plan.backward(plan.forward(PencilArray(plan.input_pencil,
                                                    data)))
        return pa.ops.sum(rt * wx)

    g = gather(PencilArray(plan.input_pencil, jax.grad(loss)(x.data)))
    np.testing.assert_allclose(g, w, rtol=1e-9, atol=1e-10)


def test_pencilarray_is_grad_argument(topo):
    """PencilArray is a pytree: jax.grad differentiates a
    PencilArray -> scalar function directly and returns the cotangent ON
    the pencil."""
    pen, u, x = _mk(topo, seed=5)
    g = jax.grad(pa.ops.norm)(x)
    assert isinstance(g, PencilArray)
    assert g.pencil == pen
    np.testing.assert_allclose(gather(g), u / np.linalg.norm(u),
                               rtol=1e-10)


def test_grad_through_ns_step(topo):
    """One Navier-Stokes RK2 spectral step is differentiable end-to-end
    (8 all-to-alls, nonlinear term, projection): finite-difference check
    on a directional derivative."""
    from pencilarrays_tpu.models import NavierStokesSpectral, taylor_green

    model = NavierStokesSpectral(topo, 8, viscosity=0.05,
                                 dtype=jnp.float64)
    uh0 = taylor_green(model)
    d = np.random.default_rng(6).standard_normal(uh0.data.shape)
    d = d / np.linalg.norm(d)

    def loss(data):
        out = model.step(PencilArray(uh0.pencil, data, uh0.extra_dims),
                         1e-2)
        return jnp.sum(jnp.abs(out.data) ** 2)

    g = jax.grad(loss)(uh0.data)
    # directional derivative vs central difference.  |uh|^2 is not
    # holomorphic: JAX's convention for grad of a real loss over complex
    # inputs gives conj(dL/dz); the directional derivative along a REAL
    # direction d is Re(<conj(g), d>) = Re(<g_bar * d>).
    eps = 1e-5
    lp = float(loss(uh0.data + eps * d))
    lm = float(loss(uh0.data - eps * d))
    fd = (lp - lm) / (2 * eps)
    dd = float(jnp.sum(jnp.real(jnp.conj(g) * d)))
    np.testing.assert_allclose(dd, fd, rtol=1e-4)


def test_remat_through_plan(topo):
    """jax.checkpoint through the plan round trip: same value, same
    gradient — the FLOPs-for-HBM trade composes with the framework."""
    plan = PencilFFTPlan(topo, SHAPE, real=True, dtype=np.float64)
    u = np.random.default_rng(7).standard_normal(SHAPE)
    x = PencilArray.from_global(plan.input_pencil, u)

    def body(data):
        uh = plan.forward(PencilArray(plan.input_pencil, data))
        return pa.ops.sum(PencilArray(uh.pencil, jnp.abs(uh.data) ** 2,
                                      uh.extra_dims))

    g_plain = jax.grad(body)(x.data)
    g_remat = jax.grad(jax.checkpoint(body))(x.data)
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_remat),
                               rtol=1e-12)


def test_grad_through_masked_reductions(topo):
    """Padding-masked reductions: the cotangent must be ZERO on tail
    padding and exact on true data (ragged shape forces real padding)."""
    shape = (9, 7, 5)
    pen = Pencil(topo, shape, (1, 2))
    u = np.random.default_rng(8).standard_normal(shape)
    x = PencilArray.from_global(pen, u)

    g = jax.grad(lambda a: pa.ops.sum(a * a))(x)
    np.testing.assert_allclose(gather(g), 2 * u, rtol=1e-12)
    # mean: d/du mean(u) = 1/N on every true element
    gm = jax.grad(pa.ops.mean)(x)
    np.testing.assert_allclose(gather(gm),
                               np.full(shape, 1.0 / u.size), rtol=1e-12)
