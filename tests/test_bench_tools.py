"""Benchmark-tooling contracts: the opportunistic capture's append-only
evidence rule and the pipelined-hop sweep registration (artifact +
``BENCH_*.json`` metric-line schema)."""

import json
import os
import sys
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fake_bench_run(stdout_lines):
    def runner(*a, **k):
        return types.SimpleNamespace(
            returncode=0, stdout="\n".join(stdout_lines), stderr="")
    return runner


def test_capture_run_bench_is_append_only(tmp_path, monkeypatch):
    """A later (even wedged) attempt must never erase an earlier
    attempt's captured lines — the module's own docstring contract
    (ADVICE r5 low #3): top-level fields describe the latest attempt,
    prior docs accumulate under ``prior_attempts``."""
    from benchmarks import opportunistic_capture as cap

    monkeypatch.setattr(cap, "_REPO", str(tmp_path))
    art = tmp_path / "BENCH_SELF_r05.json"

    rich = ['{"bench_metric": "a", "value": 1}',
            '{"metric": "x", "value": 2.5}']
    monkeypatch.setattr(cap.subprocess, "run", _fake_bench_run(rich))
    assert cap.run_bench(attempt=1)
    doc1 = json.loads(art.read_text())
    assert doc1["attempt"] == 1 and len(doc1["lines"]) == 2
    assert "prior_attempts" not in doc1

    # second attempt captures LESS (simulated wedge: summary has no
    # value) — the first attempt's richer evidence must survive
    poor = ['{"metric": "x", "value": null}']
    monkeypatch.setattr(cap.subprocess, "run", _fake_bench_run(poor))
    assert not cap.run_bench(attempt=2)
    doc2 = json.loads(art.read_text())
    assert doc2["attempt"] == 2 and not doc2["ok"]
    assert len(doc2["prior_attempts"]) == 1
    assert doc2["prior_attempts"][0]["attempt"] == 1
    assert len(doc2["prior_attempts"][0]["lines"]) == 2

    # third attempt: history keeps accumulating in order
    monkeypatch.setattr(cap.subprocess, "run", _fake_bench_run(rich))
    assert cap.run_bench(attempt=3)
    doc3 = json.loads(art.read_text())
    assert [d["attempt"] for d in doc3["prior_attempts"]] == [1, 2]


@pytest.mark.slow  # 4 plan compiles x timed loops on the virtual mesh
def test_pipeline_sweep_writes_artifact_and_bench_lines(
        tmp_path, capsys, devices):
    """The sweep registered for CI (slow-marked so tier-1 stays fast):
    produces the PIPELINE_SWEEP.json verdict artifact
    (``PencilFFTPlan(pipeline='auto')``'s input) and per-K metric lines
    in the BENCH_*.json schema."""
    from benchmarks.pipeline_sweep import measure_roundtrips

    import pencilarrays_tpu as pa

    topo = pa.Topology((2, 4))
    points, verdict = measure_roundtrips(topo, (16, 12, 10), ks=(1, 2),
                                         k0=1, k1=3, repeats=2)
    assert [p["k"] for p in points] == [1, 2]
    assert all(p["seconds"] > 0 for p in points)
    assert points[1]["fused_hops"] >= 1
    assert verdict["best_k"] in (1, 2)
    assert isinstance(verdict["pipelined_wins"], bool)
    # BENCH-line schema of the CLI path, via an artifact written to tmp
    art = tmp_path / "PIPELINE_SWEEP.json"
    doc = {"points": points, "verdict": verdict}
    art.write_text(json.dumps(doc))
    loaded = json.loads(art.read_text())
    assert loaded["verdict"]["best_k"] == verdict["best_k"]


@pytest.mark.slow  # several compiles + timed loops on the virtual mesh
def test_wire_bench_smoke_writes_artifact(tmp_path, devices):
    """The ``--wire`` arm registered in ``benchmarks/suite.py``
    (slow-marked so tier-1 stays fast): the suite produces per-format
    transpose timings whose predicted bytes are HLO-pinned EQUAL to the
    compiled stats (bf16/f16 half of full precision), and nonzero
    error envelopes for the NS and diffusion spectral consumers."""
    import jax

    from benchmarks.wire_bench import run_wire_suite, write_artifact

    res = run_wire_suite(jax.devices(), n=8, k1=2, repeats=2, ns_steps=1)
    assert res["hlo_pinned"] is True
    for arm in ("transpose_f32", "transpose_c64"):
        full = res[arm]["none"]["predicted_bytes"]
        for wire in ("bf16", "f16"):
            assert res[arm][wire]["predicted_bytes"] * 2 == full
            assert res[arm][wire]["hlo_pinned"] is True
    for wl in ("workload_navier_stokes", "workload_diffusion"):
        assert res[wl]["none"]["rel_err_max"] == 0.0
        for wire in ("bf16", "f16"):
            assert 0.0 < res[wl][wire]["rel_err_max"] < 0.05
        # f16 carries 3 more mantissa bits than bf16: its envelope is
        # never meaningfully worse (at this smoke-test grid size other
        # error sources can tie the two, so the claim is an upper
        # bound, not a strict ordering — the committed n=24 artifact
        # shows the ~8x separation)
        assert (res[wl]["f16"]["rel_err_l2"]
                <= res[wl]["bf16"]["rel_err_l2"] * 1.25)
    art = tmp_path / "BENCH_WIRE.json"
    write_artifact(res, str(art), devs=jax.devices())
    doc = json.loads(art.read_text())
    assert doc["n_devices"] == 8 and doc["hlo_pinned"] is True
