"""Broadcasting interop — the Python analog of ``test/broadcast.jl:37-74``.

The reference checks that PencilArray participates in Julia's broadcast
machinery: mixed operands, style resolution (PencilArrayStyle beats plain
array styles), operations running on parents with zero allocations
(``broadcast.jl:38-40``).  Here the analogs are the NumPy
``__array_ufunc__``/``__array_function__`` protocols, raw-operand
alignment to the parent layout, and a zero-extra-collectives HLO guard.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    Pencil, PencilArray, Permutation, Topology, gather,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def pen(topo):
    # permuted + ragged so alignment must permute AND pad
    return Pencil(topo, (13, 11, 9), (1, 2), permutation=Permutation(2, 0, 1))


def make(pen, seed=0):
    u = np.random.default_rng(seed).standard_normal(pen.size_global())
    return u, PencilArray.from_global(pen, u)


def test_np_ufunc_unary(pen):
    u, x = make(pen)
    y = np.cos(x)
    assert isinstance(y, PencilArray) and y.pencil == pen
    np.testing.assert_allclose(gather(y), np.cos(u), rtol=1e-12)


def test_np_ufunc_binary_pencil_pencil(pen):
    u, x = make(pen, 1)
    v, y = make(pen, 2)
    z = np.add(x, y)
    assert isinstance(z, PencilArray)
    np.testing.assert_allclose(gather(z), u + v, rtol=1e-12)
    z = np.arctan2(x, y)
    assert isinstance(z, PencilArray)
    np.testing.assert_allclose(gather(z), np.arctan2(u, v), rtol=1e-12)


def test_style_resolution_raw_left(pen):
    """np.add(raw, u): ndarray defers to PencilArray's protocol — the
    analog of PencilArrayStyle beating DefaultArrayStyle
    (``broadcast.jl:15-29``)."""
    u, x = make(pen, 3)
    raw = np.linspace(0, 1, 9).reshape(1, 1, 9)
    z = np.add(raw, x)
    assert isinstance(z, PencilArray)
    np.testing.assert_allclose(gather(z), raw + u, rtol=1e-12)


def test_infix_with_broadcast_raw(pen):
    """PencilArray-vs-raw-array expressions: operands are interpreted
    against the LOGICAL shape (right-aligned numpy rules), permuted and
    padded to the parent layout."""
    u, x = make(pen, 4)
    kx = np.linspace(0, 1, 13).reshape(13, 1, 1)
    kz = np.linspace(2, 3, 9)  # rank-1: right-aligns to last logical dim
    z = x * kx + x * kz
    assert isinstance(z, PencilArray)
    np.testing.assert_allclose(gather(z), u * kx + u * kz, rtol=1e-12)
    z = (x + 1.0) / 2.0  # scalars still fine
    np.testing.assert_allclose(gather(z), (u + 1.0) / 2.0, rtol=1e-12)


def test_full_shape_raw_operand(pen):
    """A full logical-shape raw operand is permuted+padded to the parent."""
    u, x = make(pen, 5)
    w = np.random.default_rng(6).standard_normal(pen.size_global())
    z = x + w
    np.testing.assert_allclose(gather(z), u + w, rtol=1e-12)


def test_not_broadcastable_raises(pen):
    _, x = make(pen)
    with pytest.raises(ValueError, match="broadcastable"):
        _ = x + np.zeros((2, 11, 9))


def test_pencil_mismatch_raises(pen, topo):
    _, x = make(pen)
    pen2 = Pencil(topo, (13, 11, 9), (0, 2))
    y = PencilArray.zeros(pen2, dtype=x.dtype)
    with pytest.raises(ValueError, match="different pencils"):
        np.add(x, y)


def test_np_reductions_forward_to_masked(pen):
    """np.sum/np.max on a PencilArray route to the padding-masked
    distributed reductions (padding garbage never leaks in)."""
    u, x = make(pen, 7)
    # poison the padding: scalar arithmetic touches padded entries too
    x2 = (x + 100.0) - 100.0
    assert np.isclose(float(np.sum(x2)), u.sum(), rtol=1e-8)
    assert np.isclose(float(np.max(x2)), u.max(), rtol=1e-12)
    assert np.isclose(float(np.mean(x2)), u.mean(), rtol=1e-8)


def test_component_stack_roundtrip(pen):
    rng = np.random.default_rng(8)
    u = rng.standard_normal(pen.size_global() + (3,))
    x = PencilArray.from_global(pen, u)
    comps = [x.component(i) for i in range(3)]
    assert comps[0].extra_dims == ()
    np.testing.assert_allclose(gather(comps[1]), u[..., 1], rtol=1e-12)
    back = PencilArray.stack(comps)
    assert back.extra_dims == (3,)
    np.testing.assert_allclose(gather(back), u, rtol=1e-12)


def test_broadcast_zero_extra_collectives(pen):
    """The HLO analog of the reference's zero-allocation broadcast
    assertion (``test/broadcast.jl:38-40``): a mixed
    PencilArray/raw/scalar expression compiles with NO collectives."""
    _, x = make(pen, 9)
    kx = jnp.linspace(0, 1, 13).reshape(13, 1, 1)

    def f(d):
        a = PencilArray(x.pencil, d)
        return (np.cos(a) * kx + a * 2.0).data

    hlo = jax.jit(f).lower(x.data).compile().as_text()
    for op in ("all-to-all", "all-gather", "all-reduce",
               "collective-permute"):
        assert not re.findall(rf" {op}\(", hlo), op


def test_jnp_escape_hatch_warns_once(pen):
    """jnp.* has no third-party dispatch: jnp.cos(u) unwraps to a plain
    logical-order jax.Array — allowed, but LOUD (round-3 fix of the
    silent-unwrap trap): one warning per process, pointing at the
    wrapped spellings."""
    import warnings

    from pencilarrays_tpu.parallel import arrays as arrays_mod

    u, x = make(pen, 10)
    arrays_mod._unwrap_warned = False
    with pytest.warns(UserWarning, match="pencil is dropped"):
        y = jnp.cos(x)
    assert not isinstance(y, PencilArray)
    assert y.shape == x.shape  # true logical shape
    np.testing.assert_allclose(np.asarray(y), np.cos(u), rtol=1e-12)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second use: silent
        jnp.sin(x)


def test_jnp_unwrap_policy_error(pen, topo, monkeypatch):
    """The policy binds at TRACE time (jnp jit-caches per signature, and
    the unwrap is baked into the compiled artifact on cache hits), so
    each policy is probed with a FRESH pencil signature."""
    monkeypatch.setenv("PENCILARRAYS_TPU_UNWRAP", "error")
    x_err = PencilArray.zeros(Pencil(topo, (10, 14, 6), (1, 2)))
    with pytest.raises(TypeError, match="pencil is dropped"):
        jnp.cos(x_err)
    monkeypatch.setenv("PENCILARRAYS_TPU_UNWRAP", "allow")
    import warnings

    from pencilarrays_tpu.parallel import arrays as arrays_mod

    arrays_mod._unwrap_warned = False
    x_ok = PencilArray.zeros(Pencil(topo, (6, 10, 14), (1, 2)))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # silent, by request
        assert not isinstance(jnp.cos(x_ok), PencilArray)


def test_wrapped_numpy_namespace(pen):
    """pencilarrays_tpu.numpy: elementwise jnp functions that STAY
    wrapped (run on memory-order parents, zero collectives); reductions
    redirect to the masked ops module."""
    import pencilarrays_tpu.numpy as pnp

    u, x = make(pen, 12)
    v, y = make(pen, 13)
    out = pnp.cos(x)
    assert isinstance(out, PencilArray) and out.pencil == x.pencil
    np.testing.assert_allclose(gather(out), np.cos(u), rtol=1e-12)
    np.testing.assert_allclose(gather(pnp.add(x, y)), u + v, rtol=1e-12)
    # mixed raw operand aligns to the logical shape
    row = np.arange(u.shape[-1], dtype=u.dtype)
    np.testing.assert_allclose(gather(pnp.multiply(x, row)), u * row,
                               rtol=1e-12)
    # where with scalar branch
    np.testing.assert_allclose(gather(pnp.where(pnp.greater(x, 0), x, 0.0)),
                               np.where(u > 0, u, 0.0), rtol=1e-12)
    with pytest.raises(ValueError, match="different pencils"):
        pnp.add(x, PencilArray.zeros(pen.replace(decomp_dims=(0, 1)),
                                     x.extra_dims, x.dtype))
    with pytest.raises(AttributeError, match="ops.sum"):
        pnp.sum(x)
    # single-argument where returns index tuples, not an elementwise
    # result — rejected loudly (indices over the padded parent would be
    # wrong anyway)
    with pytest.raises(TypeError, match="not elementwise"):
        pnp.where(pnp.greater(x, 0))
    with pytest.raises(AttributeError, match="elementwise"):
        pnp.einsum
    # no PencilArray operands: plain jnp passthrough
    assert float(pnp.cos(0.0)) == 1.0


def test_gufunc_and_multi_output_rejected(pen):
    """Only elementwise single-output ufuncs dispatch to the parent: a
    gufunc would contract over a memory-order axis (wrong logical axis),
    and nout>1 has no single wrapped result."""
    _, x = make(pen, 11)
    with pytest.raises(TypeError):
        np.matmul(x, x)
    with pytest.raises(TypeError):
        np.modf(x)
